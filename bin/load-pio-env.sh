#!/usr/bin/env bash
# Load conf/pio-env.sh exactly once, exporting every assignment
# (reference: bin/load-pio-env.sh). Honors PIO_CONF_DIR. Sourced by every
# launcher (pio, pio-start-all, pio-stop-all, pio-daemon, install.sh) so
# services and the CLI see the same storage configuration.
if [ -z "${PIO_ENV_LOADED:-}" ]; then
  export PIO_ENV_LOADED=1
  _pio_parent="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
  _pio_conf_dir="${PIO_CONF_DIR:-${_pio_parent}/conf}"
  if [ -f "${_pio_conf_dir}/pio-env.sh" ]; then
    set -a  # export every assignment the env file makes
    # shellcheck disable=SC1091
    . "${_pio_conf_dir}/pio-env.sh"
    set +a
  fi
  unset _pio_parent _pio_conf_dir
fi
