#!/usr/bin/env bash
# First-run setup (reference: bin/install.sh, minus the JVM downloads):
# writes conf/pio-env.sh from the template if absent, loads it, creates
# the storage base directory, pre-compiles the native C++ runtime
# libraries, and verifies every storage DAO with a live write.
set -euo pipefail
PIO_HOME="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

if [ ! -f "${PIO_HOME}/conf/pio-env.sh" ] && [ -f "${PIO_HOME}/conf/pio-env.sh.template" ]; then
  cp "${PIO_HOME}/conf/pio-env.sh.template" "${PIO_HOME}/conf/pio-env.sh"
  echo "Wrote conf/pio-env.sh from template (edit to configure storage)."
fi

# shellcheck disable=SC1091
. "${PIO_HOME}/bin/load-pio-env.sh"
mkdir -p "${PIO_FS_BASEDIR:-$HOME/.predictionio_tpu}"

export PYTHONPATH="${PIO_HOME}${PYTHONPATH:+:${PYTHONPATH}}"
python3 - <<'PY'
from predictionio_tpu.native import LIBRARIES, NativeBuildError, build_library

for name in LIBRARIES:
    try:
        build_library(name)
        print(f"native library ready: {name}")
    except NativeBuildError as exc:
        print(f"native build skipped ({name}): {exc} — Python fallbacks apply")
PY

"${PIO_HOME}/bin/pio" status
echo "Installation verified. Next: bin/pio app new <name>"
