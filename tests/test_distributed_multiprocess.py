"""True multi-process distributed smoke test.

The dryrun (`__graft_entry__.dryrun_multichip`) validates multi-device
sharding in ONE process; this test validates the multi-HOST path — two OS
processes joined through ``jax.distributed`` (the framework's analogue of
the reference's driver↔executor cluster boundary), each contributing 4
virtual CPU devices to an 8-device global mesh, running a psum that spans
the process boundary over the distributed runtime.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, os.environ["PIO_REPO"])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()

    import jax
    jax.config.update("jax_platforms", "cpu")

    from predictionio_tpu.parallel.distributed import (
        initialize_from_env, process_info, hybrid_mesh,
    )

    assert initialize_from_env()
    rank, world = process_info()
    assert world == 2, world
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # dp axis crosses the process (DCN) boundary, data stays process-local
    mesh = hybrid_mesh({"data": 4}, {"dp": 2})
    assert dict(mesh.shape) == {"dp": 2, "data": 4}

    # global array sharded over both axes; psum must cross processes
    @jax.jit
    def total(x):
        return jnp.sum(x)

    sharding = NamedSharding(mesh, P(("dp", "data")))
    global_shape = (16,)
    local = np.arange(16, dtype=np.float32).reshape(2, 4, 2)[rank]
    arrs = [
        jax.device_put(local[i], d)
        for i, d in enumerate(mesh.local_devices)
    ]
    x = jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrs
    )
    result = float(total(x))
    assert result == float(np.arange(16).sum()), result
    print(f"RANK_{rank}_OK", flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_psum(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(
            PIO_REPO=REPO,
            PIO_DIST_COORDINATOR=f"127.0.0.1:{port}",
            PIO_DIST_NUM_PROCESSES="2",
            PIO_DIST_PROCESS_ID=str(rank),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    # 120 s covers a cold two-process jax init with margin; a hang past
    # it is the failure being diagnosed, and the kill below bounds the
    # damage to one timeout instead of wedging the tier-1 budget
    for rank, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
            pytest.fail(f"rank {rank} timed out")
        outs.append((proc.returncode, out, err))
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert f"RANK_{rank}_OK" in out
