"""Checkpoint subsystem (predictionio_tpu/ckpt): the preemption
contract, CI-sized.

Four layers:

1. **Store commit protocol**: manifest-last atomicity (a crash inside
   the array-write window leaves NOTHING loadable), checksum verify on
   load (corrupt = loud skip + counter, never a silent load), loud
   config-mismatch refusal, GC retention math.
2. **Background writer**: bounded queue that drops (and counts) under
   backpressure rather than stalling an iteration, error containment.
3. **Step-resume equivalence**: a run checkpointed at iteration 1 and
   resumed to iteration 3 — at the SAME or a DIFFERENT shard count —
   matches the uninterrupted twin within the PR-12 sharding tolerances
   (canonical row order makes the shard count a free variable;
   docs/checkpoint.md#resume-contract).
4. **Operator surface**: ``pio ckpt ls|verify|gc`` exit codes and the
   cadence/resume tri-state resolution.

CI budget: every resume case reads one module-level cache over the
test_sharded_train recipe (iterations=1 base + one resumed and one
uninterrupted training per shard count), all on the conftest 8-device
virtual CPU mesh — no subprocesses (the kill-mid-run drill lives in
bench.py where wall-clock is budgeted).
"""

import json
import os
import threading

import numpy as np
import pytest

from predictionio_tpu.ckpt import (
    EVERY_ENV,
    RESUME_ENV,
    CheckpointCorrupt,
    CheckpointMismatch,
    CheckpointStore,
    CheckpointWriter,
    resolve_every,
    resolve_resume,
)
from predictionio_tpu.ckpt.cli import main as ckpt_main
from predictionio_tpu.ops.als import ALSConfig
from predictionio_tpu.ops.als_sharded import als_train_sharded

#: the PR-12 equivalence tolerances — resume re-deals canonical rows
#: through the balancer, so the only drift is float reassociation
RTOL, ATOL = 1e-3, 1e-4


def _arrays(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.normal(size=(6, 4)).astype(np.float32),
        "y": rng.normal(size=(5, 4)).astype(np.float32),
    }


META = {"rank": 4, "lambda": 0.1, "seed": 2}


class TestCommitProtocol:
    def test_save_load_roundtrip(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        arrays = _arrays()
        store.save(3, arrays, {**META, "iteration": 3})
        assert store.steps() == [3]
        loaded = store.load(expect_meta=META)
        assert loaded.step == 3
        np.testing.assert_array_equal(loaded.arrays["x"], arrays["x"])
        np.testing.assert_array_equal(loaded.arrays["y"], arrays["y"])
        assert loaded.meta["iteration"] == 3

    def test_crash_before_manifest_leaves_nothing_loadable(
        self, tmp_path, monkeypatch
    ):
        """Kill the writer anywhere inside the array-write window: the
        step dir exists but carries no manifest, so it is crash garbage
        — invisible to steps()/load(), listed by uncommitted()."""
        store = CheckpointStore(str(tmp_path))

        def boom(d, step, files, meta):
            raise KeyboardInterrupt("preempted mid-commit")

        monkeypatch.setattr(store, "_commit_manifest", boom)
        with pytest.raises(KeyboardInterrupt):
            store.save(1, _arrays(), META)
        assert store.steps() == []
        assert store.load(expect_meta=META) is None
        assert store.uncommitted() == ["step_00000001"]
        monkeypatch.undo()
        # the recovering run re-saves the same step over the garbage
        store.save(1, _arrays(), {**META, "iteration": 1})
        assert store.steps() == [1]
        assert store.uncommitted() == []

    def test_corrupt_checksum_is_skipped_loudly(self, tmp_path, caplog):
        """A flipped bit in the newest step: load skips it (counted,
        ERROR-logged), falls back to the older committed step, and
        verify_step raises — a corrupt checkpoint is NEVER loaded."""
        store = CheckpointStore(str(tmp_path))
        store.save(1, _arrays(1), {**META, "iteration": 1})
        store.save(2, _arrays(2), {**META, "iteration": 2})
        target = os.path.join(store.step_dir(2), "x.npy")
        blob = bytearray(open(target, "rb").read())
        blob[-1] ^= 0xFF
        with open(target, "wb") as fh:
            fh.write(blob)
        with pytest.raises(CheckpointCorrupt):
            store.verify_step(2)
        with caplog.at_level("ERROR"):
            loaded = store.load(expect_meta=META)
        assert loaded.step == 1
        assert store.corrupt_skipped == 1
        assert any("corrupt" in r.message.lower() for r in caplog.records)

    def test_missing_file_is_corrupt(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.save(1, _arrays(), META)
        os.unlink(os.path.join(store.step_dir(1), "y.npy"))
        with pytest.raises(CheckpointCorrupt):
            store.verify_step(1)
        assert store.load(expect_meta=META) is None
        assert store.corrupt_skipped == 1

    def test_config_mismatch_refuses_loudly(self, tmp_path):
        """A checkpoint from a different recipe must never silently
        seed this run: the refusal names every differing key."""
        store = CheckpointStore(str(tmp_path))
        store.save(1, _arrays(), {**META, "iteration": 1})
        with pytest.raises(CheckpointMismatch, match="lambda"):
            store.load_step(1, expect_meta={**META, "lambda": 0.05})
        # load() propagates the refusal rather than skipping: mismatch
        # is an operator error, not corruption
        with pytest.raises(CheckpointMismatch):
            store.load(expect_meta={**META, "lambda": 0.05})

    def test_verify_report(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.save(1, _arrays(1), META)
        store.save(2, _arrays(2), META)
        report = store.verify()
        assert [r["step"] for r in report] == [1, 2]
        assert all(r["ok"] for r in report)
        assert all(r["files"] == 2 for r in report)


class TestRetention:
    def test_keep_last_k(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=3)
        for s in range(1, 8):
            store.save(s, _arrays(s), META)
        assert store.steps() == [5, 6, 7]

    def test_keep_every_j_survives_gc(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=2, keep_every=4)
        for s in range(1, 11):
            store.save(s, _arrays(s), META)
        # newest 2 plus every 4th: 4 and 8 pinned for archaeology
        assert store.steps() == [4, 8, 9, 10]

    def test_gc_prunes_uncommitted_only_when_asked(
        self, tmp_path, monkeypatch
    ):
        store = CheckpointStore(str(tmp_path), keep_last=2)
        monkeypatch.setattr(
            store, "_commit_manifest",
            lambda *a, **k: (_ for _ in ()).throw(OSError("crash")),
        )
        with pytest.raises(OSError):
            store.save(9, _arrays(), META)
        monkeypatch.undo()
        store.save(10, _arrays(), META)
        assert store.uncommitted() == ["step_00000009"]
        store.gc()  # routine GC leaves crash evidence for inspection
        assert store.uncommitted() == ["step_00000009"]
        store.gc(prune_uncommitted=True)  # the explicit `pio ckpt gc`
        assert store.uncommitted() == []
        assert store.steps() == [10]


class TestWriter:
    def test_backpressure_drops_and_counts(self, tmp_path):
        """A full queue must cost a DROPPED snapshot, never a stalled
        iteration: gate the store's save, flood the queue, count."""
        gate = threading.Event()

        class SlowStore(CheckpointStore):
            def save(self, step, arrays, meta):
                gate.wait(timeout=30)
                return super().save(step, arrays, meta)

        store = SlowStore(str(tmp_path), keep_last=10)
        w = CheckpointWriter(store, queue_depth=1)
        assert w.submit(1, _arrays(1), META)  # dequeued, blocked in save
        # poll until the worker holds step 1 (queue drained) so the
        # depth-1 queue state is deterministic
        for _ in range(1000):
            if w._queue.empty():
                break
            threading.Event().wait(0.005)
        assert w.submit(2, _arrays(2), META)  # fills the queue
        assert not w.submit(3, _arrays(3), META)  # Full -> dropped
        gate.set()
        stats = w.close()
        assert stats["written"] == 2
        assert stats["dropped"] == 1
        assert stats["errors"] == 0
        assert store.steps() == [1, 2]

    def test_save_error_is_contained(self, tmp_path):
        class BrokenStore(CheckpointStore):
            def save(self, step, arrays, meta):
                raise OSError("disk gone")

        w = CheckpointWriter(BrokenStore(str(tmp_path)), queue_depth=2)
        w.flush_submit(1, _arrays(), META)
        stats = w.close()
        assert stats["errors"] == 1
        assert "disk gone" in stats["lastError"]

    def test_submit_after_close_is_refused(self, tmp_path):
        w = CheckpointWriter(CheckpointStore(str(tmp_path)))
        w.close()
        with pytest.raises(RuntimeError, match="closed"):
            w.submit(1, _arrays(), META)


# ---------------------------------------------------------------------------
# step-resume equivalence (the tentpole's contract)
# ---------------------------------------------------------------------------


def _recipe():
    rng = np.random.default_rng(7)
    nnz, n_u, n_i = 6_000, 240, 100
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)
    return u, i, v, n_u, n_i


_CFG1 = ALSConfig(rank=8, iterations=1, lambda_=0.05, seed=2)
_CFG3 = ALSConfig(rank=8, iterations=3, lambda_=0.05, seed=2)
_CACHE: dict = {}


@pytest.fixture(scope="module")
def base_store(tmp_path_factory):
    """One interrupted run: 4 shards, stopped after iteration 1 with a
    committed checkpoint — the recipe's canonical factors. ``iterations``
    is deliberately absent from the config identity, so resuming it to 3
    iterations at ANY shard count is the legal continuation."""
    root = str(tmp_path_factory.mktemp("ckpt") / "als")
    store = CheckpointStore(root)
    u, i, v, n_u, n_i = _recipe()
    als_train_sharded(
        u, i, v, n_u, n_i, _CFG1, shards=4,
        checkpoint=store, checkpoint_every=1,
    )
    assert store.steps() == [1]
    return store


def _uninterrupted(shards):
    key = ("full", shards)
    if key not in _CACHE:
        u, i, v, n_u, n_i = _recipe()
        f = als_train_sharded(u, i, v, n_u, n_i, _CFG3, shards=shards)
        _CACHE[key] = (
            np.asarray(f.user_factors), np.asarray(f.item_factors)
        )
    return _CACHE[key]


def _fork(base_store, tmp_path):
    """A private copy of the interrupted run's store: the resumed run
    commits steps 2/3 into its own fork, keeping the module-cached base
    pristine for the other parametrizations."""
    import shutil

    dst = str(tmp_path / "fork")
    shutil.copytree(base_store.root, dst)
    return CheckpointStore(dst)


class TestStepResume:
    @pytest.mark.parametrize("resume_shards", [1, 2, 4])
    def test_resume_matches_uninterrupted_twin(
        self, base_store, tmp_path, resume_shards
    ):
        """Interrupted at 4 shards after iteration 1, resumed at
        ``resume_shards`` to iteration 3: factors match the twin that
        never died — N→M included, because the checkpoint stores
        canonical (global-order) rows that the balancer re-deals."""
        u, i, v, n_u, n_i = _recipe()
        store = _fork(base_store, tmp_path)
        profile: dict = {}
        f = als_train_sharded(
            u, i, v, n_u, n_i, _CFG3, shards=resume_shards,
            checkpoint=store, checkpoint_every=1, profile=profile,
        )
        assert store.steps()[-1] == 3  # the fork carries the new steps
        assert profile["ckpt"]["resumedFrom"] == 1
        ref_u, ref_i = _uninterrupted(resume_shards)
        np.testing.assert_allclose(
            np.asarray(f.user_factors), ref_u, rtol=RTOL, atol=ATOL
        )
        np.testing.assert_allclose(
            np.asarray(f.item_factors), ref_i, rtol=RTOL, atol=ATOL
        )

    def test_already_complete_returns_without_training(self, base_store):
        """Resuming a run whose checkpoint already covers cfg.iterations
        returns the checkpointed factors — zero iterations re-run."""
        u, i, v, n_u, n_i = _recipe()
        profile: dict = {}
        f = als_train_sharded(
            u, i, v, n_u, n_i, _CFG1, shards=2,
            checkpoint=base_store, checkpoint_every=1, profile=profile,
        )
        assert profile["ckpt"]["resumedFrom"] == 1
        assert profile["iteration_s"] == []
        loaded = base_store.load_step(1, expect_meta=None)
        np.testing.assert_array_equal(
            np.asarray(f.user_factors), loaded.arrays["x"]
        )

    def test_mismatched_recipe_refuses(self, base_store):
        """The same store fed to a different lambda: loud refusal, not a
        silent warm start from the wrong model."""
        u, i, v, n_u, n_i = _recipe()
        with pytest.raises(CheckpointMismatch, match="lambda"):
            als_train_sharded(
                u, i, v, n_u, n_i,
                ALSConfig(rank=8, iterations=3, lambda_=0.1, seed=2),
                shards=2, checkpoint=base_store, checkpoint_every=1,
            )

    def test_profile_ledgers_writer_stats(self, tmp_path):
        u, i, v, n_u, n_i = _recipe()
        store = CheckpointStore(str(tmp_path / "p"))
        profile: dict = {}
        als_train_sharded(
            u, i, v, n_u, n_i, _CFG1, shards=2,
            checkpoint=store, checkpoint_every=1, profile=profile,
        )
        ck = profile["ckpt"]
        assert ck["written"] == 1
        assert ck["dropped"] == 0
        assert ck["errors"] == 0
        assert ck["resumedFrom"] is None
        assert ck["snapshotS"] >= 0.0


# ---------------------------------------------------------------------------
# operator surface
# ---------------------------------------------------------------------------


class TestResolution:
    def test_cadence_precedence(self, monkeypatch):
        monkeypatch.setenv(EVERY_ENV, "7")
        assert resolve_every(None, workflow=None) == 7
        assert resolve_every(None, workflow=5) == 5
        assert resolve_every(2, workflow=5) == 2
        assert resolve_every(0, workflow=5) == 0  # explicit off wins
        monkeypatch.delenv(EVERY_ENV)
        assert resolve_every(None, workflow=None) == 0

    def test_invalid_cadence_fails_loudly(self, monkeypatch):
        with pytest.raises(ValueError):
            resolve_every(-1)
        monkeypatch.setenv(EVERY_ENV, "three")
        with pytest.raises(ValueError):
            resolve_every(None)

    def test_resume_default_on(self, monkeypatch):
        monkeypatch.delenv(RESUME_ENV, raising=False)
        assert resolve_resume() is True
        monkeypatch.setenv(RESUME_ENV, "0")
        assert resolve_resume() is False
        assert resolve_resume(True) is True  # explicit beats env


class TestCkptCLI:
    def _seeded(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "s"), keep_last=10)
        for s in (1, 2, 3):
            store.save(s, _arrays(s), {**META, "iteration": s})
        return store

    def test_ls_json(self, tmp_path, capsys):
        store = self._seeded(tmp_path)
        assert ckpt_main(["ls", "--dir", store.root, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [s["step"] for s in doc["steps"]] == [1, 2, 3]

    def test_verify_exit_codes(self, tmp_path, capsys):
        store = self._seeded(tmp_path)
        assert ckpt_main(["verify", "--dir", store.root]) == 0
        target = os.path.join(store.step_dir(2), "x.npy")
        with open(target, "ab") as fh:
            fh.write(b"junk")
        assert ckpt_main(["verify", "--dir", store.root]) == 1
        out = capsys.readouterr().out
        assert "corrupt" in out.lower()

    def test_gc_applies_retention(self, tmp_path, capsys):
        store = self._seeded(tmp_path)
        assert ckpt_main(
            ["gc", "--dir", store.root, "--keep-last", "1"]
        ) == 0
        assert CheckpointStore(store.root).steps() == [3]

    def test_missing_dir_is_an_error(self, tmp_path, capsys):
        assert ckpt_main(
            ["ls", "--dir", str(tmp_path / "nope")]
        ) != 0

    def test_console_forwards_ckpt(self, tmp_path, capsys):
        """``pio ckpt`` head-forwards before argparse/platform setup —
        the same jax-free dispatch lint and perf use."""
        from predictionio_tpu.tools.console import main as pio_main

        store = self._seeded(tmp_path)
        assert pio_main(["ckpt", "ls", "--dir", store.root]) == 0
        assert "files" in capsys.readouterr().out


class TestCkptLedger:
    def test_overhead_ratio_is_trend_only_and_family_disjoint(self):
        from predictionio_tpu.obs import perfledger

        bench = {
            "ckptResume": {
                "ok": True,
                "overheadRatio": 1.07,
                "trainShards": 2,
                "resumeShards": 4,
                "killStep": 1,
                "resumedFrom": 1,
                "resumeS": 2.5,
                "plainS": 3.0,
                "ckptS": 3.2,
                "snapshotS": 0.04,
                "written": 3,
                "dropped": 0,
                "errors": 0,
                "maxAbsDiff": 1e-5,
                "device": "cpu",
            },
            "shardedTrain": {
                "ok": True,
                "counts": {"4": {"trainS": 4.0, "rmse": 0.9,
                                 "device": "cpu"}},
            },
        }
        records = perfledger.ckpt_records(bench)
        assert [r["metric"] for r in records] == [
            "train_ckpt_overhead_ratio"
        ]
        rec = records[0]
        # NOT "s": the gate only compares lower-is-better "s"/"bytes",
        # so checkpointing cost can trend but never fail a perf gate
        assert rec["unit"] == "ratio"
        assert rec["value"] == pytest.approx(1.07)
        assert rec["extra"]["resumedFrom"] == 1
        assert rec["extra"]["written"] == 3
        # disjoint from the sharded-train family even at the same scale
        sharded = perfledger.sharded_records(bench)[0]
        assert perfledger.comparable_key(rec) != (
            perfledger.comparable_key(sharded)
        )

    def test_failed_or_missing_drill_records_nothing(self):
        from predictionio_tpu.obs import perfledger

        assert perfledger.ckpt_records({}) == []
        assert perfledger.ckpt_records(
            {"ckptResume": {"ok": False, "overheadRatio": 1.1}}
        ) == []
        assert perfledger.ckpt_records(
            {"ckptResume": {"ok": True, "overheadRatio": None}}
        ) == []

    def test_block_rides_bench_record_extras(self):
        from predictionio_tpu.obs import perfledger

        record = perfledger.bench_to_record(
            {"metric": "als_train_s", "value": 9.0,
             "ckptResume": {"ok": True, "overheadRatio": 1.05}}
        )
        assert record["extra"]["ckptResume"]["overheadRatio"] == 1.05
