"""e2 engine library tests: CategoricalNaiveBayes + MarkovChain.

Mirrors the reference suites
(``e2/src/test/scala/io/prediction/e2/engine/CategoricalNaiveBayesTest.scala``
and ``MarkovChainTest.scala``) with the same fruit / transition-matrix
fixtures and expected values.
"""

import math

import numpy as np
import pytest

from predictionio_tpu.ops import markov, naive_bayes
from predictionio_tpu.ops.naive_bayes import LabeledPoint

TOL = 1e-4

BANANA, ORANGE, OTHER = "Banana", "Orange", "Other Fruit"
NOT_LONG, LONG = "Not Long", "Long"
NOT_SWEET, SWEET = "Not Sweet", "Sweet"
NOT_YELLOW, YELLOW = "Not Yellow", "Yellow"

FRUIT_POINTS = [
    LabeledPoint(BANANA, (LONG, SWEET, YELLOW)),
    LabeledPoint(BANANA, (LONG, SWEET, YELLOW)),
    LabeledPoint(BANANA, (LONG, SWEET, YELLOW)),
    LabeledPoint(BANANA, (LONG, SWEET, YELLOW)),
    LabeledPoint(BANANA, (NOT_LONG, NOT_SWEET, NOT_YELLOW)),
    LabeledPoint(ORANGE, (NOT_LONG, SWEET, NOT_YELLOW)),
    LabeledPoint(ORANGE, (NOT_LONG, NOT_SWEET, NOT_YELLOW)),
    LabeledPoint(OTHER, (LONG, SWEET, NOT_YELLOW)),
    LabeledPoint(OTHER, (NOT_LONG, SWEET, NOT_YELLOW)),
    LabeledPoint(OTHER, (LONG, SWEET, YELLOW)),
    LabeledPoint(OTHER, (NOT_LONG, NOT_SWEET, NOT_YELLOW)),
]


@pytest.fixture(scope="module")
def fruit_model():
    return naive_bayes.train(FRUIT_POINTS)


class TestCategoricalNaiveBayes:
    def _prior(self, m, label):
        return m.log_priors[m.label_vocab[label]]

    def _lik(self, m, label, slot, value):
        return m.log_likelihoods[slot][m.label_vocab[label], m.feature_vocabs[slot][value]]

    def test_priors_and_likelihoods(self, fruit_model):
        m = fruit_model
        assert self._prior(m, BANANA) == pytest.approx(-0.7885, abs=TOL)
        assert self._prior(m, ORANGE) == pytest.approx(-1.7047, abs=TOL)
        assert self._prior(m, OTHER) == pytest.approx(-1.0116, abs=TOL)

        assert self._lik(m, BANANA, 0, LONG) == pytest.approx(-0.2231, abs=TOL)
        assert self._lik(m, BANANA, 0, NOT_LONG) == pytest.approx(-1.6094, abs=TOL)
        assert self._lik(m, BANANA, 1, SWEET) == pytest.approx(-0.2231, abs=TOL)
        assert self._lik(m, BANANA, 1, NOT_SWEET) == pytest.approx(-1.6094, abs=TOL)
        assert self._lik(m, BANANA, 2, YELLOW) == pytest.approx(-0.2231, abs=TOL)
        assert self._lik(m, BANANA, 2, NOT_YELLOW) == pytest.approx(-1.6094, abs=TOL)

        # Orange never saw Long/Yellow: those cells are -inf (the reference
        # simply has no map entry)
        assert self._lik(m, ORANGE, 0, LONG) == -math.inf
        assert self._lik(m, ORANGE, 0, NOT_LONG) == pytest.approx(0.0, abs=TOL)
        assert self._lik(m, ORANGE, 1, SWEET) == pytest.approx(-0.6931, abs=TOL)
        assert self._lik(m, ORANGE, 1, NOT_SWEET) == pytest.approx(-0.6931, abs=TOL)
        assert self._lik(m, ORANGE, 2, NOT_YELLOW) == pytest.approx(0.0, abs=TOL)
        assert self._lik(m, ORANGE, 2, YELLOW) == -math.inf

        assert self._lik(m, OTHER, 0, LONG) == pytest.approx(-0.6931, abs=TOL)
        assert self._lik(m, OTHER, 1, SWEET) == pytest.approx(-0.2877, abs=TOL)
        assert self._lik(m, OTHER, 1, NOT_SWEET) == pytest.approx(-1.3863, abs=TOL)
        assert self._lik(m, OTHER, 2, YELLOW) == pytest.approx(-1.3863, abs=TOL)
        assert self._lik(m, OTHER, 2, NOT_YELLOW) == pytest.approx(-0.2877, abs=TOL)

    def test_log_score(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, (LONG, NOT_SWEET, NOT_YELLOW))
        )
        assert score == pytest.approx(-4.2304, abs=TOL)

    def test_log_score_unknown_feature_is_neg_inf(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, (LONG, NOT_SWEET, "Not Exist"))
        )
        assert score == -math.inf

    def test_log_score_unknown_label_is_none(self, fruit_model):
        assert (
            fruit_model.log_score(
                LabeledPoint("Not Exist", (LONG, NOT_SWEET, YELLOW))
            )
            is None
        )

    def test_log_score_default_likelihood(self, fruit_model):
        # reference: ls => ls.min - log(2)
        score = fruit_model.log_score(
            LabeledPoint(BANANA, (LONG, NOT_SWEET, "Not Exist")),
            lambda ls: min(ls) - math.log(2),
        )
        assert score is not None and np.isfinite(score)
        # slot-2 fallback = min(Banana slot-2 likelihoods) - log 2
        expected = (
            fruit_model.log_priors[fruit_model.label_vocab[BANANA]]
            + fruit_model.log_likelihoods[0][
                fruit_model.label_vocab[BANANA],
                fruit_model.feature_vocabs[0][LONG],
            ]
            + fruit_model.log_likelihoods[1][
                fruit_model.label_vocab[BANANA],
                fruit_model.feature_vocabs[1][NOT_SWEET],
            ]
            + (-1.6094 - math.log(2))
        )
        assert score == pytest.approx(expected, abs=TOL)

    def test_predict(self, fruit_model):
        assert fruit_model.predict((LONG, SWEET, YELLOW)) == BANANA
        assert fruit_model.predict((NOT_LONG, SWEET, NOT_YELLOW)) == OTHER

    def test_predict_batch_matches_predict(self, fruit_model):
        m = fruit_model
        pts = [p.features for p in FRUIT_POINTS]
        fids = np.array(
            [[m.feature_vocabs[i][f[i]] for i in range(3)] for f in pts],
            np.int32,
        )
        batch = m.predict_batch(fids)
        labels = m.labels
        for f, li in zip(pts, batch):
            assert labels[int(li)] == m.predict(f)

    def test_empty_and_ragged_raise(self):
        with pytest.raises(ValueError):
            naive_bayes.train([])
        with pytest.raises(ValueError):
            naive_bayes.train(
                [LabeledPoint("a", ("x",)), LabeledPoint("b", ("x", "y"))]
            )


TWO_BY_TWO = [(0, 0, 3.0), (0, 1, 7.0), (1, 0, 10.0), (1, 1, 10.0)]
FIVE_BY_FIVE = [
    (0, 1, 12.0), (0, 2, 8.0),
    (1, 0, 3.0), (1, 1, 3.0), (1, 2, 9.0), (1, 3, 2.0), (1, 4, 8.0),
    (2, 1, 10.0), (2, 2, 8.0), (2, 4, 10.0),
    (3, 0, 2.0), (3, 3, 3.0), (3, 4, 4.0),
    (4, 1, 7.0), (4, 3, 8.0), (4, 4, 10.0),
]


def _row_as_dict(model, s):
    return {
        int(i): float(p)
        for i, p in zip(model.indices[s], model.probs[s])
        if p > 0
    }


class TestMarkovChain:
    def test_two_by_two_full(self):
        model = markov.train(TWO_BY_TWO, top_n=2)
        assert model.n == 2
        assert _row_as_dict(model, 0) == pytest.approx({0: 0.3, 1: 0.7})
        assert _row_as_dict(model, 1) == pytest.approx({0: 0.5, 1: 0.5})

    def test_five_by_five_top2(self):
        # expected values from MarkovChainTest.scala:26-39
        model = markov.train(FIVE_BY_FIVE, top_n=2)
        assert _row_as_dict(model, 0) == pytest.approx({1: 0.6, 2: 0.4})
        assert _row_as_dict(model, 1) == pytest.approx({2: 9 / 25, 4: 8 / 25})
        assert _row_as_dict(model, 2) == pytest.approx({1: 10 / 28, 4: 10 / 28})
        assert _row_as_dict(model, 3) == pytest.approx({3: 3 / 9, 4: 4 / 9})
        assert _row_as_dict(model, 4) == pytest.approx({3: 8 / 25, 4: 0.4})

    def test_predict(self):
        model = markov.train(TWO_BY_TWO, top_n=2)
        next_state = model.predict([0.4, 0.6])
        assert next_state == pytest.approx([0.42, 0.58], abs=1e-6)

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            markov.train([], top_n=2)
