"""Attention schedules: flash vs naive, ring/Ulysses vs flash on the mesh.

The sequence-parallel schedules must be numerically equivalent to plain
attention — the mesh changes the communication pattern, never the math.
"""

import jax
import numpy as np
import pytest

# interpret-mode flash attention at real shapes: minutes on CPU
pytestmark = pytest.mark.slow

from predictionio_tpu.ops.attention import (
    attention,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from predictionio_tpu.parallel import MeshConfig, create_mesh


def naive(q, k, v, causal):
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((q.shape[2], k.shape[2]), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    shape = (2, 4, 64, 16)  # B, H, L, D
    return tuple(rng.normal(size=shape).astype(np.float32) for _ in range(3))


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh(MeshConfig((("seq", 8),)))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_k", [16, 64, 48])
def test_flash_matches_naive(qkv, causal, block_k):
    q, k, v = qkv
    ref = naive(q, k, v, causal)
    got = np.asarray(flash_attention(q, k, v, causal=causal, block_k=block_k))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_naive(qkv, seq_mesh, causal):
    q, k, v = qkv
    ref = naive(q, k, v, causal)
    got = np.asarray(ring_attention(q, k, v, seq_mesh, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_naive(qkv, causal):
    # H=4 heads need a 4-device seq axis (heads must divide)
    mesh4 = create_mesh(
        MeshConfig((("seq", 4),)), devices=jax.devices()[:4]
    )
    q, k, v = qkv
    ref = naive(q, k, v, causal)
    got = np.asarray(ulysses_attention(q, k, v, mesh4, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_dispatch(qkv, seq_mesh):
    q, k, v = qkv
    # no mesh → flash; mesh → ring; both equal naive
    ref = naive(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(attention(q, k, v)), ref, rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(attention(q, k, v, mesh=seq_mesh)), ref, rtol=2e-4, atol=2e-5
    )
    with pytest.raises(ValueError):
        attention(q, k, v, mesh=seq_mesh, schedule="bogus")


def test_ring_rejects_indivisible_length(seq_mesh):
    rng = np.random.default_rng(1)
    q, k, v = (rng.normal(size=(1, 2, 60, 8)).astype(np.float32)
               for _ in range(3))
    with pytest.raises(AssertionError):
        ring_attention(q, k, v, seq_mesh)


class TestFlashPallas:
    """The fused Pallas flash kernel must match the XLA online-softmax
    path exactly-ish (same math, different blocking) — including ragged
    lengths, non-causal, cross-attention (Lq != Lk), and dispatch via
    attention(impl="pallas")."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize(
        "b,h,lq,lk,d,bq,bk",
        [
            (2, 4, 64, 64, 16, 32, 32),
            (1, 2, 60, 60, 8, 32, 16),   # ragged L vs blocks
            (1, 1, 7, 13, 8, 8, 8),      # tiny + cross-attention
            (2, 2, 128, 96, 32, 64, 32),
        ],
    )
    def test_matches_xla_flash(self, causal, b, h, lq, lk, d, bq, bk):
        from predictionio_tpu.ops.attention import flash_attention_pallas

        rng = np.random.default_rng(7)
        q = rng.normal(size=(b, h, lq, d)).astype(np.float32)
        k = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        v = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        got = np.asarray(flash_attention_pallas(
            q, k, v, causal=causal, block_q=bq, block_k=bk
        ))
        ref = np.asarray(flash_attention(q, k, v, causal=causal,
                                         block_k=max(16, lk // 2)))
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_dispatch_impl(self, qkv):
        q, k, v = qkv
        ref = naive(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(attention(q, k, v, impl="pallas")), ref,
            rtol=2e-4, atol=2e-5,
        )
        with pytest.raises(ValueError, match="impl"):
            attention(q, k, v, impl="bogus")


def test_flash_pallas_gradients_match_xla():
    """The custom VJP (pallas forward, flash-style XLA recompute
    backward) must produce the same gradients as differentiating the
    XLA path directly."""
    from predictionio_tpu.ops.attention import flash_attention_pallas

    rng = np.random.default_rng(9)
    q, k, v = (rng.normal(size=(1, 2, 32, 8)).astype(np.float32)
               for _ in range(3))

    def loss_p(q, k, v):
        return (flash_attention_pallas(q, k, v, causal=True) ** 2).sum()

    def loss_x(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
