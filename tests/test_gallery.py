"""Remote template gallery tests (Template.scala:56-375 parity).

The environment has no egress, so the gallery contract — ETag conditional
requests, 304 cache hits, offline fallback, zipball extraction — is driven
against a local request-counting HTTP server.
"""

import io
import json
import os
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from predictionio_tpu.tools.gallery import (
    GalleryError,
    fetch_cached,
    get_remote,
    list_remote,
)


def make_zip(files: dict, prefix: str = "") -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        for name, content in files.items():
            zf.writestr(prefix + name, content)
    return buf.getvalue()


class _GalleryHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        srv = self.server
        srv.hits.setdefault(self.path, []).append(
            self.headers.get("If-None-Match")
        )
        body, etag = srv.routes.get(self.path, (None, None))
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        if etag and self.headers.get("If-None-Match") == etag:
            self.send_response(304)
            self.end_headers()
            return
        self.send_response(200)
        if etag:
            self.send_header("ETag", etag)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture()
def gallery_server(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path / "base"))
    archive = make_zip(
        {"engine.json": '{"id": "default"}', "engine.py": "# template\n",
         "sub/helper.py": "x = 1\n"},
        prefix="repo-1.0/",  # GitHub-zipball single top folder shape
    )
    index = json.dumps(
        [
            {"name": "gallery-rec", "description": "a remote template",
             "version": "1.0", "archive_url": "/archives/rec.zip"},
        ]
    ).encode()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _GalleryHandler)
    srv.daemon_threads = True
    srv.routes = {
        "/index.json": (index, '"etag-index-1"'),
        "/archives/rec.zip": (archive, '"etag-zip-1"'),
    }
    srv.hits = {}
    import threading

    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/index.json"
    monkeypatch.setenv("PIO_TEMPLATE_GALLERY_URL", url)
    yield srv, url
    srv.shutdown()
    srv.server_close()


def test_list_remote_uses_etag_cache(gallery_server):
    srv, url = gallery_server
    first = list_remote()
    assert first == [
        {"name": "gallery-rec", "description": "a remote template",
         "version": "1.0"}
    ]
    assert srv.hits["/index.json"][0] is None  # no etag on first request
    second = list_remote()
    assert second == first
    # second request was conditional and got a 304 (cache served the body)
    assert srv.hits["/index.json"][1] == '"etag-index-1"'


def test_offline_falls_back_to_cache(gallery_server, monkeypatch):
    srv, url = gallery_server
    assert list_remote() != []
    srv.shutdown()
    srv.server_close()
    assert list_remote() != []  # served from cache
    # a never-fetched URL with no cache raises
    with pytest.raises(GalleryError, match="unreachable"):
        fetch_cached(url.replace("/index.json", "/never.json"))


def test_server_error_falls_back_to_cache(gallery_server):
    srv, url = gallery_server
    assert list_remote() != []  # warm the cache
    srv.routes["/index.json"] = (None, None)  # now 404s
    assert list_remote() != []  # served from cache despite HTTP error


def test_get_remote_extracts_and_strips_root(gallery_server, tmp_path):
    srv, url = gallery_server
    target = tmp_path / "proj"
    out = get_remote("gallery-rec", str(target))
    assert out["version"] == "1.0"
    assert (target / "engine.json").read_text() == '{"id": "default"}'
    assert (target / "sub" / "helper.py").read_text() == "x = 1\n"
    with pytest.raises(ValueError, match="not empty"):
        get_remote("gallery-rec", str(target))
    with pytest.raises(KeyError, match="nosuch"):
        get_remote("nosuch", str(tmp_path / "p2"))


def test_get_remote_rejects_zip_slip(gallery_server, tmp_path, monkeypatch):
    srv, url = gallery_server
    evil = make_zip({"../../evil.txt": "pwned"})
    srv.routes["/archives/evil.zip"] = (evil, None)
    srv.routes["/index.json"] = (
        json.dumps(
            [{"name": "evil", "archive_url": "/archives/evil.zip"}]
        ).encode(),
        '"etag-index-2"',
    )
    with pytest.raises(ValueError, match="escapes target"):
        get_remote("evil", str(tmp_path / "p3"))
    assert not (tmp_path / "evil.txt").exists()


def test_console_template_falls_through_to_gallery(gallery_server, tmp_path):
    from predictionio_tpu.tools.console import main

    target = tmp_path / "from-cli"
    rc = main(["template", "get", "gallery-rec", str(target)])
    assert rc == 0
    assert (target / "engine.py").exists()


def test_no_gallery_configured(monkeypatch, tmp_path):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    monkeypatch.delenv("PIO_TEMPLATE_GALLERY_URL", raising=False)
    with pytest.raises(GalleryError, match="No remote gallery"):
        list_remote()
