"""Live partition migration + fleet autoscaling (ISSUE 17).

Covers the elastic-fleet robustness contract end to end:

1. **PartitionMigration** over in-process layouts: dual-write acking,
   backfill to the per-keyspace watermark, the race-window write
   between the watermark check and the flip (must land in BOTH
   layouts), M < N merge direction, abort leaving the old layout
   byte-identical, and coordinator kill/resume from durable cursors.
2. **The chaos drill** (``loadgen --migrate-drill``) over real HTTP
   fleets: new-layout primary killed mid-backfill, coordinator killed
   mid-dual-write, zero lost acked writes, zero duplicated folds
   through the cursor handoff (docs/storage.md#live-migration).
3. **OpLog.adopt_slot** — the empty-log slot-adoption path the new
   layout's logs use, and its history/conflict refusals.
4. **FleetAutoscaler** — the synthetic-overload drill: exactly one
   bounded action, hysteresis (no flapping on recovery), every
   decision in the flight recorder, and the ``pio autoscale`` CLI
   (docs/robustness.md#autoscaler).
"""

import datetime as dt
import json
import os

import pytest

from predictionio_tpu.continuous.watcher import LocalFeed, handoff_cursors
from predictionio_tpu.fleet.autoscale import (
    AutoscaleConfig,
    AutoscaleSignals,
    FleetAutoscaler,
)
from predictionio_tpu.storage.changefeed import Changefeed
from predictionio_tpu.storage.event import Event
from predictionio_tpu.storage.migration import (
    MigrationError,
    MigrationFrozen,
    PartitionMigration,
)
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.storage.partition import partition_for_event
from predictionio_tpu.storage.sqlite_events import SqliteEventStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
APP = 1


# ---------------------------------------------------------------------------
# in-process layout harness: N sqlite partitions + oplogs, one client
# ---------------------------------------------------------------------------
class LocalLayout:
    def __init__(self, root, count):
        self.count = count
        self.parts = []
        for i in range(count):
            events = SqliteEventStore(":memory:")
            oplog = OpLog(os.path.join(root, f"p{i}"), partition=(i, count))
            self.parts.append((events, Changefeed(oplog, events, None, None),
                               oplog))

    def feeds(self):
        return [LocalFeed(p[2]) for p in self.parts]


class LocalLayoutClient:
    """The slice of the partitioned-store client surface the migration
    coordinator drives (insert/write/delete/init/remove + count)."""

    def __init__(self, layout):
        self._l = layout
        self.partition_count = layout.count

    def _cf(self, app_id, entity_id):
        return self._l.parts[
            partition_for_event(self._l.count, app_id, entity_id)
        ][1]

    def insert(self, event, app_id):
        eid, _seq = self._cf(app_id, event.entity_id).insert_event(
            event, app_id
        )
        return eid

    def write(self, events, app_id):
        by = {}
        for e in events:
            by.setdefault(
                partition_for_event(self._l.count, app_id, e.entity_id), []
            ).append(e)
        for idx, evs in by.items():
            self._l.parts[idx][1].write_events(evs, app_id, fresh=False)

    def delete(self, event_id, app_id):
        for _, cf, _ in self._l.parts:
            found, _ = cf.delete_event(event_id, app_id)
            if found:
                return True
        return False

    def init(self, app_id):
        for _, cf, _ in self._l.parts:
            cf.init_app(app_id)
        return True

    def remove(self, app_id):
        for _, cf, _ in self._l.parts:
            cf.remove_app(app_id)
        return True

    def find_ids(self, app_id):
        ids = set()
        for events, _, _ in self._l.parts:
            for e in events.find(app_id):
                ids.add(e.event_id)
        return ids

    def dump(self, app_id):
        """Full-content snapshot, partition-attributed — the
        byte-identical comparison the abort contract needs."""
        rows = []
        for idx, (events, _, _) in enumerate(self._l.parts):
            for e in events.find(app_id):
                rows.append((
                    idx, e.event_id, e.event, e.entity_type, e.entity_id,
                    e.target_entity_type, e.target_entity_id,
                    json.dumps(dict(e.properties), sort_keys=True),
                ))
        return sorted(rows)


def ev(i):
    return Event(
        event="rate", entity_type="user", entity_id=f"u{i}",
        target_entity_type="item", target_entity_id=f"i{i % 7}",
        properties={"rating": float(i % 5)},
        event_time=dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc),
    )


def make_layouts(tmp_path, old_count=2, new_count=3):
    old = LocalLayout(str(tmp_path / "old"), old_count)
    new = LocalLayout(str(tmp_path / "new"), new_count)
    oc, nc = LocalLayoutClient(old), LocalLayoutClient(new)
    oc.init(APP)
    nc.init(APP)
    return old, new, oc, nc


def pump_to_ready(mig, rounds=60, max_ops=100):
    for _ in range(rounds):
        if mig.pump(max_ops=max_ops)["phase"] == "ready":
            return
    raise AssertionError(f"never reached ready: {mig.status()}")


# ---------------------------------------------------------------------------
# 1. migration core: expand, merge, race window, abort, kill/resume
# ---------------------------------------------------------------------------
class TestPartitionMigration:
    def test_expand_2_to_3_converges_exactly(self, tmp_path):
        old, new, oc, nc = make_layouts(tmp_path, 2, 3)
        pre = [oc.insert(ev(i), APP) for i in range(40)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        live = mig.write([ev(100 + i) for i in range(10)], APP)
        pump_to_ready(mig)
        assert mig.watermark()["ok"]
        assert mig.cutover(timeout_s=10)["phase"] == "done"
        new_ids = nc.find_ids(APP)
        acked = set(pre) | set(live)
        assert acked <= new_ids
        assert new_ids == oc.find_ids(APP)  # converged exactly, no extras
        # post-flip writes land in the new layout ONLY
        post = set(mig.write([ev(2000)], APP))
        assert post <= nc.find_ids(APP)
        assert not post & oc.find_ids(APP)

    def test_merge_3_to_2_converges_exactly(self, tmp_path):
        """M < N: a merge is the same protocol run the other way — the
        bucket space is fixed, only the bucket→partition map changes."""
        old, new, oc, nc = make_layouts(tmp_path, 3, 2)
        pre = [oc.insert(ev(i), APP) for i in range(30)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        live = mig.write([ev(200 + i) for i in range(8)], APP)
        pump_to_ready(mig)
        assert mig.cutover(timeout_s=10)["phase"] == "done"
        assert set(pre) | set(live) <= nc.find_ids(APP)
        assert nc.find_ids(APP) == oc.find_ids(APP)

    def test_race_window_write_lands_in_both_layouts(self, tmp_path):
        """A write acked between the operator's watermark check and the
        cutover flip must exist in BOTH layouts: acked to old (it was
        pre-flip), carried to new by the final in-freeze drain."""
        old, new, oc, nc = make_layouts(tmp_path)
        [oc.insert(ev(i), APP) for i in range(12)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        pump_to_ready(mig)
        assert mig.watermark()["ok"]
        race = set(mig.write([ev(999)], APP))  # after the check
        assert mig.cutover(timeout_s=10)["phase"] == "done"
        assert race <= oc.find_ids(APP)
        assert race <= nc.find_ids(APP)

    def test_abort_leaves_old_layout_byte_identical(self, tmp_path):
        old, new, oc, nc = make_layouts(tmp_path)
        [oc.insert(ev(i), APP) for i in range(20)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        mig.write([ev(300 + i) for i in range(5)], APP)
        mig.begin_backfill()
        mig.pump(max_ops=7)  # partial backfill, then the operator bails
        before = oc.dump(APP)
        out = mig.abort("operator says no")
        assert out["phase"] == "aborted"
        assert oc.dump(APP) == before  # abort touched nothing in old
        # post-abort writes are plain old-layout writes: no mirroring
        post = set(mig.write([ev(400)], APP))
        assert post <= oc.find_ids(APP)
        assert not post & nc.find_ids(APP)

    def test_abort_after_flip_refuses(self, tmp_path):
        old, new, oc, nc = make_layouts(tmp_path)
        [oc.insert(ev(i), APP) for i in range(6)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        pump_to_ready(mig)
        mig.cutover(timeout_s=10)
        with pytest.raises(MigrationError):
            mig.abort("too late")

    def test_early_cutover_refused_before_watermark(self, tmp_path):
        """With the new layout dead the backfill cannot reach the
        head; cutover must refuse inside its deadline — and succeed
        once the layout is back."""
        old, new, oc, nc = make_layouts(tmp_path)
        [oc.insert(ev(i), APP) for i in range(25)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        mig.begin_backfill()
        healthy_insert, healthy_write = nc.insert, nc.write

        def dead(*_a, **_k):
            raise RuntimeError("new primary dead")

        nc.insert = nc.write = dead
        mig.pump(max_ops=3)  # stalls loudly, cursor holds
        assert not mig.watermark()["ok"]
        with pytest.raises(MigrationError):
            mig.cutover(timeout_s=0.2)
        assert mig.phase != "done"
        assert not mig.writes_frozen  # the failed freeze thawed
        nc.insert, nc.write = healthy_insert, healthy_write  # "promote"
        pump_to_ready(mig)
        assert mig.cutover(timeout_s=10)["phase"] == "done"
        assert nc.find_ids(APP) == oc.find_ids(APP)

    def test_pump_auto_advances_dual_write_to_backfill(self, tmp_path):
        old, new, oc, nc = make_layouts(tmp_path)
        [oc.insert(ev(i), APP) for i in range(20)]
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        assert mig.phase == "dual_write"
        # max_ops=1 keeps the first tick short of the head: the phase
        # must already have left dual_write for backfill
        assert mig.pump(max_ops=1)["phase"] == "backfill"

    def test_kill_then_resume_from_durable_cursors(self, tmp_path):
        """The coordinator dies mid-backfill; its writer role (the
        event-server side of the split) keeps acking; a fresh instance
        over the same state dir resumes and converges."""
        old, new, oc, nc = make_layouts(tmp_path)
        pre = [oc.insert(ev(i), APP) for i in range(50)]
        state = str(tmp_path / "mig")
        mig = PartitionMigration(oc, nc, state, old_feeds=old.feeds())
        mig.start()
        mig.begin_backfill()
        mig.pump(max_ops=10)  # partial
        mig.kill()
        with pytest.raises(MigrationError):
            mig.pump()
        survivors = mig.write([ev(500)], APP)  # writer role survives
        mig2 = PartitionMigration(oc, nc, state, old_feeds=old.feeds())
        assert mig2.phase == "backfill"
        assert mig2.state.cursors  # resumed mid-stream, not from zero
        pump_to_ready(mig2)
        assert mig2.cutover(timeout_s=10)["phase"] == "done"
        assert set(pre) | set(survivors) <= nc.find_ids(APP)
        assert nc.find_ids(APP) == oc.find_ids(APP)

    def test_cutover_freeze_sheds_writes_with_retry_after(self, tmp_path):
        old, new, oc, nc = make_layouts(tmp_path)
        mig = PartitionMigration(
            oc, nc, str(tmp_path / "mig"), old_feeds=old.feeds()
        )
        mig.start()
        mig.writes_frozen = True  # the in-cutover posture
        with pytest.raises(MigrationFrozen) as exc:
            mig.check_frozen()
        assert exc.value.retry_after_s > 0
        mig.writes_frozen = False


# ---------------------------------------------------------------------------
# 2. the chaos drill over real HTTP fleets (tier-1, per the ISSUE gate)
# ---------------------------------------------------------------------------
class TestMigrateDrill:
    def test_drill_holds_every_invariant(self, tmp_path):
        from predictionio_tpu.tools.loadgen import run_migrate_drill

        report = run_migrate_drill(
            old_partitions=2, new_partitions=3, ops_per_phase=12,
            state_root=str(tmp_path),
        )
        assert report["ok"], report
        assert report["deadCoordinatorRefusesPump"]
        assert report["resumedPhase"] == "dual_write"
        assert report["earlyCutoverRefused"]
        assert report["lostAckedWrites"] == 0
        assert report["layoutsIdenticalAtFlip"]
        assert report["duplicateFolds"] == 0
        assert report["postFlipInNewOnly"]
        assert report["dualWriteOverhead"] > 0


# ---------------------------------------------------------------------------
# 3. OpLog slot adoption (the new layout's empty logs joining it)
# ---------------------------------------------------------------------------
class TestAdoptSlot:
    def test_empty_log_adopts_and_persists(self, tmp_path):
        log = OpLog(str(tmp_path / "log"))
        log.adopt_slot(1, 3)
        assert log.partition == [1, 3]
        assert log.checkpoint()["partition"] == [1, 3]
        # durable: a reopen configured for the slot agrees
        again = OpLog(str(tmp_path / "log"), partition=(1, 3))
        assert again.checkpoint()["partition"] == [1, 3]

    def test_matching_slot_is_a_noop(self, tmp_path):
        log = OpLog(str(tmp_path / "log"), partition=(0, 2))
        log.adopt_slot(0, 2)
        assert log.partition == [0, 2]

    def test_conflicting_slot_is_loud(self, tmp_path):
        log = OpLog(str(tmp_path / "log"), partition=(0, 2))
        with pytest.raises(ValueError):
            log.adopt_slot(1, 2)

    def test_log_with_history_refuses(self, tmp_path):
        events = SqliteEventStore(":memory:")
        log = OpLog(str(tmp_path / "log"))
        cf = Changefeed(log, events, None, None)
        cf.init_app(APP)
        cf.insert_event(ev(1), APP)
        with pytest.raises(ValueError, match="history"):
            log.adopt_slot(0, 2)

    def test_changefeed_adopt_updates_its_slot(self, tmp_path):
        events = SqliteEventStore(":memory:")
        log = OpLog(str(tmp_path / "log"))
        cf = Changefeed(log, events, None, None)
        cf.adopt_slot(2, 4)
        assert cf.partition == (2, 4)
        assert log.partition == [2, 4]


# ---------------------------------------------------------------------------
# 4. watcher cursor handoff across the flip
# ---------------------------------------------------------------------------
class TestHandoffCursors:
    def _feed(self, tmp_path, name, n):
        events = SqliteEventStore(":memory:")
        log = OpLog(str(tmp_path / name))
        cf = Changefeed(log, events, None, None)
        cf.init_app(APP)
        for i in range(n):
            cf.insert_event(ev(i), APP)
        return LocalFeed(log)

    def test_partitioned_cursors_seed_at_feed_heads(self, tmp_path):
        feeds = [
            self._feed(tmp_path, "f0", 3), self._feed(tmp_path, "f1", 5)
        ]
        state = str(tmp_path / "watch")
        written = handoff_cursors(feeds, state)
        assert set(written) == {0, 1}
        for i, feed in enumerate(feeds):
            path = os.path.join(
                state, f"partition-{i}", "continuous_cursor.json"
            )
            with open(path) as fh:
                cur = json.load(fh)
            assert cur["seq"] == feed.checkpoint()["seq"]
            assert cur["seq"] > 0

    def test_single_feed_writes_flat_cursor(self, tmp_path):
        feed = self._feed(tmp_path, "f0", 2)
        state = str(tmp_path / "watch")
        handoff_cursors([feed], state)
        assert os.path.exists(
            os.path.join(state, "continuous_cursor.json")
        )


# ---------------------------------------------------------------------------
# 5. the autoscaler drill: bounded, damped, ledgered
# ---------------------------------------------------------------------------
def _hot(replicas=1, **kw):
    return AutoscaleSignals(
        replicas_per_shard=replicas, shard_count=2, partition_count=2,
        firing=("query-availability",), **kw
    )


def _calm(replicas=2):
    return AutoscaleSignals(
        replicas_per_shard=replicas, shard_count=2, partition_count=2
    )


class TestFleetAutoscaler:
    def test_overload_drill_exactly_one_action_no_flapping(self):
        """Synthetic overload: exactly ONE add-replica, then cooldown
        holds through continued pain, then recovery does not flap a
        remove until down_ticks calm ticks elapse."""
        from predictionio_tpu.obs.flight import default_recorder

        recorder = default_recorder()
        mark = len(recorder)
        scaler = FleetAutoscaler(AutoscaleConfig(
            up_ticks=2, down_ticks=6, cooldown_ticks=5, dry_run=True,
        ))
        actions = []
        for _ in range(4):  # hot: tick 2 acts, 3-4 are cooldown holds
            actions += scaler.observe(_hot())
        for _ in range(5):  # recovered: cooldown tail + calm build-up
            actions += scaler.observe(_calm())
        assert [a.kind for a in actions] == ["add_replica"]
        assert actions[0].target == 2
        assert actions[0].dry_run and not actions[0].executed
        # every tick — the action AND the holds — hit the ledger
        ledgered = [
            e for e in recorder.dump()[max(0, mark - 2048):]
            if e["site"] == "fleet.autoscale.decide"
        ]
        assert len(ledgered) >= scaler.tick_count
        assert any(
            e["details"]["action"] == "add_replica" for e in ledgered
        )

    def test_calm_scale_down_is_slow_and_floored(self):
        scaler = FleetAutoscaler(AutoscaleConfig(
            up_ticks=2, down_ticks=3, cooldown_ticks=0, dry_run=True,
            min_replicas=1,
        ))
        acts = []
        for _ in range(3):
            acts += scaler.observe(_calm(replicas=2))
        assert [a.kind for a in acts] == ["remove_replica"]
        assert acts[0].target == 1
        # at the floor, calm ticks hold forever
        scaler2 = FleetAutoscaler(AutoscaleConfig(
            down_ticks=2, cooldown_ticks=0, dry_run=True, min_replicas=1,
        ))
        for _ in range(6):
            assert scaler2.observe(_calm(replicas=1)) == []

    def test_ingest_pressure_recommends_n_plus_one_migration(self):
        actuated = []
        scaler = FleetAutoscaler(
            AutoscaleConfig(
                up_ticks=2, cooldown_ticks=5, dry_run=False,
                max_partitions=8,
            ),
            actuator=actuated.append,
        )
        sig = AutoscaleSignals(
            replicas_per_shard=1, shard_count=2, partition_count=2,
            partition_shed={0: 3.0, 1: 1.0},
        )
        assert scaler.observe(sig) == []
        (action,) = scaler.observe(sig)
        assert action.kind == "migrate_partitions"
        assert action.target == 3  # N+1, never a jump
        assert action.executed and action.error is None
        assert [a.kind for a in actuated] == ["migrate_partitions"]

    def test_hot_at_max_replicas_holds_not_acts(self):
        scaler = FleetAutoscaler(AutoscaleConfig(
            up_ticks=1, cooldown_ticks=0, dry_run=True, max_replicas=2,
        ))
        assert scaler.observe(_hot(replicas=2)) == []
        assert scaler.decisions()[-1]["action"]["kind"] == "hold"

    def test_actuator_failure_marks_action_never_raises(self):
        def boom(_action):
            raise RuntimeError("provisioner down")

        scaler = FleetAutoscaler(
            AutoscaleConfig(up_ticks=1, cooldown_ticks=0, dry_run=False),
            actuator=boom,
        )
        (action,) = scaler.observe(_hot())
        assert not action.executed
        assert "provisioner down" in action.error

    def test_cli_dry_run_emits_decisions(self, tmp_path, capsys):
        from predictionio_tpu.tools import console

        signals = tmp_path / "signals.json"
        signals.write_text(json.dumps({
            "replicasPerShard": 1, "shardCount": 2, "partitionCount": 2,
            "firing": ["query-availability"],
        }))
        rc = console.main(
            ["autoscale", "--signals", str(signals), "--ticks", "3"]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["dryRun"] is True
        assert out["ticks"] == 3
        assert [a["kind"] for a in out["actions"]] == ["add_replica"]
        assert len(out["decisions"]) == 3  # holds ledgered too


# ---------------------------------------------------------------------------
# 6. perf-ledger records: trend-only, keyed by the layout move
# ---------------------------------------------------------------------------
class TestMigrationLedger:
    def _bench(self, old=2, new=3, ok=True):
        return {
            "device": "cpu",
            "migrationDrill": {
                "ok": ok, "oldPartitions": old, "newPartitions": new,
                "opsPerPhase": 12, "wallS": 0.8,
                "dualWriteOverhead": 1.4, "lostAckedWrites": 0,
                "duplicateFolds": 0,
            },
        }

    def test_records_are_trend_only_and_keyed_by_layout_move(self):
        from predictionio_tpu.obs import perfledger

        records = perfledger.migration_records(self._bench())
        assert [r["metric"] for r in records] == [
            "migration_drill_wall_s", "migration_dualwrite_overhead"
        ]
        # neither unit is the gated "s": both are pure trend records
        assert all(r["unit"] != "s" for r in records)
        assert all(r["scale"] == "2->3" for r in records)
        # a 2->3 expansion and a 3->2 merge never share a comparable
        # group, so `pio perf diff` can never compare across moves
        merge = perfledger.migration_records(self._bench(old=3, new=2))
        keys = {
            perfledger.comparable_key(r) for r in records + merge
        }
        assert len(keys) == 4
        # a failed drill records nothing — it timed a broken run
        assert perfledger.migration_records(self._bench(ok=False)) == []


# ---------------------------------------------------------------------------
# 7. metric catalog rows for the new planes (docs/observability.md)
# ---------------------------------------------------------------------------
class TestMigrationMetricCatalog:
    def test_new_metrics_are_cataloged(self):
        with open(os.path.join(REPO, "docs", "observability.md")) as fh:
            doc = fh.read()
        for name in (
            "pio_migration_phase",
            "pio_migration_backfill_lag_events",
            "pio_autoscale_actions_total",
        ):
            assert name in doc, f"{name} missing from the metric catalog"
