"""CLI console + tools tests.

Covers the ``pio``-equivalent console (SURVEY §2.3: ``Console.scala``
dispatch), engine registration manifests, export/import round-trips, the
dashboard server, and the full build→train→deploy→query→undeploy lifecycle
over a scaffolded bundled template — the analogue of the reference
quickstart exercised end-to-end in one process.
"""

import datetime as dt
import json
import os
import urllib.request

import pytest

from predictionio_tpu.storage import Event, StorageRegistry, get_registry
from predictionio_tpu.tools import console
from predictionio_tpu.tools import register as register_mod
from predictionio_tpu.tools import run_server, run_workflow
from predictionio_tpu.tools.export_events import export_events
from predictionio_tpu.tools.import_events import ImportError_, import_events
from predictionio_tpu.tools.templates import get_template, list_templates

T0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    """Global-registry-backed fixture: templates read via get_registry()."""
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    reg = get_registry(refresh=True)
    yield reg
    get_registry(refresh=True)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode() or "{}")


def _get_raw(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read().decode())


# ---------------------------------------------------------------------------
# app / accesskey consoles
# ---------------------------------------------------------------------------


def test_app_lifecycle(registry):
    out = console.app_new(registry, "myapp", access_key="k1")
    assert out["accessKey"] == "k1" and out["id"] >= 1
    with pytest.raises(ValueError):
        console.app_new(registry, "myapp")

    apps = console.app_list(registry)
    assert [a["name"] for a in apps] == ["myapp"]
    assert apps[0]["accessKeys"] == ["k1"]

    show = console.app_show(registry, "myapp")
    assert show["accessKeys"][0]["key"] == "k1"

    console.accesskey_new(registry, "myapp", events=["rate"], key="k2")
    keys = console.accesskey_list(registry, "myapp")
    assert {k["key"] for k in keys} == {"k1", "k2"}
    console.accesskey_delete(registry, "k2")
    assert len(console.accesskey_list(registry)) == 1

    # data-delete wipes events but keeps the app
    store = registry.get_events()
    app_id = out["id"]
    store.insert(
        Event(event="$set", entity_type="user", entity_id="u1", event_time=T0),
        app_id,
    )
    from predictionio_tpu.storage import EventFilter

    console.app_data_delete(registry, "myapp")
    assert list(store.find(app_id, EventFilter())) == []

    console.app_delete(registry, "myapp")
    assert console.app_list(registry) == []


def test_console_main_app_commands(registry, capsys):
    assert console.main(["app", "new", "cliapp"], registry) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["name"] == "cliapp"
    assert console.main(["app", "list"], registry) == 0
    # destructive command without --force in a non-tty context is refused
    assert console.main(["app", "delete", "cliapp"], registry) == 1
    assert console.app_list(registry), "refused delete must not remove the app"
    capsys.readouterr()
    assert console.main(["app", "delete", "cliapp", "--force"], registry) == 0
    # unknown app → error path, exit 1
    assert console.main(["app", "show", "nope"], registry) == 1
    # not an engine project → JSON error, not a traceback
    capsys.readouterr()
    assert console.main(["build", "--engine-dir", "/tmp"], registry) == 1
    assert "error" in json.loads(capsys.readouterr().out)


def test_status(registry):
    result = console.status(registry)
    assert result["ok"] and set(result["storage"]) == {
        "metadata", "modeldata", "eventdata",
    }


# ---------------------------------------------------------------------------
# export / import
# ---------------------------------------------------------------------------


def _ingest_rates(registry, app_id=1, n_users=8, n_items=6):
    store = registry.get_events()
    store.init(app_id)
    events = []
    for u in range(n_users):
        for i in range(n_items):
            if (u + i) % 2 == 0:
                events.append(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties={"rating": float(1 + (u * i) % 5)},
                        event_time=T0 + dt.timedelta(minutes=u * n_items + i),
                    )
                )
    store.write(events, app_id)
    return len(events)


def test_export_import_roundtrip(registry, tmp_path):
    n = _ingest_rates(registry, app_id=1)
    out_file = tmp_path / "events.jsonl"
    with open(out_file, "w") as fh:
        assert export_events(registry, 1, fh) == n

    with open(out_file) as fh:
        assert import_events(registry, 2, fh, batch_size=7) == n

    from predictionio_tpu.storage import EventFilter

    src = list(registry.get_events().find(1, EventFilter()))
    dst = list(registry.get_events().find(2, EventFilter()))
    assert len(src) == len(dst) == n
    assert {e.entity_id for e in src} == {e.entity_id for e in dst}
    assert sorted(e.properties.get("rating", 0) for e in src) == sorted(
        e.properties.get("rating", 0) for e in dst
    )


def test_import_rejects_bad_lines(registry):
    with pytest.raises(ImportError_, match="line 2"):
        import_events(
            registry, 3,
            ['{"event":"rate","entityType":"user","entityId":"u1"}', "not-json"],
        )


# ---------------------------------------------------------------------------
# template gallery + registration
# ---------------------------------------------------------------------------


def test_template_list_and_get(tmp_path):
    names = {t["name"] for t in list_templates()}
    assert names == {"recommendation", "classification", "similarproduct",
                     "ecommerce", "sequencerec"}
    target = tmp_path / "proj"
    out = get_template("recommendation", str(target))
    assert os.path.exists(target / "engine.json")
    assert os.path.exists(target / "engine.py")
    assert out["template"] == "recommendation"
    with pytest.raises(ValueError):
        get_template("recommendation", str(target))  # non-empty dir
    with pytest.raises(KeyError):
        get_template("nope", str(tmp_path / "x"))


def test_register_engine_manifest(registry, tmp_path):
    target = tmp_path / "proj"
    get_template("classification", str(target))
    ed = register_mod.register_engine(registry, str(target))
    stored = registry.get_metadata().manifest_get(ed.manifest.id, ed.manifest.version)
    assert stored is not None and stored.engine_factory == "engine:engine_factory"
    assert os.path.exists(target / "manifest.json")

    # Editing the project bumps the version (rebuilt-jar fingerprint analogue)
    (target / "engine.py").write_text(
        (target / "engine.py").read_text() + "\n# edited\n"
    )
    ed2 = register_mod.register_engine(registry, str(target))
    assert ed2.manifest.id == ed.manifest.id
    assert ed2.manifest.version != ed.manifest.version


# ---------------------------------------------------------------------------
# end-to-end: build → train → deploy → query → reload → undeploy
# ---------------------------------------------------------------------------


def test_full_lifecycle_recommendation(registry, tmp_path, capsys):
    _ingest_rates(registry, app_id=1)
    target = tmp_path / "proj"
    get_template("recommendation", str(target))

    assert console.main(["build", "--engine-dir", str(target)], registry) == 0
    build_out = json.loads(capsys.readouterr().out)

    assert console.main(["train", "--engine-dir", str(target)], registry) == 0
    train_out = json.loads(capsys.readouterr().out)
    instance_id = train_out["engineInstanceId"]
    inst = registry.get_metadata().engine_instance_get(instance_id)
    assert inst is not None and inst.status == "COMPLETED"
    assert inst.engine_id == build_out["engineId"]

    srv_args = run_server.build_parser().parse_args(
        ["--engine-dir", str(target), "--port", "0"]
    )
    server = run_server.make_server(srv_args, registry, block=False)
    try:
        port = server.bound_port
        stat, body = _post(
            f"http://localhost:{port}/queries.json", {"user": "u1", "num": 3}
        )
        assert stat == 200
        assert len(body["itemScores"]) == 3
        scores = [s["score"] for s in body["itemScores"]]
        assert scores == sorted(scores, reverse=True)

        stat, _ = _get(f"http://localhost:{port}/reload")
        assert stat == 200
        stat2, body2 = _post(
            f"http://localhost:{port}/queries.json", {"user": "u1", "num": 3}
        )
        assert stat2 == 200 and body2["itemScores"]

        out = console.undeploy("localhost", port)
        assert out["status"] == 200
    finally:
        server.stop_async()
        server.server_close()


def test_train_via_spawned_subprocess(registry, tmp_path):
    """The process-boundary path (RunWorkflow.scala:103-169 analogue)."""
    import subprocess, sys

    _ingest_rates(registry, app_id=1)
    target = tmp_path / "proj"
    get_template("recommendation", str(target))

    env = dict(os.environ)
    env["PIO_FS_BASEDIR"] = os.environ["PIO_FS_BASEDIR"]
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "predictionio_tpu.tools.run_workflow",
            "--engine-dir", str(target),
        ],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    inst = registry.get_metadata().engine_instance_get(out["engineInstanceId"])
    assert inst is not None and inst.status == "COMPLETED"


def test_custom_engine_model_pickles_across_train_and_deploy(registry, tmp_path):
    """A model class defined inside the project-local engine.py must survive
    the pickle → model store → unpickle roundtrip (the 'customize the
    scaffold in place' workflow; regression for the synthetic-module-name
    pickling failure)."""
    target = tmp_path / "custom"
    target.mkdir()
    (target / "engine.json").write_text(json.dumps({
        "engineFactory": "engine:engine_factory",
        "algorithms": [{"name": "", "params": {}}],
    }))
    (target / "engine.py").write_text(
        "import dataclasses\n"
        "from predictionio_tpu.controller import (\n"
        "    Algorithm, DataSource, Engine, FirstServing, IdentityPreparator)\n"
        "\n"
        "@dataclasses.dataclass\n"
        "class MyModel:\n"
        "    weight: float\n"
        "\n"
        "class DS(DataSource):\n"
        "    def read_training(self, ctx):\n"
        "        return [1.0, 2.0, 3.0]\n"
        "\n"
        "class Algo(Algorithm):\n"
        "    def train(self, ctx, pd):\n"
        "        return MyModel(weight=sum(pd))\n"
        "    def predict(self, model, query):\n"
        "        return {'w': model.weight * query.get('x', 1)}\n"
        "\n"
        "def engine_factory():\n"
        "    return Engine({'': DS}, {'': IdentityPreparator}, {'': Algo},\n"
        "                  {'': FirstServing})\n"
    )
    assert console.main(["train", "--engine-dir", str(target)], registry) == 0

    srv_args = run_server.build_parser().parse_args(
        ["--engine-dir", str(target), "--port", "0"]
    )
    server = run_server.make_server(srv_args, registry, block=False)
    try:
        stat, body = _post(
            f"http://localhost:{server.bound_port}/queries.json", {"x": 2.0}
        )
        assert stat == 200 and body["w"] == 12.0
    finally:
        server.stop_async()
        server.server_close()


# ---------------------------------------------------------------------------
# dashboard
# ---------------------------------------------------------------------------


def test_dashboard_lists_evaluations(registry):
    from predictionio_tpu.storage import STATUS_EVALCOMPLETED
    from predictionio_tpu.storage.metadata import EvaluationInstance
    from predictionio_tpu.tools.dashboard import (
        DashboardConfig,
        create_dashboard,
    )

    md = registry.get_metadata()
    inst_id = md.evaluation_instance_insert(
        EvaluationInstance(
            id="",
            status=STATUS_EVALCOMPLETED,
            start_time=T0,
            end_time=T0,
            evaluation_class="MyEval",
            engine_params_generator_class="MyGen",
            evaluator_results="metric=0.9",
            evaluator_results_html="<html><body>0.9</body></html>",
            evaluator_results_json='{"metric": 0.9}',
        )
    )
    server = create_dashboard(DashboardConfig(port=0), registry, block=False)
    try:
        port = server.bound_port
        stat, html_body = _get_raw(f"http://localhost:{port}/")
        assert stat == 200 and "MyEval" in html_body and inst_id in html_body
        stat, js = _get(
            f"http://localhost:{port}/engine_instances/{inst_id}/evaluator_results.json"
        )
        assert stat == 200 and js["metric"] == 0.9
        stat, html2 = _get_raw(
            f"http://localhost:{port}/engine_instances/{inst_id}/evaluator_results.html"
        )
        assert stat == 200 and "0.9" in html2
        with pytest.raises(urllib.error.HTTPError):
            _get(f"http://localhost:{port}/engine_instances/zzz/evaluator_results.json")
    finally:
        server.stop_async()
        server.server_close()


def test_upgrade_migrates_between_backends(tmp_path, monkeypatch):
    """pio upgrade: sqlite → native migration preserves every event."""
    import datetime as dt

    from predictionio_tpu.storage.data_map import DataMap
    from predictionio_tpu.storage.event import Event
    from predictionio_tpu.storage.sqlite_events import SqliteEventStore
    from predictionio_tpu.storage.native_events import NativeEventStore
    from predictionio_tpu.tools.upgrade import migrate_events

    src = SqliteEventStore(str(tmp_path / "src" / "events.db"))
    src.init(1)
    src.init(2)
    t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
    for i in range(25):
        src.insert(
            Event(event="rate", entity_type="user", entity_id=f"u{i % 3}",
                  target_entity_type="item", target_entity_id=f"i{i}",
                  properties=DataMap({"rating": float(i % 5)}),
                  event_time=t0 + dt.timedelta(minutes=i)),
            1 if i % 2 else 2,
        )
    dst = NativeEventStore(str(tmp_path / "dst"))
    counts = migrate_events(src, dst, [1, 2])
    assert counts == {1: 13, 2: 12} or counts == {1: 12, 2: 13}
    for app in (1, 2):
        src_events = {e.event_id: e for e in src.find(app)}
        dst_events = {e.event_id: e for e in dst.find(app)}
        assert set(src_events) == set(dst_events)
        for eid, e in src_events.items():
            got = dst_events[eid]
            assert got.properties.to_dict() == e.properties.to_dict()
            assert got.event_time == e.event_time
    # idempotent: rerunning does not duplicate (upsert by event id)
    counts2 = migrate_events(src, dst, [1])
    assert sum(1 for _ in dst.find(1)) == counts2[1] == counts[1]
    src.close(); dst.close()


def test_upgrade_cli(tmp_path, monkeypatch):
    import json as _json

    from predictionio_tpu.storage.event import Event
    from predictionio_tpu.storage.sqlite_events import SqliteEventStore
    from predictionio_tpu.tools.console import main

    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path / "base"))
    from predictionio_tpu.storage import get_registry

    get_registry(refresh=True)
    src = SqliteEventStore(str(tmp_path / "a" / "events.db"))
    src.init(5)
    src.insert(Event(event="x", entity_type="t", entity_id="1"), 5)
    src.close()
    rc = main([
        "upgrade", "--from-type", "sqlite", "--from-path", str(tmp_path / "a"),
        "--to-type", "native", "--to-path", str(tmp_path / "b"),
        "--appid", "5",
    ])
    assert rc == 0
    from predictionio_tpu.storage.native_events import NativeEventStore

    dst = NativeEventStore(str(tmp_path / "b" / "events_native"))
    assert sum(1 for _ in dst.find(5)) == 1
    dst.close()
    get_registry(refresh=True)


class TestParquetExportImport:
    """Parquet archive roundtrip (the reference EventsToFile's default
    format) — exact event fidelity including $unset null properties."""

    def test_roundtrip(self, registry, tmp_path):
        import datetime as dt

        from predictionio_tpu.storage import DataMap, Event
        from predictionio_tpu.tools.export_events import export_events_parquet
        from predictionio_tpu.tools.import_events import import_events_parquet

        ev = registry.get_events()
        ev.init(1)
        t = dt.datetime(2026, 7, 3, 12, 0, tzinfo=dt.timezone.utc)
        events = [
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": 4.5, "note": "héllo"}),
                  event_time=t, pr_id="PR123"),
            Event(event="$set", entity_type="user", entity_id="u2",
                  properties=DataMap({"plan": "gold"}), event_time=t),
            Event(event="$unset", entity_type="user", entity_id="u2",
                  properties=DataMap({"plan": None}), event_time=t,
                  tags=("a", "b")),
        ]
        ev.write(events, 1)
        path = str(tmp_path / "events.parquet")
        n = export_events_parquet(registry, 1, path)
        assert n == 3

        n2 = import_events_parquet(registry, 2, path)
        assert n2 == 3
        from predictionio_tpu.storage.events import EventFilter

        back = list(ev.find(2, EventFilter()))
        assert len(back) == 3
        rate = [e for e in back if e.event == "rate"][0]
        assert rate.properties["rating"] == 4.5
        assert rate.properties["note"] == "héllo"
        assert rate.pr_id == "PR123"
        unset = [e for e in back if e.event == "$unset"][0]
        assert unset.properties.to_dict() == {"plan": None}  # keys survive
        assert unset.tags == ("a", "b")

    def test_empty_export_imports_cleanly(self, registry, tmp_path):
        from predictionio_tpu.tools.export_events import export_events_parquet
        from predictionio_tpu.tools.import_events import import_events_parquet

        registry.get_events().init(5)
        path = str(tmp_path / "empty.parquet")
        assert export_events_parquet(registry, 5, path) == 0
        assert import_events_parquet(registry, 6, path) == 0

    def test_cli_flags(self, registry, tmp_path, monkeypatch):
        import predictionio_tpu.storage.registry as regmod
        from predictionio_tpu.storage import DataMap, Event
        from predictionio_tpu.tools.console import main

        monkeypatch.setattr(regmod, "_default_registry", registry)
        ev = registry.get_events()
        ev.init(3)
        ev.write([Event(event="view", entity_type="user", entity_id="u9",
                        target_entity_type="item", target_entity_id="i9")], 3)
        out = str(tmp_path / "a.parquet")
        assert main(["export", "--appid", "3", "--output", out,
                     "--format", "parquet"], registry) == 0
        assert main(["import", "--appid", "4", "--input", out,
                     "--format", "parquet"], registry) == 0
        from predictionio_tpu.storage.events import EventFilter

        assert len(list(ev.find(4, EventFilter()))) == 1


class TestRevalReport:
    """reval_report folds TPU_REVALIDATION.jsonl into the evidence
    summary — newest record per step wins, malformed lines are skipped,
    and every section renders from partial evidence."""

    def _write(self, tmp_path, recs, junk=True):
        p = tmp_path / "ev.jsonl"
        lines = [json.dumps(r) for r in recs]
        if junk:
            lines.insert(1, '{"truncated": ')  # torn line must be skipped
            lines.append("")
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def test_newest_wins_and_junk_skipped(self, tmp_path):
        from predictionio_tpu.tools.reval_report import load

        path = self._write(tmp_path, [
            {"step": "baseline_f32", "value": 20.0},
            {"step": "baseline_f32", "value": 17.5},
        ])
        steps = load(path)
        assert steps["baseline_f32"]["value"] == 17.5

    def test_report_renders_partial_evidence(self, tmp_path):
        from predictionio_tpu.tools.reval_report import load, report

        path = self._write(tmp_path, [
            {"step": "baseline_f32", "value": 17.8,
             "holdout_rmse": 0.5304, "iteration_s": [2.5, 0.38],
             "device": "TPU v5 lite0", "rc": 0},
            {"step": "bf16_gather", "value": 14.2,
             "holdout_rmse": 0.5306, "rmse_gate": "pass", "rc": 0},
            {"step": "fused_smoke", "ok": True, "compiled": True,
             "kernel_max_rel": 1e-6, "rc": 0},
            {"step": "mesh_pallas", "error": "timed out", "rc": -1},
            {"step": "dispatch_bench", "catalogs": {
                "60000": {"dispatch_ms_per_batch": 3.4,
                          "implied_qps_at_depth1": 150000.0}}},
            {"step": "loadgen_depth2", "qps": 6200.1, "p99_ms": 30.2},
            {"step": "loadgen_inproc_depth2_big", "qps": 21000.0,
             "p99_ms": 9.3},
            {"step": "unknown_extra", "foo": 1},
        ])
        text = report(load(path))
        assert "17.8s train" in text
        assert "steady iter 0.380s" in text  # first iter excluded
        assert "gate=pass" in text
        assert "fused_smoke**: OK" in text
        assert "mesh_pallas**: FAILED" in text
        assert "| 60000 | 3.4 | 150000 |" in text
        assert "6200.1" in text and "21000.0" in text
        assert "unknown_extra" in text  # surfaced, not dropped

    def test_fallback_marked_invalid(self, tmp_path):
        from predictionio_tpu.tools.reval_report import load, report

        path = self._write(tmp_path, [
            {"step": "baseline_f32", "value": 12.0,
             "fallback": "cpu-fallback", "rc": 0},
        ], junk=False)
        assert "FALLBACK — INVALID" in report(load(path))
