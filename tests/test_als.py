"""ALS kernel tests: exactness of the normal-equation solves against a numpy
reference, RMSE convergence on synthetic low-rank data, bucketing correctness,
and the serving top-k kernels."""

import numpy as np
import pytest

from predictionio_tpu.ops import (
    ALSConfig,
    als_train_coo,
    bucketize,
    predict_pairs,
    rmse,
    standardize,
    top_k_for_users,
    top_k_for_vectors,
    top_k_similar_items,
)


def synthetic_ratings(n_users=60, n_items=40, rank=3, density=0.4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    y = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = x @ y.T + 3.0  # center around 3 like star ratings
    mask = rng.random((n_users, n_items)) < density
    users, items = np.nonzero(mask)
    return users, items, full[users, items].astype(np.float32)


def numpy_als_step(y, users, items, ratings, n_users, lam, rank):
    """Reference solve: one user-side update with weighted-lambda."""
    x = np.zeros((n_users, rank))
    for u in range(n_users):
        sel = users == u
        if not sel.any():
            continue
        yu = y[items[sel]]
        ru = ratings[sel]
        n_u = sel.sum()
        a = yu.T @ yu + lam * n_u * np.eye(rank)
        x[u] = np.linalg.solve(a, yu.T @ ru)
    return x


class TestBucketize:
    def test_roundtrip_contents(self):
        users, items, ratings = synthetic_ratings()
        bm = bucketize(users, items, ratings, 60, 40)
        assert bm.nnz == len(users)
        # reconstruct COO from buckets
        got = set()
        for b in bm.buckets:
            for bi in range(b.rows.shape[0]):
                for kk in range(b.width):
                    if b.mask[bi, kk]:
                        got.add((int(b.rows[bi]), int(b.idx[bi, kk]),
                                 float(b.val[bi, kk])))
        expect = {(int(u), int(i), float(r))
                  for u, i, r in zip(users, items, ratings)}
        assert got == expect

    def test_bucket_widths_fit_degrees(self):
        users = np.array([0] * 5 + [1] * 40 + [2])
        items = np.arange(46) % 50
        vals = np.ones(46, dtype=np.float32)
        bm = bucketize(users, items, vals, 3, 50)
        widths = sorted(b.width for b in bm.buckets)
        assert widths == [8, 128]  # degrees 5,1 -> 8; degree 40 -> 128

    def test_empty_rows_absent(self):
        bm = bucketize(np.array([5]), np.array([0]), np.array([1.0]), 10, 1)
        assert sum(b.rows.shape[0] for b in bm.buckets) == 1


class TestALSExplicit:
    def test_single_step_matches_numpy(self):
        """One user-side solve must match the dense numpy normal equations."""
        from predictionio_tpu.ops.als import (
            ALSConfig,
            _update_side,
            bucketize,
            init_factors,
        )
        import jax.numpy as jnp

        users, items, ratings = synthetic_ratings()
        n_users, n_items, rank, lam = 60, 40, 4, 0.05
        y = init_factors(n_items, rank, seed=1)
        by_user = bucketize(users, items, ratings, n_users, n_items)
        cfg = ALSConfig(rank=rank, lambda_=lam)
        x_jax = _update_side(y, by_user, cfg, (n_users, rank), None)
        x_np = numpy_als_step(
            np.asarray(y), users, items, ratings, n_users, lam, rank
        )
        np.testing.assert_allclose(np.asarray(x_jax), x_np, rtol=2e-3, atol=2e-4)

    def test_rmse_converges_on_low_rank_data(self):
        users, items, ratings = synthetic_ratings(rank=3)
        cfg = ALSConfig(rank=6, iterations=10, lambda_=0.01)
        factors = als_train_coo(users, items, ratings, 60, 40, cfg)
        train_rmse = rmse(factors, users, items, ratings)
        assert train_rmse < 0.15, f"train RMSE too high: {train_rmse}"

    def test_more_iterations_improve(self):
        users, items, ratings = synthetic_ratings(rank=3, seed=7)
        r1 = rmse(
            als_train_coo(users, items, ratings, 60, 40,
                          ALSConfig(rank=6, iterations=1, lambda_=0.01)),
            users, items, ratings,
        )
        r8 = rmse(
            als_train_coo(users, items, ratings, 60, 40,
                          ALSConfig(rank=6, iterations=8, lambda_=0.01)),
            users, items, ratings,
        )
        assert r8 < r1

    def test_generalization_on_holdout(self):
        users, items, ratings = synthetic_ratings(
            n_users=80, n_items=50, rank=3, density=0.5, seed=3
        )
        n = len(users)
        rng = np.random.default_rng(0)
        perm = rng.permutation(n)
        tr, te = perm[: int(n * 0.8)], perm[int(n * 0.8):]
        cfg = ALSConfig(rank=5, iterations=10, lambda_=0.05)
        factors = als_train_coo(
            users[tr], items[tr], ratings[tr], 80, 50, cfg
        )
        test_rmse = rmse(factors, users[te], items[te], ratings[te])
        assert test_rmse < 0.35, f"holdout RMSE too high: {test_rmse}"


class TestALSImplicit:
    def test_implicit_ranks_observed_higher(self):
        rng = np.random.default_rng(5)
        n_users, n_items = 30, 20
        # two user cohorts with disjoint item tastes
        users, items, vals = [], [], []
        for u in range(n_users):
            liked = range(10) if u < 15 else range(10, 20)
            for i in liked:
                if rng.random() < 0.7:
                    users.append(u)
                    items.append(i)
                    vals.append(1.0)
        cfg = ALSConfig(rank=4, iterations=8, lambda_=0.1,
                        implicit_prefs=True, alpha=10.0)
        factors = als_train_coo(
            np.array(users), np.array(items),
            np.array(vals, dtype=np.float32), n_users, n_items, cfg,
        )
        import jax.numpy as jnp

        scores = np.asarray(
            factors.user_factors @ factors.item_factors.T
        )
        # cohort-A users should prefer cohort-A items on average
        a_pref = scores[:15, :10].mean() - scores[:15, 10:].mean()
        b_pref = scores[15:, 10:].mean() - scores[15:, :10].mean()
        assert a_pref > 0.2 and b_pref > 0.2


class TestScoring:
    def test_top_k_matches_numpy(self):
        rng = np.random.default_rng(0)
        uf = rng.normal(size=(10, 4)).astype(np.float32)
        itf = rng.normal(size=(25, 4)).astype(np.float32)
        scores, idx = top_k_for_users(uf, itf, np.array([2, 5]), k=3)
        full = uf[[2, 5]] @ itf.T
        np.testing.assert_array_equal(
            np.asarray(idx), np.argsort(-full, axis=1)[:, :3]
        )
        np.testing.assert_allclose(
            np.asarray(scores), np.sort(full, axis=1)[:, ::-1][:, :3], rtol=1e-5
        )

    def test_exclude_mask(self):
        uf = np.eye(3, dtype=np.float32)
        itf = np.eye(3, dtype=np.float32)
        mask = np.zeros((1, 3), dtype=bool)
        mask[0, 0] = True  # exclude the best item for user 0
        scores, idx = top_k_for_users(uf, itf, np.array([0]), k=1,
                                      exclude_mask=mask)
        assert int(idx[0, 0]) != 0

    def test_similar_items_excludes_self(self):
        rng = np.random.default_rng(1)
        itf = rng.normal(size=(12, 4)).astype(np.float32)
        scores, idx = top_k_similar_items(itf, np.array([3]), k=5)
        assert 3 not in np.asarray(idx[0])
        assert np.all(np.asarray(scores[0]) <= 1.0 + 1e-5)

    def test_vector_query(self):
        itf = np.eye(4, dtype=np.float32)
        q = np.array([[0.0, 1.0, 0.0, 0.0]], dtype=np.float32)
        scores, idx = top_k_for_vectors(q, itf, k=1)
        assert int(idx[0, 0]) == 1

    def test_standardize(self):
        s = standardize(np.array([1.0, 2.0, 3.0], dtype=np.float32))
        np.testing.assert_allclose(np.asarray(s).mean(), 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(s).std(), 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# Shared solver sweep (tier-1 budget, ROUND9): the solve-mode, fused-gather
# and mesh equivalence tests all compare trainings of the SAME zipf dataset
# under different lever settings — and this file alone used to burn 260-350s
# re-training overlapping configs per parametrization. One module-level
# cache trains each (mode, implicit, fused, meshed) config exactly once per
# session; every equivalence test reads from it. The pallas run IS the
# fused=False run of the fused A/B, so the overlap costs nothing twice.
# ---------------------------------------------------------------------------

_SWEEP_CACHE: dict = {}


def _sweep_data():
    rng = np.random.default_rng(7)
    nnz, n_u, n_i = 30_000, 900, 250
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)
    return u, i, v, n_u, n_i


def sweep_factors(mode, implicit=False, fused=False, meshed=False,
                  gather="f32", sort=None):
    """Factors for one lever setting over the shared dataset, trained at
    most once per session (rank 12, 3 iterations, seed 2 — identical
    across every consumer so the cached runs stay comparable).

    ``sort=None`` rides the round-12 default (resolves to sorted for
    these bucketized inputs), so the cached baseline legs ARE the
    flipped-default runs; ``sort=False`` is the explicit legacy opt-out
    leg the default-equivalence test compares against. ``fused=False``
    (the signature default) is likewise the explicit einsum-build
    opt-out — under the flipped defaults a bare pallas config resolves
    fused ON, pinned in TestLeverDefaults without training anything."""
    key = (mode, implicit, fused, meshed, gather, sort)
    if key not in _SWEEP_CACHE:
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo
        from predictionio_tpu.parallel.mesh import create_mesh

        u, i, v, n_u, n_i = _sweep_data()
        cfg = ALSConfig(
            rank=12, iterations=3, lambda_=0.05,
            implicit_prefs=implicit, alpha=1.0, seed=2,
            solve_mode=mode, fused_gather=fused,
            gather_dtype=gather, sort_gather_indices=sort,
        )
        f = als_train_coo(
            u, i, v, n_users=n_u, n_items=n_i, cfg=cfg,
            mesh=create_mesh() if meshed else None,
        )
        _SWEEP_CACHE[key] = (
            np.asarray(f.user_factors), np.asarray(f.item_factors)
        )
    return _SWEEP_CACHE[key]


class TestSolveModes:
    """"two_phase" (one batched Cholesky per bucket) must reproduce the
    default chunked solve to float tolerance, explicit and implicit."""

    @pytest.mark.parametrize("implicit", [False, True])
    def test_alternate_modes_match_chunked(self, implicit):
        chunked = sweep_factors("chunked", implicit=implicit)
        for mode in ("two_phase", "pallas"):
            out = sweep_factors(mode, implicit=implicit)
            np.testing.assert_allclose(
                chunked[0], out[0], rtol=2e-3, atol=2e-4
            )
            np.testing.assert_allclose(
                chunked[1], out[1], rtol=2e-3, atol=2e-4
            )

    def test_unknown_mode_fails_loudly(self):
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo

        cfg = ALSConfig(rank=4, iterations=1, solve_mode="bogus")
        # unknown mode silently behaving like "chunked" would hide typos
        with pytest.raises(ValueError, match="solve_mode"):
            als_train_coo(
                np.array([0, 1], dtype=np.int32),
                np.array([0, 1], dtype=np.int32),
                np.ones(2, dtype=np.float32),
                n_users=2, n_items=2, cfg=cfg,
            )


class TestPallasModeGuards:
    """Explicit solve_mode="pallas" outside the kernel's VMEM envelope must
    fail loudly — "auto" silently falls back instead. (Meshes are accepted
    since round 3: the kernel runs per-device inside shard_map; equality
    tests live in tests/test_parallel.py.)"""

    def test_pallas_accepts_mesh(self):
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo
        from predictionio_tpu.parallel.mesh import create_mesh

        u = np.array([0, 1, 2], dtype=np.int32)
        i = np.array([0, 1, 0], dtype=np.int32)
        v = np.ones(3, dtype=np.float32)
        cfg = ALSConfig(rank=4, iterations=1, solve_mode="pallas")
        factors = als_train_coo(
            u, i, v, n_users=3, n_items=2, cfg=cfg, mesh=create_mesh()
        )
        assert np.isfinite(np.asarray(factors.user_factors)).all()

    def test_pallas_rejects_high_rank(self):
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo

        u = np.array([0, 1, 2], dtype=np.int32)
        i = np.array([0, 1, 0], dtype=np.int32)
        v = np.ones(3, dtype=np.float32)
        cfg = ALSConfig(rank=88, iterations=1, solve_mode="pallas")
        with pytest.raises(ValueError, match="rank"):
            als_train_coo(u, i, v, n_users=3, n_items=2, cfg=cfg)


class TestSortGatherIndices:
    """Within-row index sorting (gather locality) must be invisible to the
    math: the Gramian sum over K is permutation-invariant *in exact
    arithmetic*. In float32 the sort reorders the einsum accumulation, so
    factors agree only to reassociation rounding — ~1e-5 per solve,
    amplified through the alternating iterations (ROUND7_NOTES.md pins
    the analysis; the seed's atol=1e-5 over 3 iterations sat exactly on
    that noise floor). The contract worth pinning is two-part: the
    *multiset* of (idx, val) pairs per row is exactly preserved
    (bit-level, below) and training quality is unchanged — factors equal
    to a documented reassociation tolerance and training RMSE equal to
    1e-3."""

    def test_sorted_buckets_preserve_rows_and_padding(self):
        from predictionio_tpu.ops.als import bucketize, sort_bucket_indices

        rng = np.random.default_rng(5)
        nnz, n_u, n_i = 5000, 300, 120
        u = rng.integers(0, n_u, nnz).astype(np.int32)
        i = rng.integers(0, n_i, nnz).astype(np.int32)
        v = rng.normal(size=nnz).astype(np.float32)
        side = bucketize(u, i, v, n_u, n_i, pad_to_blocks=True)
        sorted_side = sort_bucket_indices(side)
        for b0, b1 in zip(side.buckets, sorted_side.buckets):
            np.testing.assert_array_equal(b0.rows, b1.rows)
            np.testing.assert_array_equal(b0.counts, b1.counts)
            for r in range(b0.idx.shape[0]):
                c = int(b0.counts[r])
                # valid prefix: same multiset, now ascending
                assert sorted(b0.idx[r, :c].tolist()) == b1.idx[r, :c].tolist()
                # (idx, val) pairing preserved
                assert (
                    sorted(zip(b0.idx[r, :c], b0.val[r, :c]))
                    == sorted(zip(b1.idx[r, :c], b1.val[r, :c]))
                )
                # padding tail untouched in place
                np.testing.assert_array_equal(b0.idx[r, c:], b1.idx[r, c:])

    def test_staged_input_with_sort_flag_is_loud(self):
        """The flag can only act pre-staging; silently ignoring it would
        corrupt an A/B measurement."""
        from predictionio_tpu.ops.als import (
            ALSConfig, als_train, bucketize, stage,
        )

        rng = np.random.default_rng(7)
        u = rng.integers(0, 50, 500).astype(np.int32)
        i = rng.integers(0, 30, 500).astype(np.int32)
        v = np.ones(500, dtype=np.float32)
        bu = stage(bucketize(u, i, v, 50, 30, pad_to_blocks=True))
        bi = stage(bucketize(i, u, v, 30, 50, pad_to_blocks=True))
        with pytest.raises(ValueError, match="sort_gather_indices"):
            als_train(
                bu, bi,
                ALSConfig(rank=4, iterations=1, sort_gather_indices=True),
            )

    def test_training_result_unchanged(self):
        """The round-12 default flip's equivalence proof: the DEFAULT
        config (sort resolves ON for bucketized inputs) vs the explicit
        ``sort_gather_indices=False`` legacy opt-out, riding the shared
        sweep cache — the sorted leg IS every other equivalence test's
        baseline, so the flip costs one extra cached training run."""
        from predictionio_tpu.ops.als import ALSFactors, rmse

        u, i, v, _, _ = _sweep_data()
        sorted_run = sweep_factors("chunked")  # default ⇒ sorted
        legacy = sweep_factors("chunked", sort=False)
        # Factor parity to the f32 reassociation tolerance: the sort
        # reorders each row's einsum accumulation, so per-solve rounding
        # is ~1e-5 and three alternating iterations amplify it through
        # the Cholesky solves (ROUND7_NOTES.md). The seed-era atol=1e-5
        # bound asserted bitwise-ish equality that f32 cannot promise.
        np.testing.assert_allclose(
            sorted_run[0], legacy[0], rtol=1e-3, atol=1e-4,
        )
        # ...and the bound that actually matters for an A/B: training
        # quality is unchanged.
        r_sorted = rmse(ALSFactors(*sorted_run, rank=12), u, i, v)
        r_legacy = rmse(ALSFactors(*legacy, rank=12), u, i, v)
        assert abs(r_sorted - r_legacy) < 1e-3

    def test_staged_input_default_resolves_unsorted(self):
        """Staged inputs + the None default must NOT raise (the flip
        keeps pre-staged callers working): the sort resolves OFF and the
        resolved levers say so in the profile."""
        from predictionio_tpu.ops.als import (
            ALSConfig, als_train, bucketize, stage,
        )

        rng = np.random.default_rng(7)
        u = rng.integers(0, 50, 500).astype(np.int32)
        i = rng.integers(0, 30, 500).astype(np.int32)
        v = np.ones(500, dtype=np.float32)
        bu = stage(bucketize(u, i, v, 50, 30, pad_to_blocks=True))
        bi = stage(bucketize(i, u, v, 30, 50, pad_to_blocks=True))
        profile: dict = {}
        factors = als_train(
            bu, bi, ALSConfig(rank=4, iterations=1), profile=profile,
        )
        assert np.isfinite(np.asarray(factors.user_factors)).all()
        assert profile["sort_gather"] is False
        assert profile["fused_gather"] is False  # chunked on CPU
        assert profile["gather_dtype"] == "f32"


class TestGatherDtype:
    """bf16 gathers must track the f32 result closely (input rounding at
    2^-8 relative; the λ·n_u ridge keeps solves stable) and fail loudly on
    unknown dtypes. Rides the shared sweep cache (tier-1 budget): the
    f32 leg IS TestSolveModes' chunked baseline, so only the bf16 legs
    train."""

    @pytest.mark.parametrize("implicit", [False, True])
    def test_bf16_tracks_f32(self, implicit):
        f32 = sweep_factors("chunked", implicit=implicit)
        bf16 = sweep_factors("chunked", implicit=implicit, gather="bf16")
        rel = np.linalg.norm(f32[0] - bf16[0]) / np.linalg.norm(f32[0])
        assert np.isfinite(bf16[0]).all()
        assert rel < 0.05, rel  # tracks, within reduced-precision drift

    def test_bf16_rmse_within_bench_gate(self):
        """The bench's bf16 RMSE gate (docs/performance.md#levers) holds
        at test scale too: reduced-precision gathers move training RMSE
        by far less than the documented 0.01 bound."""
        from predictionio_tpu.ops.als import ALSFactors, rmse

        u, i, v, _, _ = _sweep_data()
        f32 = sweep_factors("chunked")
        bf16 = sweep_factors("chunked", gather="bf16")
        r_f32 = rmse(ALSFactors(*f32, rank=12), u, i, v)
        r_bf16 = rmse(ALSFactors(*bf16, rank=12), u, i, v)
        assert abs(r_f32 - r_bf16) <= 0.01, (r_f32, r_bf16)

    def test_unknown_dtype_fails_loudly(self):
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo

        cfg = ALSConfig(rank=4, iterations=1, gather_dtype="f16")
        with pytest.raises(ValueError, match="gather_dtype"):
            als_train_coo(
                np.array([0], dtype=np.int32), np.array([0], dtype=np.int32),
                np.ones(1, dtype=np.float32), n_users=1, n_items=1, cfg=cfg,
            )


class TestFusedGather:
    """fused_gather=True (the fused gather+Gramian Pallas kernel) must
    reproduce the einsum-built pallas solve — same buckets, same solver,
    only the normal-equation build differs. Reads the shared sweep
    cache: the fused=False leg IS TestSolveModes' pallas run."""

    @pytest.mark.parametrize("implicit", [False, True])
    def test_fused_matches_einsum_build(self, implicit):
        einsum = sweep_factors("pallas", implicit=implicit, fused=False)
        fused = sweep_factors("pallas", implicit=implicit, fused=True)
        np.testing.assert_allclose(
            einsum[0], fused[0], rtol=2e-3, atol=2e-4
        )
        np.testing.assert_allclose(
            einsum[1], fused[1], rtol=2e-3, atol=2e-4
        )

    def test_fused_on_mesh_matches_single_device(self):
        """Under a data mesh the whole fused build+solve runs per-device
        inside shard_map; factors must match the unmeshed fused run."""
        single = sweep_factors("pallas", fused=True)
        meshed = sweep_factors("pallas", fused=True, meshed=True)
        np.testing.assert_allclose(
            single[0], meshed[0], rtol=2e-3, atol=2e-4
        )

    def test_fused_requires_pallas_solver(self):
        from predictionio_tpu.ops.als import ALSConfig, als_train_coo

        cfg = ALSConfig(rank=8, iterations=1, solve_mode="chunked",
                        fused_gather=True)
        # silently ignoring the flag would corrupt the hardware A/B
        with pytest.raises(ValueError, match="fused_gather"):
            als_train_coo(
                np.array([0, 1], dtype=np.int32),
                np.array([0, 1], dtype=np.int32),
                np.ones(2, dtype=np.float32),
                n_users=2, n_items=2, cfg=cfg,
            )


class TestLeverDefaults:
    """The round-12 default flip, pinned WITHOUT training anything:
    ``resolve_levers`` is the one home for the tri-state resolution the
    trainer, the bench and the ledger all read."""

    def test_defaults_resolve_fast_paths_on(self):
        from predictionio_tpu.ops.als import ALSConfig

        levers = ALSConfig().resolve_levers()
        # CPU test host: auto solve resolves chunked, so fused follows
        # it off — but sort is host-side and unconditional for
        # bucketized inputs
        assert levers["sort_gather"] is True
        assert levers["solve_mode"] == "chunked"
        assert levers["fused_gather"] is False
        assert levers["gather_dtype"] == "f32"

    def test_pallas_solver_resolves_fused_on(self):
        from predictionio_tpu.ops.als import ALSConfig

        levers = ALSConfig(solve_mode="pallas").resolve_levers()
        assert levers["fused_gather"] is True
        # ...and the explicit opt-out wins over the default
        opted = ALSConfig(
            solve_mode="pallas", fused_gather=False
        ).resolve_levers()
        assert opted["fused_gather"] is False

    def test_staged_inputs_resolve_sort_off(self):
        from predictionio_tpu.ops.als import ALSConfig

        assert (
            ALSConfig().resolve_levers(staged_inputs=True)["sort_gather"]
            is False
        )

    def test_explicit_opt_outs(self):
        from predictionio_tpu.ops.als import ALSConfig

        levers = ALSConfig(
            sort_gather_indices=False, fused_gather=False
        ).resolve_levers()
        assert levers["sort_gather"] is False
        assert levers["fused_gather"] is False


class TestAllocBlock:
    """Right-sized bucket allocation (round 12): blocks cap at the
    device bound but shrink to the bucket's pow2 row envelope — sentinel
    padding rows cost real solve FLOPs (74–99% of them at the bench's
    CPU-fallback scale before the fix)."""

    def test_alloc_block_arithmetic(self):
        from predictionio_tpu.ops.als import _alloc_block

        assert _alloc_block(32768, 1) == 8  # sublane floor
        assert _alloc_block(32768, 16) == 16
        assert _alloc_block(8192, 7) == 8
        assert _alloc_block(128, 1051) == 2048  # pow2 envelope
        assert _alloc_block(32, 10_000) == 8192  # device bound caps
        assert _alloc_block(512, 1024) == 1024

    def test_bucketize_allocates_right_sized_blocks(self):
        from predictionio_tpu.ops.als import _alloc_block, bucketize

        rng = np.random.default_rng(3)
        nnz, n_u, n_i = 8000, 400, 150
        u = rng.integers(0, n_u, nnz).astype(np.int32)
        i = rng.integers(0, n_i, nnz).astype(np.int32)
        v = np.ones(nnz, dtype=np.float32)
        side = bucketize(u, i, v, n_u, n_i, pad_to_blocks=True)
        for b in side.buckets:
            real = int((b.counts > 0).sum())
            block = _alloc_block(b.width, real)
            assert b.rows.shape[0] == -(-real // block) * block
            # the pow2 envelope bounds waste: less than one block spare
            assert b.rows.shape[0] - real < block

    def test_stage_keeps_right_sized_chunks(self):
        """stage() must not re-pad a right-sized bucket back up to a
        full device block (that would undo the allocation win)."""
        from predictionio_tpu.ops.als import bucketize, stage

        rng = np.random.default_rng(4)
        u = rng.integers(0, 100, 3000).astype(np.int32)
        i = rng.integers(0, 60, 3000).astype(np.int32)
        v = np.ones(3000, dtype=np.float32)
        side = bucketize(u, i, v, 100, 60, pad_to_blocks=True)
        staged = stage(side)
        for b, s in zip(side.buckets, staged.buckets):
            assert int(np.prod(s.rows.shape)) == b.rows.shape[0]


class TestHbmBytesModel:
    """The roofline bytes accounting (``pio profile --train-smoke`` /
    bench est_hbm_*), pinned on hand-computed arithmetic so the model
    cannot silently drift from the kernels it describes."""

    @staticmethod
    def _staged(rows, width, idx_dtype=np.int32):
        from predictionio_tpu.ops.als import StagedMatrix, _StagedBucket

        bucket = _StagedBucket(
            rows=np.zeros((1, rows), np.int32),
            idx=np.zeros((1, rows, width), idx_dtype),
            val=np.zeros((1, rows, width), np.float32),
            counts=np.zeros((1, rows), np.int32),
        )
        return StagedMatrix(n_rows=rows, n_cols=64, nnz=rows * width,
                            buckets=[bucket])

    def test_einsum_path_counts_gather_at_dtype_width(self):
        from predictionio_tpu.ops.als import estimate_iteration_hbm_bytes

        side = self._staged(rows=4, width=16)
        empty = self._staged(rows=0, width=8)
        rank = 8
        # per row: gather 16·8·elt, idx+val 16·(4+4), counts 4, out 8·4
        f32 = estimate_iteration_hbm_bytes(side, empty, rank, "f32")
        assert f32 == 4 * (16 * 8 * 4 + 16 * 8 + 4 + 32)
        bf16 = estimate_iteration_hbm_bytes(side, empty, rank, "bf16")
        assert bf16 == 4 * (16 * 8 * 2 + 16 * 8 + 4 + 32)

    def test_fused_path_counts_lane_padded_f32_rows(self):
        """The fused kernel DMAs whole 128-lane f32 rows (bf16 upcasts
        at entry), so its gather bytes are dtype-INDEPENDENT and the
        [B, R, R] transpose round trip is charged."""
        from predictionio_tpu.ops.als import estimate_iteration_hbm_bytes

        side = self._staged(rows=4, width=16)
        empty = self._staged(rows=0, width=8)
        rank = 8
        expect = 4 * (
            16 * 128 * 4  # per-rating lane-padded row DMA
            + 16 * 8  # idx + val
            + 4  # counts
            + 3 * 8 * 8 * 4  # A write + transposed round trip
            + 2 * 8 * 4  # rhs + solution
        )
        for dtype in ("f32", "bf16"):
            got = estimate_iteration_hbm_bytes(
                side, empty, rank, dtype, fused_gather=True
            )
            assert got == expect, (dtype, got, expect)

    def test_fused_gate_spares_narrow_buckets(self):
        """Buckets narrower than the rank keep the einsum build (the
        _solve_side_traced auto-gate) and must be charged accordingly."""
        from predictionio_tpu.ops.als import estimate_iteration_hbm_bytes

        narrow = self._staged(rows=4, width=4)  # width < rank
        empty = self._staged(rows=0, width=8)
        rank = 8
        fused = estimate_iteration_hbm_bytes(
            narrow, empty, rank, "f32", fused_gather=True
        )
        plain = estimate_iteration_hbm_bytes(narrow, empty, rank, "f32")
        assert fused == plain

    def test_topk_bytes_model(self):
        """Serve-side companion: streaming removes BOTH score-matrix
        trips; everything else is identical."""
        from predictionio_tpu.ops.scoring import estimate_topk_hbm_bytes

        b, n, r, k = 8, 1000, 8, 10
        factors = b * r * 4 + n * r * 4
        results = b * k * 8
        dense = estimate_topk_hbm_bytes(b, n, r, k, streaming=False)
        stream = estimate_topk_hbm_bytes(b, n, r, k, streaming=True)
        assert dense == factors + 2 * b * n * 4 + results
        assert stream == factors + results
        assert dense - stream == 2 * b * n * 4


class TestFusedTopK:
    """The serve-side fused score+select entries must reproduce the
    dense kernels exactly — same items, same order, scores to f32
    reassociation tolerance — on BOTH dispatch legs: the XLA fallback
    ("never"/off-TPU) and the Pallas streaming kernel ("always",
    interpret mode on CPU). The score contract is the fleet merge's
    ``merged_matches_reference`` (one home, fleet/merge.py)."""

    @staticmethod
    def _item_scores(scores, idx):
        return [
            {"item": str(int(i)), "score": float(s)}
            for s, i in zip(np.asarray(scores), np.asarray(idx))
            if i >= 0
        ]

    def _assert_matches(self, got, want):
        from predictionio_tpu.fleet.merge import merged_matches_reference

        got_s, got_i = got
        want_s, want_i = want
        for row in range(np.asarray(want_i).shape[0]):
            assert merged_matches_reference(
                {"itemScores": self._item_scores(got_s[row], got_i[row])},
                {"itemScores": self._item_scores(want_s[row], want_i[row])},
            ), (row, got_i[row], want_i[row])

    def test_users_fused_matches_dense(self):
        from predictionio_tpu.ops.scoring import (
            top_k_for_users, top_k_for_users_fused,
        )

        rng = np.random.default_rng(2)
        uf = rng.normal(size=(12, 8)).astype(np.float32)
        itf = rng.normal(size=(64, 8)).astype(np.float32)
        users = np.array([1, 4, 9, 11], dtype=np.int32)
        want = top_k_for_users(uf, itf, users, k=8)
        for mode in ("never", "always"):
            got = top_k_for_users_fused(uf, itf, users, k=8, mode=mode)
            # ranking exact — same items, same order
            np.testing.assert_array_equal(
                np.asarray(got[1]), np.asarray(want[1]), err_msg=mode
            )
            self._assert_matches(got, want)

    def test_similar_items_fused_matches_dense(self):
        from predictionio_tpu.ops.scoring import (
            top_k_similar_items, top_k_similar_items_fused,
        )

        rng = np.random.default_rng(5)
        itf = rng.normal(size=(40, 8)).astype(np.float32)
        queries = np.array([3, 17, 25], dtype=np.int32)
        want = top_k_similar_items(itf, queries, k=6)
        for mode in ("never", "always"):
            got = top_k_similar_items_fused(itf, queries, k=6, mode=mode)
            np.testing.assert_array_equal(
                np.asarray(got[1]), np.asarray(want[1]), err_msg=mode
            )
            self._assert_matches(got, want)
            # self-exclusion holds on both legs
            for row, q in enumerate(queries):
                assert int(q) not in np.asarray(got[1])[row].tolist()

    def test_sentinel_contract_past_catalog(self):
        """k beyond the catalog: sub-k slots are (-inf, -1) on BOTH
        legs — callers must never index with the sentinel."""
        from predictionio_tpu.ops.scoring import top_k_fused_vectors

        q = np.eye(2, 4, dtype=np.float32)
        itf = np.eye(3, 4, dtype=np.float32)
        for mode in ("never", "always"):
            scores, idx = top_k_fused_vectors(q, itf, k=5, mode=mode)
            assert np.asarray(idx).shape == (2, 5)
            assert (np.asarray(idx)[:, 3:] == -1).all(), mode
            assert np.isneginf(np.asarray(scores)[:, 3:]).all(), mode
