"""Streaming top-k serving kernel vs. the XLA reference path.

Runs the Pallas kernel in interpret mode on CPU (auto-selected) and checks
exact agreement with ``jax.lax.top_k`` over the materialized score matrix.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from predictionio_tpu.ops.pallas_kernels import (
    top_k_for_users_streaming,
    top_k_streaming,
)
from predictionio_tpu.ops.scoring import top_k_for_vectors


def _ref_topk(q, items, k, exclude_idx=None):
    scores = q @ items.T
    if exclude_idx is not None:
        for b in range(scores.shape[0]):
            for e in exclude_idx[b]:
                if e >= 0:
                    scores[b, e] = -np.inf
    idx = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, idx, axis=1), idx


@pytest.mark.parametrize("b,n,r,k", [(4, 100, 16, 5), (8, 1030, 50, 10), (3, 7, 4, 3)])
def test_matches_reference(b, n, r, k):
    rng = np.random.default_rng(0)
    q = rng.normal(size=(b, r)).astype(np.float32)
    items = rng.normal(size=(n, r)).astype(np.float32)
    got_s, got_i = top_k_streaming(q, items, k, block_items=256)
    ref_s, ref_i = _ref_topk(q, items, k)
    np.testing.assert_allclose(np.asarray(got_s), ref_s, rtol=1e-5, atol=1e-5)
    # indices can differ only on exact ties; scores already checked exactly
    same = np.asarray(got_i) == ref_i
    tied = np.isclose(np.asarray(got_s), ref_s)
    assert (same | tied).all()


def test_exclusion_lists():
    rng = np.random.default_rng(1)
    b, n, r, k = 4, 64, 8, 6
    q = rng.normal(size=(b, r)).astype(np.float32)
    items = rng.normal(size=(n, r)).astype(np.float32)
    # exclude the unfiltered top-2 of each row, padded with -1
    s0, i0 = top_k_streaming(q, items, 2)
    excl = np.concatenate(
        [np.asarray(i0), np.full((b, 3), -1, np.int32)], axis=1
    ).astype(np.int32)
    got_s, got_i = top_k_streaming(q, items, k, exclude_idx=jnp.asarray(excl))
    for row in range(b):
        assert not set(np.asarray(got_i)[row]).intersection(set(np.asarray(i0)[row]))
    ref_s, ref_i = _ref_topk(q, items, k, excl)
    np.testing.assert_allclose(np.asarray(got_s), ref_s, rtol=1e-5, atol=1e-5)


def test_k_larger_than_catalog():
    rng = np.random.default_rng(2)
    q = rng.normal(size=(2, 4)).astype(np.float32)
    items = rng.normal(size=(3, 4)).astype(np.float32)
    s, i = top_k_streaming(q, items, 8)
    assert s.shape == (2, 8) and i.shape == (2, 8)
    assert np.isneginf(np.asarray(s)[:, 3:]).all()
    assert (np.asarray(i)[:, 3:] == -1).all()


def test_user_gather_wrapper_agrees_with_xla_path():
    rng = np.random.default_rng(3)
    uf = rng.normal(size=(20, 12)).astype(np.float32)
    itf = rng.normal(size=(200, 12)).astype(np.float32)
    uidx = np.array([3, 17, 5], dtype=np.int32)
    s1, i1 = top_k_for_users_streaming(uf, itf, uidx, 7, block_items=128)
    s2, i2 = top_k_for_vectors(uf[uidx], itf, 7)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
    assert (np.asarray(i1) == np.asarray(i2)).all() or np.allclose(
        np.asarray(s1), np.asarray(s2)
    )


def test_fallback_path_contract(monkeypatch):
    """The no-pallas fallback must honor exclusions and k > catalog."""
    import predictionio_tpu.ops.pallas_kernels as pk

    monkeypatch.setattr(pk, "_HAVE_PALLAS", False)
    rng = np.random.default_rng(4)
    q = rng.normal(size=(3, 8)).astype(np.float32)
    items = rng.normal(size=(20, 8)).astype(np.float32)
    s0, i0 = pk.top_k_streaming(q, items, 2)
    excl = np.concatenate(
        [np.asarray(i0), np.full((3, 2), -1, np.int32)], axis=1
    ).astype(np.int32)
    s, i = pk.top_k_streaming(q, items, 5, exclude_idx=jnp.asarray(excl))
    for row in range(3):
        assert not set(np.asarray(i)[row]).intersection(set(np.asarray(i0)[row]))
    s2, i2 = pk.top_k_streaming(q, items, 25)
    assert s2.shape == (3, 25)
    assert np.isneginf(np.asarray(s2)[:, 20:]).all()


def test_fallback_sentinel_matches_kernel_when_exclusions_exhaust_catalog(
    monkeypatch,
):
    """Both paths must return -1 (never a real excluded id) in -inf slots —
    the divergence flagged in round-1 ADVICE: a caller gathering by index
    would map a real-but-excluded id to a live item."""
    import predictionio_tpu.ops.pallas_kernels as pk

    rng = np.random.default_rng(6)
    q = rng.normal(size=(2, 4)).astype(np.float32)
    items = rng.normal(size=(5, 4)).astype(np.float32)
    # exclude ALL 5 items: fewer than k=3 valid candidates remain
    excl = np.tile(np.arange(5, dtype=np.int32), (2, 1))

    s_k, i_k = pk.top_k_streaming(q, items, 3, exclude_idx=jnp.asarray(excl))
    monkeypatch.setattr(pk, "_HAVE_PALLAS", False)
    s_f, i_f = pk.top_k_streaming(q, items, 3, exclude_idx=jnp.asarray(excl))

    for s, i in ((s_k, i_k), (s_f, i_f)):
        assert np.isneginf(np.asarray(s)).all()
        assert (np.asarray(i) == -1).all()


def test_wide_exclusion_list():
    """Exclusion lists wider than the kernel chunk (fori_loop path)."""
    rng = np.random.default_rng(5)
    b, n, r = 2, 300, 8
    q = rng.normal(size=(b, r)).astype(np.float32)
    items = rng.normal(size=(n, r)).astype(np.float32)
    # exclude the top 40 of each row (several 16-wide chunks + padding)
    _, i0 = top_k_streaming(q, items, 40, block_items=128)
    s, i = top_k_streaming(
        q, items, 10, exclude_idx=np.asarray(i0, np.int32), block_items=128
    )
    for row in range(b):
        assert not set(np.asarray(i)[row]).intersection(set(np.asarray(i0)[row]))


# ---------------------------------------------------------------------------
# spd_solve_t — fused batched Cholesky solve
# ---------------------------------------------------------------------------
class TestSpdSolve:
    def _systems(self, bsz, r, k, seed=0, lam=0.05):
        rng = np.random.default_rng(seed)
        g = rng.standard_normal((bsz, k, r)).astype(np.float32)
        a = np.einsum("bkr,bks->brs", g, g) + lam * k * np.eye(
            r, dtype=np.float32
        )
        b = rng.standard_normal((bsz, r)).astype(np.float32)
        return a, b

    def _to_t(self, a, b, n):
        bsz, r = b.shape
        a_t = np.zeros((n, n, bsz), np.float32)
        a_t[:r, :r] = np.transpose(a, (1, 2, 0))
        b_t = np.zeros((n, bsz), np.float32)
        b_t[:r] = b.T
        return jnp.asarray(a_t), jnp.asarray(b_t)

    @pytest.mark.parametrize("r,n", [(4, 8), (50, 56), (13, 16)])
    def test_matches_cho_solve(self, r, n):
        from predictionio_tpu.ops.pallas_kernels import spd_solve_t

        bsz = 128
        a, b = self._systems(bsz, r, k=32)
        ref = np.linalg.solve(a, b[..., None])[..., 0]
        a_t, b_t = self._to_t(a, b, n)
        x = np.asarray(spd_solve_t(a_t, b_t))[:r].T
        rel = np.linalg.norm(x - ref, axis=-1) / (
            np.linalg.norm(ref, axis=-1) + 1e-9
        )
        assert np.max(rel) < 1e-4

    def test_zero_padded_systems_solve_to_zero(self):
        """Bucket-padding rows are all-zero systems; the inv_d guard must
        produce exact zeros (NaNs would poison the factor scatter)."""
        from predictionio_tpu.ops.pallas_kernels import spd_solve_t

        bsz, r, n = 128, 8, 8
        a, b = self._systems(64, r, k=16)
        a_t, b_t = self._to_t(a, b, n)
        a_t = jnp.pad(a_t, ((0, 0), (0, 0), (0, bsz - 64)))
        b_t = jnp.pad(b_t, ((0, 0), (0, bsz - 64)), constant_values=1.0)
        x = np.asarray(spd_solve_t(a_t, b_t))
        assert np.all(np.isfinite(x))
        np.testing.assert_array_equal(x[:, 64:], 0.0)
        ref = np.linalg.solve(a, b[..., None])[..., 0]
        np.testing.assert_allclose(x[:r, :64].T, ref, rtol=1e-3, atol=1e-4)

    def test_shape_validation(self):
        from predictionio_tpu.ops.pallas_kernels import spd_solve_t

        with pytest.raises(ValueError, match="spd_solve_t"):
            spd_solve_t(jnp.zeros((7, 7, 128)), jnp.zeros((7, 128)))
        with pytest.raises(ValueError, match="spd_solve_t"):
            spd_solve_t(jnp.zeros((8, 8, 100)), jnp.zeros((8, 100)))


# ---------------------------------------------------------------------------
# gramian_fused — fused gather + normal-equation build
# ---------------------------------------------------------------------------
class TestGramianFused:
    """Interpret-mode equality vs the einsum reference at multiple shapes
    and ranks, including non-multiple-of-block edges (the wrapper pads B
    and K; R must be pre-padded to 8s by the caller, as the ALS solver
    path does)."""

    def _ref(self, y, idx, w2, rhs, ridge, yty=None):
        y = np.asarray(y, np.float32)
        g = y[np.asarray(idx)]
        a = np.einsum("bkr,bk,bks->brs", g, w2, g)
        r = y.shape[1]
        a += ridge[:, None, None] * np.eye(r, dtype=np.float32)
        if yty is not None:
            a += np.asarray(yty)[None]
        b = np.einsum("bkr,bk->br", g, rhs)
        return a, b

    def _data(self, b, k, n, r, seed=0, frac_valid=0.7):
        rng = np.random.default_rng(seed)
        y = rng.standard_normal((n, r), dtype=np.float32)
        idx = rng.integers(0, n, (b, k)).astype(np.int32)
        w2 = (rng.random((b, k)) < frac_valid).astype(np.float32)
        rhs = rng.standard_normal((b, k)).astype(np.float32) * w2
        ridge = rng.random(b).astype(np.float32)
        return y, idx, w2, rhs, ridge

    @pytest.mark.parametrize(
        "b,k,n,r",
        [
            (32, 16, 500, 56),   # typical narrow bucket
            (16, 512, 300, 56),  # one full K tile
            (8, 1024, 200, 24),  # K tiling (2 tiles), small rank
            (25, 13, 77, 16),    # non-multiple B and K (wrapper pads)
            (3, 600, 50, 8),     # B < tile, K pads to 1024
        ],
    )
    def test_matches_einsum(self, b, k, n, r):
        from predictionio_tpu.ops.pallas_kernels import gramian_fused

        y, idx, w2, rhs, ridge = self._data(b, k, n, r)
        a, bv = gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                              jnp.asarray(w2), jnp.asarray(rhs),
                              jnp.asarray(ridge))
        a_ref, b_ref = self._ref(y, idx, w2, rhs, ridge)
        np.testing.assert_allclose(np.asarray(a), a_ref, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(bv), b_ref, rtol=1e-4,
                                   atol=1e-4)

    def test_yty_base(self):
        """Implicit mode seeds every system with YtY."""
        from predictionio_tpu.ops.pallas_kernels import gramian_fused

        y, idx, w2, rhs, ridge = self._data(8, 32, 100, 16, seed=3)
        yty = (y.T @ y).astype(np.float32)
        a, bv = gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                              jnp.asarray(w2), jnp.asarray(rhs),
                              jnp.asarray(ridge), jnp.asarray(yty))
        a_ref, b_ref = self._ref(y, idx, w2, rhs, ridge, yty)
        np.testing.assert_allclose(np.asarray(a), a_ref, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(bv), b_ref, rtol=1e-4,
                                   atol=1e-4)

    def test_bf16_gathers(self):
        """bf16 factor table: the kernel upcasts it to f32 at entry —
        the per-row DMA floor is 128 lanes × 32 bits, so bf16 cannot
        reduce the fused path's gathered bytes (deviceless-AOT finding;
        see gramian_fused). Result must match the f32 reference computed
        from the bf16-quantized table exactly up to accumulation order."""
        from predictionio_tpu.ops.pallas_kernels import gramian_fused

        y, idx, w2, rhs, ridge = self._data(16, 64, 200, 24, seed=4)
        y_bf = jnp.asarray(y, jnp.bfloat16)
        a, bv = gramian_fused(y_bf, jnp.asarray(idx), jnp.asarray(w2),
                              jnp.asarray(rhs), jnp.asarray(ridge))
        # reference: bf16 quantization applies to the table ONLY; w2/rhs
        # stay f32 (the kernel upcasts, so g.dtype is f32)
        y_r = np.asarray(y_bf, np.float32)
        a_ref = np.einsum("bkr,bk,bks->brs", y_r[idx], w2, y_r[idx])
        a_ref += ridge[:, None, None] * np.eye(y.shape[1], dtype=np.float32)
        b_ref = np.einsum("bkr,bk->br", y_r[idx], rhs)
        assert np.asarray(a).dtype == np.float32
        np.testing.assert_allclose(np.asarray(a), a_ref, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(bv), b_ref, rtol=1e-4,
                                   atol=1e-4)

    def test_zero_weight_rows_give_ridge_only(self):
        """Bucket-padding rows (all weights 0, ridge 0) must produce an
        exactly-zero system — the SPD kernel's zero→zero contract depends
        on it; index padding must never leak gathered values."""
        from predictionio_tpu.ops.pallas_kernels import gramian_fused

        y, idx, w2, rhs, ridge = self._data(8, 16, 50, 8, seed=5)
        w2[4:] = 0.0
        rhs[4:] = 0.0
        ridge[4:] = 0.0
        a, bv = gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                              jnp.asarray(w2), jnp.asarray(rhs),
                              jnp.asarray(ridge))
        np.testing.assert_array_equal(np.asarray(a)[4:], 0.0)
        np.testing.assert_array_equal(np.asarray(bv)[4:], 0.0)

    def test_rank_validation(self):
        from predictionio_tpu.ops.pallas_kernels import gramian_fused

        with pytest.raises(ValueError, match="rank"):
            gramian_fused(jnp.zeros((10, 7)), jnp.zeros((4, 4), jnp.int32),
                          jnp.zeros((4, 4)), jnp.zeros((4, 4)),
                          jnp.zeros((4,)))

    def test_wide_k_split_matches_einsum(self, monkeypatch):
        """K wider than the per-call SMEM bound splits into slices summed
        in XLA (base terms counted once) — forced small here so the test
        exercises 3 slices without a 32k-wide problem."""
        import predictionio_tpu.ops.pallas_kernels as pk

        monkeypatch.setattr(pk, "_FUSED_K_SPLIT", 32)
        y, idx, w2, rhs, ridge = self._data(6, 80, 60, 16, seed=6)
        yty = (y.T @ y).astype(np.float32)
        a, bv = pk.gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                                 jnp.asarray(w2), jnp.asarray(rhs),
                                 jnp.asarray(ridge), jnp.asarray(yty))
        a_ref, b_ref = self._ref(y, idx, w2, rhs, ridge, yty)
        np.testing.assert_allclose(np.asarray(a), a_ref, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(bv), b_ref, rtol=1e-4,
                                   atol=1e-4)
