"""Rollout plane tests (``predictionio_tpu/rollout``, docs/rollouts.md).

Covers the ISSUE-5 acceptance contract end to end, on injected clocks
with zero wall-clock sleeps on the decision paths:

- deterministic sticky splits (pure function; stable across process
  restarts and across the HA metadata read-failover path);
- gate evaluation (error-rate delta, p99 ratio, shadow divergence,
  hold timers) on a fake clock;
- the durable ``RolloutPlan`` DAO + replication through the changefeed;
- the full state machine: shadow → canary(10%) → live when gates pass,
  auto-rollback from canary when the candidate fails (zero
  client-visible failures), terminal state durable across a server
  restart, rolled-back candidates quarantined from implicit redeploy;
- deployment teardown: retired deployments drop their model references
  (no resident-model leak across swaps);
- the serving surface: POST /reload (GET kept, deprecated), /rollout
  routes, variant-tagged feedback events, the dashboard /rollouts page,
  and the loadgen --rollout chaos scenario.
"""

import gc
import json
import weakref

import pytest
import requests

from predictionio_tpu.controller import WorkflowParams
from predictionio_tpu.rollout.controller import RolloutController
from predictionio_tpu.rollout.plan import (
    BASELINE,
    CANDIDATE,
    GateConfig,
    prediction_divergence,
    sticky_key,
    variant_for_key,
)
from predictionio_tpu.storage import (
    MetadataStore,
    RolloutPlan,
    SqliteEventStore,
    StorageRegistry,
    utcnow,
)
from predictionio_tpu.storage.changefeed import Changefeed, apply_op
from predictionio_tpu.storage.metadata import (
    ROLLOUT_CANARY,
    ROLLOUT_LIVE,
    ROLLOUT_ROLLED_BACK,
    ROLLOUT_SHADOW,
)
from predictionio_tpu.storage.model_store import SqliteModelStore
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.testing import faults
from predictionio_tpu.workflow.core_workflow import run_train
from predictionio_tpu.workflow.serving import QueryServer, ServerConfig

from sample_engine import reset_all_counts
from test_engine import make_engine, make_params


class FakeClock:
    """Injectable monotonic clock."""

    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()
    faults.deactivate()
    yield
    faults.deactivate()


@pytest.fixture()
def registry(tmp_path):
    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})


def _train(registry, engine, algo_id=11):
    return run_train(
        engine,
        make_params(algo_ids=(algo_id,)),
        registry,
        engine_id="default",
        engine_version="1",
        workflow_params=WorkflowParams(batch="rollout-test"),
    )


def _server(registry, engine, clock, instance_id=None, **config_kw):
    return QueryServer(
        ServerConfig(
            ip="127.0.0.1",
            port=0,
            batching=False,
            engine_instance_id=instance_id,
            **config_kw,
        ),
        engine,
        registry,
        clock=clock,
    )


#: Tight gates that converge in a handful of queries. The latency gate
#: is effectively disabled: these e2e tests record REAL wall-clock
#: latencies into tiny windows, and scheduler jitter on a loaded test
#: host can push one variant's p99 past any honest ratio — the gate's
#: logic is pinned deterministically in TestRolloutController instead.
def _gates(**overrides):
    g = {
        "min_samples": 5,
        "window_s": 100_000.0,
        "shadow_hold_s": 10.0,
        "canary_hold_s": 10.0,
        "max_divergence": 1.0,
        "max_p99_latency_ratio": 1_000.0,
    }
    g.update(overrides)
    return g


# ---------------------------------------------------------------------------
# sticky split + divergence (pure functions)
# ---------------------------------------------------------------------------


class TestShadowPoolConcurrency:
    """Regression tests for the ISSUE-6 ``conc-*`` sweep findings in the
    rollout manager: the shadow-futures deque was appended/popped
    outside the manager lock (concurrent drains could IndexError or
    double-pop), and the scrape-thread gauge callbacks read ``self.plan``
    without it."""

    def _manager(self):
        import time as _time

        from predictionio_tpu.obs.metrics import MetricsRegistry
        from predictionio_tpu.rollout.manager import RolloutManager

        class _Stub:
            pass

        server = _Stub()
        server.clock = _time.monotonic
        server.metrics = MetricsRegistry()
        return RolloutManager(server)

    def test_concurrent_drains_never_double_pop_or_indexerror(self):
        import threading
        from concurrent.futures import Future

        mgr = self._manager()
        try:
            errors = []
            for _round in range(8):
                for _ in range(200):  # deque maxlen is 256: stay under it
                    fut = Future()
                    fut.set_result(None)
                    mgr._shadow_futures.append(fut)

                def drain():
                    try:
                        mgr.drain_shadow(timeout_s=5)
                    except Exception as exc:  # IndexError pre-fix
                        errors.append(exc)

                threads = [
                    threading.Thread(target=drain) for _ in range(4)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30)
                assert not mgr._shadow_futures
            assert errors == [], errors
        finally:
            mgr.close()

    def test_gauge_callbacks_read_under_the_manager_lock(self):
        import threading

        mgr = self._manager()
        try:
            got = []
            mgr._lock.acquire()
            try:
                t = threading.Thread(
                    target=lambda: got.append(
                        (mgr._stage_code(), mgr._live_percent())
                    )
                )
                t.start()
                t.join(timeout=0.05)
                # the scrape-thread callbacks must be blocked on the lock
                assert t.is_alive(), (
                    "gauge callback returned while the manager lock was "
                    "held — it reads rollout state without the lock"
                )
            finally:
                mgr._lock.release()
            t.join(timeout=30)
            assert got == [(0, 0.0)]  # no active plan
        finally:
            mgr.close()


class TestStickySplit:
    def test_deterministic_and_percent_bounded(self):
        keys = [f"user={i}" for i in range(2000)]
        first = {k: variant_for_key("salt-a", k, 10.0) for k in keys}
        second = {k: variant_for_key("salt-a", k, 10.0) for k in keys}
        assert first == second  # pure function: restart-stable for free
        share = sum(1 for v in first.values() if v == CANDIDATE) / len(keys)
        assert 0.05 < share < 0.15  # ~10% of keys

    def test_percent_edges(self):
        assert variant_for_key("s", "k", 0) == BASELINE
        assert variant_for_key("s", "k", 100) == CANDIDATE

    def test_salt_rotates_the_sampled_subset(self):
        keys = [f"user={i}" for i in range(500)]
        a = {k for k in keys if variant_for_key("salt-a", k, 20.0) == CANDIDATE}
        b = {k for k in keys if variant_for_key("salt-b", k, 20.0) == CANDIDATE}
        assert a != b  # consecutive rollouts don't reuse one cohort

    def test_sticky_key_prefers_entity_fields(self):
        assert sticky_key({"user": "7", "num": 10}) == "user=7"
        assert sticky_key({"entityId": 3}) == "entityId=3"
        # no conventional field: canonicalized payload, still deterministic
        assert sticky_key({"z": 1, "a": 2}) == sticky_key({"a": 2, "z": 1})


class TestPlanEpoch:
    """The cache-invalidation epoch (docs/fleet.md#response-cache):
    pure over the plan, and it MUST move for every field that can
    change what a query is answered with."""

    class _Plan:
        def __init__(self, **kw):
            self.id = kw.get("id", "RP-1")
            self.stage = kw.get("stage", "CANARY")
            self.percent = kw.get("percent", 10.0)
            self.salt = kw.get("salt", "s")
            self.baseline_instance_id = kw.get("baseline", "EI-1")
            self.candidate_instance_id = kw.get("candidate", "EI-2")
            self.updated_time = kw.get("updated", "t0")

    def test_deterministic_and_none_is_its_own_epoch(self):
        from predictionio_tpu.rollout.plan import plan_epoch

        assert plan_epoch(None) == "-"
        assert plan_epoch(self._Plan()) == plan_epoch(self._Plan())
        assert plan_epoch(self._Plan()) != "-"

    def test_every_serving_relevant_field_moves_the_epoch(self):
        from predictionio_tpu.rollout.plan import plan_epoch

        base = plan_epoch(self._Plan())
        for change in (
            {"id": "RP-2"},
            {"stage": "SHADOW"},
            {"percent": 50.0},
            {"salt": "other"},
            {"baseline": "EI-9"},
            {"candidate": "EI-9"},
            {"updated": "t1"},
        ):
            assert plan_epoch(self._Plan(**change)) != base, change


class TestBucketGoldenVectors:
    """Exact bucket ids for fixed (salt, key) pairs.

    EVERY fleet-wide sticky assignment — canary splits, the router
    tier's replica affinity (docs/fleet.md) — is downstream of
    ``bucket_for_key``. The property tests above would survive swapping
    the hash for any other stable function; these golden vectors would
    not: a refactor that changes the digest, the byte-slice, the
    separator, or the modulus silently reassigns every user on the next
    deploy. If this test fails, the change is wrong — do not update the
    expected values."""

    # computed once from the shipped implementation:
    # SHA-256(f"{salt}|{key}")[:8] as big-endian uint64, mod 10_000
    GOLDEN = {
        ("fleet-golden", "user=0"): 1188,
        ("fleet-golden", "user=1"): 8857,
        ("fleet-golden", "user=2"): 4115,
        ("fleet-golden", "user=42"): 4945,
        ("fleet-golden", "entityId=abc"): 4878,
        ("fleet-golden", '{"q": 1}'): 5626,
        ("s2", "user=0"): 8615,
        ("s2", "user=1"): 8530,
        ("s2", "user=2"): 8835,
    }

    def test_exact_bucket_assignments(self):
        from predictionio_tpu.rollout.plan import NUM_BUCKETS, bucket_for_key

        assert NUM_BUCKETS == 10_000  # percent resolution is part of the
        # contract: variant thresholds are computed against this modulus
        for (salt, key), expected in self.GOLDEN.items():
            assert bucket_for_key(salt, key) == expected, (salt, key)

    def test_variant_threshold_derives_from_buckets(self):
        """variant_for_key must remain exactly `bucket < percent/100 *
        NUM_BUCKETS` over the golden buckets — the split a restarted or
        failed-over server recomputes from the durable plan."""
        from predictionio_tpu.rollout.plan import bucket_for_key

        for (salt, key), bucket in self.GOLDEN.items():
            assert bucket_for_key(salt, key) == bucket
            for percent in (0.0, 11.88, 11.89, 48.78, 50.0, 100.0):
                expected = (
                    CANDIDATE
                    if 0 < percent
                    and (percent >= 100 or bucket < round(percent * 100))
                    else BASELINE
                )
                assert variant_for_key(salt, key, percent) == expected


class TestDivergence:
    def test_identical_is_zero(self):
        result = {"items": [{"item": "a", "score": 1.5}], "n": 3}
        assert prediction_divergence(result, result) == 0.0

    def test_disjoint_is_one(self):
        assert prediction_divergence({"a": 1}, {"b": 2}) == 1.0

    def test_numeric_relative_distance(self):
        d = prediction_divergence({"score": 1.0}, {"score": 3.0})
        assert d == pytest.approx(2.0 / 4.0)

    def test_rank_shift_counts(self):
        a = {"items": ["x", "y"]}
        b = {"items": ["y", "x"]}
        assert prediction_divergence(a, b) == 1.0
        assert prediction_divergence(a, a) == 0.0


# ---------------------------------------------------------------------------
# gate controller (injected clock)
# ---------------------------------------------------------------------------


class TestRolloutController:
    def _ctl(self, clock, **gates):
        return RolloutController(GateConfig.from_dict(_gates(**gates)), clock)

    def test_holds_until_samples_then_hold_timer(self):
        clock = FakeClock()
        ctl = self._ctl(clock)
        verdict, reason = ctl.evaluate(ROLLOUT_SHADOW)
        assert verdict == "hold" and "samples" in reason
        for _ in range(5):
            ctl.record(True, 0.01, ok=True)
            ctl.record(False, 0.01, ok=True)
        verdict, reason = ctl.evaluate(ROLLOUT_SHADOW)
        assert verdict == "hold" and "holding" in reason
        clock.advance(11)
        verdict, _ = ctl.evaluate(ROLLOUT_SHADOW)
        assert verdict == "promote"

    def test_error_gate_rolls_back_before_hold_elapses(self):
        ctl = self._ctl(FakeClock())
        for _ in range(10):
            ctl.record(False, 0.01, ok=True)
            ctl.record(True, 0.01, ok=False)  # candidate hard-failing
        verdict, reason = ctl.evaluate(ROLLOUT_CANARY)
        assert verdict == "rollback" and "error-rate" in reason

    def test_latency_gate(self):
        clock = FakeClock()
        ctl = self._ctl(clock, max_p99_latency_ratio=2.0)
        for _ in range(20):
            ctl.record(False, 0.010, ok=True)
            ctl.record(True, 0.100, ok=True)  # 10x the baseline p99
        verdict, reason = ctl.evaluate(ROLLOUT_CANARY)
        assert verdict == "rollback" and "p99" in reason

    def test_divergence_gate_shadow_only(self):
        clock = FakeClock()
        ctl = self._ctl(clock, max_divergence=0.25)
        for _ in range(10):
            ctl.record(False, 0.01, ok=True)
            ctl.record(True, 0.01, ok=True)
            ctl.record_divergence(0.9)
        verdict, reason = ctl.evaluate(ROLLOUT_SHADOW)
        assert verdict == "rollback" and "divergence" in reason
        # the same windows in CANARY: divergence no longer gates
        clock.advance(11)
        verdict, _ = ctl.evaluate(ROLLOUT_CANARY)
        assert verdict == "promote"

    def test_window_expires_old_samples(self):
        clock = FakeClock()
        ctl = self._ctl(clock, window_s=60.0)
        for _ in range(10):
            ctl.record(True, 0.01, ok=False)
        assert ctl.candidate.count() == 10
        clock.advance(61)
        assert ctl.candidate.count() == 0

    def test_gate_config_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown gate option"):
            GateConfig.from_dict({"max_errors": 1})


# ---------------------------------------------------------------------------
# durable plan DAO + changefeed replication
# ---------------------------------------------------------------------------


def _plan(**kw):
    now = utcnow()
    defaults = dict(
        id="",
        stage=ROLLOUT_SHADOW,
        engine_id="default",
        engine_version="1",
        engine_variant="engine.json",
        baseline_instance_id="EI-base",
        candidate_instance_id="EI-cand",
        percent=10.0,
        salt="abc123",
        created_time=now,
        updated_time=now,
        gates={"min_samples": 5.0},
        history=[{"stage": ROLLOUT_SHADOW, "atMs": 1, "reason": "start"}],
    )
    defaults.update(kw)
    return RolloutPlan(**defaults)


class TestRolloutPlanDAO:
    def test_roundtrip_and_active_selection(self, metadata_store):
        md = metadata_store
        pid = md.rollout_plan_upsert(_plan())
        assert pid.startswith("RO-")
        got = md.rollout_plan_get(pid)
        assert got.salt == "abc123"
        assert got.gates == {"min_samples": 5.0}
        assert got.history[0]["reason"] == "start"
        active = md.rollout_plan_get_active("default", "1", "engine.json")
        assert active is not None and active.id == pid
        # terminal stages are not "active" but remain the latest
        md.rollout_plan_upsert(
            _plan(id=pid, stage=ROLLOUT_ROLLED_BACK)
        )
        assert md.rollout_plan_get_active("default", "1", "engine.json") is None
        latest = md.rollout_plan_get_latest("default", "1", "engine.json")
        assert latest.id == pid and latest.stage == ROLLOUT_ROLLED_BACK
        assert [p.id for p in md.rollout_plan_get_all()] == [pid]

    def test_upsert_replicates_through_changefeed(self, tmp_path):
        src = (
            SqliteEventStore(":memory:"),
            MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        cf = Changefeed(OpLog(str(tmp_path / "oplog")), *src)
        pid, seq = cf.metadata_rpc("rollout_plan_upsert", [_plan()])
        assert seq is not None  # every transition ships a change
        # replay the feed into a fresh replica store: the logged op
        # carries the RESOLVED id, so replay converges byte-for-byte
        dst = (
            SqliteEventStore(":memory:"),
            MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        entries, _last = cf.oplog.read_since(0, 100)
        for _seq, op in entries:
            apply_op(op, *dst)
        replica_plan = dst[1].rollout_plan_get(pid)
        assert replica_plan is not None
        assert replica_plan.salt == "abc123"
        assert replica_plan.stage == ROLLOUT_SHADOW


class TestStickyAcrossFailover:
    def test_same_split_via_ha_metadata_after_primary_death(
        self, tmp_path, monkeypatch
    ):
        """Satellite: the sticky split survives the HA read-failover
        path — a plan read from a failed-over replica yields the exact
        assignments the primary's copy did."""
        from predictionio_tpu.storage import remote
        from predictionio_tpu.storage.replica import StorageReplica
        from predictionio_tpu.storage.storage_server import StorageServer

        monkeypatch.setenv("PIO_BREAKER_FAILURES", "1")
        remote.reset_resilience()
        primary = StorageServer(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            changefeed=None,
        )
        primary.changefeed = Changefeed(
            OpLog(str(tmp_path / "oplog")),
            primary.events, primary.metadata, primary.models,
        )
        primary.start_background()
        replica = StorageReplica(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            f"http://127.0.0.1:{primary.bound_port}",
            str(tmp_path / "replica_state"),
            catchup_wait_s=0.0,
        )
        replica.start_background()
        try:
            md = remote.RemoteMetadataStore(
                f"pio+ha://127.0.0.1:{primary.bound_port},"
                f"127.0.0.1:{replica.bound_port}"
            )
            pid = md.rollout_plan_upsert(_plan(stage=ROLLOUT_CANARY))
            replica.catch_up()
            plan_before = md.rollout_plan_get_active(
                "default", "1", "engine.json"
            )
            keys = [f"user={i}" for i in range(200)]
            before = {
                k: variant_for_key(plan_before.salt, k, plan_before.percent)
                for k in keys
            }
            primary.kill()
            plan_after = md.rollout_plan_get_active(
                "default", "1", "engine.json"
            )  # served by the replica now
            assert plan_after.id == pid
            assert plan_after.salt == plan_before.salt
            after = {
                k: variant_for_key(plan_after.salt, k, plan_after.percent)
                for k in keys
            }
            assert after == before
        finally:
            remote.reset_resilience()
            for server in (primary, replica):
                try:
                    server.kill()
                except Exception:
                    pass


# ---------------------------------------------------------------------------
# the state machine end to end (sample engine, injected clock)
# ---------------------------------------------------------------------------


class TestRolloutE2E:
    def _drive(self, server, n, start=0):
        """n queries over distinct sticky keys; returns variant counts.
        Every request must answer 200 (the zero-client-failures
        invariant holds through every stage transition)."""
        counts: dict = {}
        for i in range(start, start + n):
            info: dict = {}
            _result, status = server.handle_query({"id": i}, info=info)
            assert status == 200
            counts[info.get("variant", "-")] = (
                counts.get(info.get("variant", "-"), 0) + 1
            )
        return counts

    def test_shadow_canary_live_when_gates_pass(self, registry):
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        clock = FakeClock()
        srv = _server(registry, engine, clock, instance_id=base_id)
        try:
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=10, gates=_gates()
            )
            assert srv.rollout.stage == ROLLOUT_SHADOW
            # shadow: clients see baseline only; duplicates hit candidate
            counts = self._drive(srv, 10)
            srv.rollout.drain_shadow()
            assert counts == {"baseline": 10}
            assert srv.rollout.controller.candidate.count() >= 5
            assert srv.rollout.controller.mean_divergence() is not None
            clock.advance(11)  # past shadow_hold_s
            self._drive(srv, 1, start=100)
            srv.rollout.drain_shadow()
            assert srv.rollout.stage == ROLLOUT_CANARY
            # canary: ~10% of distinct keys served by the candidate
            counts = self._drive(srv, 300, start=1000)
            assert counts.get("candidate", 0) >= 5
            assert counts["baseline"] > counts.get("candidate", 0)
            clock.advance(11)  # past canary_hold_s
            self._drive(srv, 5, start=5000)
            assert srv.rollout.stage == ROLLOUT_LIVE
            assert srv.deployment.instance.id == cand_id
            # terminal state durable + visible after a server restart
            plan = registry.get_metadata().rollout_plan_get_all()[0]
            assert plan.stage == ROLLOUT_LIVE
            assert [h["stage"] for h in plan.history] == [
                ROLLOUT_SHADOW, ROLLOUT_CANARY, ROLLOUT_LIVE,
            ]
            srv2 = _server(registry, engine, FakeClock())
            try:
                assert srv2.deployment.instance.id == cand_id
                assert not srv2.rollout.active
            finally:
                srv2.server_close()
        finally:
            srv.server_close()

    def test_restart_mid_canary_resumes_same_sticky_split(self, registry):
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        clock = FakeClock()
        srv = _server(registry, engine, clock, instance_id=base_id)
        try:
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=50, gates=_gates()
            )
            self._drive(srv, 6)
            srv.rollout.drain_shadow()
            clock.advance(11)
            self._drive(srv, 1, start=50)
            srv.rollout.drain_shadow()
            assert srv.rollout.stage == ROLLOUT_CANARY
            # "restart": a fresh server against the same metadata. It
            # would naturally load cand_id (latest completed) — resume
            # must reinstate baseline vs candidate and the same split.
            srv2 = _server(registry, engine, FakeClock())
            try:
                assert srv2.rollout.stage == ROLLOUT_CANARY
                assert srv2.deployment.instance.id == base_id
                assert (
                    srv2.rollout.candidate_dep.instance.id == cand_id
                )
                assert srv2.rollout.plan.salt == srv.rollout.plan.salt
                for i in range(100):
                    payload = {"id": i}
                    assert srv.rollout.variant_for(payload) == (
                        srv2.rollout.variant_for(payload)
                    )
            finally:
                srv2.server_close()
        finally:
            srv.server_close()

    def test_failing_candidate_auto_rolls_back_with_zero_client_failures(
        self, registry
    ):
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        clock = FakeClock()
        srv = _server(registry, engine, clock, instance_id=base_id)
        try:
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=50,
                gates=_gates(canary_hold_s=100_000.0),
            )
            self._drive(srv, 6)
            srv.rollout.drain_shadow()
            clock.advance(11)
            self._drive(srv, 1, start=50)
            srv.rollout.drain_shadow()
            assert srv.rollout.stage == ROLLOUT_CANARY
            # candidate dies mid-canary: every request still answers 200
            # (asserted inside _drive) and the error gate rolls back
            with faults.inject(
                faults.FaultSpec(site="serving.candidate", kind="refuse")
            ) as plan:
                self._drive(srv, 100, start=1000)
                assert plan.fired("serving.candidate") > 0
            assert srv.rollout.stage == ROLLOUT_ROLLED_BACK
            # baseline serves 100% of subsequent queries
            counts = self._drive(srv, 50, start=9000)
            assert counts == {"-": 50}
            assert srv.deployment.instance.id == base_id
            # terminal state durably recorded, visible after restart —
            # and the rolled-back candidate is quarantined from being
            # implicitly redeployed as latest-completed
            durable = registry.get_metadata().rollout_plan_get_all()[0]
            assert durable.stage == ROLLOUT_ROLLED_BACK
            assert "error-rate" in durable.history[-1]["reason"]
            srv2 = _server(registry, engine, FakeClock())
            try:
                assert not srv2.rollout.active
                assert srv2.deployment.instance.id == base_id
                assert srv2.rollout.plan.stage == ROLLOUT_ROLLED_BACK
            finally:
                srv2.server_close()
        finally:
            srv.server_close()

    def test_terminal_persist_retried_after_metadata_outage(
        self, registry, monkeypatch
    ):
        """A transition decided during a metadata outage must still
        become durable: terminal stages have no later observe() to ride,
        so handle_query retries the pending write."""
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock(), instance_id=base_id)
        try:
            srv.rollout.start(candidate_instance_id=cand_id, gates=_gates())
            md = registry.get_metadata()
            real_upsert = md.rollout_plan_upsert
            outage = {"on": True}

            def flaky(plan):
                if outage["on"]:
                    raise RuntimeError("metadata down")
                return real_upsert(plan)

            monkeypatch.setattr(md, "rollout_plan_upsert", flaky)
            srv.rollout.abort("during outage")  # persist fails, deferred
            assert md.rollout_plan_get_all()[0].stage == ROLLOUT_SHADOW
            outage["on"] = False
            _result, status = srv.handle_query({"id": 1})  # retry lands it
            assert status == 200
            assert md.rollout_plan_get_all()[0].stage == "ABORTED"
        finally:
            srv.server_close()

    def test_resume_with_unloadable_baseline_closes_the_plan(self, registry):
        """Restart mid-rollout with the plan's baseline gone: the plan
        must finish ABORTED (loudly, durably) instead of staying active
        while the candidate serves 100% unwatched."""
        engine = make_engine()
        cand_id = _train(registry, engine, algo_id=13)
        md = registry.get_metadata()
        md.rollout_plan_upsert(
            _plan(
                stage=ROLLOUT_CANARY,
                baseline_instance_id="EI-missing",
                candidate_instance_id=cand_id,
            )
        )
        srv = _server(registry, engine, FakeClock())
        try:
            assert not srv.rollout.active
            assert srv.rollout.plan.stage == "ABORTED"
            assert "baseline unloadable" in srv.rollout.plan.history[-1]["reason"]
            assert srv.deployment.instance.id == cand_id
            durable = md.rollout_plan_get_all()[0]
            assert durable.stage == "ABORTED"
        finally:
            srv.server_close()

    def test_client_deadline_expiry_not_charged_to_candidate(self, registry):
        """A budget that was already gone at dispatch is the client's
        fault — candidate-routed expiries at that stage must not feed
        the candidate's error gate."""
        from predictionio_tpu.utils.resilience import (
            Deadline,
            DeadlineExceeded,
        )

        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        clock = FakeClock()
        srv = _server(registry, engine, clock, instance_id=base_id)
        try:
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=100, gates=_gates()
            )
            srv.rollout.promote("straight to canary")
            before = srv.rollout.controller.candidate.count()
            expired = Deadline.after_ms(1, clock)
            clock.advance(1.0)
            with pytest.raises(DeadlineExceeded):
                srv.handle_query({"id": 1}, deadline=expired)
            assert srv.rollout.controller.candidate.count() == before
        finally:
            srv.server_close()

    def test_fleet_wide_errors_do_not_trip_the_delta_gate(self, registry):
        """Errors the whole fleet is suffering (a shared dependency
        down) must raise BOTH windows' error rates — the delta gate is a
        comparison against the live baseline, not an absolute candidate
        threshold, so a healthy canary survives bad weather."""
        import unittest.mock as mock

        from sample_engine import Serving0

        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock(), instance_id=base_id)
        try:
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=50,
                gates=_gates(canary_hold_s=100_000.0),
            )
            srv.rollout.promote("straight to canary")
            with mock.patch.object(
                Serving0, "serve", side_effect=RuntimeError("dep down")
            ):
                for i in range(60):
                    with pytest.raises(RuntimeError, match="dep down"):
                        srv.handle_query({"id": i})
            ctl = srv.rollout.controller
            assert ctl.baseline.error_rate() > 0.5
            assert ctl.candidate.error_rate() > 0.5
            # equal misery on both sides: the delta gate must NOT fire
            assert srv.rollout.stage == ROLLOUT_CANARY
        finally:
            srv.server_close()

    def test_start_rejects_out_of_range_percent(self, registry):
        """A NaN or out-of-range split would 500 every canary query
        (variant_for_key round()) — refuse it at start."""
        from predictionio_tpu.rollout.manager import RolloutError

        engine = make_engine()
        _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock())
        try:
            for bad in (0, -5, 150, float("nan")):
                with pytest.raises(RolloutError, match="percent"):
                    srv.rollout.start(
                        candidate_instance_id=cand_id, percent=bad,
                        gates=_gates(),
                    )
            assert not srv.rollout.active
        finally:
            srv.server_close()

    def test_reload_refused_while_rollout_active(self, registry):
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock(), instance_id=base_id)
        try:
            srv.rollout.start(candidate_instance_id=cand_id, gates=_gates())
            with pytest.raises(RuntimeError, match="promote or abort"):
                srv.reload()
            srv.rollout.abort("test cleanup")
            srv.reload()  # fine again once the plan is terminal
        finally:
            srv.server_close()

    def test_live_swap_and_rollback_drop_model_references(self, registry):
        """Satellite: retiring a deployment (go-live retiring the
        baseline; rollback retiring the candidate) must drop every
        server-side reference to its prepared models so device buffers
        are reclaimable."""
        engine = make_engine()
        _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock())
        try:
            # rollback path: candidate models released
            srv.rollout.start(candidate_instance_id=cand_id, gates=_gates())
            cand_ref = weakref.ref(srv.rollout.candidate_dep.models[0])
            srv.rollout.abort("teardown test")
            gc.collect()
            assert cand_ref() is None
            # go-live path: baseline models released
            srv.rollout.start(candidate_instance_id=cand_id, gates=_gates())
            base_ref = weakref.ref(srv.deployment.models[0])
            srv.rollout.promote("to canary")
            srv.rollout.promote("to live")
            gc.collect()
            assert base_ref() is None
            assert srv.deployment.instance.id == cand_id
        finally:
            srv.server_close()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


class TestRolloutHTTP:
    @pytest.fixture()
    def live(self, registry):
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(registry, engine, FakeClock(), instance_id=base_id)
        srv.start_background()
        yield f"http://127.0.0.1:{srv.bound_port}", srv, registry, engine, cand_id
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:
            pass

    def test_post_reload_accepted(self, live):
        base, srv, registry, engine, _cand = live
        new_id = _train(registry, engine, algo_id=17)
        r = requests.post(f"{base}/reload")
        assert r.status_code == 200
        assert srv.deployment.instance.id == new_id
        # deprecated GET spelling still answers (CreateServer parity)
        r = requests.get(f"{base}/reload")
        assert r.status_code == 200

    def test_rollout_routes(self, live):
        base, srv, _registry, _engine, cand_id = live
        r = requests.post(
            f"{base}/rollout/start",
            json={"instanceId": cand_id, "percent": 20, "gates": _gates()},
        )
        assert r.status_code == 200
        body = r.json()
        assert body["active"] and body["plan"]["stage"] == ROLLOUT_SHADOW
        assert body["plan"]["percent"] == 20
        # double-start → 409
        r = requests.post(f"{base}/rollout/start", json={})
        assert r.status_code == 409
        # reload blocked mid-rollout → 409
        assert requests.post(f"{base}/reload").status_code == 409
        assert requests.get(f"{base}/rollout.json").json()["active"]
        assert requests.get(f"{base}/status.json").json()["rollout"]["active"]
        r = requests.post(f"{base}/rollout/promote", json={"reason": "t"})
        assert r.status_code == 200
        assert r.json()["plan"]["stage"] == ROLLOUT_CANARY
        r = requests.post(f"{base}/rollout/abort", json={"reason": "done"})
        assert r.status_code == 200
        assert r.json()["plan"]["stage"] == "ABORTED"
        # nothing active anymore → 409
        r = requests.post(f"{base}/rollout/promote", json={})
        assert r.status_code == 409

    def test_bad_gate_option_is_400(self, live):
        base, _srv, _registry, _engine, cand_id = live
        r = requests.post(
            f"{base}/rollout/start",
            json={"instanceId": cand_id, "gates": {"nope": 1}},
        )
        assert r.status_code == 400

    def test_response_counter_carries_variant_label(self, live):
        base, srv, _registry, _engine, _cand = live
        requests.post(f"{base}/queries.json", json={"id": 1})
        from predictionio_tpu.obs.expo import render

        text = render(srv.metrics)
        assert 'pio_http_responses_total{status="200",variant="-"}' in text


class TestFeedbackVariant:
    def test_feedback_event_tagged_with_serving_variant(self, registry):
        """Satellite: pio_pr prediction-record events carry the variant
        so offline evaluation can score canary vs. baseline from the
        event store."""
        import time as _time

        from predictionio_tpu.api import EventServer, EventServerConfig
        from predictionio_tpu.storage import AccessKey, App, EventFilter

        md = registry.get_metadata()
        app_id = md.app_insert(App(id=0, name="fbapp"))
        md.access_key_insert(AccessKey(key="FBKEY", appid=app_id, events=[]))
        registry.get_events().init(app_id)
        ev_srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0, stats=False),
            registry.get_events(),
            md,
        )
        ev_srv.start_background()
        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        srv = _server(
            registry, engine, FakeClock(), instance_id=base_id,
            feedback=True, event_server_ip="127.0.0.1",
            event_server_port=ev_srv.bound_port, access_key="FBKEY",
        )
        try:
            # percent=100: every key routes to the candidate in CANARY
            srv.rollout.start(
                candidate_instance_id=cand_id, percent=100, gates=_gates()
            )
            srv.rollout.promote("straight to canary")
            info: dict = {}
            _result, status = srv.handle_query({"id": 5}, info=info)
            assert status == 200 and info["variant"] == CANDIDATE
            deadline = _time.time() + 10
            events = []
            while _time.time() < deadline and not events:
                events = list(
                    registry.get_events().find(
                        app_id, EventFilter(event_names=["predict"])
                    )
                )
                _time.sleep(0.05)
            assert len(events) == 1
            assert events[0].properties.get("variant") == CANDIDATE
            assert events[0].properties.get("engineInstanceId") == cand_id
        finally:
            srv.server_close()
            ev_srv.shutdown()
            ev_srv.server_close()


# ---------------------------------------------------------------------------
# chaos scenario + dashboard
# ---------------------------------------------------------------------------


class TestRolloutChaos:
    def test_loadgen_rollout_chaos_scenario(self, registry):
        """Satellite: the --rollout chaos drill as a tier-1 test —
        shadow → promote to canary → candidate faults → auto-rollback,
        zero client-visible failures, durable terminal state. Injected
        clock, no wall-clock sleeps."""
        from predictionio_tpu.tools.loadgen import run_rollout_chaos

        engine = make_engine()
        base_id = _train(registry, engine, algo_id=11)
        cand_id = _train(registry, engine, algo_id=13)
        report = run_rollout_chaos(
            engine=engine,
            registry=registry,
            baseline_instance_id=base_id,
            candidate_instance_id=cand_id,
            payload_template='{"id": {i}}',
            clock=FakeClock(),
        )
        assert report["ok"], report
        assert report["clientFailures"] == 0
        assert report["candidateFaultsFired"] > 0
        assert report["finalStage"] == ROLLOUT_ROLLED_BACK
        assert report["durableStage"] == ROLLOUT_ROLLED_BACK
        assert report["postRollbackCandidateServed"] == 0
        assert report["shadowSamples"] > 0


class TestRolloutCLI:
    def test_rollout_help_renders(self):
        """argparse %-interpolates help text: a stray literal ``%``
        crashes ``pio rollout -h`` with ValueError instead of usage."""
        from predictionio_tpu.tools.console import build_parser

        parser = build_parser()
        for argv in (["rollout", "--help"], ["rollout", "abort", "--help"]):
            with pytest.raises(SystemExit) as excinfo:
                parser.parse_args(argv)
            assert excinfo.value.code == 0


class TestDashboardRollouts:
    def test_rollouts_page_and_json(self, registry):
        from predictionio_tpu.tools.dashboard import (
            DashboardConfig,
            DashboardServer,
        )

        md = registry.get_metadata()
        pid = md.rollout_plan_upsert(_plan(stage=ROLLOUT_CANARY))
        server = DashboardServer(
            DashboardConfig(ip="127.0.0.1", port=0), registry
        )
        server.start_background()
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            html_page = requests.get(f"{base}/rollouts")
            assert html_page.status_code == 200
            assert pid in html_page.text
            assert ROLLOUT_CANARY in html_page.text
            rows = requests.get(f"{base}/rollouts.json").json()
            assert rows[0]["id"] == pid
            assert rows[0]["stage"] == ROLLOUT_CANARY
            assert rows[0]["history"][0]["reason"] == "start"
        finally:
            server.shutdown()
            server.server_close()
