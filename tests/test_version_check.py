"""Upgrade-check tests (the UpgradeCheckRunner analogue,
reference ``WorkflowUtils.scala:392-413``)."""

import http.server
import json
import threading

import pytest

from predictionio_tpu import __version__
from predictionio_tpu.workflow.version_check import (
    _parse_version,
    _run_check,
    check_upgrade,
    check_url,
)


class _IndexHandler(http.server.BaseHTTPRequestHandler):
    payload: dict = {}
    requests: list = []

    def do_GET(self):
        type(self).requests.append(self.path)
        body = json.dumps(self.payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture()
def versions_host(monkeypatch):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _IndexHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    _IndexHandler.requests = []
    monkeypatch.setenv(
        "PIO_VERSIONS_HOST", f"http://127.0.0.1:{srv.server_address[1]}/"
    )
    yield srv
    srv.shutdown()
    srv.server_close()


class TestUrlScheme:
    def test_component_url_matches_reference_scheme(self, monkeypatch):
        monkeypatch.setenv("PIO_VERSIONS_HOST", "http://h/")
        assert check_url("training", version="1.2.3") == (
            "http://h/1.2.3/training.json"
        )

    def test_engine_url_variant(self, monkeypatch):
        monkeypatch.setenv("PIO_VERSIONS_HOST", "http://h")
        assert check_url("training", "MyEngine", version="1.2.3") == (
            "http://h/1.2.3/training/MyEngine.json"
        )


class TestVersionParse:
    @pytest.mark.parametrize(
        "s,expect",
        [
            ("0.9.2", (0, 9, 2)),
            ("0.9.2-SNAPSHOT", (0, 9, 2)),
            ("1.10", (1, 10)),
            ("garbage", None),
        ],
    )
    def test_parse(self, s, expect):
        assert _parse_version(s) == expect


class TestCheck:
    def test_newer_version_detected(self, versions_host):
        _IndexHandler.payload = {"version": "99.0.0"}
        assert _run_check("training", "") == "99.0.0"
        assert _IndexHandler.requests == [f"/{__version__}/training.json"]

    def test_current_version_is_quiet(self, versions_host):
        _IndexHandler.payload = {"version": __version__}
        assert _run_check("training", "") is None

    def test_unreachable_host_is_silent(self, monkeypatch):
        monkeypatch.setenv("PIO_VERSIONS_HOST", "http://127.0.0.1:9/")
        assert _run_check("training", "") is None  # must not raise

    def test_bad_payload_is_silent(self, versions_host):
        _IndexHandler.payload = {"unexpected": True}
        assert _run_check("training", "") is None

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("PIO_NO_UPGRADE_CHECK", "1")
        assert check_upgrade("training") is None

    def test_opt_in_no_host_means_no_check(self, monkeypatch):
        # With no PIO_VERSIONS_HOST configured the check must not fire at
        # all: the reference's hard-coded direct.prediction.io is a defunct
        # domain, and a default-on request there is a takeover vector.
        monkeypatch.delenv("PIO_VERSIONS_HOST", raising=False)
        monkeypatch.delenv("PIO_NO_UPGRADE_CHECK", raising=False)
        assert check_upgrade("training") is None

    def test_advertised_version_sanitized(self, versions_host):
        # Control chars / non-ASCII from a hijacked index must never reach
        # the logs; the numeric comparison still sees the newer version.
        _IndexHandler.payload = {"version": "99.0.0\x1b[31mEVIL\nLOG"}
        out = _run_check("training", "")
        assert out is not None
        assert "\x1b" not in out and "\n" not in out
        assert out.startswith("99.0.0")

    def test_fire_and_forget_thread(self, versions_host, monkeypatch):
        monkeypatch.delenv("PIO_NO_UPGRADE_CHECK", raising=False)
        _IndexHandler.payload = {"version": "99.0.0"}
        t = check_upgrade("deployment", "Engine0")
        assert t is not None
        t.join(10.0)
        assert not t.is_alive()
        assert _IndexHandler.requests == [
            f"/{__version__}/deployment/Engine0.json"
        ]
