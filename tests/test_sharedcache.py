"""The fleet's shared cache tier (``fleet/sharedcache``,
docs/fleet.md#shared-cache-tier).

Three layers:

1. **Sidecar server**: the HTTP surface (lookup/put/flush/top/status)
   and its epoch-checked reads.
2. **Advisory client**: the degrade contract — any doubt (dead sidecar,
   open breaker, epoch skew) is a RECORDED miss, never a stale serve
   and never a client-visible failure.
3. **Router integration**: cross-router reuse with local promotion,
   negative caching, cache warming on deploy, and the kill-the-tier
   acceptance drill (``loadgen --shared-cache-drill``).
"""

from __future__ import annotations

import http.client
import json

import pytest

from predictionio_tpu.fleet.router import RouterConfig, RouterServer
from predictionio_tpu.fleet.sharedcache import (
    SHARED_OUTCOMES,
    SharedCacheClient,
    SharedCacheServer,
)
from predictionio_tpu.testing.clock import FakeClock
from predictionio_tpu.utils.resilience import CircuitBreaker


@pytest.fixture()
def sidecar():
    server = SharedCacheServer(ip="127.0.0.1", port=0)
    server.start_background()
    yield server
    server.kill()


def _client(server, **kw):
    return SharedCacheClient(f"127.0.0.1:{server.bound_port}", **kw)


def _raw(port, method, path, payload=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2.0)
    try:
        body = payload if isinstance(payload, bytes) else (
            json.dumps(payload).encode() if payload is not None else None
        )
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def _mini_router(shared=None, clock=None, **kw):
    kw.setdefault("cache_enabled", True)
    kw.setdefault("cache_ttl_s", 30.0)
    kw.setdefault("plan_refresh_s", 0.0)
    kw.setdefault("engine_id", "eng")
    if shared is not None:
        kw.setdefault("shared_cache", f"127.0.0.1:{shared.bound_port}")
        kw.setdefault("shared_warm", False)
    cfg = RouterConfig(
        ip="127.0.0.1", port=0, backends=kw.pop("backends", ("h1:1",)), **kw
    )
    return RouterServer(cfg, clock=clock or FakeClock())


class TestSidecarServer:
    def test_put_lookup_roundtrip_is_epoch_checked(self, sidecar):
        client = _client(sidecar)
        key = ("-", '{"user":"u1"}')
        assert client.put(key, {"n": 1}, "baseline", "E1") is True
        entry = client.lookup(key, "E1")
        assert entry is not None
        assert entry.body == {"n": 1} and entry.variant == "baseline"
        # a lookup under another epoch is a miss AND drops the entry
        # server-side — the tier never carries answers across epochs
        assert client.lookup(key, "E2") is None
        assert len(sidecar.cache) == 0
        assert client.outcomes == {"put": 1, "hit": 1, "miss": 1}

    def test_flush_and_top_routes(self, sidecar):
        client = _client(sidecar)
        client.put(("-", "q1"), {"n": 1}, None, "E1")
        client.put(("-", "q2"), {"n": 2}, None, "E1")
        client.lookup(("-", "q1"), "E1")  # q1 now the hotter entry
        top = client.top(10)
        assert [item["query"] for item in top] == ["q1", "q2"]
        assert top[0]["hits"] == 1 and top[0]["epoch"] == "E1"
        assert client.flush(reason="test") == 2
        assert client.top(10) == []

    def test_warming_export_respects_byte_budget(self, sidecar):
        """Size-aware warming: a giant blob with the most hits must not
        crowd the whole budget out — it is skipped and the smaller but
        still-hot entry behind it makes the cut."""
        client = _client(sidecar)
        client.put(("-", "giant"), {"blob": "x" * 4096}, None, "E1")
        client.put(("-", "small"), {"n": 1}, None, "E1")
        for _ in range(3):  # giant is the hotter entry by far
            client.lookup(("-", "giant"), "E1")
        unbounded = client.top(10)
        assert [item["query"] for item in unbounded] == ["giant", "small"]
        budgeted = client.top(10, max_bytes=512)
        assert [item["query"] for item in budgeted] == ["small"]
        # the env default applies when the query param is absent
        status, body = _raw(
            sidecar.bound_port, "GET", "/cache/top?n=10&maxBytes=512"
        )
        assert status == 200
        assert [item["query"] for item in body["entries"]] == ["small"]
        status, body = _raw(
            sidecar.bound_port, "GET", "/cache/top?n=10&maxBytes=junk"
        )
        assert status == 400

    def test_warming_export_env_budget(self, sidecar, monkeypatch):
        client = _client(sidecar)
        client.put(("-", "giant"), {"blob": "y" * 4096}, None, "E1")
        client.put(("-", "small"), {"n": 2}, None, "E1")
        monkeypatch.setenv("PIO_SHAREDCACHE_WARM_BYTES", "512")
        assert [item["query"] for item in client.top(10)] == ["small"]

    def test_status_and_error_routes(self, sidecar):
        port = sidecar.bound_port
        status, body = _raw(port, "GET", "/status.json")
        assert status == 200 and body["server"] == "sharedcache"
        assert body["cache"]["entries"] == 0
        status, body = _raw(port, "GET", "/cache/top?n=junk")
        assert status == 400
        status, body = _raw(port, "GET", "/nope")
        assert status == 404
        status, body = _raw(port, "POST", "/cache/lookup", b"not json")
        assert status == 400
        status, body = _raw(port, "POST", "/cache/lookup", payload=[1, 2])
        assert status == 400

    def test_sidecar_metrics_move(self, sidecar):
        from predictionio_tpu.obs.expo import parse_text, render

        client = _client(sidecar)
        client.put(("-", "q1"), {"n": 1}, None, "E1")
        client.lookup(("-", "q1"), "E1")
        client.lookup(("-", "q9"), "E1")
        client.lookup(("-", "q1"), "E2")  # epoch drop
        scraped = parse_text(render(sidecar.metrics))
        lookups = {
            labels["outcome"]: v
            for labels, v in scraped["pio_sharedcache_lookups_total"]
        }
        assert lookups == {"hit": 1.0, "miss": 2.0}
        reasons = {
            labels["reason"]: v
            for labels, v in scraped["pio_sharedcache_invalidations_total"]
        }
        assert reasons.get("epoch") == 1.0
        assert scraped["pio_sharedcache_entries"] == [({}, 0.0)]


class TestAdvisoryClient:
    def test_dead_sidecar_degrades_to_recorded_miss(self):
        server = SharedCacheServer(ip="127.0.0.1", port=0)
        server.start_background()
        client = _client(server)
        server.kill()
        assert client.lookup(("-", "q"), "E1") is None
        assert client.put(("-", "q"), {"n": 1}, None, "E1") is False
        assert client.flush() is None
        assert client.top() == []
        out = client.status()
        assert out["outcomes"]["error"] >= 2
        assert out["outcomes"]["put_error"] == 1
        assert out["lastError"]  # the degrade is visible, never silent

    def test_open_breaker_short_circuits_to_recorded_miss(self):
        server = SharedCacheServer(ip="127.0.0.1", port=0)
        server.start_background()
        breaker = CircuitBreaker.from_env(
            "sharedcache-test",
            env={"PIO_BREAKER_FAILURES": "1", "PIO_BREAKER_RESET_S": "60"},
        )
        client = _client(server, breaker=breaker)
        server.kill()
        assert client.lookup(("-", "q"), "E1") is None  # trips the breaker
        assert client.lookup(("-", "q"), "E1") is None  # short-circuited
        assert client.outcomes.get("error") == 1
        assert client.outcomes.get("open") == 1
        assert client.status()["breaker"]["state"] == CircuitBreaker.OPEN

    def test_skewed_sidecar_answer_is_dropped_locally(self, sidecar):
        """Belt and braces: even if a (buggy) sidecar answered across
        epochs, the client drops the entry locally and counts the
        skew — a stale serve needs BOTH sides wrong at once."""
        client = _client(sidecar)
        client._request = lambda *a, **k: {
            "found": True, "body": {"n": 1}, "servedVariant": "-",
            "epoch": "OTHER", "negative": False,
        }
        assert client.lookup(("-", "q"), "E1") is None
        assert client.outcomes == {"epoch_skew": 1}

    def test_outcome_vocabulary_stays_closed(self, sidecar):
        """Every counted outcome is in SHARED_OUTCOMES — the vocabulary
        is a metric label (bounded cardinality, docs/observability.md)."""
        client = _client(sidecar)
        client.put(("-", "q"), {"n": 1}, None, "E1")
        client.lookup(("-", "q"), "E1")
        client.lookup(("-", "other"), "E1")
        sidecar.kill()
        client.lookup(("-", "q"), "E1")
        assert set(client.outcomes) <= set(SHARED_OUTCOMES)

    def test_lookup_budget_caps_the_socket_timeout(self, sidecar):
        client = _client(sidecar, timeout_s=0.25)
        client.put(("-", "q"), {"n": 1}, None, "E1")
        seen = {}
        original = client._request

        def spy(method, path, payload=None, timeout_s=None):
            seen["timeout"] = timeout_s
            return original(method, path, payload, timeout_s=timeout_s)

        client._request = spy
        assert client.lookup(("-", "q"), "E1", budget_s=0.05) is not None
        # the per-call budget undercuts the configured client timeout:
        # the tier can never blow the caller's remaining deadline
        assert seen["timeout"] == pytest.approx(0.05)


class TestRouterSharedTier:
    def _leg(self, counter, body=None):
        def leg(*_a, **_k):
            counter["n"] += 1
            return 200, body or {"items": ["a"]}, {"x-pio-variant": "-"}

        return leg

    def test_cross_router_reuse_promotes_to_local(self, sidecar):
        router_a = _mini_router(shared=sidecar)
        router_b = _mini_router(shared=sidecar)
        calls_a, calls_b = {"n": 0}, {"n": 0}
        router_a._leg = self._leg(calls_a)
        router_b._leg = self._leg(calls_b)
        try:
            info: dict = {}
            _s, body_a, _v = router_a.route_query(
                b'{"user": "u1"}', None, info=info
            )
            assert info["cache"] == "miss" and calls_a["n"] == 1
            assert router_a._shared.outcomes.get("put") == 1
            # a DIFFERENT router answers from the tier without touching
            # its backend, byte-identical to the filling router
            info = {}
            _s, body_b, _v = router_b.route_query(
                b'{"user": "u1"}', None, info=info
            )
            assert info["cache"] == "hit-shared"
            assert calls_b["n"] == 0
            assert body_b == body_a
            # ...and the hit was PROMOTED into b's local LRU
            info = {}
            router_b.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
        finally:
            router_a.server_close()
            router_b.server_close()

    def test_killed_tier_is_invisible_to_clients(self, sidecar):
        router = _mini_router(shared=sidecar)
        calls = {"n": 0}
        router._leg = self._leg(calls)
        try:
            sidecar.kill()
            info: dict = {}
            status, body, _v = router.route_query(
                b'{"user": "u1"}', None, info=info
            )
            assert status == 200 and body == {"items": ["a"]}
            assert info["cache"] == "miss" and calls["n"] == 1
            out = router.status_json()["sharedCache"]
            assert out["enabled"] is True
            assert out["outcomes"].get("error", 0) >= 1
            assert out["lastError"]
        finally:
            router.server_close()

    def test_negative_caching_rides_a_short_fuse(self):
        clock = FakeClock()
        router = _mini_router(clock=clock, negative_ttl_s=2.0)
        calls = {"n": 0}

        def leg(*_a, **_k):
            calls["n"] += 1
            return 200, {"itemScores": []}, {"x-pio-variant": "-"}

        router._leg = leg
        try:
            info: dict = {}
            router.route_query(b'{"user": "ghost"}', None, info=info)
            assert info["cache"] == "miss" and calls["n"] == 1
            # the known-empty answer IS cached (no punch-through)...
            info = {}
            router.route_query(b'{"user": "ghost"}', None, info=info)
            assert info["cache"] == "hit" and calls["n"] == 1
            # ...but on the negative fuse, not the cache-wide TTL
            clock.advance(2.5)
            info = {}
            router.route_query(b'{"user": "ghost"}', None, info=info)
            assert info["cache"] == "miss" and calls["n"] == 2
        finally:
            router.server_close()

    def test_negative_flag_travels_through_the_tier(self, sidecar):
        router_a = _mini_router(shared=sidecar, negative_ttl_s=5.0)
        router_b = _mini_router(shared=sidecar, negative_ttl_s=5.0)
        empty = {"itemScores": []}
        router_a._leg = self._leg({"n": 0}, body=empty)
        router_b._leg = self._leg({"n": 0}, body=empty)
        try:
            router_a.route_query(b'{"user": "ghost"}', None)
            entry = next(iter(sidecar.cache._cache.values()))
            assert entry.negative is True and entry.ttl_s == 5.0
            info: dict = {}
            _s, body, _v = router_b.route_query(
                b'{"user": "ghost"}', None, info=info
            )
            assert info["cache"] == "hit-shared" and body == empty
            assert router_b._shared.outcomes.get("negative_hit") == 1
        finally:
            router_a.server_close()
            router_b.server_close()

    def test_warm_from_shared_imports_only_current_epoch(self, sidecar):
        filler = _mini_router(shared=sidecar)
        filler._leg = self._leg({"n": 0})
        try:
            filler.route_query(b'{"user": "u1"}', None)
            filler.route_query(b'{"user": "u2"}', None)
        finally:
            filler.server_close()
        # a leftover entry from another epoch must not seed the cache
        _client(sidecar).put(("-", '{"user":"u3"}'), {"n": 3}, None, "OLD")
        fresh = _mini_router(shared=sidecar)
        calls = {"n": 0}
        fresh._leg = self._leg(calls)
        try:
            assert fresh.warm_from_shared() == 2
            assert fresh.status_json()["sharedCache"]["warmedEntries"] == 2
            info: dict = {}
            fresh.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit" and calls["n"] == 0
        finally:
            fresh.server_close()

    def test_status_json_disabled_block(self):
        router = _mini_router()
        try:
            assert router.status_json()["sharedCache"] == {"enabled": False}
        finally:
            router.server_close()


# ---------------------------------------------------------------------------
# the kill-the-tier acceptance drill (loadgen --shared-cache-drill)
# ---------------------------------------------------------------------------


class TestSharedCacheDrill:
    def test_kill_the_tier_zero_stale_zero_failures(self):
        from predictionio_tpu.tools.loadgen import run_shared_cache_drill

        report = run_shared_cache_drill(queries=96)
        assert report["clientFailures"] == 0
        assert report["crossRouterReuse"] is True
        assert report["sharedHitRate"] > 0.3
        # the kill: recorded degrades, byte-identical re-computed
        # answers, zero client-visible failures
        assert report["degradesRecorded"] > 0
        assert report["byteIdenticalAfterKill"] is True
        # recovery: the restarted tier fills back up and warms a
        # restarting router into local hits
        assert report["recoveredSharedHits"] > 0
        assert report["warmedEntries"] > 0
        assert report["warmServesLocalHit"] is True
        # the push plane: the rollout's epoch move arrives pushed and
        # no router serves a pre-rollout answer
        assert report["pushFlushObserved"] is True
        assert report["epochInvalidations"] > 0
        assert report["staleAfterRollout"] == 0
        assert report["ok"] is True
