"""Platform self-forcing: the spawn/dry-run boundary must come up on the
CPU backend regardless of accelerator boot hooks in the environment
(the spark-submit env-propagation analogue, ``RunWorkflow.scala:37-40``)."""

import os
import subprocess
import sys

from predictionio_tpu.utils.platform import (
    current_platform,
    force_cpu_env,
    jax_child_env,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_force_cpu_env_scrubs_boot_hook():
    base = {
        "JAX_PLATFORMS": "axon",
        "PALLAS_AXON_POOL_IPS": "127.0.0.1",
        "PALLAS_AXON_REMOTE_COMPILE": "1",
        "AXON_LOOPBACK_RELAY": "1",
        "TPU_WORKER_HOSTNAMES": "localhost",
        "PYTHONPATH": "/root/.axon_site" + os.pathsep + "/somewhere/else",
        "HOME": "/root",
    }
    env = force_cpu_env(base, n_devices=8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["PIO_JAX_PLATFORM"] == "cpu"
    assert not any(k.startswith(("PALLAS_AXON", "AXON_", "TPU_")) for k in env)
    assert "axon_site" not in env.get("PYTHONPATH", "")
    assert "/somewhere/else" in env["PYTHONPATH"]
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert env["HOME"] == "/root"  # unrelated vars pass through


def test_force_cpu_env_replaces_existing_device_count():
    base = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2 --other"}
    env = force_cpu_env(base, n_devices=8)
    assert env["XLA_FLAGS"].count("device_count") == 1
    assert "device_count=8" in env["XLA_FLAGS"]
    assert "--other" in env["XLA_FLAGS"]


def test_jax_child_env_passthrough_on_accelerator():
    base = {"JAX_PLATFORMS": "axon", "PALLAS_AXON_POOL_IPS": "1.2.3.4"}
    # current process is cpu-pinned under conftest, so patch the decision
    # inputs explicitly via the base mapping semantics: jax_child_env reads
    # the *process* platform, which conftest pins to cpu — children of a
    # cpu parent must be hard-pinned.
    assert current_platform() == "cpu"
    env = jax_child_env(base)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "PALLAS_AXON_POOL_IPS" not in env


def test_dryrun_multichip_self_forces_from_accelerator_env():
    """The driver artifact path: a parent pinned to an accelerator platform
    (JAX_PLATFORMS=axon, jax never imported) must still complete the CPU
    dry-run by re-execing itself with a scrubbed environment."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"  # simulate the driver's pinned env
    env.pop("_PIO_DRYRUN_CHILD", None)
    env.pop("PIO_JAX_PLATFORM", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "__graft_entry__.py"),
         "--dryrun", "8"],
        env=env,
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dryrun_multichip(8) ok" in proc.stdout
