"""Second-language engine authoring (the reference's controller/java shim
rebuilt as subprocess DASE components over JSON stdio —
``controller/foreign.py`` + ``sdk/cpp/pio_engine.hpp``).

The worked example (``examples/cpp_engine/popularity.cc``) is compiled
with the system toolchain and driven through the real Engine train path,
the serving predict path (incl. the micro-batcher), pickle round-trip
(the deploy-time model store), and failure modes (bad query, child
crash)."""

import os
import pickle
import subprocess
import sys

import pytest

from predictionio_tpu.controller import Engine
from predictionio_tpu.controller.dase import IdentityPreparator, Serving
from predictionio_tpu.controller.foreign import (
    ForeignAlgorithm,
    ForeignModel,
    ForeignParams,
    ForeignProcessError,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLE = os.path.join(_REPO, "examples", "cpp_engine")


@pytest.fixture(scope="module")
def popularity_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("cppengine") / "popularity")
    subprocess.run(
        [
            "g++", "-O2", "-std=c++17",
            "-I", os.path.join(_REPO, "sdk", "cpp"),
            "-o", out, os.path.join(_EXAMPLE, "popularity.cc"),
        ],
        check=True,
        capture_output=True,
    )
    return out


RATINGS = [
    ["u1", "i1", 5.0], ["u2", "i1", 4.0], ["u3", "i1", 3.0],
    ["u1", "i2", 5.0], ["u2", "i2", 4.0],
    ["u1", "i3", 1.0],
]


class TestForeignAlgorithm:
    def _algo(self, popularity_bin, **params):
        return ForeignAlgorithm(
            ForeignParams(cmd=[popularity_bin], params=params, timeout_s=30)
        )

    def test_train_and_predict(self, popularity_bin):
        algo = self._algo(popularity_bin)
        model = algo.train(None, {"ratings": RATINGS})
        assert isinstance(model, ForeignModel)
        assert model.model_json["items"][0] == "i1"  # sum 12 > 9 > 1
        pred = algo.predict(model, {"user": "u9", "num": 2})
        assert [r["item"] for r in pred["itemScores"]] == ["i1", "i2"]
        assert pred["itemScores"][0]["score"] == 12.0

    def test_params_reach_the_child(self, popularity_bin):
        algo = self._algo(popularity_bin, min_count=3)
        model = algo.train(None, {"ratings": RATINGS})
        # only i1 has >= 3 ratings
        assert model.model_json["items"] == ["i1"]

    def test_model_pickle_roundtrip_fresh_child(self, popularity_bin):
        """Deploy analogue: the trained model goes through the model store
        (pickle), and a NEW algorithm instance serves it by respawning the
        child and pushing the model back with `load`."""
        algo = self._algo(popularity_bin)
        model = algo.train(None, {"ratings": RATINGS})
        blob = pickle.dumps(model)
        restored = pickle.loads(blob)
        server_algo = self._algo(popularity_bin)  # fresh process
        pred = server_algo.predict(restored, {"user": "u1", "num": 1})
        assert pred["itemScores"][0]["item"] == "i1"

    def test_bad_query_fails_alone(self, popularity_bin):
        algo = self._algo(popularity_bin)
        model = algo.train(None, {"ratings": RATINGS})
        with pytest.raises(RuntimeError, match="num must be >= 0"):
            algo.predict(model, {"user": "u1", "num": -1})
        # the child survived the component-level error
        ok = algo.predict(model, {"user": "u1", "num": 1})
        assert ok["itemScores"][0]["item"] == "i1"

    def test_child_crash_is_loud_then_recovers(self, popularity_bin):
        algo = self._algo(popularity_bin)
        model = algo.train(None, {"ratings": RATINGS})
        algo._proc._proc.kill()  # simulate the component dying
        algo._proc._proc.wait()
        # next predict respawns the child and reloads the model
        pred = algo.predict(model, {"user": "u1", "num": 1})
        assert pred["itemScores"][0]["item"] == "i1"

    def test_non_bmp_strings_roundtrip(self, popularity_bin):
        """json.dumps escapes emoji as \\uD83D\\uDE00 surrogate pairs; the
        C++ JSON codec must recombine them (CESU-8 halves would poison the
        pipe when echoed back)."""
        algo = self._algo(popularity_bin)
        ratings = [["u😀", "item🎉", 5.0], ["u2", "item🎉", 2.0]]
        model = algo.train(None, {"ratings": ratings})
        assert model.model_json["items"][0] == "item🎉"
        pred = algo.predict(model, {"user": "u😀", "num": 1})
        assert pred["itemScores"][0]["item"] == "item🎉"

    def test_partial_line_hang_trips_timeout(self, tmp_path):
        """A child that writes half a response then wedges must trip the
        per-request deadline, not block the serving thread forever."""
        import textwrap

        script = tmp_path / "wedge.py"
        script.write_text(textwrap.dedent("""
            import sys, time
            sys.stdin.readline()
            sys.stdout.write('{"id": 1, ')   # partial line, no newline
            sys.stdout.flush()
            time.sleep(600)
        """))
        algo = ForeignAlgorithm(
            ForeignParams(cmd=[sys.executable, str(script)], timeout_s=1.5)
        )
        import time

        t0 = time.monotonic()
        with pytest.raises(ForeignProcessError, match="timed out"):
            algo.train(None, {"ratings": []})
        assert time.monotonic() - t0 < 10

    def test_missing_binary_is_loud(self):
        algo = ForeignAlgorithm(
            ForeignParams(cmd=["/nonexistent/engine-bin"], timeout_s=5)
        )
        with pytest.raises(ForeignProcessError, match="cannot start"):
            algo.train(None, {"ratings": RATINGS})


class _DictServing(Serving):
    def serve(self, query, predictions):
        return predictions[0]


class _ListSource:
    """Python DataSource feeding the foreign algorithm — the mixed-language
    engine the reference's Java shim exists for."""

    params = None

    def __init__(self, params=None):
        self.params = params

    def read_training(self, ctx):
        return {"ratings": RATINGS}

    def read_eval(self, ctx):
        return []


class TestMixedLanguageEngine:
    def test_engine_train_with_foreign_algorithm(self, popularity_bin):
        engine = Engine(
            {"": _ListSource},
            {"": IdentityPreparator},
            {"": ForeignAlgorithm},
            {"": _DictServing},
        )
        from predictionio_tpu.controller.engine import EngineParams

        ep = EngineParams(
            algorithm_params_list=[
                ("", ForeignParams(cmd=[popularity_bin], timeout_s=30))
            ],
        )
        models = engine.train(None, ep)
        assert len(models) == 1 and isinstance(models[0], ForeignModel)
