"""bench.py host-side helpers: holdout split and synth-cache reaper.

The reaper rules were reworked twice by review (live-writer protection,
then pid-recycling age bound) — this pins the final contract: a YOUNG
tmp with a live writer pid survives, a young tmp with a dead writer is
reaped, and an OLD tmp is reaped even if its (possibly recycled) pid is
alive.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_holdout_mask_deterministic_five_percent():
    m1 = bench.holdout_mask(200_000)
    m2 = bench.holdout_mask(200_000)
    np.testing.assert_array_equal(m1, m2)
    assert 0.045 < m1.mean() < 0.055


def test_synth_cache_orphan_reaper(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_SYNTH_CACHE", str(tmp_path))
    scale = 0.0001
    cache = tmp_path / f"synth_ml20m_v{bench._SYNTH_VERSION}_s{scale}_seed0.npz"

    # pid 1 is always alive (and not OUR pid — synth_ml20m's own savez
    # tmp uses os.getpid() and would collide)
    young_alive = tmp_path / f"{cache.name}.1.tmp.npz"
    young_dead = tmp_path / f"{cache.name}.999999.tmp.npz"
    old_alive = tmp_path / f"{cache.name}.x.1.tmp.npz"
    for p in (young_alive, young_dead, old_alive):
        p.write_bytes(b"x")
    old = time.time() - 7 * 3600
    os.utime(old_alive, (old, old))

    bench.synth_ml20m(scale)

    assert cache.exists(), "cache file not written"
    assert young_alive.exists(), "live writer's young tmp was reaped"
    assert not young_dead.exists(), "dead writer's tmp not reaped"
    assert not old_alive.exists(), "old tmp kept alive by recycled pid"
