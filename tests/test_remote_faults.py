"""``storage/remote.py`` resilience under injected faults.

The stale-connection contract, proven through the deterministic fault
harness instead of a lying socket server: keep-alive connection closed
server-side → idempotent reads retry exactly once on a fresh connection;
writes never retry without an idempotency key; a retried keyed write
inserts exactly one event. Plus the per-netloc circuit breaker and the
deadline short-circuit, both on injected clocks.
"""

import time

import pytest

from predictionio_tpu.storage import MetadataStore, SqliteEventStore
from predictionio_tpu.storage.event import (
    Event,
    idempotency_event_id,
    with_event_id,
)
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.storage.model_store import SqliteModelStore
from predictionio_tpu.storage.remote import (
    RemoteEventStore,
    RemoteStorageError,
    _pool,
    _request,
    reset_resilience,
)
from predictionio_tpu.storage.storage_server import StorageServer
from predictionio_tpu.testing import faults
from predictionio_tpu.utils.resilience import (
    Deadline,
    DeadlineExceeded,
    deadline_scope,
)

from test_resilience import FakeClock

APP = 5


@pytest.fixture()
def server():
    srv = StorageServer(
        "127.0.0.1", 0, SqliteEventStore(":memory:"),
        MetadataStore(":memory:"), SqliteModelStore(":memory:"),
    )
    srv.start_background()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def store(server):
    base = f"http://127.0.0.1:{server.bound_port}"
    # hermetic per test: no pooled connections or breaker state carried
    # over, and the breaker clock is real again afterwards
    _pool.conns.clear()
    reset_resilience(clock=time.monotonic)
    st = RemoteEventStore(base)
    st.init(APP)  # also pools a live keep-alive connection
    yield st, base
    faults.deactivate()
    _pool.conns.clear()
    reset_resilience(clock=time.monotonic)


def _event() -> Event:
    return Event(
        event="rate", entity_type="user", entity_id="u1",
        target_entity_type="item", target_entity_id="i1",
    )


#: fault: the server closed the pooled keep-alive connection — fires on
#: REUSED connections only (a fresh connect succeeds), exactly once
def _stale_close(times=1):
    return faults.FaultSpec(
        "remote.send", "close", times=times,
        when=lambda info: not info.get("fresh", True),
    )


class TestStaleConnectionContract:
    def test_idempotent_read_retries_exactly_once_on_fresh_conn(self, store):
        st, base = store
        assert _pool.conns.get(base), "precondition: a pooled connection"
        with faults.inject(_stale_close()) as plan:
            assert st.get("no-such-event", APP) is None  # 404 → None
            # one injected stale failure, one fresh-connection retry
            assert plan.fired("remote.send") == 1
            assert plan.hits("remote.send") == 2

    def test_unkeyed_write_never_retries(self, store):
        st, base = store
        assert _pool.conns.get(base)
        with faults.inject(_stale_close()) as plan:
            with pytest.raises(RemoteStorageError, match="unreachable"):
                st.insert(_event(), APP)
            # the failure surfaced loudly after ONE send attempt: an
            # unkeyed write must never be replayed
            assert plan.hits("remote.send") == 1
        assert list(st.find(APP, EventFilter())) == []

    def test_keyed_write_retries_and_inserts_exactly_once(self, store):
        st, base = store
        keyed = with_event_id(_event(), idempotency_event_id(APP, "req-9"))
        assert _pool.conns.get(base)
        with faults.inject(_stale_close()) as plan:
            eid = st.insert(keyed, APP)
            assert eid == keyed.event_id
            assert plan.fired("remote.send") == 1
            assert plan.hits("remote.send") == 2
        # and a full client-level replay of the same keyed insert still
        # lands on itself: exactly one stored event
        st.insert(keyed, APP)
        stored = list(st.find(APP, EventFilter()))
        assert len(stored) == 1
        assert stored[0].event_id == keyed.event_id


class TestRemoteBreaker:
    def test_breaker_opens_fast_fails_and_recovers(self, store, monkeypatch):
        st, base = store
        monkeypatch.setenv("PIO_BREAKER_FAILURES", "2")
        monkeypatch.setenv("PIO_BREAKER_RESET_S", "5")
        clock = FakeClock()
        reset_resilience(clock=clock)  # fresh breakers on the fake clock
        with faults.inject(
            faults.FaultSpec("remote.send", "refuse")
        ) as plan:
            for _ in range(2):
                with pytest.raises(RemoteStorageError, match="unreachable"):
                    st.get("x", APP)
            assert plan.hits("remote.send") == 2
            # circuit open: the third op fails FAST, no socket attempt
            with pytest.raises(RemoteStorageError, match="circuit"):
                st.get("x", APP)
            assert plan.hits("remote.send") == 2
        # cooldown elapses on the injected clock; the dependency is back
        # (faults off): the half-open probe succeeds and the circuit closes
        clock.advance(5.5)
        assert st.get("no-such-event", APP) is None
        assert st.get("no-such-event", APP) is None  # closed: flows freely

    def test_http_error_responses_do_not_trip_the_breaker(
        self, store, monkeypatch
    ):
        st, base = store
        monkeypatch.setenv("PIO_BREAKER_FAILURES", "2")
        reset_resilience(clock=time.monotonic)
        # 404s are the server TALKING — dependency alive, breaker closed
        for _ in range(5):
            assert st.get("ghost", APP) is None
        assert st.get("ghost", APP) is None


class TestDeadlinePropagation:
    def test_expired_ambient_deadline_short_circuits_client_side(self, store):
        st, base = store
        clock = FakeClock()
        d = Deadline.after_ms(10, clock)
        clock.advance(1.0)
        with faults.inject(faults.FaultSpec("remote.send", "refuse")) as plan:
            with deadline_scope(d):
                with pytest.raises(DeadlineExceeded):
                    st.get("x", APP)
            # raised before any socket work: the wire was never touched
            assert plan.hits("remote.send") == 0

    def test_explicit_deadline_param_reaches_request(self, store):
        st, base = store
        clock = FakeClock()
        d = Deadline.after_ms(0, clock)
        with pytest.raises(DeadlineExceeded):
            _request(f"{base}/health", deadline=d)

    def test_live_deadline_header_is_forwarded(self, store):
        st, base = store
        # the server sees the header: an expired budget forged AT the
        # wire level (header forwarded by the client, remaining > 0
        # locally is impossible to fake) — instead verify end to end that
        # a generous budget flows through and the request succeeds
        d = Deadline.after_ms(30000)
        with deadline_scope(d):
            assert st.get("nope", APP) is None
