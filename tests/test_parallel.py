"""Distributed machinery on the 8-device virtual CPU mesh.

The analogue of the reference testing multi-node behavior on ``local[4]``
Spark (SURVEY §4): collectives, hybrid mesh construction, and mesh-sharded
ALS training are exercised with real multi-device sharding semantics — the
same annotations that drive ICI collectives on a pod slice.
"""

import jax
import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, als_train_coo
from predictionio_tpu.parallel import (
    MeshConfig,
    all_gather_rows,
    all_reduce_sum,
    create_mesh,
    hybrid_mesh,
    initialize_from_env,
    process_info,
    reduce_scatter_rows,
    ring_shift,
    sharded_matmul_allreduce,
)


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(MeshConfig((("data", 8),)))


@pytest.fixture(scope="module")
def mesh_2d():
    return create_mesh(MeshConfig((("data", 4), ("model", 2))))


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = np.arange(16, dtype=np.float32).reshape(16, 1)
        out = all_reduce_sum(x, mesh8, "data")
        # psum of 8 shards, each [2, 1]
        expect = x.reshape(8, 2, 1).sum(axis=0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_all_gather_rows(self, mesh8):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        out = all_gather_rows(x, mesh8, "data")
        np.testing.assert_allclose(np.asarray(out), x)

    def test_reduce_scatter_rows(self, mesh8):
        x = np.ones((16, 2), dtype=np.float32)
        out = reduce_scatter_rows(x, mesh8, "data")
        assert out.shape == (16, 2)
        np.testing.assert_allclose(np.asarray(out), 8.0 * x)

    def test_ring_shift(self, mesh8):
        # 8 shards of 1 row each; shifting by 1 rotates rows by one shard
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = np.asarray(ring_shift(x, mesh8, "data", shift=1))
        np.testing.assert_allclose(out.ravel(), np.roll(np.arange(8), 1))

    def test_sharded_matmul_allreduce(self, mesh8):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 16)).astype(np.float32)
        b = rng.normal(size=(16, 4)).astype(np.float32)
        out = sharded_matmul_allreduce(a, b, mesh8, "data")
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-5)


class TestDistributedInit:
    def test_noop_without_env(self):
        assert initialize_from_env({}) is False

    def test_process_info_single(self):
        assert process_info() == (0, 1)

    def test_hybrid_mesh_single_slice(self):
        m = hybrid_mesh({"data": 4, "model": 2})
        assert m.shape == {"data": 4, "model": 2}
        m2 = hybrid_mesh({"model": 2}, dcn_axes={"data": 4})
        assert tuple(m2.axis_names) == ("data", "model")
        assert m2.shape == {"data": 4, "model": 2}

    def test_hybrid_mesh_too_many_devices(self):
        with pytest.raises(ValueError):
            hybrid_mesh({"data": 64})


class TestDistributedALS:
    def _toy(self, seed=0):
        rng = np.random.default_rng(seed)
        n_users, n_items, nnz, rank = 96, 48, 2500, 6
        gt_u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
        gt_i = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
        users = rng.integers(0, n_users, size=nnz)
        items = rng.integers(0, n_items, size=nnz)
        ratings = ((gt_u[users] * gt_i[items]).sum(1) + 3.0).astype(np.float32)
        return users, items, ratings, n_users, n_items

    def test_data_parallel_matches_single_device(self, mesh8):
        users, items, ratings, nu, ni = self._toy()
        cfg = ALSConfig(rank=6, iterations=3, lambda_=0.05, seed=0)
        single = als_train_coo(users, items, ratings, nu, ni, cfg)
        sharded = als_train_coo(
            users, items, ratings, nu, ni, cfg, mesh=mesh8
        )
        np.testing.assert_allclose(
            np.asarray(single.user_factors),
            np.asarray(sharded.user_factors),
            rtol=2e-3, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(single.item_factors),
            np.asarray(sharded.item_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_model_sharded_factors(self, mesh_2d):
        users, items, ratings, nu, ni = self._toy(1)
        cfg = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0)
        single = als_train_coo(users, items, ratings, nu, ni, cfg)
        sharded = als_train_coo(
            users, items, ratings, nu, ni, cfg,
            mesh=mesh_2d, factor_sharding="model",
        )
        # factor tables live row-sharded over the model axis
        spec = sharded.item_factors.sharding.spec
        assert spec[0] == "model"
        np.testing.assert_allclose(
            np.asarray(single.user_factors),
            np.asarray(sharded.user_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_bad_factor_sharding_rejected(self, mesh8):
        users, items, ratings, nu, ni = self._toy()
        with pytest.raises(ValueError):
            als_train_coo(
                users, items, ratings, nu, ni,
                ALSConfig(rank=4, iterations=1),
                mesh=mesh8, factor_sharding="nope",
            )


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_entry(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        scores, idx = fn(*args)
        assert scores.shape == (8, 10) and idx.shape == (8, 10)
