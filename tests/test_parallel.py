"""Distributed machinery on the 8-device virtual CPU mesh.

The analogue of the reference testing multi-node behavior on ``local[4]``
Spark (SURVEY §4): collectives, hybrid mesh construction, and mesh-sharded
ALS training are exercised with real multi-device sharding semantics — the
same annotations that drive ICI collectives on a pod slice.
"""

import jax
import numpy as np
import pytest

# ~7 min of CPU-mesh collectives + sharded ALS: outside the tier-1 budget
pytestmark = pytest.mark.slow

from predictionio_tpu.ops.als import ALSConfig, als_train_coo
from predictionio_tpu.parallel import (
    MeshConfig,
    all_gather_rows,
    all_reduce_sum,
    create_mesh,
    hybrid_mesh,
    initialize_from_env,
    process_info,
    reduce_scatter_rows,
    ring_shift,
    sharded_matmul_allreduce,
)


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(MeshConfig((("data", 8),)))


@pytest.fixture(scope="module")
def mesh_2d():
    return create_mesh(MeshConfig((("data", 4), ("model", 2))))


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = np.arange(16, dtype=np.float32).reshape(16, 1)
        out = all_reduce_sum(x, mesh8, "data")
        # psum of 8 shards, each [2, 1]
        expect = x.reshape(8, 2, 1).sum(axis=0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_all_gather_rows(self, mesh8):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        out = all_gather_rows(x, mesh8, "data")
        np.testing.assert_allclose(np.asarray(out), x)

    def test_reduce_scatter_rows(self, mesh8):
        x = np.ones((16, 2), dtype=np.float32)
        out = reduce_scatter_rows(x, mesh8, "data")
        assert out.shape == (16, 2)
        np.testing.assert_allclose(np.asarray(out), 8.0 * x)

    def test_ring_shift(self, mesh8):
        # 8 shards of 1 row each; shifting by 1 rotates rows by one shard
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = np.asarray(ring_shift(x, mesh8, "data", shift=1))
        np.testing.assert_allclose(out.ravel(), np.roll(np.arange(8), 1))

    def test_sharded_matmul_allreduce(self, mesh8):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 16)).astype(np.float32)
        b = rng.normal(size=(16, 4)).astype(np.float32)
        out = sharded_matmul_allreduce(a, b, mesh8, "data")
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-5)


class TestDistributedInit:
    def test_noop_without_env(self):
        assert initialize_from_env({}) is False

    def test_process_info_single(self):
        assert process_info() == (0, 1)

    def test_hybrid_mesh_single_slice(self):
        m = hybrid_mesh({"data": 4, "model": 2})
        assert m.shape == {"data": 4, "model": 2}
        m2 = hybrid_mesh({"model": 2}, dcn_axes={"data": 4})
        assert tuple(m2.axis_names) == ("data", "model")
        assert m2.shape == {"data": 4, "model": 2}

    def test_hybrid_mesh_too_many_devices(self):
        with pytest.raises(ValueError):
            hybrid_mesh({"data": 64})


class TestDistributedALS:
    def _toy(self, seed=0):
        rng = np.random.default_rng(seed)
        n_users, n_items, nnz, rank = 96, 48, 2500, 6
        gt_u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
        gt_i = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
        users = rng.integers(0, n_users, size=nnz)
        items = rng.integers(0, n_items, size=nnz)
        ratings = ((gt_u[users] * gt_i[items]).sum(1) + 3.0).astype(np.float32)
        return users, items, ratings, n_users, n_items

    def test_data_parallel_matches_single_device(self, mesh8):
        users, items, ratings, nu, ni = self._toy()
        cfg = ALSConfig(rank=6, iterations=3, lambda_=0.05, seed=0)
        single = als_train_coo(users, items, ratings, nu, ni, cfg)
        sharded = als_train_coo(
            users, items, ratings, nu, ni, cfg, mesh=mesh8
        )
        np.testing.assert_allclose(
            np.asarray(single.user_factors),
            np.asarray(sharded.user_factors),
            rtol=2e-3, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(single.item_factors),
            np.asarray(sharded.item_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_model_sharded_factors(self, mesh_2d):
        users, items, ratings, nu, ni = self._toy(1)
        cfg = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0)
        single = als_train_coo(users, items, ratings, nu, ni, cfg)
        sharded = als_train_coo(
            users, items, ratings, nu, ni, cfg,
            mesh=mesh_2d, factor_sharding="model",
        )
        # factor tables live row-sharded over the model axis
        spec = sharded.item_factors.sharding.spec
        assert spec[0] == "model"
        np.testing.assert_allclose(
            np.asarray(single.user_factors),
            np.asarray(sharded.user_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_pallas_solve_under_mesh_matches_chunked(self, mesh8):
        """Round-3 lift of the single-device pallas restriction: the fused
        SPD solver runs per-device inside shard_map over the data axis.
        On this CPU mesh the kernel executes in interpret mode per shard —
        same code path shape as 8 real chips."""
        users, items, ratings, nu, ni = self._toy(2)
        chunked = als_train_coo(
            users, items, ratings, nu, ni,
            ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0,
                      solve_mode="chunked"),
            mesh=mesh8,
        )
        pallas = als_train_coo(
            users, items, ratings, nu, ni,
            ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0,
                      solve_mode="pallas"),
            mesh=mesh8,
        )
        np.testing.assert_allclose(
            np.asarray(chunked.user_factors),
            np.asarray(pallas.user_factors),
            rtol=2e-3, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(chunked.item_factors),
            np.asarray(pallas.item_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_pallas_solve_mesh_with_model_sharding(self, mesh_2d):
        """pallas solve + model-sharded factor tables compose: the solve
        shards over `data`, the tables over `model`."""
        users, items, ratings, nu, ni = self._toy(3)
        cfg = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0,
                        solve_mode="pallas")
        single = als_train_coo(
            users, items, ratings, nu, ni,
            ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0),
        )
        sharded = als_train_coo(
            users, items, ratings, nu, ni, cfg,
            mesh=mesh_2d, factor_sharding="model",
        )
        assert sharded.item_factors.sharding.spec[0] == "model"
        np.testing.assert_allclose(
            np.asarray(single.user_factors),
            np.asarray(sharded.user_factors),
            rtol=2e-3, atol=2e-4,
        )

    def test_model_sharding_memory_at_scale(self):
        """Scale-realistic sharding validation (round-3 VERDICT item 5):
        factor tables big enough that replication is the thing being
        avoided, row-sharded over ``model``; assert the per-device shard
        bytes match the sharding math exactly.

        Budget being validated (rank 48, f32): full tables are
        400k×48×4 + 80k×48×4 = 92 MB; sharded over model=4 each device
        holds (100k + 20k)×48×4 = 23 MB — ML-20M at rank 50 scales the
        same math to 138k users + 27k items (32 MB full, 8 MB/device on
        a 4-way model axis), and a 10M-user catalog (1.9 GB full) only
        fits a 16 GB chip next to the training workspace when sharded."""
        nu, ni, rank, nnz = 400_000, 80_000, 48, 200_000
        model = 4
        mesh = create_mesh(MeshConfig((("data", 2), ("model", model))))
        rng = np.random.default_rng(7)
        users = rng.integers(0, nu, size=nnz)
        items = rng.integers(0, ni, size=nnz)
        ratings = rng.normal(3.5, 1.0, size=nnz).astype(np.float32)
        factors = als_train_coo(
            users, items, ratings, nu, ni,
            ALSConfig(rank=rank, iterations=1, lambda_=0.1, seed=0),
            mesh=mesh, factor_sharding="model",
        )
        for table, rows in (
            (factors.user_factors, nu),
            (factors.item_factors, ni),
        ):
            assert table.sharding.spec[0] == "model"
            shards = table.addressable_shards
            # every device holds exactly one shard (replicated over data)
            assert len(shards) == 8
            for s in shards:
                assert s.data.shape == (rows // model, rank)
                assert s.data.nbytes == rows // model * rank * 4
        assert np.isfinite(np.asarray(factors.user_factors[:64])).all()

    def test_bad_factor_sharding_rejected(self, mesh8):
        users, items, ratings, nu, ni = self._toy()
        with pytest.raises(ValueError):
            als_train_coo(
                users, items, ratings, nu, ni,
                ALSConfig(rank=4, iterations=1),
                mesh=mesh8, factor_sharding="nope",
            )


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_entry(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        scores, idx = fn(*args)
        assert scores.shape == (8, 10) and idx.shape == (8, 10)
