"""Step checkpoint/resume + profiling hooks.

The reference retrains from scratch on any mid-train crash (SURVEY §5);
these tests pin the stronger contract: ALS resumes from the newest complete
checkpoint and produces the same factors as an uninterrupted run.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, als_train_coo
from predictionio_tpu.utils.profiling import StepTimer, device_trace
from predictionio_tpu.workflow.checkpoint import CheckpointManager


def toy_ratings(seed=0):
    rng = np.random.default_rng(seed)
    n_users, n_items, nnz = 60, 30, 1500
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1, 5, nnz).astype(np.float32)
    return users, items, ratings, n_users, n_items


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        tree = {"a": np.arange(6).reshape(2, 3), "nest": [np.ones(4), np.zeros(2)]}
        cm.save(3, tree, {"k": "v"})
        step, got, meta = cm.restore(like={"a": 0, "nest": [0, 0]})
        assert step == 3 and meta == {"k": "v"}
        np.testing.assert_array_equal(got["a"], tree["a"])
        np.testing.assert_array_equal(got["nest"][0], tree["nest"][0])

    def test_flat_restore_without_template(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.save(1, {"x": np.ones(3)})
        _, flat, _ = cm.restore()
        assert set(flat) == {"x"}

    def test_prune_keeps_newest(self, tmp_path):
        cm = CheckpointManager(str(tmp_path), keep=2)
        for s in (1, 2, 3, 4):
            cm.save(s, {"x": np.full(2, s)})
        assert cm.all_steps() == [3, 4]
        assert cm.latest_step() == 4

    def test_incomplete_checkpoint_ignored(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.save(1, {"x": np.ones(2)})
        # simulate a crash mid-save: step dir without the _COMPLETE marker
        os.makedirs(tmp_path / "step_2")
        (tmp_path / "step_2" / "arrays.npz").write_bytes(b"torn")
        assert cm.latest_step() == 1
        step, _, _ = cm.restore()
        assert step == 1

    def test_restore_empty_raises(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            cm.restore()

    def test_slash_in_key_rejected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        with pytest.raises(ValueError):
            cm.save(1, {"a/b": np.ones(1)})


class TestALSResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        users, items, ratings, nu, ni = toy_ratings()
        cfg = ALSConfig(rank=6, iterations=6, lambda_=0.05, seed=0)
        full = als_train_coo(users, items, ratings, nu, ni, cfg)

        # interrupted run: 3 iterations, checkpointing every step
        cm = CheckpointManager(str(tmp_path / "ck"))
        cfg3 = ALSConfig(rank=6, iterations=3, lambda_=0.05, seed=0)
        als_train_coo(users, items, ratings, nu, ni, cfg3,
                      checkpoint=cm, checkpoint_every=1)
        assert cm.latest_step() == 3

        # resumed run: picks up at step 3, finishes the remaining 3
        resumed = als_train_coo(users, items, ratings, nu, ni, cfg,
                                checkpoint=cm, checkpoint_every=1)
        np.testing.assert_allclose(
            np.asarray(full.user_factors),
            np.asarray(resumed.user_factors),
            rtol=1e-4, atol=1e-5,
        )
        assert cm.latest_step() == 6

    def test_stale_checkpoint_shape_mismatch_ignored(self, tmp_path):
        users, items, ratings, nu, ni = toy_ratings()
        cm = CheckpointManager(str(tmp_path / "ck"))
        cm.save(2, {"x": np.ones((5, 5)), "y": np.ones((4, 5))},
                {"rank": 5, "iteration": 2})
        cfg = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0)
        out = als_train_coo(users, items, ratings, nu, ni, cfg,
                            checkpoint=cm, checkpoint_every=2)
        assert out.user_factors.shape == (nu, 6)

    def test_stale_higher_step_falls_back_to_valid_lower_step(self, tmp_path):
        """After lowering cfg.iterations, a surviving higher-step checkpoint
        must not force a from-scratch retrain when an in-range step exists
        (ADVICE round-1: stale step > iterations blocked resume forever)."""
        users, items, ratings, nu, ni = toy_ratings()
        cm = CheckpointManager(str(tmp_path / "ck"), keep=10)
        cfg6 = ALSConfig(rank=6, iterations=6, lambda_=0.05, seed=0)
        als_train_coo(users, items, ratings, nu, ni, cfg6,
                      checkpoint=cm, checkpoint_every=1)
        assert cm.latest_step() == 6

        # rerun with iterations lowered to 4: step_4 must be resumed (a
        # no-op finish), not a full retrain from 0 blocked by step_5/6
        cfg4 = ALSConfig(rank=6, iterations=4, lambda_=0.05, seed=0)
        four = als_train_coo(users, items, ratings, nu, ni, cfg4,
                             checkpoint=cm, checkpoint_every=1)
        step, tree, _ = cm.restore(4, like={"x": 0, "y": 0})
        np.testing.assert_allclose(
            np.asarray(four.user_factors), tree["x"], rtol=1e-5, atol=1e-6
        )

    def test_corrupt_checkpoint_treated_as_absent(self, tmp_path):
        """An unreadable arrays.npz under a durable _COMPLETE marker (power
        loss torn write) must fall back to fresh training, not crash."""
        users, items, ratings, nu, ni = toy_ratings()
        cm = CheckpointManager(str(tmp_path / "ck"))
        cfg = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0)
        als_train_coo(users, items, ratings, nu, ni, cfg,
                      checkpoint=cm, checkpoint_every=1)
        # corrupt every saved step's arrays while keeping markers durable
        for step in cm.all_steps():
            (tmp_path / "ck" / f"step_{step}" / "arrays.npz").write_bytes(
                b"not-an-npz"
            )
        out = als_train_coo(users, items, ratings, nu, ni, cfg,
                            checkpoint=cm, checkpoint_every=0)
        assert np.isfinite(np.asarray(out.user_factors)).all()


class TestProfiling:
    def test_step_timer(self):
        t = StepTimer()
        with t.time("read"):
            pass
        t.record("train[0]", 1.5)
        t.record("train[0]", 0.5)
        s = t.summary()
        assert s["train[0]"]["count"] == 2
        assert s["train[0]"]["total_s"] == 2.0
        assert "read" in t.format_summary()

    def test_device_trace_noop_and_real(self, tmp_path):
        with device_trace(None):
            pass
        with device_trace(str(tmp_path / "prof")):
            import jax.numpy as jnp

            jnp.ones(4).sum().block_until_ready()

    def test_workflow_records_phases(self):
        from predictionio_tpu.workflow.context import WorkflowContext

        ctx = WorkflowContext()
        with ctx.timer.time("read"):
            pass
        assert "read" in ctx.timer.summary()

    def test_engine_train_times_phases(self):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from sample_engine import (
            Algo0, DataSource0, DSParams, IdParams, Preparator0, Serving0,
        )

        from predictionio_tpu.controller.engine import Engine, EngineParams
        from predictionio_tpu.workflow.context import WorkflowContext

        engine = Engine(DataSource0, Preparator0, Algo0, Serving0)
        ctx = WorkflowContext()
        engine.train(
            ctx,
            EngineParams(
                data_source_params=("", DSParams(id=1)),
                preparator_params=("", IdParams(id=1)),
                algorithm_params_list=[("", IdParams(id=1))],
            ),
        )
        phases = ctx.timer.summary()
        assert {"read", "prepare", "train[0]"} <= set(phases)


class TestCheckpointIdentity:
    def test_different_hyperparams_do_not_resume(self, tmp_path):
        # same shapes, different lambda: the checkpoint must be ignored
        users, items, ratings, nu, ni = toy_ratings()
        cm = CheckpointManager(str(tmp_path / "ck"))
        cfg_a = ALSConfig(rank=6, iterations=2, lambda_=0.05, seed=0)
        als_train_coo(users, items, ratings, nu, ni, cfg_a,
                      checkpoint=cm, checkpoint_every=1)
        cfg_b = ALSConfig(rank=6, iterations=2, lambda_=0.5, seed=0)
        fresh = als_train_coo(users, items, ratings, nu, ni, cfg_b)
        maybe_resumed = als_train_coo(users, items, ratings, nu, ni, cfg_b,
                                      checkpoint=cm, checkpoint_every=0)
        np.testing.assert_allclose(
            np.asarray(fresh.user_factors),
            np.asarray(maybe_resumed.user_factors),
            rtol=1e-5,
        )

    def test_multi_algo_namespacing(self, tmp_path, monkeypatch):
        # two ALS blocks in one engine: each gets its own checkpoint subdir
        import datetime as dt

        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
        from predictionio_tpu.storage import Event, get_registry

        get_registry(refresh=True)
        store = get_registry().get_events()
        store.init(3)
        rng = np.random.default_rng(0)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        store.write(
            [Event(event="rate", entity_type="user", entity_id=f"u{u}",
                   target_entity_type="item", target_entity_id=f"i{i}",
                   properties={"rating": float(r)}, event_time=t0)
             for u, i, r in zip(rng.integers(0, 20, 300),
                                rng.integers(0, 10, 300),
                                rng.uniform(1, 5, 300))],
            3,
        )
        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithmParams, RecDataSourceParams, engine_factory)
        from predictionio_tpu.workflow.context import WorkflowContext

        ctx = WorkflowContext()
        ctx.checkpoint_dir = str(tmp_path / "run-ck")
        ep = EngineParams(
            data_source_params=("", RecDataSourceParams(
                app_id=3, event_names=("rate",))),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(rank=4, num_iterations=2,
                                           lambda_=0.05, checkpoint_every=1)),
                ("als", ALSAlgorithmParams(rank=4, num_iterations=2,
                                           lambda_=0.9, seed=7,
                                           checkpoint_every=1)),
            ],
        )
        models = engine_factory().train(ctx, ep)
        assert (tmp_path / "run-ck" / "algo_0").exists()
        assert (tmp_path / "run-ck" / "algo_1").exists()
        # different hyperparams must produce different factors
        assert not np.allclose(models[0].user_factors, models[1].user_factors)
        get_registry(refresh=True)


def test_spawn_detached_reports_dead_child(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    # generous liveness window: the child must merely *die* within it, and
    # a loaded CI host can take >4 s just to reach the argparse failure
    monkeypatch.setenv("PIO_SPAWN_POLL_S", "60")
    from predictionio_tpu.tools.console import EXIT_FAIL, _spawn_detached

    rc = _spawn_detached("predictionio_tpu.tools.run_server",
                         ["--bogus-flag-that-does-not-exist"])
    assert rc == EXIT_FAIL
    logs = list((tmp_path / "logs").glob("*.log"))
    assert logs and logs[0].stat().st_size > 0
