"""Observability plane (``predictionio_tpu/obs``, docs/observability.md).

Five layers:

1. **Registry semantics**: histogram bucket math on the fixed log-scale
   buckets, percentile estimation, the cardinality bound's overflow
   collapse, and schema pinning (name reuse with a different kind/label
   set must raise).
2. **Exposition**: a golden Prometheus text document for a fixed
   registry, label escaping, and the parse round trip ``pio top`` and
   ``loadgen --scrape-metrics`` rely on.
3. **Tracing**: span parent/child structure on injected clocks, ring
   buffer bounds, header sanitization.
4. **Server wiring**: all three servers (query, event, storage) plus the
   dashboard serve ``GET /metrics`` in valid exposition format, and a
   single client-set ``X-PIO-Trace`` id is observable in the span dumps
   of BOTH the query server and the storage server for the same request
   — end-to-end through the remote storage client, and through replica
   failover after the primary dies (the ISSUE 4 acceptance proof).
5. **Instrumentation**: ServingStats percentiles (every pre-existing
   camelCase key preserved), MicroBatcher flush/queue metrics, train
   phase persistence, and the ``obs-*`` lint fixture twins.

Everything runs on injected clocks with zero wall-clock sleeps: the only
waiting anywhere is HTTP round trips on loopback.
"""

from __future__ import annotations

import math
import os
import re

import pytest
import requests

from predictionio_tpu.obs import expo
from predictionio_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    OVERFLOW_VALUE,
    percentile_from_buckets,
)
from predictionio_tpu.obs.trace import (
    TRACE_HEADER,
    SpanStore,
    Tracer,
    sanitize_trace_id,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


class FakeClock:
    """Injected monotonic clock: advances only when told."""

    def __init__(self, start: float = 1000.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


# ---------------------------------------------------------------------------
# 1. Registry semantics
# ---------------------------------------------------------------------------


class TestHistogramBucketMath:
    def test_default_buckets_are_log_scale(self):
        ratios = {
            round(b2 / b1, 6)
            for b1, b2 in zip(DEFAULT_BUCKETS, DEFAULT_BUCKETS[1:])
        }
        assert ratios == {2.0}
        assert DEFAULT_BUCKETS[0] == pytest.approx(0.0005)

    def test_cumulative_counts_and_sum(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=[1.0, 2.0, 4.0])
        for v in (0.5, 1.0, 1.5, 3.0, 100.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(106.0)
        # cumulative: <=1 -> 2 (0.5, 1.0 sits ON the bound), <=2 -> 3,
        # <=4 -> 4, +Inf -> 5
        assert snap["buckets"] == [
            (1.0, 2),
            (2.0, 3),
            (4.0, 4),
            (math.inf, 5),
        ]

    def test_percentile_interpolates_within_bucket(self):
        # 10 observations all in (1, 2]: p50 lands mid-bucket
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=[1.0, 2.0, 4.0])
        for _ in range(10):
            h.observe(1.5)
        assert h.percentile(0.5) == pytest.approx(1.5)
        assert h.percentile(1.0) == pytest.approx(2.0)

    def test_percentile_beyond_last_bucket_clamps(self):
        assert percentile_from_buckets([1.0, 2.0], [0, 0, 5], 0.99) == 2.0

    def test_percentile_empty_is_zero(self):
        assert percentile_from_buckets([1.0], [0, 0], 0.5) == 0.0

    def test_buckets_must_increase(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("bad", buckets=[2.0, 1.0])

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("c").inc(-1)

    def test_schema_pinning(self):
        reg = MetricsRegistry()
        reg.counter("x", labelnames=("a",))
        assert reg.counter("x", labelnames=("a",)) is reg.counter(
            "x", labelnames=("a",)
        )
        with pytest.raises(ValueError):
            reg.gauge("x")  # kind mismatch
        with pytest.raises(ValueError):
            reg.counter("x", labelnames=("b",))  # label schema mismatch

    def test_label_value_mismatch_raises(self):
        reg = MetricsRegistry()
        c = reg.counter("c", labelnames=("kind",))
        with pytest.raises(ValueError):
            c.inc(1, wrong="x")


class TestCardinalityBound:
    def test_overflow_collapse(self):
        reg = MetricsRegistry(max_label_sets=3)
        c = reg.counter("c", labelnames=("user",))
        for i in range(10):
            c.inc(1, user=f"u{i}")
        series = dict(c.series())
        # 3 real series + ONE overflow absorbing the other 7
        assert len(series) == 4
        assert series[(OVERFLOW_VALUE,)].value == 7
        # the overflow series keeps totals honest
        assert sum(ch.value for ch in series.values()) == 10


# ---------------------------------------------------------------------------
# 2. Exposition
# ---------------------------------------------------------------------------


class TestExposition:
    def test_golden_document(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_requests_total", "Requests", ("route",))
        c.inc(3, route="POST /queries.json")
        g = reg.gauge("pio_lag", "Lag")
        g.set(2.5)
        h = reg.histogram("pio_lat_seconds", "Latency", buckets=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        assert expo.render(reg) == (
            "# HELP pio_lag Lag\n"
            "# TYPE pio_lag gauge\n"
            "pio_lag 2.5\n"
            "# HELP pio_lat_seconds Latency\n"
            "# TYPE pio_lat_seconds histogram\n"
            'pio_lat_seconds_bucket{le="0.1"} 1\n'
            'pio_lat_seconds_bucket{le="1"} 2\n'
            'pio_lat_seconds_bucket{le="+Inf"} 3\n'
            "pio_lat_seconds_sum 5.55\n"
            "pio_lat_seconds_count 3\n"
            "# HELP pio_requests_total Requests\n"
            "# TYPE pio_requests_total counter\n"
            'pio_requests_total{route="POST /queries.json"} 3\n'
        )

    def test_label_escaping_round_trips(self):
        reg = MetricsRegistry()
        nasty = 'a"b\\c\nd'
        reg.counter("c", labelnames=("v",)).inc(1, v=nasty)
        parsed = expo.parse_text(expo.render(reg))
        assert parsed["c"] == [({"v": nasty}, 1.0)]

    def test_parse_round_trip(self):
        reg = MetricsRegistry()
        reg.gauge("g", labelnames=("a", "b")).set(7, a="x", b="y")
        h = reg.histogram("h", buckets=[1.0])
        h.observe(0.5)
        parsed = expo.parse_text(expo.render(reg))
        assert parsed["g"] == [({"a": "x", "b": "y"}, 7.0)]
        assert ({"le": "+Inf"}, 1.0) in parsed["h_bucket"]
        assert parsed["h_count"] == [({}, 1.0)]

    def test_nan_and_infinities_never_break_render(self):
        reg = MetricsRegistry()
        reg.gauge("g_nan").set(float("nan"))
        reg.gauge("g_ninf").set(float("-inf"))
        reg.gauge_callback("g_cb", lambda: float("nan"))
        text = expo.render(reg)  # must not raise — ever
        assert "g_nan NaN" in text
        assert "g_ninf -Inf" in text
        parsed = expo.parse_text(text)
        assert math.isnan(parsed["g_nan"][0][1])
        assert parsed["g_ninf"][0][1] == -math.inf

    def test_backslash_before_n_round_trips(self):
        # 'a\nb' with a LITERAL backslash then n: chained unescape would
        # corrupt it into a newline
        reg = MetricsRegistry()
        reg.counter("c", labelnames=("v",)).inc(1, v="a\\nb")
        parsed = expo.parse_text(expo.render(reg))
        assert parsed["c"] == [({"v": "a\\nb"}, 1.0)]

    def test_instrument_clear_drops_series(self):
        reg = MetricsRegistry()
        g = reg.gauge("g", labelnames=("phase",))
        g.set(1.0, phase="old")
        g.clear()
        g.set(2.0, phase="new")
        assert [key for key, _c in g.series()] == [("new",)]

    def test_callback_gauge_pulled_at_collect(self):
        state = {"v": 1}
        reg = MetricsRegistry()
        reg.gauge_callback("g", lambda: state["v"], labels={"dep": "x"})
        assert 'g{dep="x"} 1' in expo.render(reg)
        state["v"] = 9
        assert 'g{dep="x"} 9' in expo.render(reg)


# ---------------------------------------------------------------------------
# 3. Tracing
# ---------------------------------------------------------------------------


class TestTracer:
    def test_spans_on_injected_clocks(self):
        clock, wall = FakeClock(0.0), FakeClock(5000.0)
        tracer = Tracer("svc", clock=clock, wall=wall)
        with tracer.server_span("root", header_value="abc123") as root:
            clock.advance(0.25)
            with tracer.span("child", tags={"k": "v"}) as child:
                clock.advance(0.5)
            assert child.trace_id == "abc123"
        spans = tracer.store.dump()
        assert [s["name"] for s in spans] == ["child", "root"]
        child_s, root_s = spans
        assert root_s["traceId"] == child_s["traceId"] == "abc123"
        assert child_s["parentId"] == root_s["spanId"]
        assert root_s["durationMs"] == pytest.approx(750.0)
        assert child_s["durationMs"] == pytest.approx(500.0)
        assert child_s["tags"] == {"k": "v"}
        assert root_s["kind"] == "server"

    def test_error_spans_tagged(self):
        tracer = Tracer("svc", clock=FakeClock(), wall=FakeClock())
        with pytest.raises(RuntimeError):
            with tracer.server_span("boom"):
                raise RuntimeError("x")
        assert tracer.store.dump()[0]["error"] == "RuntimeError"

    def test_missing_header_mints_id(self):
        tracer = Tracer("svc", clock=FakeClock(), wall=FakeClock())
        with tracer.server_span("r", header_value=None) as ctx:
            pass
        assert re.fullmatch(r"[0-9a-f]{16}", ctx.trace_id)

    def test_sanitize(self):
        assert sanitize_trace_id("  ok-id_1.2  ") == "ok-id_1.2"
        assert sanitize_trace_id('ha"}\n{x') == "hax"
        assert sanitize_trace_id("") is None
        assert sanitize_trace_id("x" * 200) == "x" * 64

    def test_ring_buffer_bounds(self):
        store = SpanStore(capacity=3)
        for i in range(10):
            store.add({"traceId": "t", "i": i})
        assert [s["i"] for s in store.dump()] == [7, 8, 9]


# ---------------------------------------------------------------------------
# 4. Server wiring (the acceptance layer)
# ---------------------------------------------------------------------------

#: every exposition line is a comment or `name[{labels}] value`
_EXPO_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+)$"
)


def _assert_valid_exposition(text: str) -> dict:
    for line in text.rstrip("\n").splitlines():
        assert _EXPO_LINE.match(line), f"invalid exposition line: {line!r}"
    parsed = expo.parse_text(text)
    assert parsed, "no samples in exposition"
    return parsed


@pytest.fixture()
def registry(tmp_path):
    from predictionio_tpu.storage import StorageRegistry

    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})


def _storage_pair(tmp_path):
    """Primary (with changefeed) + tailing replica, background-started."""
    from predictionio_tpu.storage import MetadataStore, SqliteEventStore
    from predictionio_tpu.storage.changefeed import Changefeed
    from predictionio_tpu.storage.model_store import SqliteModelStore
    from predictionio_tpu.storage.oplog import OpLog
    from predictionio_tpu.storage.replica import StorageReplica
    from predictionio_tpu.storage.storage_server import StorageServer

    primary = StorageServer(
        "127.0.0.1", 0,
        SqliteEventStore(":memory:"), MetadataStore(":memory:"),
        SqliteModelStore(":memory:"),
    )
    primary.changefeed = Changefeed(
        OpLog(str(tmp_path / "oplog")),
        primary.events, primary.metadata, primary.models,
    )
    primary.start_background()
    replica = StorageReplica(
        "127.0.0.1", 0,
        SqliteEventStore(":memory:"), MetadataStore(":memory:"),
        SqliteModelStore(":memory:"),
        f"http://127.0.0.1:{primary.bound_port}",
        str(tmp_path / "replica_state"),
        catchup_wait_s=0.0,
    )
    replica.start_background()
    return primary, replica


class TestMetricsRoutes:
    def test_event_server_metrics(self, registry):
        from predictionio_tpu.api import EventServer, EventServerConfig

        srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=registry.get_events(),
            metadata=registry.get_metadata(),
        )
        srv.start_background()
        try:
            base = f"http://127.0.0.1:{srv.bound_port}"
            assert requests.get(base + "/").status_code == 200
            r = requests.get(base + "/metrics")
            assert r.status_code == 200
            assert r.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            parsed = _assert_valid_exposition(r.text)
            assert "pio_http_responses_total" in parsed
            assert "pio_http_request_seconds_count" in parsed
        finally:
            srv.shutdown()
            srv.server_close()

    def test_storage_server_and_replica_metrics(self, tmp_path):
        primary, replica = _storage_pair(tmp_path)
        try:
            base = f"http://127.0.0.1:{primary.bound_port}"
            from predictionio_tpu.storage import remote

            store = remote.RemoteEventStore(base)
            store.init(1)
            replica.catch_up()
            parsed = _assert_valid_exposition(
                requests.get(base + "/metrics").text
            )
            assert parsed["pio_changefeed_seq"][0][1] >= 1
            assert "pio_storage_op_seconds_count" in parsed
            rparsed = _assert_valid_exposition(
                requests.get(
                    f"http://127.0.0.1:{replica.bound_port}/metrics"
                ).text
            )
            assert rparsed["pio_replication_lag_ops"][0][1] == 0
        finally:
            primary.kill()
            replica.kill()

    def test_dashboard_metrics_and_train_runs(self, registry):
        from predictionio_tpu.tools.dashboard import (
            DashboardConfig,
            DashboardServer,
        )

        srv = DashboardServer(
            DashboardConfig(ip="127.0.0.1", port=0), registry
        )
        srv.start_background()
        try:
            base = f"http://127.0.0.1:{srv.bound_port}"
            _assert_valid_exposition(requests.get(base + "/metrics").text)
            assert requests.get(base + "/train_runs").status_code == 200
            assert (
                requests.get(base + "/train_runs.json").json() == []
            )
        finally:
            srv.shutdown()
            srv.server_close()


# -- the query-server end-to-end (needs a trained toy engine) ---------------


def _make_query_server(registry, remote_store, clock=None):
    """Train the sample engine and deploy it with a Serving whose
    supplement reads through ``remote_store`` — the realistic serve-time
    storage dependency the trace must follow."""
    import time

    from predictionio_tpu.controller import Engine, WorkflowParams
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.serving import QueryServer, ServerConfig

    from sample_engine import (
        Algo0,
        DataSource0,
        Preparator0,
        Query,
        Serving0,
    )
    from test_engine import make_params

    class TypedAlgo(Algo0):
        count = 0

        def query_class(self):
            return Query

    class RemoteReadingServing(Serving0):
        count = 0
        store = remote_store

        def supplement(self, query):
            if type(self).store is not None:
                type(self).store.get("missing-event", 1)
            return query

    engine = Engine(
        {"": DataSource0},
        {"": Preparator0},
        {"": TypedAlgo},
        {"": RemoteReadingServing},
    )
    run_train(
        engine, make_params(algo_ids=(11,)), registry,
        engine_id="default", engine_version="1",
        workflow_params=WorkflowParams(batch="obs-test"),
    )
    server = QueryServer(
        ServerConfig(ip="127.0.0.1", port=0, batch_wait_ms=0.0),
        engine,
        registry,
        clock=clock or time.monotonic,
    )
    server.start_background()
    return server


class TestTraceEndToEnd:
    """The ISSUE 4 acceptance: one client-set ``X-PIO-Trace`` id visible
    in the span dumps of the query server AND the storage server for the
    same request — and, across failover, in the replica's."""

    @pytest.fixture(autouse=True)
    def _fast_breaker(self, monkeypatch):
        from predictionio_tpu.storage import remote

        monkeypatch.setenv("PIO_BREAKER_FAILURES", "1")
        remote.reset_resilience()
        yield
        remote.reset_resilience()

    def test_trace_id_spans_query_and_storage_servers(
        self, registry, tmp_path
    ):
        from predictionio_tpu.storage import remote
        from predictionio_tpu.storage.event import Event

        primary, replica = _storage_pair(tmp_path)
        clock = FakeClock()
        server = None
        try:
            # injected clocks on every tracer in the chain: durations are
            # deterministic, nothing sleeps
            primary.tracer = Tracer(
                "storage-server", clock=FakeClock(), wall=FakeClock()
            )
            replica.tracer = Tracer(
                "storage-replica", clock=FakeClock(), wall=FakeClock()
            )
            store = remote.RemoteEventStore(
                f"pio+ha://127.0.0.1:{primary.bound_port},"
                f"127.0.0.1:{replica.bound_port}",
                timeout=10.0,
            )
            store.init(1)
            store.insert(
                Event(event="rate", entity_type="user", entity_id="u1"), 1
            )
            replica.catch_up()
            server = _make_query_server(registry, store, clock=clock)
            base = f"http://127.0.0.1:{server.bound_port}"

            tid = "e2e-trace-0001"
            r = requests.post(
                f"{base}/queries.json",
                json={"id": 1},
                headers={TRACE_HEADER: tid},
            )
            assert r.status_code == 200
            assert r.headers[TRACE_HEADER] == tid

            # query-server side: admission span + the remote client span
            qspans = server.tracer.store.for_trace(tid)
            names = {s["name"] for s in qspans}
            assert "POST /queries.json" in names
            assert "storage.GET" in names
            # the micro-batcher's queue-wait/device split rode the same
            # trace (captured across the thread hop)
            assert {"batch.queue-wait", "batch.device"} <= names
            # storage-server side: same trace id at admission, via the
            # X-PIO-Trace header the remote client forwarded
            pspans = primary.tracer.store.for_trace(tid)
            assert any(s["name"] == "GET /events" for s in pspans)
            assert all(s["service"] == "storage-server" for s in pspans)

            # -- failover leg: kill the primary; the same client trace id
            # must surface in the REPLICA's span dump
            primary.kill()
            tid2 = "e2e-trace-0002"
            r = requests.post(
                f"{base}/queries.json",
                json={"id": 2},
                headers={TRACE_HEADER: tid2},
            )
            assert r.status_code == 200
            rspans = replica.tracer.store.for_trace(tid2)
            assert any(s["name"] == "GET /events" for s in rspans)
            assert all(s["service"] == "storage-replica" for s in rspans)

            # /traces.json exposes the same dumps over HTTP, and the CLI
            # stitches them (pio trace)
            doc = requests.get(f"{base}/traces.json").json()
            assert doc["service"] == "query-server"
            assert any(s["traceId"] == tid for s in doc["spans"])
            from predictionio_tpu.obs.top import collect_trace, render_trace

            nodes = (
                f"127.0.0.1:{server.bound_port},"
                f"127.0.0.1:{replica.bound_port}"
            )
            stitched = collect_trace(tid2, nodes)
            assert {s["service"] for s in stitched} >= {
                "query-server",
                "storage-replica",
            }
            assert tid2 in render_trace(tid2, stitched)

            # -- jit telemetry rides the same exposition (ISSUE 8): the
            # process telemetry is bound to this server's registry, so a
            # compile observed anywhere in-process surfaces as series on
            # the query server's /metrics. Driven with a fake jitted fn
            # so the assertion is deterministic under any cache warmth.
            from predictionio_tpu.obs.profile import default_telemetry

            class _FakeJit:
                def __init__(self):
                    self._sigs = set()

                def _cache_size(self):
                    return len(self._sigs)

                def __call__(self, sig):
                    self._sigs.add(sig)
                    return sig

            fake = _FakeJit()
            default_telemetry().call("obs_e2e.fn", fake, "a")
            default_telemetry().call("obs_e2e.fn", fake, "b")
            text = requests.get(f"{base}/metrics").text
            parsed = _assert_valid_exposition(text)
            compiles = {
                labels.get("fn"): value
                for labels, value in parsed["pio_jit_compiles_total"]
            }
            assert compiles["obs_e2e.fn"] == 2.0
            retraces = {
                labels.get("fn"): value
                for labels, value in parsed["pio_jit_retraces_total"]
            }
            assert retraces["obs_e2e.fn"] == 1.0
            assert "pio_jit_compile_seconds_bucket" in parsed
            assert "pio_jit_cache_hits" in parsed
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            primary.kill()
            replica.kill()

    def test_feedback_delivery_carries_trace(self, registry, monkeypatch):
        """The feedback POST (pool thread) forwards the request's trace
        id: the Event Server's admission span joins the trace."""
        import dataclasses as dc

        from predictionio_tpu.api import EventServer, EventServerConfig
        from predictionio_tpu.storage.metadata import AccessKey

        md = registry.get_metadata()
        registry.get_events().init(1)
        from predictionio_tpu.storage.metadata import App

        app_id = md.app_insert(App(id=0, name="obs-app"))
        md.access_key_insert(AccessKey(key="k", appid=app_id, events=()))
        es = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=registry.get_events(),
            metadata=md,
        )
        es.start_background()
        server = None
        try:
            server = _make_query_server(registry, None)
            server.config = dc.replace(
                server.config,
                feedback=True,
                event_server_ip="127.0.0.1",
                event_server_port=es.bound_port,
                access_key="k",
            )
            tid = "feedback-trace-01"
            r = requests.post(
                f"http://127.0.0.1:{server.bound_port}/queries.json",
                json={"id": 3},
                headers={TRACE_HEADER: tid},
            )
            assert r.status_code == 200
            server._feedback_pool.shutdown(wait=True)  # drain delivery
            es_names = {
                s["name"] for s in es.tracer.store.for_trace(tid)
            }
            assert "POST /events.json" in es_names
            q_names = {
                s["name"] for s in server.tracer.store.for_trace(tid)
            }
            assert "serving.feedback" in q_names
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            es.shutdown()
            es.server_close()


# ---------------------------------------------------------------------------
# 5. Instrumentation details
# ---------------------------------------------------------------------------


class TestServingStats:
    def test_percentiles_and_preserved_keys(self):
        from predictionio_tpu.workflow.serving import ServingStats

        stats = ServingStats()
        for ms in (1, 1, 1, 1, 1, 1, 1, 1, 1, 500):
            stats.record_request(ms / 1000.0)
        stats.inc("shed")
        snap = stats.snapshot()
        # every pre-observability wire key survives
        for key in (
            "requests", "lastServingMs", "avgServingMs", "shed",
            "deadlineExpired", "feedbackSent", "feedbackFailures",
            "feedbackSkipped", "errorLogFailures", "errorLogSkipped",
        ):
            assert key in snap, key
        assert snap["requests"] == 10
        assert snap["shed"] == 1
        # the tail is no longer invisible: p50 stays ~1ms while p99
        # reflects the 500ms outlier the average smears away
        assert snap["p50Ms"] < 10
        assert snap["p99Ms"] > 100
        assert snap["p95Ms"] >= snap["p50Ms"]

    def test_unknown_counter_still_rejected(self):
        from predictionio_tpu.workflow.serving import ServingStats

        with pytest.raises(ValueError):
            ServingStats().inc("nope")


class TestBatcherMetrics:
    def test_flush_reasons_and_queue_metrics(self):
        from predictionio_tpu.workflow.batching import MicroBatcher

        reg = MetricsRegistry()
        mb = MicroBatcher(
            lambda items: [x * 2 for x in items],
            max_batch=4,
            max_wait_ms=0.0,
            metrics=reg,
        )
        try:
            assert mb.submit(21) == 42
        finally:
            mb.close()
        parsed = expo.parse_text(expo.render(reg))
        assert parsed["pio_batch_size_count"][0][1] == 1
        assert parsed["pio_batch_items_total"][0][1] == 1
        flushes = {
            labels["reason"]: v
            for labels, v in parsed["pio_batch_flush_total"]
        }
        assert sum(flushes.values()) == 1
        assert parsed["pio_batch_queue_wait_seconds_count"][0][1] == 1

    def test_failed_batches_still_counted(self):
        from predictionio_tpu.workflow.batching import MicroBatcher

        def boom(items):
            raise RuntimeError("device died")

        reg = MetricsRegistry()
        mb = MicroBatcher(boom, max_batch=1, max_wait_ms=0.0, metrics=reg)
        try:
            with pytest.raises(RuntimeError, match="device died"):
                mb.submit(1)
        finally:
            mb.close()
        parsed = expo.parse_text(expo.render(reg))
        # the erroring fleet is exactly when the batch signals matter:
        # the failed batch still counts as a flush AND as a failure
        assert parsed["pio_batch_failures_total"][0][1] == 1
        assert sum(v for _l, v in parsed["pio_batch_flush_total"]) == 1
        assert parsed["pio_batch_size_count"][0][1] == 1


class TestTrainPhases:
    def test_persisted_and_served(self, registry):
        from predictionio_tpu.utils.profiling import (
            TRAIN_PHASES_ENV_KEY,
            phases_from_env,
        )

        server = _make_query_server(registry, None)
        try:
            inst = server.deployment.instance
            assert TRAIN_PHASES_ENV_KEY in inst.env
            phases = phases_from_env(inst.env)
            assert {"read", "prepare", "train[0]"} <= set(phases)
            status = requests.get(
                f"http://127.0.0.1:{server.bound_port}/status.json"
            ).json()
            assert set(status["trainPhases"]) == set(phases)
            parsed = _assert_valid_exposition(
                requests.get(
                    f"http://127.0.0.1:{server.bound_port}/metrics"
                ).text
            )
            exported = {
                labels["phase"]
                for labels, _v in parsed["pio_train_phase_seconds"]
            }
            assert exported == set(phases)
        finally:
            server.shutdown()
            server.server_close()

    def test_reload_clears_stale_phase_series(self, registry):
        """A redeploy to an instance without phase data must not leave
        the old instance's gauges on /metrics."""
        import dataclasses as dc

        server = _make_query_server(registry, None)
        try:
            gauge = server.metrics.gauge(
                "pio_train_phase_seconds", labelnames=("phase",)
            )
            assert gauge.series()  # exported at deploy time
            server.deployment = dc.replace(
                server.deployment,
                instance=dc.replace(server.deployment.instance, env={}),
            )
            server._export_train_phases()
            assert gauge.series() == []
            status = requests.get(
                f"http://127.0.0.1:{server.bound_port}/status.json"
            ).json()
            assert "trainPhases" not in status
        finally:
            server.shutdown()
            server.server_close()

    def test_phases_from_env_tolerates_garbage(self):
        from predictionio_tpu.utils.profiling import (
            TRAIN_PHASES_ENV_KEY,
            phases_from_env,
        )

        assert phases_from_env(None) == {}
        assert phases_from_env({}) == {}
        assert phases_from_env({TRAIN_PHASES_ENV_KEY: "{not json"}) == {}


class TestLoadgenScrape:
    def test_digest_serving_metrics(self):
        from predictionio_tpu.tools.loadgen import digest_serving_metrics
        from predictionio_tpu.workflow.serving import ServingStats

        stats = ServingStats()
        for _ in range(100):
            stats.record_request(0.002)
        stats.inc("shed")
        digest = digest_serving_metrics(
            expo.parse_text(expo.render(stats.metrics))
        )
        assert digest["requests"] == 100
        assert 0 < digest["p50_ms"] < 10
        assert digest["p99_ms"] >= digest["p50_ms"]
        assert digest["shed"] == 1


class TestPioTop:
    def test_node_row_and_table(self, tmp_path):
        primary, replica = _storage_pair(tmp_path)
        try:
            from predictionio_tpu.obs.top import node_row, render_table

            rows = [
                node_row(f"127.0.0.1:{primary.bound_port}"),
                node_row(f"127.0.0.1:{replica.bound_port}"),
                node_row("127.0.0.1:1"),  # nothing listens here
            ]
            assert rows[0]["up"] and rows[1]["up"]
            assert rows[1]["lag"] == 0
            assert rows[2] == {"node": "127.0.0.1:1", "up": False}
            # garbled node specs render DOWN, never crash the table
            assert node_row("127.0.0.1:abc")["up"] is False
            table = render_table(rows)
            assert "NODE" in table and "LAG" in table and "DOWN" in table
        finally:
            primary.kill()
            replica.kill()

    def test_console_has_top_and_trace(self):
        from predictionio_tpu.tools.console import build_parser

        p = build_parser()
        args = p.parse_args(["top", "--nodes", "a:1", "--json"])
        assert args.command == "top" and args.nodes == "a:1"
        args = p.parse_args(["trace", "deadbeef", "--nodes", "a:1"])
        assert args.command == "trace" and args.trace_id == "deadbeef"


# ---------------------------------------------------------------------------
# obs-* lint fixtures (the round-5 fixture discipline, family D)
# ---------------------------------------------------------------------------


class TestObsLintFixtures:
    def _unsuppressed(self, path):
        from predictionio_tpu.lint import lint_file

        return [f for f in lint_file(path) if not f.suppressed]

    def test_bad_fixture_fires_exactly_intended_rule(self):
        path = os.path.join(FIXTURES, "obs_label_bad.py")
        findings = self._unsuppressed(path)
        assert [f.rule_id for f in findings] == ["obs-unbounded-label"], [
            (f.rule_id, f.line) for f in findings
        ]
        with open(path) as fh:
            marked = next(
                i for i, line in enumerate(fh, 1) if "BAD" in line
            )
        assert findings[0].line == marked

    def test_clean_twin_has_no_findings(self):
        findings = self._unsuppressed(
            os.path.join(FIXTURES, "obs_label_clean.py")
        )
        assert findings == [], [(f.rule_id, f.line) for f in findings]

    def test_interpolation_shapes_all_flagged(self):
        from predictionio_tpu.lint import lint_file

        src = (
            "def f(c, uid):\n"
            "    c.inc(1, user=f'u-{uid}')\n"
            "    c.inc(1, user='u-' + uid)\n"
            "    c.inc(1, user='u-%s' % uid)\n"
            "    c.inc(1, user='u-{}'.format(uid))\n"
            "    c.inc(1, user=str(uid))\n"
            "    c.labels(user=f'{uid}').inc()\n"
        )
        findings = [
            f
            for f in lint_file("x.py", source=src)
            if f.rule_id == "obs-unbounded-label"
        ]
        assert len(findings) == 6

    def test_bounded_shapes_clean(self):
        from predictionio_tpu.lint import lint_file

        src = (
            "def f(c, route, reg, breaker):\n"
            "    c.inc(1, route=route)\n"
            "    c.inc(1, route='POST /queries.json')\n"
            "    c.inc(2.0, amount=2.0)\n"
            "    reg.gauge_callback('g', lambda: 1, labels={'dep': 'es'})\n"
        )
        findings = [
            f
            for f in lint_file("x.py", source=src)
            if f.rule_id == "obs-unbounded-label"
        ]
        assert findings == []
