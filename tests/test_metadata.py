"""Metadata DAO + registry tests (reference: ES metadata backends, Storage)."""

import os

from predictionio_tpu.storage import (
    STATUS_COMPLETED,
    AccessKey,
    App,
    EngineManifest,
    Model,
    SqliteModelStore,
    StorageRegistry,
    new_engine_instance,
)
from predictionio_tpu.storage.metadata import STATUS_EVALCOMPLETED, EvaluationInstance
from predictionio_tpu.storage.event import utcnow


class TestApps:
    def test_crud(self, metadata_store):
        md = metadata_store
        app_id = md.app_insert(App(id=0, name="myapp", description="d"))
        assert app_id is not None
        got = md.app_get(app_id)
        assert got.name == "myapp"
        assert md.app_get_by_name("myapp").id == app_id
        # duplicate name rejected
        assert md.app_insert(App(id=0, name="myapp")) is None
        assert len(md.app_get_all()) == 1
        assert md.app_update(App(id=app_id, name="renamed"))
        assert md.app_get(app_id).name == "renamed"
        assert md.app_delete(app_id)
        assert md.app_get(app_id) is None


class TestAccessKeys:
    def test_generate_and_auth(self, metadata_store):
        md = metadata_store
        key = md.access_key_insert(AccessKey(key="", appid=7, events=("rate",)))
        assert key and len(key) > 20
        ak = md.access_key_get(key)
        assert ak.appid == 7
        assert ak.events == ("rate",)
        assert md.access_key_get_by_app(7)[0].key == key
        assert md.access_key_delete(key)
        assert md.access_key_get(key) is None


class TestEngineInstances:
    def test_lifecycle(self, metadata_store):
        md = metadata_store
        inst = new_engine_instance(
            engine_id="eid", engine_version="1", engine_variant="engine.json",
            engine_factory="pkg.Factory",
        )
        iid = md.engine_instance_insert(inst)
        got = md.engine_instance_get(iid)
        assert got.status == "INIT"
        # no completed instance yet
        assert (
            md.engine_instance_get_latest_completed("eid", "1", "engine.json")
            is None
        )
        import dataclasses

        md.engine_instance_update(
            dataclasses.replace(got, status=STATUS_COMPLETED)
        )
        latest = md.engine_instance_get_latest_completed(
            "eid", "1", "engine.json"
        )
        assert latest.id == iid

    def test_latest_completed_picks_newest(self, metadata_store):
        import dataclasses
        import datetime as dt

        md = metadata_store
        for offset in (0, 100):
            inst = new_engine_instance("e", "1", "v.json", "F")
            inst = dataclasses.replace(
                inst,
                status=STATUS_COMPLETED,
                start_time=inst.start_time + dt.timedelta(seconds=offset),
            )
            iid = md.engine_instance_insert(inst)
        assert md.engine_instance_get_latest_completed("e", "1", "v.json").id == iid


class TestEvaluationInstances:
    def test_insert_and_completed_list(self, metadata_store):
        md = metadata_store
        now = utcnow()
        iid = md.evaluation_instance_insert(
            EvaluationInstance(
                id="", status=STATUS_EVALCOMPLETED, start_time=now,
                end_time=now, evaluation_class="Eval1",
                evaluator_results="metric=0.5",
            )
        )
        assert md.evaluation_instance_get(iid).evaluation_class == "Eval1"
        assert [i.id for i in md.evaluation_instance_get_completed()] == [iid]


class TestManifests:
    def test_upsert_get(self, metadata_store):
        md = metadata_store
        m = EngineManifest(
            id="abc", version="1", name="my-engine",
            files=("a.py",), engine_factory="pkg.f",
        )
        md.manifest_update(m)
        got = md.manifest_get("abc", "1")
        assert got.name == "my-engine"
        assert got.files == ("a.py",)
        assert md.manifest_get("abc", "2") is None


class TestModelStore:
    def test_roundtrip(self):
        ms = SqliteModelStore(":memory:")
        ms.insert(Model(id="m1", models=b"\x00" * 1000))
        assert ms.get("m1").models == b"\x00" * 1000
        ms.delete("m1")
        assert ms.get("m1") is None

    def test_localfs(self, tmp_path):
        from predictionio_tpu.storage import LocalFSModelStore

        ms = LocalFSModelStore(str(tmp_path))
        ms.insert(Model(id="a/b", models=b"xyz"))
        assert ms.get("a/b").models == b"xyz"
        ms.delete("a/b")
        assert ms.get("a/b") is None


class TestRegistry:
    def test_default_wiring(self, tmp_path):
        reg = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
        assert reg.get_metadata() is reg.get_metadata()
        status = reg.verify_all_data_objects()
        assert status == {"metadata": True, "modeldata": True, "eventdata": True}
        assert os.path.exists(os.path.join(str(tmp_path), "events.db"))

    def test_env_source_wiring(self, tmp_path):
        env = {
            "PIO_STORAGE_SOURCES_MAIN_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_MAIN_PATH": str(tmp_path / "main"),
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "fs"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MAIN",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MAIN",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
        reg = StorageRegistry(env=env)
        from predictionio_tpu.storage import LocalFSModelStore

        assert isinstance(reg.get_models(), LocalFSModelStore)
        assert reg.verify_all_data_objects()["eventdata"] is True

    def test_bad_source_reference(self, tmp_path):
        from predictionio_tpu.storage import StorageError

        env = {
            "PIO_STORAGE_SOURCES_A_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_A_PATH": str(tmp_path),
            "PIO_STORAGE_SOURCES_B_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_B_PATH": str(tmp_path),
        }
        reg = StorageRegistry(env=env)
        import pytest

        with pytest.raises(StorageError):
            reg.get_metadata()  # ambiguous without REPOSITORIES binding
