"""Sequence-recommendation engine: transformer next-item prediction.

Toy data with a deterministic transition pattern (item i is always followed
by item i+1 mod V) — the trained model must put the correct next item in its
top predictions, and the whole DASE chain must run through the Engine.
"""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.models.sequencerec import (
    PreparedData,
    Query,
    SeqDataSource,
    SeqDataSourceParams,
    SeqPreparator,
    SeqPreparatorParams,
    SeqRecAlgorithm,
    SeqRecAlgorithmParams,
    TrainingData,
    engine_factory,
)
from predictionio_tpu.storage import Event, get_registry
from predictionio_tpu.workflow.context import WorkflowContext


V = 12  # vocabulary of items i0..i11


def cyclic_training_data(n_users=30, length=40, seed=0):
    rng = np.random.default_rng(seed)
    users, seqs = [], []
    for u in range(n_users):
        start = int(rng.integers(0, V))
        seqs.append([f"i{(start + t) % V}" for t in range(length)])
        users.append(f"u{u}")
    return TrainingData(user_ids=users, sequences=seqs)


@pytest.fixture(scope="module")
def trained():
    td = cyclic_training_data()
    pd = SeqPreparator(SeqPreparatorParams(seq_len=16, window_stride=8)).prepare(
        None, td
    )
    algo = SeqRecAlgorithm(
        SeqRecAlgorithmParams(
            d_model=32, n_heads=2, n_layers=2, steps=250, batch_size=32,
            learning_rate=3e-3, seed=0,
        )
    )
    model = algo.train(None, pd)
    return algo, model


class TestPreparator:
    def test_windows_and_padding(self):
        td = TrainingData(
            user_ids=["a", "b"],
            sequences=[["x", "y", "z"], ["y"]],
        )
        pd = SeqPreparator(SeqPreparatorParams(seq_len=4)).prepare(None, td)
        assert pd.windows.shape[1] == 5
        # short history is left-padded with the PAD id
        assert pd.windows[0, 0] == pd.pad_id
        # single-item user contributes recents but no window
        assert pd.user_recent["b"] == [pd.item_map["y"]]

    def test_empty_histories_rejected(self):
        td = TrainingData(user_ids=["a"], sequences=[["x"]])
        with pytest.raises(ValueError):
            SeqPreparator().prepare(None, td)


class TestModelQuality:
    def test_learns_cycle(self, trained):
        algo, model = trained
        hits = 0
        for start in range(V):
            recent = tuple(f"i{(start + t) % V}" for t in range(8))
            res = algo.predict(model, Query(recent_items=recent, num=3))
            want = f"i{(start + 8) % V}"
            got = [s.item for s in res.item_scores]
            hits += want in got
        assert hits >= 10, f"only {hits}/12 cycle continuations in top-3"

    def test_user_history_query(self, trained):
        algo, model = trained
        res = algo.predict(model, Query(user="u0", num=5))
        assert len(res.item_scores) == 5
        # never recommends items in the user's recent window context? at
        # minimum: scores are finite and sorted descending
        scores = [s.score for s in res.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty(self, trained):
        algo, model = trained
        assert algo.predict(model, Query(user="nobody")).item_scores == ()

    def test_sanity_check(self, trained):
        _, model = trained
        model.sanity_check()


class TestSequenceParallelTraining:
    def test_ring_schedule_trains(self):
        from predictionio_tpu.parallel.mesh import MeshConfig

        td = cyclic_training_data(n_users=8, length=20)
        pd = SeqPreparator(SeqPreparatorParams(seq_len=8)).prepare(None, td)
        ctx = WorkflowContext(mesh_config=MeshConfig((("seq", 8),)))
        algo = SeqRecAlgorithm(
            SeqRecAlgorithmParams(
                d_model=16, n_heads=2, n_layers=1, steps=5, schedule="ring"
            )
        )
        model = algo.train(ctx, pd)
        model.sanity_check()


class TestEngineIntegration:
    def test_datasource_orders_by_time(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
        get_registry(refresh=True)
        store = get_registry().get_events()
        store.init(7)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        # insert out of order; sequence must come back time-ordered
        for i in [2, 0, 1]:
            store.insert(
                Event(event="view", entity_type="user", entity_id="u1",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      event_time=t0 + dt.timedelta(minutes=i)),
                7,
            )
        td = SeqDataSource(SeqDataSourceParams(app_id=7)).read_training(None)
        assert td.sequences[td.user_ids.index("u1")] == ["i0", "i1", "i2"]
        get_registry(refresh=True)

    def test_engine_train_and_eval_chain(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
        get_registry(refresh=True)
        store = get_registry().get_events()
        store.init(9)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        for u in range(6):
            for t in range(12):
                store.insert(
                    Event(event="view", entity_type="user",
                          entity_id=f"u{u}",
                          target_entity_type="item",
                          target_entity_id=f"i{(u + t) % 6}",
                          event_time=t0 + dt.timedelta(minutes=t)),
                    9,
                )
        engine = engine_factory()
        algo_params = SeqRecAlgorithmParams(
            d_model=16, n_heads=2, n_layers=1, steps=10)
        ep = EngineParams(
            data_source_params=("", SeqDataSourceParams(app_id=9)),
            preparator_params=("", SeqPreparatorParams(seq_len=8)),
            algorithm_params_list=[("", algo_params)],
        )
        ctx = WorkflowContext()
        models = engine.train(ctx, ep)
        assert len(models) == 1
        algo = SeqRecAlgorithm(algo_params)
        preds = algo.predict(
            models[0], Query(recent_items=("i0", "i1"), num=3)
        )
        assert len(preds.item_scores) <= 3
        get_registry(refresh=True)


class TestWindowTail:
    def test_tail_window_anchored(self):
        # stride not dividing the history: newest items must appear
        td = TrainingData(
            user_ids=["a"],
            sequences=[[f"x{i}" for i in range(96)]],
        )
        pd = SeqPreparator(
            SeqPreparatorParams(seq_len=64, window_stride=32)
        ).prepare(None, td)
        last = pd.item_map["x95"]
        assert (pd.windows == last).any(), "newest interaction not in any window"

    def test_device_params_not_pickled(self, trained):
        import pickle

        _, model = trained
        model.device_params()  # populate cache
        blob = pickle.dumps(model)
        clone = pickle.loads(blob)
        assert "_device_params" not in clone.__dict__


def test_predicted_result_wire_shape():
    """Serving JSON must be the reference's camelCase itemScores — shared
    with every recommender template via models.wire."""
    from predictionio_tpu.models.sequencerec import ItemScore, PredictedResult
    from predictionio_tpu.workflow.serving import encode_result

    r = PredictedResult(item_scores=(ItemScore(item="i1", score=0.5),))
    assert encode_result(r) == {
        "itemScores": [{"item": "i1", "score": 0.5}]
    }


def test_flash_impl_pallas_trains_equivalently():
    """flash_impl="pallas" must reproduce the default (XLA) training to
    float tolerance — the kernel changes blocking, never math."""
    import numpy as np

    from predictionio_tpu.models.sequencerec import (
        SeqPreparator,
        SeqPreparatorParams,
        SeqRecAlgorithm,
        SeqRecAlgorithmParams,
        TrainingData,
    )

    seqs = [[f"i{(u + j) % 9}" for j in range(12)] for u in range(6)]
    td = TrainingData(
        user_ids=[f"u{u}" for u in range(6)], sequences=seqs
    )
    pd = SeqPreparator(SeqPreparatorParams(seq_len=8)).prepare(None, td)
    out = {}
    for impl in ("xla", "pallas"):
        model = SeqRecAlgorithm(
            SeqRecAlgorithmParams(
                d_model=16, n_heads=2, n_layers=1, steps=3,
                batch_size=4, seed=5, flash_impl=impl,
            )
        ).train(None, pd)
        out[impl] = model.params
    for key in ("embed", "pos"):
        np.testing.assert_allclose(
            np.asarray(out["xla"][key]), np.asarray(out["pallas"][key]),
            rtol=1e-3, atol=1e-4,
        )
