"""Fast chaos smoke suite for the serving/ingestion resilience layer.

The ISSUE-2 acceptance battery, proven deterministically: every timing-
sensitive behavior (breaker cooldown, retry schedule) runs on injected
clocks and no-op sleeps — there is not a single wall-clock sleep in this
file, so the whole fault suite rides inside the tier-1 budget.

The query-server tests run against a STUB deployment (no training, no
jax): ``QueryServer`` takes a prebuilt ``Deployment``, so the resilience
machinery is exercised through real HTTP round trips while the model
plane is a two-line echo algorithm.
"""

import json
import threading

import pytest
import requests

from predictionio_tpu.api.event_server import EventServer, EventServerConfig
from predictionio_tpu.storage import (
    AccessKey,
    App,
    MetadataStore,
    SqliteEventStore,
)
from predictionio_tpu.storage.event import idempotency_event_id, utcnow
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.storage.metadata import STATUS_COMPLETED, EngineInstance
from predictionio_tpu.testing import faults
from predictionio_tpu.utils.resilience import (
    CircuitBreaker,
    RetryPolicy,
)
from predictionio_tpu.workflow.serving import (
    Deployment,
    QueryServer,
    ServerConfig,
)

from test_resilience import FakeClock


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# Stub model plane
# ---------------------------------------------------------------------------


class EchoAlgo:
    """predict = identity; batch_predict counts device dispatches."""

    def __init__(self, on_predict=None):
        self.dispatches = 0
        self.on_predict = on_predict

    def query_class(self):
        return None

    def predict(self, model, query):
        if self.on_predict is not None:
            self.on_predict()
        return {"echo": query}

    def batch_predict(self, model, indexed):
        self.dispatches += 1
        return [(pos, {"echo": q}) for pos, q in indexed]


class PassServing:
    def supplement(self, query):
        return query

    def serve(self, query, predictions):
        return predictions[0]


def _deployment(algo):
    now = utcnow()
    inst = EngineInstance(
        id="inst-chaos", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="chaos", engine_version="1",
        engine_variant="engine.json", engine_factory="stub.Factory",
    )
    return Deployment(
        instance=inst, engine_params=None, algorithms=[algo],
        models=[None], serving=PassServing(),
    )


def _server(algo=None, clock=None, **cfg):
    """A QueryServer over the stub deployment; retries never sleep."""
    algo = algo or EchoAlgo()
    clock = clock or FakeClock()
    cfg.setdefault("batching", False)
    config = ServerConfig(ip="127.0.0.1", port=0, **cfg)
    srv = QueryServer(
        config,
        engine=None,
        registry=None,
        deployment=_deployment(algo),
        clock=clock,
        retry_policy=RetryPolicy(attempts=2, sleep=lambda s: None),
        feedback_breaker=CircuitBreaker(
            "event-server", failure_threshold=2, reset_timeout_s=10.0,
            clock=clock,
        ),
        error_log_breaker=CircuitBreaker(
            "error-log", failure_threshold=2, reset_timeout_s=10.0,
            clock=clock,
        ),
        reload_breaker=CircuitBreaker(
            "reload", failure_threshold=2, reset_timeout_s=10.0, clock=clock,
        ),
    )
    srv.start_background()
    return srv, f"http://127.0.0.1:{srv.bound_port}", algo, clock


def _close(srv):
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:
        pass


class _Sink:
    """Tiny always-201 HTTP sink (a healthy Event Server stand-in)."""

    def __enter__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        hits = self.hits = []

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                hits.append(self.path)
                self.send_response(201)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self._srv.server_address[1]}/events.json"
        return self

    def __exit__(self, *exc):
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# Load shedding (bounded admission)
# ---------------------------------------------------------------------------


class TestLoadShedding:
    def test_overload_sheds_503_with_retry_after(self):
        entered = threading.Semaphore(0)
        release = threading.Event()

        def block():
            entered.release()
            assert release.wait(timeout=30)

        srv, base, _, _ = _server(algo=EchoAlgo(on_predict=block), max_queue=2)
        try:
            results = []

            def post():
                results.append(
                    requests.post(f"{base}/queries.json", json={"q": 1},
                                  timeout=30)
                )

            workers = [threading.Thread(target=post) for _ in range(2)]
            for w in workers:
                w.start()
            # both requests are INSIDE predict (admitted, occupying the
            # whole queue) before the third arrives — deterministic
            assert entered.acquire(timeout=10)
            assert entered.acquire(timeout=10)

            shed = requests.post(f"{base}/queries.json", json={"q": 3},
                                 timeout=10)
            assert shed.status_code == 503
            assert "Retry-After" in shed.headers
            assert int(shed.headers["Retry-After"]) >= 1

            release.set()
            for w in workers:
                w.join(timeout=30)
            assert [r.status_code for r in results] == [200, 200]
            assert srv.stats.shed == 1
            # admission slots were released: the server accepts again
            ok = requests.post(f"{base}/queries.json", json={"q": 4},
                               timeout=10)
            assert ok.status_code == 200
        finally:
            release.set()
            _close(srv)

    def test_zero_max_queue_disables_shedding(self):
        srv, base, _, _ = _server(max_queue=0)
        try:
            assert requests.post(f"{base}/queries.json", json={},
                                 timeout=10).status_code == 200
            assert srv.stats.shed == 0
        finally:
            _close(srv)

    def test_env_knob_sets_the_cap(self, monkeypatch):
        monkeypatch.setenv("PIO_SERVING_MAX_QUEUE", "17")
        srv, _, _, _ = _server()  # max_queue=None → env
        try:
            assert srv._max_queue == 17
        finally:
            _close(srv)


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


class TestDeadlines:
    def test_expired_deadline_short_circuits_before_device_dispatch(self):
        algo = EchoAlgo()
        srv, base, _, _ = _server(algo=algo, batching=True)
        try:
            r = requests.post(
                f"{base}/queries.json", json={"q": 1},
                headers={"X-PIO-Deadline-Ms": "0"}, timeout=10,
            )
            assert r.status_code == 504
            assert "deadline" in r.json()["message"]
            assert r.json()["stage"] == "admission"  # caught at the door
            # the whole point: the expired query never reached the device
            assert algo.dispatches == 0
            assert srv.stats.deadline_expired == 1

            # a live budget flows through normally
            r = requests.post(
                f"{base}/queries.json", json={"q": 2},
                headers={"X-PIO-Deadline-Ms": "30000"}, timeout=10,
            )
            assert r.status_code == 200
            assert algo.dispatches == 1
        finally:
            _close(srv)

    def test_no_header_means_no_deadline(self):
        srv, base, _, _ = _server()
        try:
            assert requests.post(f"{base}/queries.json", json={},
                                 timeout=10).status_code == 200
            assert srv.stats.deadline_expired == 0
        finally:
            _close(srv)

    def test_malformed_header_degrades_to_no_deadline(self):
        srv, base, _, _ = _server()
        try:
            r = requests.post(
                f"{base}/queries.json", json={},
                headers={"X-PIO-Deadline-Ms": "soon-ish"}, timeout=10,
            )
            assert r.status_code == 200
        finally:
            _close(srv)


# ---------------------------------------------------------------------------
# Breaker + degraded mode (feedback plane down, serving stays up)
# ---------------------------------------------------------------------------


class TestBreakerAndDegradedMode:
    def test_breaker_opens_then_recovers_via_half_open_probe(self):
        srv, base, _, clock = _server(feedback=True)
        data = {"event": "predict", "idempotencyKey": "k"}
        try:
            with faults.inject(faults.FaultSpec("serving.feedback", "refuse")):
                # threshold=2 deliveries (each internally retried twice)
                srv._deliver_feedback("http://127.0.0.1:1/events.json", data)
                srv._deliver_feedback("http://127.0.0.1:1/events.json", data)
                assert srv.stats.feedback_failures == 2
                assert srv.feedback_breaker.state == CircuitBreaker.OPEN
                assert srv.degraded

                # while open: delivery is SKIPPED (no attempt, counted)
                srv._deliver_feedback("http://127.0.0.1:1/events.json", data)
                assert srv.stats.feedback_skipped == 1
                assert srv.stats.feedback_failures == 2

            # cooldown elapses on the injected clock → half-open; the
            # dependency is back (fault deactivated, healthy sink): the
            # probe succeeds and closes the circuit
            clock.advance(10.5)
            assert srv.feedback_breaker.state == CircuitBreaker.HALF_OPEN
            with _Sink() as sink:
                srv._deliver_feedback(sink.url, data)
            assert srv.feedback_breaker.state == CircuitBreaker.CLOSED
            assert srv.stats.feedback_sent == 1
            assert not srv.degraded
        finally:
            _close(srv)

    def test_keeps_answering_degraded_while_event_server_down(self):
        srv, base, _, _ = _server(
            feedback=True, event_server_ip="127.0.0.1",
            event_server_port=1, access_key="K",
        )
        try:
            with faults.inject(faults.FaultSpec("serving.feedback", "refuse")):
                # trip the breaker deterministically (synchronous path)
                url = "http://127.0.0.1:1/events.json"
                srv._deliver_feedback(url, {"event": "predict"})
                srv._deliver_feedback(url, {"event": "predict"})
                assert srv.feedback_breaker.state == CircuitBreaker.OPEN

                # queries still answer from the resident model
                r = requests.post(f"{base}/queries.json", json={"q": 9},
                                  timeout=10)
                assert r.status_code == 200
                assert r.json()["echo"] == {"q": 9}

                # ...and the status surfaces say so, on both routes
                js = requests.get(
                    f"{base}/", headers={"Accept": "application/json"},
                    timeout=10,
                ).json()
                assert js["degraded"] is True
                assert js["status"] == "degraded"
                assert js["breakers"]["eventServer"]["state"] == "open"
                js2 = requests.get(f"{base}/status.json", timeout=10).json()
                assert js2["degraded"] is True
                html = requests.get(f"{base}/", timeout=10)
                assert "text/html" in html.headers["Content-Type"]
                assert "Degraded" in html.text
        finally:
            _close(srv)

    def test_status_json_counts_shed_and_deadline(self):
        srv, base, _, _ = _server()
        try:
            requests.post(
                f"{base}/queries.json", json={},
                headers={"X-PIO-Deadline-Ms": "0"}, timeout=10,
            )
            js = requests.get(f"{base}/status.json", timeout=10).json()
            assert js["stats"]["deadlineExpired"] == 1
            assert js["stats"]["shed"] == 0
            assert js["maxQueue"] == srv._max_queue
            assert set(js["breakers"]) == {"eventServer", "errorLog", "reload"}
        finally:
            _close(srv)

    def test_error_log_breaker_stops_an_error_storm(self):
        srv, base, _, _ = _server(log_url="http://127.0.0.1:1/log")
        try:
            with faults.inject(
                faults.FaultSpec("serving.error_log", "refuse")
            ):
                # drive the delivery function synchronously (the pool is
                # asynchronous in production; determinism wins here)
                for _ in range(3):
                    try:
                        srv.error_log_breaker.call(
                            srv._post_json, "serving.error_log",
                            "http://127.0.0.1:1/log", {"m": 1},
                        )
                    except Exception:
                        pass
                assert srv.error_log_breaker.state == CircuitBreaker.OPEN
                assert srv.degraded
        finally:
            _close(srv)


# ---------------------------------------------------------------------------
# Event Server idempotency keys
# ---------------------------------------------------------------------------


class TestIdempotencyKey:
    @pytest.fixture()
    def ev(self):
        events = SqliteEventStore(":memory:")
        md = MetadataStore(":memory:")
        app_id = md.app_insert(App(id=0, name="chaosapp"))
        md.access_key_insert(AccessKey(key="CK", appid=app_id, events=[]))
        events.init(app_id)
        srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0), events, md
        )
        srv.start_background()
        yield f"http://127.0.0.1:{srv.bound_port}", events, app_id
        srv.shutdown()
        srv.server_close()

    @staticmethod
    def _event(key=None, **over):
        data = {
            "event": "rate", "entityType": "user", "entityId": "u1",
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 5},
        }
        if key is not None:
            data["idempotencyKey"] = key
        data.update(over)
        return data

    def test_duplicate_post_same_key_inserts_exactly_once(self, ev):
        base, events, app_id = ev
        url = f"{base}/events.json?accessKey=CK"
        r1 = requests.post(url, json=self._event(key="req-1"), timeout=10)
        r2 = requests.post(url, json=self._event(key="req-1"), timeout=10)
        assert r1.status_code == r2.status_code == 201
        assert r1.json()["eventId"] == r2.json()["eventId"]
        stored = list(events.find(app_id, EventFilter(event_names=["rate"])))
        assert len(stored) == 1
        assert stored[0].event_id == idempotency_event_id(app_id, "req-1")

    def test_different_keys_insert_separately(self, ev):
        base, events, app_id = ev
        url = f"{base}/events.json?accessKey=CK"
        assert requests.post(url, json=self._event(key="a"),
                             timeout=10).status_code == 201
        assert requests.post(url, json=self._event(key="b"),
                             timeout=10).status_code == 201
        assert len(list(events.find(app_id))) == 2

    def test_key_does_not_leak_into_stored_properties(self, ev):
        base, events, app_id = ev
        url = f"{base}/events.json?accessKey=CK"
        requests.post(url, json=self._event(key="leak-check"), timeout=10)
        stored = list(events.find(app_id))[0]
        assert "idempotencyKey" not in stored.properties.to_dict()

    def test_bad_key_is_a_400(self, ev):
        base, _, _ = ev
        url = f"{base}/events.json?accessKey=CK"
        r = requests.post(url, json=self._event(key=""), timeout=10)
        assert r.status_code == 400
        r = requests.post(url, json=self._event(key=7), timeout=10)
        assert r.status_code == 400

    def test_batch_route_dedupes_keyed_events(self, ev):
        base, events, app_id = ev
        url = f"{base}/batches/events.json?accessKey=CK"
        batch = [self._event(key="dup"), self._event(key="dup"),
                 self._event()]
        r = requests.post(url, json=batch, timeout=10)
        assert r.status_code == 200
        results = r.json()
        assert [e["status"] for e in results] == [201, 201, 201]
        assert results[0]["eventId"] == results[1]["eventId"]
        # two distinct rows: the deduped pair + the unkeyed event
        assert len(list(events.find(app_id))) == 2

    def test_explicit_event_id_wins_over_key(self, ev):
        base, events, app_id = ev
        url = f"{base}/events.json?accessKey=CK"
        r = requests.post(
            url, json=self._event(key="k", eventId="explicit-1"), timeout=10
        )
        assert r.json()["eventId"] == "explicit-1"


# ---------------------------------------------------------------------------
# Storage server health parity
# ---------------------------------------------------------------------------


class TestStorageServerHealth:
    @pytest.fixture()
    def storage(self):
        from predictionio_tpu.storage.model_store import SqliteModelStore
        from predictionio_tpu.storage.storage_server import StorageServer

        srv = StorageServer(
            "127.0.0.1", 0, SqliteEventStore(":memory:"),
            MetadataStore(":memory:"), SqliteModelStore(":memory:"),
        )
        srv.start_background()
        yield f"http://127.0.0.1:{srv.bound_port}"
        srv.shutdown()
        srv.server_close()

    def test_root_returns_alive_like_event_server(self, storage):
        r = requests.get(f"{storage}/", timeout=10)
        assert r.status_code == 200
        body = r.json()
        assert body["status"] == "alive"
        assert body["stores"]["events"] == "SqliteEventStore"
        assert "startTime" in body

    def test_health_route_still_answers(self, storage):
        assert requests.get(f"{storage}/health", timeout=10).json() == {
            "status": "alive"
        }

    def test_expired_deadline_short_circuits_storage_work(self, storage):
        r = requests.post(
            f"{storage}/events/1/find", data=b"{}",
            headers={"X-PIO-Deadline-Ms": "0"}, timeout=10,
        )
        assert r.status_code == 504
