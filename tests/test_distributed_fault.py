"""Fault injection for distributed training (SURVEY §5).

The reference has NO failure-detection story for its training path (a dead
Spark executor strands the job); round 2 added crash-consistent
checkpointing, and this test closes the loop the round-3 VERDICT asked
for: kill one process of a two-process ``jax.distributed`` training run
MID-TRAIN and verify both halves of the contract —

1. **loud failure**: the surviving process exits nonzero within the
   ``PIO_DIST_HEARTBEAT_S`` detection bound instead of hanging in a
   collective;
2. **checkpoint resume**: a restarted (single-process) run resumes from
   the last durable step — it does not start over and does not lose the
   pre-kill progress.

The training loop is the distributed pattern itself: a global array
sharded over a ``data`` axis spanning both processes, each step doing a
global reduction (cross-process collective) + update, checkpointed every
step through ``workflow/checkpoint.py``.
"""

import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, os.environ["PIO_REPO"])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["PIO_TEST_LOCAL_DEVICES"]
    ).strip()

    import jax
    jax.config.update("jax_platforms", "cpu")

    from predictionio_tpu.parallel.distributed import initialize_from_env
    from predictionio_tpu.workflow.checkpoint import CheckpointManager

    initialize_from_env()
    rank = jax.process_index()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.array(jax.devices())  # spans both processes when multi
    mesh = Mesh(devices, ("data",))
    steps = int(os.environ["PIO_TEST_STEPS"])

    @jax.jit
    def step_fn(x):
        # global mean = cross-process all-reduce every step
        return x - 0.01 * jnp.mean(x) + 1.0

    gather = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))

    ck = CheckpointManager(os.environ["PIO_TEST_CKPT"])
    start = 0
    x0 = np.arange(16, dtype=np.float32)
    for s in reversed(ck.all_steps()):
        try:
            s, tree, meta = ck.restore(s, like={"x": 0})
        except Exception:
            continue
        x0 = np.asarray(tree["x"])
        start = s
        break
    print(f"RESUMED_FROM_{start}", flush=True)

    sharding = NamedSharding(mesh, P("data"))
    x = jax.device_put(x0, NamedSharding(mesh, P()))  # replicated input
    x = jax.jit(lambda a: a, out_shardings=sharding)(x)

    import time as _t
    for step in range(start, steps):
        x = step_fn(x)
        xg = np.asarray(gather(x))  # replicated -> host (cross-process)
        if rank == 0:
            ck.save(step + 1, {"x": xg}, {"step": step + 1})
            print(f"STEP_{step + 1}", flush=True)
        _t.sleep(0.05)  # widen the mid-train kill window
    print(f"TRAIN_DONE_{steps}", flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_peer_death_is_loud_and_resume_continues(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    port = _free_port()
    total_steps = 2000  # far more than can finish before the kill

    def env_for(rank, multi=True, local_devices=4):
        env = {
            k: v for k, v in os.environ.items()
            # a developer shell may export PIO_DIST_* (pio-env.sh); they
            # must not leak into the single-process resume run
            if not k.startswith("PIO_DIST_")
        }
        env.pop("JAX_PLATFORMS", None)
        env.update(
            PIO_REPO=REPO,
            PIO_TEST_CKPT=ckpt,
            PIO_TEST_STEPS=str(total_steps),
            PIO_TEST_LOCAL_DEVICES=str(local_devices),
        )
        if multi:
            env.update(
                PIO_DIST_COORDINATOR=f"127.0.0.1:{port}",
                PIO_DIST_NUM_PROCESSES="2",
                PIO_DIST_PROCESS_ID=str(rank),
                PIO_DIST_HEARTBEAT_S="10",
            )
        return env

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", TRAIN],
            env=env_for(rank),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for rank in range(2)
    ]

    # Drain every pipe on threads: a blocked readline must not disable the
    # watch deadline, and an undrained stderr must not wedge a child whose
    # crash dump overflows the 64 KiB pipe buffer.
    import queue as queue_mod
    import threading

    out_q: "queue_mod.Queue" = queue_mod.Queue()
    sinks = {0: {"out": [], "err": []}, 1: {"out": [], "err": []}}

    def drain(stream, sink, q=None):
        for line in stream:
            sink.append(line.rstrip("\n"))
            if q is not None:
                q.put(line.rstrip("\n"))

    threads = [
        threading.Thread(
            target=drain, args=(procs[0].stdout, sinks[0]["out"], out_q),
            daemon=True,
        ),
        threading.Thread(
            target=drain, args=(procs[0].stderr, sinks[0]["err"]),
            daemon=True,
        ),
        threading.Thread(
            target=drain, args=(procs[1].stdout, sinks[1]["out"]),
            daemon=True,
        ),
        threading.Thread(
            target=drain, args=(procs[1].stderr, sinks[1]["err"]),
            daemon=True,
        ),
    ]
    for t in threads:
        t.start()

    # watch rank 0's stdout; kill rank 1 once training has made progress.
    # Fail FAST when the children die before ever reaching STEP_3 (e.g.
    # a jax.distributed.initialize API error): polling a dead process
    # until the deadline would burn minutes of the tier-1 870 s budget
    # on a failure that was fully diagnosed in the first second.
    killed_at = None
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            try:
                line = out_q.get(timeout=1.0)
            except queue_mod.Empty:
                if procs[0].poll() is not None and out_q.empty():
                    break  # rank 0 already dead: no STEP_3 is coming
                continue
            if line.startswith("STEP_3"):
                procs[1].kill()
                killed_at = 3
                break
        assert killed_at == 3, (
            f"never reached STEP_3 (rank0 rc={procs[0].poll()}): "
            f"{sinks[0]['out'][-20:]} stderr: {sinks[0]['err'][-10:]}"
        )

        # 1) loud failure: rank 0 must EXIT NONZERO within the bound
        try:
            rc0 = procs[0].wait(timeout=90)
        except subprocess.TimeoutExpired:
            pytest.fail(
                "surviving rank hung after peer death — failure detection "
                "did not fire within the heartbeat bound"
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                # bounded reap: a kill that somehow doesn't stick must
                # fail this test, not wedge the whole tier-1 run
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    for t in threads:
        t.join(timeout=10)
    err0 = "\n".join(sinks[0]["err"])
    assert rc0 != 0, "rank 0 exited 0 despite losing its peer mid-train"
    # the death must be diagnosed, not silent: the runtime names the lost
    # peer / failed collective in stderr (exact wording varies by jax
    # version, so match loosely)
    assert err0.strip(), "rank 0 died with an empty stderr (silent failure)"
    assert f"TRAIN_DONE_{total_steps}" not in sinks[0]["out"], (
        "rank 0 claims training completed after peer death"
    )

    # 2) restart resumes from the last durable checkpoint, not step 0
    env = env_for(0, multi=False, local_devices=8)
    env["PIO_TEST_STEPS"] = "12"  # finish quickly single-process
    out = subprocess.run(
        [sys.executable, "-c", TRAIN],
        env=env,
        capture_output=True,
        text=True,
        # single-process, 12 steps: 180 s is 10x generous; the old 300 s
        # budget let one hung resume eat a third of the tier-1 window
        timeout=180,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    resumed = [
        ln for ln in out.stdout.splitlines() if ln.startswith("RESUMED_FROM_")
    ]
    assert resumed, out.stdout
    start = int(resumed[0].rsplit("_", 1)[1])
    assert start >= 3, f"resume lost pre-kill progress (start={start})"
    assert "TRAIN_DONE_12" in out.stdout, out.stdout
