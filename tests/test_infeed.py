"""Streaming infeed tests: chunked columnar scans, incremental indexing,
and the native bucketize fast path.

The reference's analogous surface is the HBase region-split read feeding
executor partitions (``HBPEvents.scala:58-98``); these tests pin the
bounded-memory streaming contract and its equivalence to the one-shot
paths.
"""

import numpy as np
import pytest

from predictionio_tpu.storage.bimap import BiMap
from predictionio_tpu.storage.event import Event, utcnow
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.workflow.infeed import (
    StreamingIndexer,
    stream_ratings,
)


def _insert_rates(store, n, app_id=1):
    for j in range(n):
        store.insert(
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{j % 7}",
                target_entity_type="item",
                target_entity_id=f"i{j % 5}",
                properties={"rating": float(j % 5) + 1.0},
                event_time=utcnow(),
            ),
            app_id,
        )


# -- chunked columnar scan (runs against sqlite, native, remote) ----------


def test_scan_columnar_iter_chunks_concat_to_full_scan(event_store):
    _insert_rates(event_store, 25)
    full = event_store.scan_columnar(1, EventFilter(event_names=["rate"]))
    chunks = list(
        event_store.scan_columnar_iter(
            1, EventFilter(event_names=["rate"]), chunk_rows=10
        )
    )
    assert [len(c["event"]) for c in chunks] == [10, 10, 5]
    for key in ("event", "entity_id", "target_entity_id", "properties"):
        joined = [v for c in chunks for v in c[key]]
        assert joined == list(full[key])
    joined_t = np.concatenate([c["event_time_ms"] for c in chunks])
    assert np.array_equal(joined_t, full["event_time_ms"])


def test_scan_columnar_iter_respects_limit(event_store):
    _insert_rates(event_store, 20)
    chunks = list(
        event_store.scan_columnar_iter(
            1, EventFilter(event_names=["rate"], limit=12), chunk_rows=5
        )
    )
    assert sum(len(c["event"]) for c in chunks) == 12


def test_scan_columnar_iter_empty(event_store):
    assert list(event_store.scan_columnar_iter(1, EventFilter())) == []


# -- streaming indexer ----------------------------------------------------


def test_streaming_indexer_matches_one_shot_bimap():
    keys = [f"k{j % 13}" for j in range(100)]
    ix = StreamingIndexer()
    parts = [ix.index_chunk(keys[a:a + 9]) for a in range(0, 100, 9)]
    streamed = np.concatenate(parts)
    one_shot = BiMap.string_int(keys)
    assert np.array_equal(streamed, one_shot.map_array(keys))
    assert ix.to_bimap() == one_shot


# -- stream_ratings -------------------------------------------------------


def test_stream_ratings_value_rules_and_skip(event_store):
    _insert_rates(event_store, 12)
    # a 'buy' (fixed value) and a target-less event (skipped)
    event_store.insert(
        Event(event="buy", entity_type="user", entity_id="u0",
              target_entity_type="item", target_entity_id="i9",
              event_time=utcnow()),
        1,
    )
    event_store.insert(
        Event(event="rate", entity_type="user", entity_id="u0",
              properties={"rating": 5.0}, event_time=utcnow()),
        1,
    )
    batch = stream_ratings(
        event_store, 1, {"rate": "rating", "buy": 4.0}, chunk_rows=5
    )
    assert len(batch.users) == 13  # 12 rates + 1 buy; target-less skipped
    # the buy (only interaction with i9) carries the fixed implicit value
    i9 = batch.item_map["i9"]
    assert list(batch.ratings[batch.items == i9]) == [4.0]
    # decoded ids roundtrip
    u0_idx = batch.user_map["u0"]
    assert batch.user_map.inverse[u0_idx] == "u0"


def test_stream_ratings_missing_property_raises(event_store):
    event_store.insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=utcnow()),
        1,
    )
    with pytest.raises(ValueError, match="rating"):
        stream_ratings(event_store, 1, {"rate": "rating"})


def test_stream_ratings_empty_store(event_store):
    batch = stream_ratings(event_store, 1, {"rate": "rating"})
    assert len(batch.users) == 0 and len(batch.user_map) == 0


# -- hashed big-ID path ---------------------------------------------------


def test_hashed_id_map_basics():
    from predictionio_tpu.storage.bimap import HashedIdMap

    m = HashedIdMap(1 << 16)
    idx = m.map_array([f"user_{j}" for j in range(1000)])
    assert idx.dtype == np.int32
    assert ((idx >= 0) & (idx < (1 << 16))).all()
    # deterministic and salt-sensitive
    again = m.map_array([f"user_{j}" for j in range(1000)])
    assert np.array_equal(idx, again)
    salted = HashedIdMap(1 << 16, salt=7).map_array(
        [f"user_{j}" for j in range(1000)]
    )
    assert not np.array_equal(idx, salted)
    assert m["user_3"] == idx[3]
    with pytest.raises(ValueError, match="power of two"):
        HashedIdMap(1000)
    with pytest.raises(TypeError, match="inverted"):
        m.inverse
    # aliased-id estimate: 1000 ids in 65536 slots ≈ 1-e^-0.0153 ≈ 1.5%
    assert 0.01 < m.expected_collision_fraction(1000) < 0.02
    with pytest.raises(ValueError, match="2\\^31"):
        HashedIdMap(1 << 32)


def test_hashed_batch_matches_pure_python():
    """Native batch fnv1a64 must equal the reference Python implementation
    (and the event log's evlog_fnv1a64 constants)."""
    from predictionio_tpu.storage import bimap as bm

    keys = ["", "a", "user_1", "ü–🎉", "x" * 300]
    native = bm._fnv1a64_batch(keys, salt=5)
    mask = (1 << 64) - 1
    for j, k in enumerate(keys):
        h = 14695981039346656037 ^ 5
        for b in k.encode("utf-8"):
            h = ((h ^ b) * 1099511628211) & mask
        assert native[j] == (h if h else 1)


def test_stream_ratings_hashed_users(event_store):
    from predictionio_tpu.storage.bimap import HashedIdMap

    _insert_rates(event_store, 30)
    exact = stream_ratings(event_store, 1, {"rate": "rating"})
    hashed = stream_ratings(
        event_store, 1, {"rate": "rating"}, hashed_users=1 << 12
    )
    assert isinstance(hashed.user_map, HashedIdMap)
    # same interactions, same item indexing, user indices are the hashes
    assert np.array_equal(hashed.items, exact.items)
    assert np.array_equal(hashed.ratings, exact.ratings)
    u_inv = exact.user_map.inverse
    expect = hashed.user_map.map_array(
        [u_inv[int(u)] for u in exact.users]
    )
    assert np.array_equal(hashed.users, expect)


# -- native ratings scan --------------------------------------------------


@pytest.fixture()
def native_store(tmp_path):
    from predictionio_tpu.native import NativeBuildError

    try:
        from predictionio_tpu.storage.native_events import NativeEventStore

        store = NativeEventStore(str(tmp_path / "ev"))
    except NativeBuildError as exc:
        pytest.skip(f"native event log unavailable: {exc}")
    store.init(1)
    yield store
    store.close()


def test_native_scan_ratings_matches_python_path(native_store):
    _insert_rates(native_store, 40)
    native_store.insert(
        Event(event="buy", entity_type="user", entity_id="u2",
              target_entity_type="item", target_entity_id="i3",
              event_time=utcnow()),
        1,
    )
    rules = {"rate": "rating", "buy": 4.0}
    fast = stream_ratings(native_store, 1, rules)  # native path
    # force the generic chunked path for comparison
    slow_u, slow_i, slow_v = [], [], []

    def grab(u, i, v):
        slow_u.append(u), slow_i.append(i), slow_v.append(v)

    slow = stream_ratings(native_store, 1, rules, chunk_rows=7, on_chunk=grab)
    assert np.array_equal(fast.users, slow.users)
    assert np.array_equal(fast.items, slow.items)
    assert np.array_equal(fast.ratings, slow.ratings)
    assert fast.user_map == slow.user_map
    assert fast.item_map == slow.item_map
    assert len(slow_u) == len(list(slow_u))  # hook saw every chunk


def test_native_scan_ratings_unicode_and_escapes(native_store):
    """The C++ JSON walker must decode escapes exactly as Python json."""
    weird_user = 'u"\\back\nslash\tñ–🎉'
    weird_item = "item/ü\u0007"
    native_store.insert(
        Event(event="rate", entity_type="user", entity_id=weird_user,
              target_entity_type="item", target_entity_id=weird_item,
              properties={"rating": 2.5}, event_time=utcnow()),
        1,
    )
    batch = stream_ratings(native_store, 1, {"rate": "rating"})
    assert list(batch.user_map) == [weird_user]
    assert list(batch.item_map) == [weird_item]
    assert batch.ratings[0] == 2.5


def test_native_scan_ratings_respects_tombstones(native_store):
    _insert_rates(native_store, 5)
    eid = native_store.insert(
        Event(event="rate", entity_type="user", entity_id="uDEAD",
              target_entity_type="item", target_entity_id="iDEAD",
              properties={"rating": 1.0}, event_time=utcnow()),
        1,
    )
    native_store.delete(eid, 1)
    batch = stream_ratings(native_store, 1, {"rate": "rating"})
    assert len(batch.users) == 5
    assert "uDEAD" not in batch.user_map


def test_native_scan_ratings_missing_property_raises(native_store):
    native_store.insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=utcnow()),
        1,
    )
    with pytest.raises(ValueError, match="missing required property"):
        stream_ratings(native_store, 1, {"rate": "rating"})


# -- native bucketize -----------------------------------------------------


def test_native_bucketize_matches_numpy():
    from predictionio_tpu.native import NativeBuildError
    from predictionio_tpu.ops.als import _bucketize_native, _bucketize_numpy

    rng = np.random.default_rng(7)
    n_rows, n_cols, nnz = 800, 400, 30_000
    w = 1.0 / np.arange(1, n_rows + 1) ** 0.8
    rows = rng.choice(n_rows, size=nnz, p=w / w.sum()).astype(np.int32)
    cols = rng.integers(0, n_cols, nnz).astype(np.int32)
    vals = rng.normal(size=nnz).astype(np.float32)
    ref = _bucketize_numpy(rows, cols, vals, n_rows, n_cols)
    try:
        got = _bucketize_native(rows, cols, vals, n_rows, n_cols)
    except NativeBuildError as exc:
        pytest.skip(f"native bucketize unavailable: {exc}")
    assert len(ref.buckets) == len(got.buckets)
    for a, b in zip(ref.buckets, got.buckets):
        assert np.array_equal(a.rows, b.rows)
        assert np.array_equal(a.idx, b.idx)
        assert np.array_equal(a.val, b.val)
        assert np.array_equal(a.mask, b.mask)


def test_native_bucketize_truncation_matches_numpy():
    from predictionio_tpu.native import NativeBuildError
    from predictionio_tpu.ops.als import _bucketize_native, _bucketize_numpy

    rows = np.zeros(100, dtype=np.int32)
    cols = np.arange(100, dtype=np.int32)
    vals = np.arange(100, dtype=np.float32)
    ref = _bucketize_numpy(rows, cols, vals, 1, 100, bucket_widths=(8, 32))
    try:
        got = _bucketize_native(rows, cols, vals, 1, 100, bucket_widths=(8, 32))
    except NativeBuildError as exc:
        pytest.skip(f"native bucketize unavailable: {exc}")
    assert np.array_equal(ref.buckets[0].idx, got.buckets[0].idx)
    assert np.array_equal(ref.buckets[0].val, got.buckets[0].val)
    assert np.array_equal(ref.buckets[0].mask, got.buckets[0].mask)
