"""Deprecated batch-view layer (parity with the reference's deprecated
``data/view/LBatchView.scala``); tests mirror the reference semantics the
shim preserves: filter combinators (exclusive start), event-ordered
per-entity folds, and the legacy DataMap aggregator."""

import datetime as dt

import pytest

from predictionio_tpu.storage import DataMap, Event, SqliteEventStore
from predictionio_tpu.storage.batch_view import BatchView, EventSeq

UTC = dt.timezone.utc


def ts(h):
    return dt.datetime(2021, 6, 1, h, tzinfo=UTC)


@pytest.fixture()
def store():
    s = SqliteEventStore(":memory:")
    s.init(1)
    s.write(
        [
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties=DataMap({"a": 1, "b": 2}), event_time=ts(1)),
            Event(event="$unset", entity_type="item", entity_id="i1",
                  properties=DataMap({"b": 0}), event_time=ts(2)),
            Event(event="$set", entity_type="item", entity_id="i2",
                  properties=DataMap({"a": 9}), event_time=ts(3)),
            Event(event="$delete", entity_type="item", entity_id="i2",
                  event_time=ts(4)),
            Event(event="$set", entity_type="user", entity_id="u1",
                  properties=DataMap({"x": 5}), event_time=ts(1)),
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": 4.0}), event_time=ts(5)),
        ],
        1,
    )
    return s


def _view(store, **kw):
    with pytest.deprecated_call():
        return BatchView(store, 1, **kw)


def test_aggregate_properties_folds_in_event_order(store):
    view = _view(store)
    props = view.aggregate_properties("item")
    # i1: set {a,b} then unset b -> {a: 1}; i2: set then $delete -> dropped
    assert set(props) == {"i1"}
    assert dict(props["i1"]) == {"a": 1}


def test_aggregate_properties_other_entity_type(store):
    view = _view(store)
    props = view.aggregate_properties("user")
    assert dict(props["u1"]) == {"x": 5}


def test_filter_start_time_is_exclusive(store):
    """ViewPredicates.getStartTimePredicate drops events AT start_time —
    a reference quirk the shim mirrors verbatim."""
    view = _view(store)
    seq = view.events.filter(start_time=ts(1))
    assert all(e.event_time > ts(1) for e in seq)
    assert len(seq) == len(view.events) - 2  # the two ts(1) events drop


def test_window_applies_at_view_construction(store):
    view = _view(store, until_time=ts(4))
    # the rate event at ts(5) and the $delete at ts(4) are outside the
    # (exclusive-until) window: i2's $set at ts(3) survives
    props = view.aggregate_properties("item")
    assert set(props) == {"i1", "i2"}
    assert dict(props["i2"]) == {"a": 9}


def test_aggregate_by_entity_ordered_counts(store):
    view = _view(store)
    counts = view.events.filter(entity_type="item").aggregate_by_entity_ordered(
        0, lambda acc, e: acc + 1
    )
    assert counts == {"i1": 2, "i2": 2}


def test_eventseq_chained_filters(store):
    view = _view(store)
    seq = view.events.filter(event="$set").filter(entity_type="item")
    assert {e.entity_id for e in seq} == {"i1", "i2"}


def test_naive_datetime_bounds_taken_as_utc(store):
    """Same convention as EventFilter: naive bounds are UTC."""
    view = _view(store)
    naive = dt.datetime(2021, 6, 1, 1)  # == ts(1) without tzinfo
    seq = view.events.filter(start_time=naive)
    assert all(e.event_time > ts(1) for e in seq)
    props = view.aggregate_properties("item", until_time=dt.datetime(2021, 6, 1, 4))
    assert set(props) == {"i1", "i2"}
