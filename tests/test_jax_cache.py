"""Persistent-compilation-cache wiring (utils/jax_cache.py).

The revalidation queue's subprocess isolation means every device step is
a fresh process; these tests prove the cache actually carries compiled
executables across that process boundary — the property the hardware
window depends on — using the CPU backend (same cache machinery, no
device needed).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# A compile heavy enough that a persistent-cache hit is unmistakably
# cheaper than the miss, run in a child hard-pinned to the CPU backend.
_CHILD = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
from predictionio_tpu.utils.platform import force_cpu_in_process
force_cpu_in_process()
from predictionio_tpu.utils.jax_cache import enable_compilation_cache
cache_dir = enable_compilation_cache()
import jax
import jax.numpy as jnp

def f(x):
    for i in range(12):
        x = jnp.tanh(x @ x) * (1.0 + 1.0 / (i + 2)) + x
    return x.sum()

t0 = time.monotonic()
jax.jit(f).lower(
    jax.ShapeDtypeStruct((256, 256), jnp.float32)
).compile()
print(json.dumps({{"compile_s": time.monotonic() - t0,
                   "cache_dir": cache_dir}}))
"""


def _run_child(cache_dir: str) -> dict:
    from predictionio_tpu.utils.platform import force_cpu_env

    env = force_cpu_env()
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["PIO_JAX_CACHE_DIR"] = cache_dir
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_cache_disabled_by_empty_env(monkeypatch):
    from predictionio_tpu.utils.jax_cache import enable_compilation_cache

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.setenv("PIO_JAX_CACHE_DIR", "")
    assert enable_compilation_cache() is None
    assert "JAX_COMPILATION_CACHE_DIR" not in os.environ


def test_explicit_jax_env_wins(monkeypatch, tmp_path):
    from predictionio_tpu.utils.jax_cache import enable_compilation_cache

    theirs = str(tmp_path / "theirs")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", theirs)
    monkeypatch.setenv("PIO_JAX_CACHE_DIR", str(tmp_path / "ours"))
    assert enable_compilation_cache() == theirs


def test_second_subprocess_hits_cache(tmp_path):
    """The queue property itself: process 1 populates the cache, process
    2 (identical program) must add NO new entries. File-set stability is
    the assertion that pins the behavior — key stability across
    processes: a second process that *missed* would write new entries
    under a different cache key, and that is exactly the regression this
    test exists to catch. (A wall-clock compile-time-ratio assertion
    used to ride along as corroboration, but under full-suite CPU
    contention the margin flaked — ROUND8 notes: hit ratio 0.26 on an
    idle box, >0.7 under load — while the file-set property held every
    time. Timing is an artifact of the box; the cache key contract is
    the test.)"""
    cache_dir = str(tmp_path / "cache")
    first = _run_child(cache_dir)
    assert first["cache_dir"] == cache_dir
    entries = {
        os.path.join(dp, f)
        for dp, _, fs in os.walk(cache_dir) for f in fs
    }
    assert entries, "first run wrote no cache entries"

    second = _run_child(cache_dir)
    assert second["cache_dir"] == cache_dir
    entries_after = {
        os.path.join(dp, f)
        for dp, _, fs in os.walk(cache_dir) for f in fs
    }
    assert entries_after == entries, (
        "second process missed the cache (new entries written)"
    )
