"""Resilience primitives (``utils/resilience.py``) + fault harness
(``testing/faults.py``).

Everything here runs on injected clocks / sleeps / rngs: the whole suite
is deterministic and never waits on wall time — the contract ISSUE 2
sets for the fault work staying inside the tier-1 budget.
"""

import random

import pytest

from predictionio_tpu.testing import faults
from predictionio_tpu.utils.resilience import (
    DEADLINE_HEADER,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)


class FakeClock:
    """Injectable monotonic clock."""

    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_budget_counts_down_on_the_injected_clock(self):
        clock = FakeClock()
        d = Deadline.after_ms(250, clock)
        assert d.remaining_ms() == pytest.approx(250)
        clock.advance(0.2)
        assert d.remaining_ms() == pytest.approx(50)
        assert not d.expired
        clock.advance(0.1)
        assert d.expired

    def test_check_raises_with_stage(self):
        clock = FakeClock()
        d = Deadline.after_ms(10, clock)
        d.check("dispatch")  # within budget: no raise
        clock.advance(1.0)
        with pytest.raises(DeadlineExceeded) as exc:
            d.check("dispatch")
        assert exc.value.stage == "dispatch"

    def test_header_roundtrip_is_relative(self):
        clock = FakeClock()
        d = Deadline.after_ms(500, clock)
        clock.advance(0.2)
        # forwarded budget = REMAINING ms, so a receiver with a totally
        # different clock epoch still gets 300 ms
        receiver_clock = FakeClock(now=77.0)
        d2 = Deadline.from_header(d.header_value(), receiver_clock)
        assert d2.remaining_ms() == pytest.approx(300, abs=1)

    @pytest.mark.parametrize("bad", [None, "", "not-a-number", object()])
    def test_malformed_header_is_no_deadline(self, bad):
        assert Deadline.from_header(bad) is None

    def test_negative_header_is_already_expired(self):
        d = Deadline.from_header("-50", FakeClock())
        assert d is not None and d.expired

    def test_cap_timeout_floors_above_zero(self):
        clock = FakeClock()
        d = Deadline.after_ms(100, clock)
        assert d.cap_timeout(60.0) == pytest.approx(0.1)
        assert d.cap_timeout(0.05) == pytest.approx(0.05)
        clock.advance(5)
        assert d.cap_timeout(60.0) == 0.001  # never 0: that means non-blocking

    def test_header_name_is_the_wire_contract(self):
        assert DEADLINE_HEADER == "X-PIO-Deadline-Ms"

    def test_ambient_scope(self):
        d = Deadline.after_ms(100, FakeClock())
        assert current_deadline() is None
        with deadline_scope(d):
            assert current_deadline() is d
        assert current_deadline() is None


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def _policy(self, **kw):
        sleeps = []
        kw.setdefault("rng", random.Random(7))
        policy = RetryPolicy(sleep=sleeps.append, **kw)
        return policy, sleeps

    def test_success_first_try_never_sleeps(self):
        policy, sleeps = self._policy(attempts=3)
        assert policy.call(lambda: 42) == 42
        assert sleeps == []

    def test_n_failures_then_ok(self):
        policy, sleeps = self._policy(attempts=3, base_delay_s=0.1)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("boom")
            return "ok"

        assert policy.call(flaky) == "ok"
        assert len(calls) == 3
        assert len(sleeps) == 2

    def test_full_jitter_bounds(self):
        # retry i draws from U(0, min(cap, base * 2^i)) — check the
        # envelope over the deterministic rng's draws
        policy, sleeps = self._policy(
            attempts=6, base_delay_s=0.1, max_delay_s=0.5
        )
        with pytest.raises(ValueError):
            policy.call(self._always_fail)
        assert len(sleeps) == 5
        for i, s in enumerate(sleeps):
            assert 0.0 <= s <= min(0.5, 0.1 * 2**i)

    @staticmethod
    def _always_fail():
        raise ValueError("nope")

    def test_gives_up_after_attempts(self):
        policy, sleeps = self._policy(attempts=4)
        calls = []

        def fail():
            calls.append(1)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            policy.call(fail)
        assert len(calls) == 4

    def test_should_retry_predicate_gates_retries(self):
        policy, _ = self._policy(attempts=5)
        calls = []

        def fail():
            calls.append(1)
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            policy.call(fail, should_retry=lambda e: "transient" in str(e))
        assert len(calls) == 1  # non-matching error: no retry burned

    def test_deadline_bounds_the_schedule(self):
        clock = FakeClock()
        sleeps = []

        def sleeping(s):
            sleeps.append(s)
            clock.advance(s)

        policy = RetryPolicy(
            attempts=10,
            base_delay_s=0.2,
            max_delay_s=0.2,
            rng=random.Random(3),
            sleep=sleeping,
            clock=clock,
        )
        deadline = Deadline.after_ms(300, clock)
        calls = []

        def fail():
            calls.append(1)
            clock.advance(0.05)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            policy.call(fail, deadline=deadline)
        # far fewer than 10 attempts: the 300 ms budget can't cover the
        # whole schedule
        assert len(calls) < 5

    def test_attempts_must_be_positive(self):
        with pytest.raises(ValueError):
            RetryPolicy(attempts=0)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _breaker(self, **kw):
        clock = FakeClock()
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("reset_timeout_s", 30.0)
        return CircuitBreaker("dep", clock=clock, **kw), clock

    def test_opens_after_threshold_consecutive_failures(self):
        breaker, _ = self._breaker()
        for _ in range(2):
            breaker.record_failure()
        assert breaker.state == CircuitBreaker.CLOSED
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        with pytest.raises(CircuitOpen) as exc:
            breaker.before_call()
        assert exc.value.retry_after_s > 0

    def test_success_resets_the_consecutive_count(self):
        breaker, _ = self._breaker()
        breaker.record_failure()
        breaker.record_failure()
        breaker.record_success()
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.CLOSED

    def test_half_open_probe_success_closes(self):
        breaker, clock = self._breaker()
        for _ in range(3):
            breaker.record_failure()
        clock.advance(30.0)
        assert breaker.state == CircuitBreaker.HALF_OPEN
        breaker.before_call()  # the probe is admitted
        breaker.record_success()
        assert breaker.state == CircuitBreaker.CLOSED

    def test_half_open_probe_failure_reopens_and_restarts_cooldown(self):
        breaker, clock = self._breaker()
        for _ in range(3):
            breaker.record_failure()
        clock.advance(30.0)
        breaker.before_call()
        breaker.record_failure()  # probe failed: still down
        assert breaker.state == CircuitBreaker.OPEN
        clock.advance(29.0)  # cooldown restarted — not elapsed yet
        with pytest.raises(CircuitOpen):
            breaker.before_call()
        clock.advance(1.5)
        breaker.before_call()  # next probe window

    def test_half_open_admits_bounded_probes(self):
        breaker, clock = self._breaker(half_open_probes=1)
        for _ in range(3):
            breaker.record_failure()
        clock.advance(31.0)
        breaker.before_call()  # probe 1 in flight
        with pytest.raises(CircuitOpen):
            breaker.before_call()  # probe 2 rejected

    def test_call_wraps_one_logical_operation(self):
        breaker, _ = self._breaker(failure_threshold=2)
        with pytest.raises(RuntimeError):
            breaker.call(self._boom)
        with pytest.raises(RuntimeError):
            breaker.call(self._boom)
        with pytest.raises(CircuitOpen):
            breaker.call(self._boom)  # open: fn must not even run

    @staticmethod
    def _boom():
        raise RuntimeError("dead dependency")

    def test_snapshot_shape(self):
        breaker, clock = self._breaker()
        snap = breaker.snapshot()
        assert snap["state"] == "closed"
        for _ in range(3):
            breaker.record_failure()
        snap = breaker.snapshot()
        assert snap["state"] == "open"
        assert snap["openCount"] == 1
        assert 0 < snap["retryAfterS"] <= 30.0

    def test_from_env_reads_the_knobs(self):
        clock = FakeClock()
        breaker = CircuitBreaker.from_env(
            "x",
            env={
                "PIO_BREAKER_FAILURES": "2",
                "PIO_BREAKER_RESET_S": "7.5",
                "PIO_BREAKER_HALF_OPEN_PROBES": "3",
            },
            clock=clock,
        )
        assert breaker.failure_threshold == 2
        assert breaker.reset_timeout_s == 7.5
        assert breaker.half_open_probes == 3


# ---------------------------------------------------------------------------
# Fault harness
# ---------------------------------------------------------------------------


class TestFaultHarness:
    def teardown_method(self):
        faults.deactivate()

    def test_inactive_harness_is_a_no_op(self):
        faults.deactivate()
        faults.fault_point("remote.send", url="http://x")  # must not raise

    def test_refuse_fires_connection_refused(self):
        with faults.inject(faults.FaultSpec("remote.send", "refuse")):
            with pytest.raises(ConnectionRefusedError):
                faults.fault_point("remote.send")

    def test_close_fires_remote_disconnected(self):
        import http.client

        with faults.inject(faults.FaultSpec("remote.send", "close")):
            with pytest.raises(http.client.RemoteDisconnected):
                faults.fault_point("remote.send")

    def test_n_failures_then_ok(self):
        spec = faults.FaultSpec("s", "refuse", times=2)
        with faults.inject(spec) as plan:
            for _ in range(2):
                with pytest.raises(ConnectionRefusedError):
                    faults.fault_point("s")
            faults.fault_point("s")  # budget spent: ok now
            faults.fault_point("s")
            assert plan.fired("s") == 2
            assert plan.hits("s") == 4

    def test_site_filtering(self):
        with faults.inject(faults.FaultSpec("a", "refuse")):
            faults.fault_point("b")  # different site: untouched
            with pytest.raises(ConnectionRefusedError):
                faults.fault_point("a")

    def test_when_predicate_filters_on_call_info(self):
        spec = faults.FaultSpec(
            "s", "close", when=lambda info: not info.get("fresh", True)
        )
        with faults.inject(spec):
            faults.fault_point("s", fresh=True)  # filtered out
            with pytest.raises(Exception):
                faults.fault_point("s", fresh=False)

    def test_latency_uses_injected_sleep(self):
        slept = []
        with faults.inject(
            faults.FaultSpec("s", "latency", arg=50.0), sleep=slept.append
        ):
            faults.fault_point("s")
        assert slept == [0.05]

    def test_parse_env_syntax(self):
        specs = faults.parse(
            "serving.feedback=refuse*3; remote.send=latency:50"
        )
        assert [(s.site, s.kind, s.times, s.arg) for s in specs] == [
            ("serving.feedback", "refuse", 3, 0.0),
            ("remote.send", "latency", None, 50.0),
        ]

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            faults.parse("no-equals-sign")
        with pytest.raises(ValueError):
            faults.parse("site=unknown-kind")

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv("PIO_FAULTS", "x=refuse*1")
        faults._install_from_env()
        try:
            with pytest.raises(ConnectionRefusedError):
                faults.fault_point("x")
        finally:
            faults.deactivate()
