"""C++ client SDK integration test.

Builds the SDK test binary with the system toolchain and drives it against
a live in-process Event Server — the second-language client surface
(reference Java shim analogue, ``core/src/main/java/io/prediction/
controller/java/``, and the official client SDKs' EventClient shape).
"""

import os
import shutil
import subprocess
import sys

import pytest

from predictionio_tpu.api.event_server import EventServerConfig, create_event_server
from predictionio_tpu.storage import MetadataStore, SqliteEventStore, StorageRegistry
from predictionio_tpu.storage.metadata import AccessKey, App

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SDK = os.path.join(REPO, "sdk", "cpp")

TEST_MAIN = r"""
#include <cstdio>
#include <cstring>
#include "predictionio_client.hpp"

int main(int argc, char** argv) {
  const char* host = argv[1];
  int port = atoi(argv[2]);
  const char* key = argv[3];
  pio::EventClient ev(host, port, key);

  std::string id = ev.create_event(
      R"({"event": "rate", "entityType": "user", "entityId": "cpp-user",)"
      R"( "targetEntityType": "item", "targetEntityId": "cpp-item",)"
      R"( "properties": {"rating": 3.5}})");
  if (id.empty()) { fprintf(stderr, "empty event id\n"); return 1; }

  std::string got = ev.get_event(id);
  if (got.find("cpp-user") == std::string::npos) {
    fprintf(stderr, "get_event missing entity: %s\n", got.c_str());
    return 1;
  }
  std::string found = ev.find_events("&event=rate");
  if (found.find("cpp-item") == std::string::npos) {
    fprintf(stderr, "find_events missing item: %s\n", found.c_str());
    return 1;
  }
  if (!ev.delete_event(id)) { fprintf(stderr, "delete failed\n"); return 1; }
  try {
    ev.get_event(id);
    fprintf(stderr, "get after delete should 404\n");
    return 1;
  } catch (const pio::ClientError& e) {
    if (e.status() != 404) {
      fprintf(stderr, "expected 404, got %d\n", e.status());
      return 1;
    }
  }
  // batch ingestion
  std::string batch = ev.create_events_batch(
      R"([{"event": "rate", "entityType": "user", "entityId": "cb1",)"
      R"( "targetEntityType": "item", "targetEntityId": "ci1",)"
      R"( "properties": {"rating": 1.0}},)"
      R"( {"event": "rate", "entityType": "user", "entityId": "cb2",)"
      R"( "targetEntityType": "item", "targetEntityId": "ci2",)"
      R"( "properties": {"rating": 2.0}}])");
  if (batch.find("201") == std::string::npos ||
      batch.find("eventId") == std::string::npos) {
    fprintf(stderr, "batch result unexpected: %s\n", batch.c_str());
    return 1;
  }

  // bad access key must be rejected
  pio::EventClient bad(host, port, "wrong-key");
  try {
    bad.create_event(R"({"event": "x", "entityType": "t", "entityId": "e"})");
    fprintf(stderr, "bad key accepted\n");
    return 1;
  } catch (const pio::ClientError& e) {
    if (e.status() != 401) {
      fprintf(stderr, "expected 401, got %d\n", e.status());
      return 1;
    }
  }
  printf("CPP_SDK_OK\n");
  return 0;
}
"""


@pytest.fixture(scope="module")
def sdk_binary(tmp_path_factory):
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ toolchain ({cxx})")
    build = tmp_path_factory.mktemp("cpp_sdk")
    src = build / "sdk_test.cc"
    src.write_text(TEST_MAIN)
    binary = build / "sdk_test"
    proc = subprocess.run(
        [
            cxx, "-std=c++17", "-O1", f"-I{SDK}",
            str(src), os.path.join(SDK, "predictionio_client.cc"),
            "-o", str(binary),
        ],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"SDK build failed:\n{proc.stderr}")
    return str(binary)


@pytest.fixture()
def event_server(tmp_path):
    reg = StorageRegistry({"PIO_FS_BASEDIR": str(tmp_path)})
    md = reg.get_metadata()
    app_id = md.app_insert(App(id=0, name="cppapp"))
    key = md.access_key_insert(AccessKey(key="", appid=app_id, events=()))
    reg.get_events().init(app_id)
    server = create_event_server(
        EventServerConfig(ip="127.0.0.1", port=0), registry=reg, block=False
    )
    yield server, key
    server.shutdown()
    server.server_close()


def test_cpp_sdk_event_roundtrip(sdk_binary, event_server):
    server, key = event_server
    proc = subprocess.run(
        [sdk_binary, "127.0.0.1", str(server.bound_port), key],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, f"stderr: {proc.stderr}\nstdout: {proc.stdout}"
    assert "CPP_SDK_OK" in proc.stdout


def test_example_quickstart_compiles(sdk_binary, tmp_path):
    """The shipped example must at least build (it needs live servers to
    run; the SDK test above covers the behavior)."""
    cxx = os.environ.get("CXX", "g++")
    out = tmp_path / "quickstart"
    proc = subprocess.run(
        [
            cxx, "-std=c++17", "-O1", f"-I{SDK}",
            os.path.join(SDK, "examples", "quickstart.cc"),
            os.path.join(SDK, "predictionio_client.cc"),
            "-o", str(out),
        ],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
