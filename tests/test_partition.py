"""Partitioned write path (docs/storage.md#partitioning): partition
math golden vectors, the oplog/changefeed ownership guards, the
partitioned ``pio+ha://`` client, the event server's partial-outage
shed, per-partition feed-watcher cursor semantics, the N-partition
chaos drill, and the PARTS / per-partition-freshness surfaces.

Everything here is storage-plane only — no jax, no training, in-process
servers on injected state — so the whole file stays cheap against the
tier-1 budget.
"""

import json
import os
import threading

import pytest

from predictionio_tpu.continuous.watcher import (
    FeedGap,
    FeedWatcher,
    LocalFeed,
    PartitionedFeedWatcher,
    make_watcher,
)
from predictionio_tpu.storage import MetadataStore, SqliteEventStore
from predictionio_tpu.storage import remote
from predictionio_tpu.storage.changefeed import Changefeed, WrongPartition
from predictionio_tpu.storage.event import Event
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.storage.model_store import SqliteModelStore
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.storage.partition import (
    PARTITION_SALT,
    check_partition,
    partition_for_event,
    partition_for_key,
    partition_key,
    partition_primaries,
    split_partition_sets,
)
from predictionio_tpu.storage.replica import StorageReplica
from predictionio_tpu.storage.storage_server import StorageServer


def _rate(user: str, item: str = "i1", value: float = 4.0) -> Event:
    from predictionio_tpu.storage import DataMap

    return Event(
        event="rate", entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties=DataMap({"rating": value}),
    )


# ---------------------------------------------------------------------------
# partition math
# ---------------------------------------------------------------------------


class TestPartitionMath:
    def test_golden_vectors(self):
        """Exact assignments pinned: changing the salt, the key format
        or the hash silently would strand every stored event on the
        wrong primary (the bucket golden-vector discipline, PR 9)."""
        assert partition_key(1, "u1") == "1|u1"
        assert partition_for_key(1, "1|u1") == 0  # count=1 short-circuit
        vectors = {
            ("1|u1", 2): 0,
            ("1|u2", 2): 1,
            ("1|u3", 2): 0,
            ("1|u1", 4): 2,
            ("1|u2", 4): 3,
            ("1|u3", 4): 0,
            ("2|u2", 4): 0,  # app id is part of the key (≠ 1|u2's 3)
        }
        for (key, count), expected in vectors.items():
            assert partition_for_key(count, key) == expected, (key, count)

    def test_salt_is_not_a_rollout_or_routing_salt(self):
        # the one-hash design holds only because the salts differ
        assert PARTITION_SALT not in ("", "routing")
        from predictionio_tpu.rollout.plan import bucket_for_key

        assert bucket_for_key(PARTITION_SALT, "1|u1") != bucket_for_key(
            "routing", "1|u1"
        )

    def test_every_partition_owns_some_keyspace(self):
        for count in (2, 3, 4):
            owners = {
                partition_for_event(count, 1, f"u{i}") for i in range(200)
            }
            assert owners == set(range(count))

    def test_url_splitting(self):
        assert split_partition_sets("http://x:1") == ["http://x:1"]
        assert split_partition_sets("pio+ha://a:1,b:2") == [
            "pio+ha://a:1,b:2"
        ]
        assert split_partition_sets("pio+ha://a:1,b:2;c:3") == [
            "pio+ha://a:1,b:2", "pio+ha://c:3"
        ]
        assert partition_primaries("pio+ha://a:1,b:2;c:3,d:4") == [
            "http://a:1", "http://c:3"
        ]
        assert partition_primaries("http://x:1/") == ["http://x:1"]

    def test_check_partition(self):
        check_partition(None, 1, 3)         # undeclared: tolerated
        check_partition([1, 3], 1, 3)       # match
        with pytest.raises(ValueError, match="partition mismatch"):
            check_partition([0, 3], 1, 3)
        with pytest.raises(ValueError, match="partition mismatch"):
            check_partition([1, 4], 1, 3)


# ---------------------------------------------------------------------------
# oplog + changefeed ownership guards
# ---------------------------------------------------------------------------


class TestPartitionIdentity:
    def test_oplog_meta_persists_and_guards_slot(self, tmp_path):
        log = OpLog(str(tmp_path / "ol"), partition=(1, 3))
        assert log.partition == [1, 3]
        assert log.checkpoint()["partition"] == [1, 3]
        log.close()
        # reopen with the same slot: fine; different slot: loud
        OpLog(str(tmp_path / "ol"), partition=(1, 3)).close()
        with pytest.raises(ValueError, match="partition mismatch"):
            OpLog(str(tmp_path / "ol"), partition=(2, 3))

    def test_pre_partitioning_log_adopts_declared_slot(self, tmp_path):
        OpLog(str(tmp_path / "ol")).close()  # legacy: no slot in meta
        log = OpLog(str(tmp_path / "ol"), partition=(0, 2))
        assert log.partition == [0, 2]  # upgrade stamped durably
        log.close()
        assert OpLog(str(tmp_path / "ol")).partition == [0, 2]

    def test_changefeed_rejects_misrouted_event(self, tmp_path):
        count = 2
        index = 0
        cf = Changefeed(
            OpLog(str(tmp_path / "ol"), partition=(index, count)),
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        owned = next(
            f"u{i}" for i in range(50)
            if partition_for_event(count, 1, f"u{i}") == index
        )
        foreign = next(
            f"u{i}" for i in range(50)
            if partition_for_event(count, 1, f"u{i}") != index
        )
        cf.insert_event(_rate(owned), 1)  # owned key lands
        with pytest.raises(WrongPartition) as exc_info:
            cf.insert_event(_rate(foreign), 1)
        assert exc_info.value.expected != index
        with pytest.raises(WrongPartition):
            cf.write_events([_rate(owned), _rate(foreign)], 1, fresh=True)
        # an unpartitioned feed never checks
        flat = Changefeed(
            OpLog(str(tmp_path / "flat")),
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        flat.insert_event(_rate(foreign), 1)


# ---------------------------------------------------------------------------
# live partitioned fleet helpers
# ---------------------------------------------------------------------------


@pytest.fixture
def breaker_one():
    prev = os.environ.get("PIO_BREAKER_FAILURES")
    os.environ["PIO_BREAKER_FAILURES"] = "1"
    remote.reset_resilience()
    yield
    if prev is None:
        os.environ.pop("PIO_BREAKER_FAILURES", None)
    else:
        os.environ["PIO_BREAKER_FAILURES"] = prev
    remote.reset_resilience()


def _boot_fleet(tmp_path, count: int, replicas: bool = False):
    servers, reps, sets = [], [], []
    for i in range(count):
        server = StorageServer(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            changefeed=None, partition=(i, count),
        )
        server.changefeed = Changefeed(
            OpLog(
                str(tmp_path / f"oplog-{i}"),
                partition=(i, count) if count > 1 else None,
            ),
            server.events, server.metadata, server.models,
        )
        server.start_background()
        servers.append(server)
        endpoints = f"127.0.0.1:{server.bound_port}"
        if replicas:
            rep = StorageReplica(
                "127.0.0.1", 0,
                SqliteEventStore(":memory:"), MetadataStore(":memory:"),
                SqliteModelStore(":memory:"),
                f"http://127.0.0.1:{server.bound_port}",
                str(tmp_path / f"rep-{i}"),
                catchup_wait_s=0.0, partition=(i, count),
            )
            rep.start_background()
            reps.append(rep)
            endpoints += f",127.0.0.1:{rep.bound_port}"
        sets.append(endpoints)
    return servers, reps, "pio+ha://" + ";".join(sets)


def _kill_all(servers):
    for server in servers:
        try:
            server.kill()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# the partitioned remote client
# ---------------------------------------------------------------------------


class TestPartitionedRemoteStore:
    def test_routing_reads_and_merge(self, tmp_path, breaker_one):
        servers, _reps, url = _boot_fleet(tmp_path, 2)
        try:
            store = remote.RemoteEventStore(url, timeout=5.0)
            assert store.partition_count == 2
            store.init(1)
            acked = {}
            for i in range(24):
                user = f"u{i}"
                eid = store.insert(_rate(user, value=float(i % 5)), 1)
                acked[eid] = store.partition_for(1, user)
            # both partitions took writes, on their own changefeeds
            per_server = [s.changefeed.last_seq for s in servers]
            assert all(seq > 1 for seq in per_server)
            # point reads fan; every acked id readable
            assert all(store.get(eid, 1) is not None for eid in acked)
            assert store.get("nope", 1) is None
            # find merges the per-partition streams back into global
            # (event_time, event_id) order
            events = list(store.find(1))
            assert len(events) == 24
            keys = [(e.event_time, e.event_id) for e in events]
            assert keys == sorted(keys)
            limited = list(store.find(1, EventFilter(limit=5)))
            assert len(limited) == 5
            assert [e.event_id for e in limited] == [
                e.event_id for e in events[:5]
            ]
            # columnar scan merges and re-sorts by time
            cols = store.scan_columnar(1)
            times = list(cols["event_time_ms"])
            assert times == sorted(times)
            assert len(cols["entity_id"]) == 24
            # batch write groups by partition
            batch = [_rate(f"b{i}") for i in range(10)]
            store.write(batch, 1)
            assert len(list(store.find(1))) == 34
            # delete fans
            victim = next(iter(acked))
            assert store.delete(victim, 1) is True
            assert store.get(victim, 1) is None
        finally:
            _kill_all(servers)

    def test_misrouted_direct_write_answers_409(self, tmp_path, breaker_one):
        servers, _reps, _url = _boot_fleet(tmp_path, 2)
        try:
            direct = remote.RemoteEventStore(
                f"http://127.0.0.1:{servers[0].bound_port}", timeout=5.0
            )
            direct.init(1)
            foreign = next(
                f"u{i}" for i in range(50)
                if partition_for_event(2, 1, f"u{i}") == 1
            )
            with pytest.raises(remote.RemoteStorageError) as exc_info:
                direct.insert(_rate(foreign), 1)
            assert exc_info.value.code == 409
            assert "partition" in str(exc_info.value)
        finally:
            _kill_all(servers)

    def test_dead_partition_sheds_only_its_keyspace(
        self, tmp_path, breaker_one
    ):
        servers, _reps, url = _boot_fleet(tmp_path, 2)
        try:
            store = remote.RemoteEventStore(url, timeout=5.0)
            store.init(1)
            servers[1].kill()
            shed = acked = 0
            for i in range(20):
                user = f"u{i}"
                part = store.partition_for(1, user)
                try:
                    store.insert(_rate(user), 1)
                    acked += 1
                    assert part == 0, "ack from the dead partition"
                except remote.PartitionUnavailable as exc:
                    assert exc.partitions == (1,)
                    assert part == 1
                    shed += 1
            assert acked > 0 and shed > 0
            rows = store.partition_status()
            assert [r["up"] for r in rows] == [True, False]
        finally:
            _kill_all(servers)

    def test_write_failover_discovers_promoted_replica(
        self, tmp_path, breaker_one
    ):
        servers, reps, url = _boot_fleet(tmp_path, 2, replicas=True)
        try:
            store = remote.RemoteEventStore(url, timeout=5.0)
            store.init(1)
            for i in range(12):
                store.insert(_rate(f"u{i}"), 1)
            for rep in reps:
                rep.catch_up()
            servers[1].kill()
            dead_key = next(
                f"v{i}" for i in range(50)
                if store.partition_for(1, f"v{i}") == 1
            )
            with pytest.raises(remote.PartitionUnavailable):
                store.insert(_rate(dead_key), 1)
            reps[1].promote(str(tmp_path / "promoted-oplog"))
            # same client, zero reconfiguration: the write path offers
            # the write to the standbys and the promoted one acks
            eid = store.insert(_rate(dead_key), 1)
            assert store.get(eid, 1) is not None
        finally:
            _kill_all(servers + reps)

    def test_server_replication_json_rows(self, tmp_path, breaker_one):
        servers, _reps, _url = _boot_fleet(tmp_path, 2)
        try:
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", servers[1].bound_port, timeout=5.0
            )
            conn.request("GET", "/replication.json")
            body = json.loads(conn.getresponse().read())
            conn.close()
            assert body["partitions"] == [
                {
                    "partition": 1, "of": 2, "up": True,
                    "role": "primary",
                    "seq": servers[1].changefeed.last_seq,
                    "generation": servers[1].changefeed.oplog.generation,
                }
            ]
            assert servers[1].status_json()["partition"] == [1, 2]
        finally:
            _kill_all(servers)


# ---------------------------------------------------------------------------
# event server: partial-partition degradation
# ---------------------------------------------------------------------------


class TestEventServerPartitionShed:
    @pytest.fixture
    def ingest(self, tmp_path, breaker_one):
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.storage.metadata import AccessKey, App

        servers, _reps, url = _boot_fleet(tmp_path, 2)
        store = remote.RemoteEventStore(url, timeout=5.0)
        store.init(1)
        md = MetadataStore(":memory:")
        md.app_insert(App(id=1, name="shed"))
        md.access_key_insert(AccessKey(key="K", appid=1, events=[]))
        event_srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=store, metadata=md,
        )
        event_srv.start_background()
        yield servers, store, event_srv
        _kill_all(servers + [event_srv])

    @staticmethod
    def _post(event_srv, payload, path="/events.json?accessKey=K"):
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", event_srv.bound_port, timeout=10.0
        )
        try:
            conn.request(
                "POST", path, body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        finally:
            conn.close()

    @staticmethod
    def _event_obj(user):
        return {
            "event": "rate", "entityType": "user", "entityId": user,
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 4.0},
        }

    def test_single_insert_sheds_503_with_retry_after(self, ingest):
        servers, store, event_srv = ingest
        servers[1].kill()
        alive = next(
            f"u{i}" for i in range(50) if store.partition_for(1, f"u{i}") == 0
        )
        dead = next(
            f"u{i}" for i in range(50) if store.partition_for(1, f"u{i}") == 1
        )
        status, _headers, _body = self._post(
            event_srv, self._event_obj(alive)
        )
        assert status == 201
        status, headers, body = self._post(event_srv, self._event_obj(dead))
        assert status == 503
        assert int(headers.get("Retry-After", "0")) >= 1
        assert json.loads(body)["partitions"] == [1]
        # the shed is counted, per partition, on /metrics
        from predictionio_tpu.obs.expo import parse_text, render

        samples = parse_text(render(event_srv.metrics)).get(
            "pio_ingest_partition_shed_total", []
        )
        assert [
            (labels["partition"], value) for labels, value in samples
        ] == [("1", 1.0)]

    def test_batch_sheds_per_event(self, ingest):
        servers, store, event_srv = ingest
        servers[1].kill()
        users = [f"u{i}" for i in range(12)]
        status, _headers, body = self._post(
            event_srv, [self._event_obj(u) for u in users],
            path="/batches/events.json?accessKey=K",
        )
        assert status == 200
        results = json.loads(body)
        for user, result in zip(users, results):
            expected = 201 if store.partition_for(1, user) == 0 else 503
            assert result["status"] == expected, (user, result)
        statuses = {r["status"] for r in results}
        assert statuses == {201, 503}  # a mixed batch made progress
        # the shed counter advances once per shed EVENT, so batch-heavy
        # and single-post traffic read identically on the metric
        shed_events = sum(1 for r in results if r["status"] == 503)
        from predictionio_tpu.obs.expo import parse_text, render

        samples = parse_text(render(event_srv.metrics)).get(
            "pio_ingest_partition_shed_total", []
        )
        assert [
            (labels["partition"], value) for labels, value in samples
        ] == [("1", float(shed_events))]

    def test_replication_json_reports_partition_rows(self, ingest):
        servers, _store, event_srv = ingest
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", event_srv.bound_port, timeout=5.0
        )
        conn.request("GET", "/replication.json")
        body = json.loads(conn.getresponse().read())
        conn.close()
        rows = body["partitions"]
        assert [r["partition"] for r in rows] == [0, 1]
        assert all(r["up"] for r in rows)
        assert all(r["of"] == 2 for r in rows)


# ---------------------------------------------------------------------------
# per-partition cursor semantics (the merged feed watcher)
# ---------------------------------------------------------------------------


class TestPartitionedFeedWatcher:
    def _fleet(self, tmp_path, count=2):
        """N local (changefeed, feed) pairs + the merged watcher."""
        feeds, cfs = [], []
        for i in range(count):
            cf = Changefeed(
                OpLog(str(tmp_path / f"ol-{i}"), partition=(i, count)),
                SqliteEventStore(":memory:"), MetadataStore(":memory:"),
                SqliteModelStore(":memory:"),
            )
            cfs.append(cf)
            feeds.append(LocalFeed(cf.oplog))
        watcher = PartitionedFeedWatcher(
            feeds, 1, {"rate": "rating"}, str(tmp_path / "watch")
        )
        return cfs, feeds, watcher

    def _owned_users(self, count, index, n):
        return [
            f"u{i}" for i in range(200)
            if partition_for_event(count, 1, f"u{i}") == index
        ][:n]

    def test_factory_picks_shape(self, tmp_path):
        cf = Changefeed(
            OpLog(str(tmp_path / "f")), SqliteEventStore(":memory:"),
            MetadataStore(":memory:"), SqliteModelStore(":memory:"),
        )
        flat = make_watcher(
            LocalFeed(cf.oplog), 1, {}, str(tmp_path / "w1")
        )
        assert isinstance(flat, FeedWatcher)
        single = make_watcher(
            [LocalFeed(cf.oplog)], 1, {}, str(tmp_path / "w2")
        )
        assert isinstance(single, FeedWatcher)
        merged = make_watcher(
            [LocalFeed(cf.oplog), LocalFeed(cf.oplog)], 1, {},
            str(tmp_path / "w3"),
        )
        assert isinstance(merged, PartitionedFeedWatcher)

    def test_merge_ordering_is_deterministic(self, tmp_path):
        import datetime as dt

        from predictionio_tpu.storage import DataMap

        cfs, _feeds, watcher = self._fleet(tmp_path)
        u0 = self._owned_users(2, 0, 3)
        u1 = self._owned_users(2, 1, 3)

        def rate_at(user, minute):
            return Event(
                event="rate", entity_type="user", entity_id=user,
                target_entity_type="item", target_entity_id="i1",
                properties=DataMap({"rating": 4.0}),
                event_time=dt.datetime(
                    2024, 1, 1, 0, minute, tzinfo=dt.timezone.utc
                ),
            )

        # interleaved event times across partitions, including a cross-
        # partition tie at minute 5 — broken by (partition, seq)
        cfs[0].insert_event(rate_at(u0[0], 1), 1)
        cfs[1].insert_event(rate_at(u1[0], 2), 1)
        cfs[0].insert_event(rate_at(u0[1], 5), 1)
        cfs[1].insert_event(rate_at(u1[1], 5), 1)
        cfs[1].insert_event(rate_at(u1[2], 7), 1)
        cfs[0].insert_event(rate_at(u0[2], 9), 1)
        watcher.poll()
        merged_a = [(e.user, e.seq) for e in watcher.take_batch().events]
        assert [u for u, _s in merged_a] == [
            u0[0], u1[0], u0[1], u1[1], u1[2], u0[2]
        ]
        # a second watcher over the same feeds, polled child-by-child in
        # REVERSE order, produces the identical merged order: the order
        # is a function of the consumed ops, not the poll interleaving
        other = PartitionedFeedWatcher(
            [LocalFeed(cfs[0].oplog), LocalFeed(cfs[1].oplog)], 1,
            {"rate": "rating"}, str(tmp_path / "watch2"),
        )
        for child in reversed(other.watchers):
            child.poll()
        merged_b = [(e.user, e.seq) for e in other.take_batch().events]
        assert merged_a == merged_b

    def test_commit_is_per_partition_and_durable(self, tmp_path):
        cfs, _feeds, watcher = self._fleet(tmp_path)
        for user in self._owned_users(2, 0, 3):
            cfs[0].insert_event(_rate(user), 1)
        for user in self._owned_users(2, 1, 2):
            cfs[1].insert_event(_rate(user), 1)
        watcher.poll()
        batch = watcher.take_batch()
        assert set(batch.upto_seq) == {"0", "1"}
        watcher.commit(batch.upto_seq)
        assert watcher.pending_count() == 0
        # cursor files are independent and durable
        for i in (0, 1):
            path = os.path.join(
                str(tmp_path / "watch"), f"partition-{i}",
                "continuous_cursor.json",
            )
            with open(path) as fh:
                assert json.load(fh)["seq"] == int(batch.upto_seq[str(i)])

    def test_restart_resumes_never_replays(self, tmp_path):
        cfs, _feeds, watcher = self._fleet(tmp_path)
        for user in self._owned_users(2, 0, 3):
            cfs[0].insert_event(_rate(user), 1)
        for user in self._owned_users(2, 1, 3):
            cfs[1].insert_event(_rate(user), 1)
        watcher.poll()
        first = watcher.take_batch()
        watcher.commit(first.upto_seq)
        committed = {int(k): int(v) for k, v in first.upto_seq.items()}
        # new events after the commit
        fresh0 = self._owned_users(2, 0, 5)[3:]
        for user in fresh0:
            cfs[0].insert_event(_rate(user), 1)
        # restart: same cursor dirs, fresh instance
        resumed = PartitionedFeedWatcher(
            [LocalFeed(cfs[0].oplog), LocalFeed(cfs[1].oplog)], 1,
            {"rate": "rating"}, str(tmp_path / "watch"),
        )
        resumed.poll()
        batch = resumed.take_batch()
        users = {e.user for e in batch.events}
        assert users == set(fresh0)  # resumed, exactly the suffix
        for i, child in enumerate(resumed.watchers):
            child_batch = child.take_batch()
            if child_batch is not None:
                assert all(
                    e.seq > committed[i] for e in child_batch.events
                )

    def test_single_partition_gap_scopes_resync(self, tmp_path):
        cfs, feeds, watcher = self._fleet(tmp_path)
        u0 = self._owned_users(2, 0, 2)
        u1 = self._owned_users(2, 1, 2)
        for user in u0:
            cfs[0].insert_event(_rate(user), 1)
        for user in u1:
            cfs[1].insert_event(_rate(user), 1)
        watcher.poll()
        assert watcher.pending_count() == 4
        # partition 1's store is wiped and replaced: new oplog, new
        # generation, numbering restarted — NOT a continuation
        replacement = Changefeed(
            OpLog(str(tmp_path / "ol-1b")),
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        watcher.watchers[1]._feed = LocalFeed(replacement.oplog)
        with pytest.raises(FeedGap, match=r"partition\(s\) \[1\]"):
            watcher.poll()
        # partition 0's pending delta is untouched by the gap
        assert watcher.watchers[0].pending_count() == 2
        # a second poll keeps flowing for partition 0 (new event lands)
        extra = self._owned_users(2, 0, 3)[2:]
        for user in extra:
            cfs[0].insert_event(_rate(user), 1)
        with pytest.raises(FeedGap):
            watcher.poll()
        assert watcher.watchers[0].pending_count() == 3
        # resync: ONLY the gapped partition jumps to its feed head and
        # drops its pending; partition 0 keeps its uncommitted suffix
        cursor0_before = watcher.watchers[0].cursor_seq
        watcher.resync()
        assert watcher.watchers[0].pending_count() == 3
        assert watcher.watchers[0].cursor_seq == cursor0_before
        assert watcher.watchers[1].pending_count() == 0
        assert (
            watcher.watchers[1].generation == replacement.oplog.generation
        )
        # and the loop is whole again
        assert watcher.poll() == 0

    def test_shape_mismatch_commits_raise_catchably(self, tmp_path):
        """A resharding restart can pair a durable per-partition cursor
        map with a flat watcher (or vice versa). Both mismatches must
        surface as TypeError — the catchable contract the continuous
        controller's LIVE path relies on to resync-and-retrain instead
        of wedging the loop forever."""
        cfs, _feeds, watcher = self._fleet(tmp_path)
        with pytest.raises(TypeError):
            watcher.commit(7)  # int cursor against a partitioned layout
        flat = FeedWatcher(
            LocalFeed(cfs[0].oplog), 1, {"rate": "rating"},
            str(tmp_path / "flat"),
        )
        with pytest.raises(TypeError):
            flat.commit({"0": 7})  # map cursor against a flat layout

    def test_promoted_continuation_adopts_without_gap(self, tmp_path):
        """A promoted replica CONTINUES the dead primary's numbering:
        the generation changes but the cursor stays meaningful — the
        watcher adopts and resumes instead of forcing a retrain."""
        cf = Changefeed(
            OpLog(str(tmp_path / "ol")),
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        watcher = FeedWatcher(
            LocalFeed(cf.oplog), 1, {"rate": "rating"},
            str(tmp_path / "w"),
        )
        for i in range(3):
            cf.insert_event(_rate(f"u{i}"), 1)
        watcher.poll()
        applied = cf.oplog.last_seq
        old_generation = watcher.generation
        # failover: a new log continues the numbering (promotion path)
        promoted = Changefeed(
            OpLog(str(tmp_path / "promoted"), base_seq=applied),
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        watcher._feed = LocalFeed(promoted.oplog)
        promoted.insert_event(_rate("u9"), 1)
        assert watcher.poll() == 1  # no FeedGap: continuation adopted
        assert watcher.generation == promoted.oplog.generation
        assert watcher.generation != old_generation


# ---------------------------------------------------------------------------
# the chaos drill + ingest scaling (tier-1 acceptance)
# ---------------------------------------------------------------------------


class TestPartitionChaosDrill:
    def test_drill_is_green(self):
        from predictionio_tpu.tools.loadgen import run_partition_chaos

        report = run_partition_chaos(
            partitions=2, kill_partition=1, ops_per_phase=16,
            concurrency=2,
        )
        assert report["ok"], report
        assert report["lostAckedWrites"] == 0
        assert report["failuresOnUnaffected"] == 0
        assert report["shedOnUnaffected"] == 0
        assert report["shedOnKilledPartition"] > 0
        assert report["replicationLagAfterPromote"] == 0
        assert report["watcherResumeGap"] is None
        assert report["watcherReplayedCommitted"] == 0
        assert report["watcherResumeEvents"] > 0

    def test_rejects_bad_arguments(self):
        from predictionio_tpu.tools.loadgen import run_partition_chaos

        with pytest.raises(ValueError):
            run_partition_chaos(partitions=1, kill_partition=0)
        with pytest.raises(ValueError):
            run_partition_chaos(partitions=2, kill_partition=5)


class TestIngestScaling:
    def test_in_process_shape(self):
        from predictionio_tpu.tools.loadgen import run_ingest_scaling

        report = run_ingest_scaling(
            partition_counts=(1, 2), events=24, writers=2,
            in_process=True,
        )
        assert report["ok"], report
        assert set(report["counts"]) == {"1", "2"}
        for row in report["counts"].values():
            assert row["errors"] == 0
            assert row["ackedQPS"] > 0

    def test_ledger_records_keyed_by_partition_count(self):
        from predictionio_tpu.obs import perfledger

        bench = {
            "device": "cpu",
            "ingestScaling": {
                "ok": True,
                "writers": 4,
                "counts": {
                    "1": {"ackedQPS": 100.0, "acked": 480},
                    "2": {"ackedQPS": 180.0, "acked": 480},
                    "4": {"ackedQPS": 300.0, "acked": 480},
                },
            },
        }
        records = perfledger.ingest_records(bench)
        assert [r["metric"] for r in records] == ["ingest_acked_qps"] * 3
        assert [r["scale"] for r in records] == [1, 2, 4]
        assert all(r["unit"] == "qps" for r in records)
        # different partition counts never share a comparable group, so
        # `pio perf diff` can never gate across N
        keys = {perfledger.comparable_key(r) for r in records}
        assert len(keys) == 3
        # a failed drive records nothing
        assert perfledger.ingest_records(
            {"ingestScaling": {"ok": False, "counts": {}}}
        ) == []


# ---------------------------------------------------------------------------
# fleet surfaces: PARTS column + per-partition freshness objectives
# ---------------------------------------------------------------------------


class TestPartsColumn:
    def test_fleet_columns_grow_parts(self):
        from predictionio_tpu.obs.top import FLEET_COLUMNS

        assert any(title == "PARTS" for title, _k, _f in FLEET_COLUMNS)

    def test_node_rows_render_parts(self, tmp_path, breaker_one):
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.obs.top import format_row, node_row

        servers, _reps, url = _boot_fleet(tmp_path, 2)
        event_srv = None
        try:
            store = remote.RemoteEventStore(url, timeout=5.0)
            event_srv = EventServer(
                EventServerConfig(ip="127.0.0.1", port=0),
                events=store, metadata=MetadataStore(":memory:"),
            )
            event_srv.start_background()
            ingest_row = node_row(f"127.0.0.1:{event_srv.bound_port}")
            assert ingest_row["parts"] == "2/2"
            storage_row = node_row(f"127.0.0.1:{servers[1].bound_port}")
            assert storage_row["parts"] == "p1/2"
            # a node without the surface shows '-'
            assert "-" in format_row({"node": "x", "up": True})
            servers[0].kill()
            degraded = node_row(f"127.0.0.1:{event_srv.bound_port}")
            assert degraded["parts"] == "1/2"
        finally:
            _kill_all(servers + ([event_srv] if event_srv else []))


class TestPerPartitionFreshness:
    def _engine(self, objectives):
        from predictionio_tpu.obs.metrics import MetricsRegistry
        from predictionio_tpu.obs.slo import SLOEngine
        from predictionio_tpu.testing.clock import FakeClock

        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)
        engine = SLOEngine(registry, objectives, clock=clock)
        return registry, engine, clock

    def _freshness(self):
        from predictionio_tpu.obs.slo import default_objectives

        objectives = [
            o for o in default_objectives("storage") if o.name == "freshness"
        ]
        assert objectives and objectives[0].per_label == "partition"
        return objectives

    def test_one_lagging_partition_fires_alone(self):
        registry, engine, clock = self._engine(self._freshness())
        gauge = registry.gauge(
            "pio_replication_lag_ops", "", labelnames=("partition",)
        )
        for _ in range(80):
            gauge.set(2.0, partition="0")
            gauge.set(50000.0, partition="1")  # way past max_value
            clock.advance(60.0)
            summary = engine.evaluate()
        states = {o["name"]: o["state"] for o in summary["objectives"]}
        assert states == {"freshness[0]": "OK", "freshness[1]": "FIRING"}
        # the healthy mean would have hidden it: (2 + 50000)/2 / 10000
        # barely burns, but the per-partition machine fired regardless
        assert summary["firing"] == 1

    def test_data_loss_holds_firing_state(self):
        registry, engine, clock = self._engine(self._freshness())
        gauge = registry.gauge(
            "pio_replication_lag_ops", "", labelnames=("partition",)
        )
        for _ in range(80):
            gauge.set(50000.0, partition="1")
            clock.advance(60.0)
            engine.evaluate()
        assert engine.firing() == ["freshness[1]"]
        # the node stops exporting (scrape loss): the alert HOLDS
        gauge.set(-1.0, partition="1")  # abstention sentinel
        clock.advance(60.0)
        summary = engine.evaluate()
        states = {o["name"]: o["state"] for o in summary["objectives"]}
        assert states["freshness[1]"] == "FIRING"

    def test_no_rows_is_visible_abstention(self):
        _registry, engine, clock = self._engine(self._freshness())
        clock.advance(60.0)
        summary = engine.evaluate()
        assert [
            (o["name"], o["abstaining"]) for o in summary["objectives"]
        ] == [("freshness", True)]
