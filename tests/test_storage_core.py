"""Storage-plane tests.

Mirrors the reference's data-module specs: ``EventsSpec.scala`` (insert/get/
delete roundtrip), ``LEventAggregatorSpec``/``PEventAggregatorSpec``
($set/$unset/$delete folding), ``BiMapSpec``, and DataMap/Event validation
behavior from ``DataMap.scala`` / ``Event.scala``.
"""

import datetime as dt

import pytest

from predictionio_tpu.storage import (
    BiMap,
    DataMap,
    DataMapException,
    Event,
    EventFilter,
    EventValidationError,
    aggregate_properties,
    aggregate_single,
    validate_event,
)

UTC = dt.timezone.utc


def ts(seconds: int) -> dt.datetime:
    return dt.datetime(2024, 1, 1, 0, 0, 0, tzinfo=UTC) + dt.timedelta(
        seconds=seconds
    )


# ---------------------------------------------------------------------------
# DataMap
# ---------------------------------------------------------------------------
class TestDataMap:
    def test_typed_get(self):
        d = DataMap({"a": 1, "b": "x", "c": [1, 2], "d": 2.5})
        assert d.get_as("a", int) == 1
        assert d.get_as("b", str) == "x"
        assert d.get_as("c", list) == [1, 2]
        assert d.get_as("d", float) == 2.5
        # int widens to float (json4s extracts Int as Double on demand)
        assert d.get_as("a", float) == 1.0

    def test_get_missing_raises(self):
        with pytest.raises(DataMapException):
            DataMap({}).get_as("nope", int)

    def test_get_wrong_type_raises(self):
        with pytest.raises(DataMapException):
            DataMap({"a": "str"}).get_as("a", int)

    def test_mapping_get_contract(self):
        d = DataMap({"a": 1})
        assert d.get("missing") is None
        assert d.get("missing", "fallback") == "fallback"
        assert d.get("a") == 1

    def test_get_opt_and_or_else(self):
        d = DataMap({"a": 7})
        assert d.get_opt("a", int) == 7
        assert d.get_opt("zz", int) is None
        assert d.get_or_else("zz", 3) == 3

    def test_merge_right_biased(self):
        a = DataMap({"x": 1, "y": 2})
        b = DataMap({"y": 9, "z": 3})
        assert (a | b).to_dict() == {"x": 1, "y": 9, "z": 3}

    def test_without(self):
        d = DataMap({"x": 1, "y": 2})
        assert d.without(["y"]).to_dict() == {"x": 1}


# ---------------------------------------------------------------------------
# Event validation (Event.scala:70-99)
# ---------------------------------------------------------------------------
class TestEventValidation:
    def ok(self, **kw):
        defaults = dict(event="rate", entity_type="user", entity_id="u1")
        defaults.update(kw)
        return Event(**defaults)

    def test_valid_plain_event(self):
        validate_event(
            self.ok(
                target_entity_type="item",
                target_entity_id="i1",
                properties=DataMap({"rating": 4.0}),
            )
        )

    def test_special_events_allowed(self):
        validate_event(self.ok(event="$set", properties=DataMap({"a": 1})))
        validate_event(self.ok(event="$unset", properties=DataMap({"a": None})))
        validate_event(self.ok(event="$delete"))

    def test_unknown_dollar_event_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.ok(event="$frob"))

    def test_empty_fields_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.ok(event=""))
        with pytest.raises(EventValidationError):
            validate_event(self.ok(entity_type=""))
        with pytest.raises(EventValidationError):
            validate_event(self.ok(entity_id=""))

    def test_target_entity_must_be_paired(self):
        with pytest.raises(EventValidationError):
            validate_event(self.ok(target_entity_type="item"))
        with pytest.raises(EventValidationError):
            validate_event(self.ok(target_entity_id="i1"))

    def test_unset_requires_properties(self):
        with pytest.raises(EventValidationError):
            validate_event(self.ok(event="$unset"))

    def test_special_event_cannot_have_target(self):
        with pytest.raises(EventValidationError):
            validate_event(
                self.ok(
                    event="$set",
                    target_entity_type="item",
                    target_entity_id="i1",
                )
            )

    def test_reserved_prefixes(self):
        with pytest.raises(EventValidationError):
            validate_event(self.ok(entity_type="pio_thing"))
        # builtin pio_pr is allowed
        validate_event(self.ok(entity_type="pio_pr"))
        with pytest.raises(EventValidationError):
            validate_event(self.ok(properties=DataMap({"pio_x": 1})))

    def test_json_roundtrip(self):
        e = self.ok(
            target_entity_type="item",
            target_entity_id="i1",
            properties=DataMap({"rating": 4.0}),
            event_time=ts(5),
            tags=("a", "b"),
            pr_id="pr-1",
        )
        e2 = Event.from_json_dict(e.to_json_dict())
        assert e2.event == e.event
        assert e2.entity_id == e.entity_id
        assert e2.target_entity_id == "i1"
        assert e2.properties == e.properties
        assert e2.event_time == e.event_time
        assert e2.tags == ("a", "b")
        assert e2.pr_id == "pr-1"


# ---------------------------------------------------------------------------
# Aggregation (LEventAggregatorSpec / PEventAggregator.scala)
# ---------------------------------------------------------------------------
def set_ev(eid, t, props):
    return Event(
        event="$set", entity_type="user", entity_id=eid,
        properties=DataMap(props), event_time=ts(t),
    )


def unset_ev(eid, t, keys):
    return Event(
        event="$unset", entity_type="user", entity_id=eid,
        properties=DataMap({k: None for k in keys}), event_time=ts(t),
    )


def delete_ev(eid, t):
    return Event(
        event="$delete", entity_type="user", entity_id=eid, event_time=ts(t),
    )


class TestAggregation:
    def test_set_merge_latest_wins(self):
        events = [
            set_ev("u1", 10, {"a": 1, "b": 2}),
            set_ev("u1", 20, {"b": 3, "c": 4}),
            set_ev("u1", 15, {"b": 99}),  # older than t=20 for b
        ]
        out = aggregate_properties(events)
        pm = out["u1"]
        assert pm.to_dict() == {"a": 1, "b": 3, "c": 4}
        assert pm.first_updated == ts(10)
        assert pm.last_updated == ts(20)

    def test_order_independence(self):
        events = [
            set_ev("u1", 10, {"a": 1}),
            unset_ev("u1", 15, ["a"]),
            set_ev("u1", 20, {"a": 5}),
        ]
        import itertools

        results = set()
        for perm in itertools.permutations(events):
            pm = aggregate_single(list(perm))
            results.add(tuple(sorted(pm.to_dict().items())))
        assert results == {(("a", 5),)}

    def test_unset_drops_field_when_later(self):
        events = [set_ev("u1", 10, {"a": 1, "b": 2}), unset_ev("u1", 15, ["a"])]
        assert aggregate_single(events).to_dict() == {"b": 2}

    def test_unset_before_set_is_noop(self):
        events = [set_ev("u1", 10, {"a": 1}), unset_ev("u1", 5, ["a"])]
        assert aggregate_single(events).to_dict() == {"a": 1}

    def test_unset_ties_win(self):
        # reference: unset time >= set time drops the field
        events = [set_ev("u1", 10, {"a": 1}), unset_ev("u1", 10, ["a"])]
        assert aggregate_single(events).to_dict() == {}

    def test_unset_of_never_set_key(self):
        events = [set_ev("u1", 10, {"a": 1}), unset_ev("u1", 15, ["zz"])]
        assert aggregate_single(events).to_dict() == {"a": 1}

    def test_delete_after_last_set_deletes_entity(self):
        events = [set_ev("u1", 10, {"a": 1}), delete_ev("u1", 20)]
        assert aggregate_single(events) is None
        assert aggregate_properties(events) == {}

    def test_delete_then_set_keeps_newer_fields(self):
        events = [
            set_ev("u1", 10, {"a": 1}),
            delete_ev("u1", 15),
            set_ev("u1", 20, {"b": 2}),
        ]
        assert aggregate_single(events).to_dict() == {"b": 2}

    def test_no_set_means_no_entity(self):
        assert aggregate_single([unset_ev("u1", 5, ["a"])]) is None
        assert aggregate_single([delete_ev("u1", 5)]) is None

    def test_non_special_events_ignored(self):
        rate = Event(
            event="rate", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1",
            event_time=ts(50),
        )
        events = [set_ev("u1", 10, {"a": 1}), rate]
        pm = aggregate_single(events)
        assert pm.to_dict() == {"a": 1}
        assert pm.last_updated == ts(10)  # rate doesn't move lastUpdated

    def test_multiple_entities(self):
        events = [
            set_ev("u1", 10, {"a": 1}),
            set_ev("u2", 11, {"a": 2}),
            delete_ev("u2", 12),
        ]
        out = aggregate_properties(events)
        assert set(out) == {"u1"}


# ---------------------------------------------------------------------------
# SqliteEventStore (EventsSpec analogue)
# ---------------------------------------------------------------------------
class TestEventStore:
    def test_insert_get_roundtrip(self, event_store):
        e = Event(
            event="rate", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1",
            properties=DataMap({"rating": 4.5}), event_time=ts(1),
            tags=("t1",), pr_id="p1",
        )
        eid = event_store.insert(e, app_id=1)
        got = event_store.get(eid, app_id=1)
        assert got is not None
        assert got.event == "rate"
        assert got.entity_id == "u1"
        assert got.target_entity_id == "i1"
        assert got.properties.get_as("rating", float) == 4.5
        assert got.event_time == ts(1)
        assert got.tags == ("t1",)
        assert got.pr_id == "p1"

    def test_delete(self, event_store):
        eid = event_store.insert(
            Event(event="e", entity_type="t", entity_id="i"), 1
        )
        assert event_store.delete(eid, 1) is True
        assert event_store.get(eid, 1) is None
        assert event_store.delete(eid, 1) is False

    def test_app_isolation(self, event_store):
        event_store.init(2)
        event_store.insert(Event(event="a", entity_type="t", entity_id="1"), 1)
        event_store.insert(Event(event="b", entity_type="t", entity_id="1"), 2)
        assert [e.event for e in event_store.find(1)] == ["a"]
        assert [e.event for e in event_store.find(2)] == ["b"]

    def test_find_filters(self, event_store):
        for i, (name, etype, eid_) in enumerate(
            [
                ("rate", "user", "u1"),
                ("buy", "user", "u1"),
                ("rate", "user", "u2"),
                ("view", "item", "i1"),
            ]
        ):
            event_store.insert(
                Event(
                    event=name, entity_type=etype, entity_id=eid_,
                    target_entity_type="item", target_entity_id="x",
                    event_time=ts(i),
                ),
                1,
            )
        f = EventFilter(event_names=["rate"])
        assert len(list(event_store.find(1, f))) == 2
        f = EventFilter(entity_type="user", entity_id="u1")
        assert len(list(event_store.find(1, f))) == 2
        f = EventFilter(start_time=ts(1), until_time=ts(3))
        assert [e.event for e in event_store.find(1, f)] == ["buy", "rate"]
        f = EventFilter(limit=2, reversed=True)
        got = [e.event for e in event_store.find(1, f)]
        assert got == ["view", "rate"]

    def test_aggregate_through_store(self, event_store):
        event_store.insert(set_ev("u1", 10, {"a": 1}), 1)
        event_store.insert(unset_ev("u1", 15, ["a"]), 1)
        event_store.insert(set_ev("u1", 20, {"b": 2}), 1)
        event_store.insert(set_ev("u2", 20, {"a": 9}), 1)
        out = event_store.aggregate_properties(1, "user")
        assert out["u1"].to_dict() == {"b": 2}
        assert out["u2"].to_dict() == {"a": 9}
        single = event_store.aggregate_properties_single(1, "user", "u1")
        assert single.to_dict() == {"b": 2}

    def test_aggregate_required_filter(self, event_store):
        event_store.insert(set_ev("u1", 1, {"a": 1, "b": 2}), 1)
        event_store.insert(set_ev("u2", 1, {"a": 1}), 1)
        out = event_store.aggregate_properties(1, "user", required=["b"])
        assert set(out) == {"u1"}

    def test_scan_columnar(self, event_store):
        for i in range(5):
            event_store.insert(
                Event(
                    event="rate", entity_type="user", entity_id=f"u{i % 2}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(i)}), event_time=ts(i),
                ),
                1,
            )
        cols = event_store.scan_columnar(1, EventFilter(event_names=["rate"]))
        assert cols["entity_id"] == ["u0", "u1", "u0", "u1", "u0"]
        assert [p["rating"] for p in cols["properties"]] == [0, 1, 2, 3, 4]

    def test_remove_app(self, event_store):
        event_store.insert(Event(event="a", entity_type="t", entity_id="1"), 1)
        assert event_store.remove(1)
        event_store.init(1)
        assert list(event_store.find(1)) == []


# ---------------------------------------------------------------------------
# BiMap (BiMapSpec)
# ---------------------------------------------------------------------------
class TestBiMap:
    def test_forward_inverse(self):
        m = BiMap({"a": 1, "b": 2})
        assert m["a"] == 1
        assert m.inverse[2] == "b"
        assert m.get("zz") is None
        assert m.get_or_else("zz", -1) == -1

    def test_duplicate_values_rejected(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})

    def test_inverse_is_cached_and_cycle_free(self):
        """Serving takes .inverse per batch: it must be O(1) (cached,
        dict-sharing — no catalog copies), survive pickling, and not form
        a reference cycle that would keep catalog-sized dicts alive past
        a /reload (refcount-freed, no gc pass needed)."""
        import pickle
        import weakref

        m = BiMap({f"i{k}": k for k in range(100)})
        assert m.inverse is m.inverse  # cached view, not a copy per access
        assert m.inverse._forward is m._inverse  # shared dicts
        assert m.inverse.inverse["i5"] == 5
        m2 = pickle.loads(pickle.dumps(m))
        assert m2.inverse[7] == "i7"
        ref = weakref.ref(m)
        del m, m2
        assert ref() is None  # refcount alone frees it → no cycle

    def test_string_int_dense(self):
        m = BiMap.string_int(["x", "y", "x", "z", "y"])
        assert len(m) == 3
        assert sorted(m.to_dict().values()) == [0, 1, 2]
        assert m["x"] == 0  # first-seen order

    def test_map_array(self):
        m = BiMap.string_int(["x", "y"])
        import numpy as np

        arr = m.map_array(["y", "x", "nope"])
        assert arr.tolist() == [1, 0, -1]
        assert arr.dtype == np.int32

    def test_inverse_list(self):
        m = BiMap.string_int(["x", "y", "z"])
        assert m.inverse_list([2, 0]) == ["z", "x"]
