"""Template tests: classification, similarproduct, ecommerce.

Each template runs the full DASE path end-to-end against an in-process
event store — the analogue of the reference templates' quickstart flows
(SURVEY §2.6) — asserting both dataflow wiring and model quality on
deterministic synthetic events.
"""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.storage import Event, StorageRegistry
from predictionio_tpu.workflow.context import WorkflowContext

from predictionio_tpu.models import classification, ecommerce, similarproduct

APP_ID = 1
T0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    reg = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
    import predictionio_tpu.storage.registry as regmod

    monkeypatch.setattr(regmod, "_default_registry", reg)
    reg.get_events().init(APP_ID)
    return reg


@pytest.fixture()
def ctx():
    return WorkflowContext(mode="Test")


def _t(minutes):
    return T0 + dt.timedelta(minutes=minutes)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def ingest_classification(reg, n_per_class=40):
    """Users whose attr proportions determine their plan."""
    store = reg.get_events()
    rng = np.random.default_rng(7)
    base = {0.0: [20, 2, 2], 1.0: [2, 20, 2], 2.0: [2, 2, 20]}
    uid = 0
    for plan, b in base.items():
        for _ in range(n_per_class):
            attrs = rng.poisson(b).astype(float)
            store.insert(
                Event(
                    event="$set",
                    entity_type="user",
                    entity_id=f"u{uid}",
                    properties={
                        "plan": plan,
                        "attr0": float(attrs[0]),
                        "attr1": float(attrs[1]),
                        "attr2": float(attrs[2]),
                    },
                    event_time=_t(uid),
                ),
                APP_ID,
            )
            uid += 1
    # one user missing a required property — must be skipped
    store.insert(
        Event(
            event="$set",
            entity_type="user",
            entity_id="incomplete",
            properties={"plan": 0.0, "attr0": 1.0},
            event_time=_t(uid),
        ),
        APP_ID,
    )
    return 3 * n_per_class


class TestClassificationTemplate:
    def test_datasource_skips_incomplete(self, registry, ctx):
        n = ingest_classification(registry)
        td = classification.ClassificationDataSource().read_training(ctx)
        assert td.features.shape == (n, 3)
        assert set(np.unique(td.labels)) == {0.0, 1.0, 2.0}

    def test_engine_trains_both_algorithms(self, registry, ctx):
        ingest_classification(registry)
        engine = classification.engine_factory()
        ep = EngineParams(
            data_source_params=("", classification.ClassificationDataSourceParams()),
            algorithm_params_list=[
                ("naive", classification.NaiveBayesParams(lam=1.0)),
                (
                    "randomforest",
                    classification.RandomForestParams(
                        num_classes=3, num_trees=8, max_depth=4,
                        feature_subset_strategy="all",
                    ),
                ),
            ],
        )
        models = engine.train(ctx, ep)
        assert len(models) == 2
        algos = engine._algorithms(ep)
        # both algorithms should classify the class-0 prototype correctly
        q = classification.Query(features=(20.0, 2.0, 2.0))
        for algo, model in zip(algos, models):
            assert algo.predict(model, q).label == 0.0

    def test_batch_predict_matches_predict(self, registry, ctx):
        ingest_classification(registry)
        algo = classification.NaiveBayesAlgorithm()
        td = classification.ClassificationDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        queries = [
            classification.Query(features=tuple(td.features[i]))
            for i in range(10)
        ]
        batched = dict(algo.batch_predict(model, list(enumerate(queries))))
        for i, q in enumerate(queries):
            assert batched[i] == algo.predict(model, q)


# ---------------------------------------------------------------------------
# similarproduct
# ---------------------------------------------------------------------------


def ingest_similarproduct(reg):
    """Two item clusters: users view within their cluster; likes mirror
    cluster membership."""
    store = reg.get_events()
    items_a = [f"a{i}" for i in range(6)]
    items_b = [f"b{i}" for i in range(6)]
    for it in items_a:
        store.insert(
            Event(event="$set", entity_type="item", entity_id=it,
                  properties={"categories": ["alpha"]}, event_time=_t(0)),
            APP_ID,
        )
    for it in items_b:
        store.insert(
            Event(event="$set", entity_type="item", entity_id=it,
                  properties={"categories": ["beta"]}, event_time=_t(0)),
            APP_ID,
        )
    rng = np.random.default_rng(3)
    minute = 1
    for u in range(24):
        uid = f"u{u}"
        store.insert(
            Event(event="$set", entity_type="user", entity_id=uid,
                  event_time=_t(0)),
            APP_ID,
        )
        pool = items_a if u % 2 == 0 else items_b
        for it in rng.choice(pool, size=5, replace=False):
            # repeated views strengthen the implicit-confidence signal
            for _ in range(int(rng.integers(2, 5))):
                store.insert(
                    Event(event="view", entity_type="user", entity_id=uid,
                          target_entity_type="item", target_entity_id=str(it),
                          event_time=_t(minute)),
                    APP_ID,
                )
            store.insert(
                Event(event="like", entity_type="user", entity_id=uid,
                      target_entity_type="item", target_entity_id=str(it),
                      event_time=_t(minute)),
                APP_ID,
            )
            minute += 1
    return items_a, items_b


class TestSimilarProductTemplate:
    def test_similar_items_stay_in_cluster(self, registry, ctx):
        items_a, items_b = ingest_similarproduct(registry)
        engine = similarproduct.engine_factory()
        ep = EngineParams(
            data_source_params=("", similarproduct.SimilarProductDataSourceParams()),
            algorithm_params_list=[
                ("als", similarproduct.SimilarALSParams(
                    rank=8, num_iterations=15, seed=1)),
            ],
        )
        models = engine.train(ctx, ep)
        algo = engine._algorithms(ep)[0]
        result = algo.predict(
            models[0], similarproduct.Query(items=("a0",), num=3)
        )
        assert len(result.item_scores) == 3
        top = [s.item for s in result.item_scores]
        assert "a0" not in top  # query item excluded
        assert sum(t.startswith("a") for t in top) >= 2, top

    def test_batch_predict_matches_single(self, registry, ctx):
        """The micro-batched path (one [B,R]x[R,I] matmul) must return
        exactly what per-query predict returns, mixed filters included."""
        ingest_similarproduct(registry)
        algo = similarproduct.SimilarALSAlgorithm(
            similarproduct.SimilarALSParams(rank=8, num_iterations=10, seed=1)
        )
        td = similarproduct.SimilarProductDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        queries = [
            similarproduct.Query(items=("a0",), num=3),
            similarproduct.Query(items=("nope",), num=3),  # unknown item
            similarproduct.Query(items=("b0", "b1"), num=4,
                                 black_list=("b2",)),
        ]
        batched = dict(algo.batch_predict(model, list(enumerate(queries))))
        for i, q in enumerate(queries):
            # predict() routes through batch_predict with B=1; compare
            # against a fresh B=1 call. Scores may differ in the last ulp
            # between batch sizes (matmul vs matvec accumulation order),
            # so compare items exactly and scores numerically.
            single = dict(algo.batch_predict(model, [(0, q)]))[0]
            assert [s.item for s in batched[i].item_scores] == [
                s.item for s in single.item_scores
            ], (i, batched[i], single)
            assert np.allclose(
                [s.score for s in batched[i].item_scores],
                [s.score for s in single.item_scores],
                rtol=1e-5,
            )
        assert batched[1].item_scores == ()

    def test_streaming_topk_matches_dense(self, registry, ctx):
        """The Pallas streaming path (big-catalog serving, round 3) must
        return exactly the dense path's items for unconstrained queries —
        exclusions ride per-query index lists instead of a [B, I] mask.
        Runs in interpret mode on CPU (same code path shape as TPU)."""
        ingest_similarproduct(registry)
        td = similarproduct.SimilarProductDataSource().read_training(ctx)
        plain = [
            similarproduct.Query(items=("a0",), num=3),
            similarproduct.Query(items=("b0", "b1"), num=4,
                                 black_list=("b2",)),
        ]
        constrained = similarproduct.Query(
            items=("a0",), num=3, categories=("beta",)
        )
        results = {}
        for mode in ("never", "always"):
            algo = similarproduct.SimilarALSAlgorithm(
                similarproduct.SimilarALSParams(
                    rank=8, num_iterations=10, seed=1, streaming_top_k=mode
                )
            )
            model = algo.train(ctx, td)
            # all-unconstrained batch: streams under "always"
            assert algo._use_streaming_topk(
                2, 10, [(0, q, [0]) for q in plain]
            ) == (mode == "always")
            results[mode] = dict(
                algo.batch_predict(model, list(enumerate(plain)))
            )
            # a category filter needs the dense mask: streaming declines
            assert not algo._use_streaming_topk(
                1, 10, [(0, constrained, [0])]
            )
            res_c = algo.predict(model, constrained)
            assert all(s.item.startswith("b") for s in res_c.item_scores)
        for i in range(len(plain)):
            assert [s.item for s in results["always"][i].item_scores] == [
                s.item for s in results["never"][i].item_scores
            ], (i, results["always"][i], results["never"][i])

    def test_train_without_set_entities_raises(self, registry, ctx):
        """View events whose users/items were never $set must fail loudly
        instead of training a silent all-zero model."""
        ev = registry.get_events()
        ev.write(
            [
                Event(event="view", entity_type="user", entity_id="u1",
                      target_entity_type="item", target_entity_id="i1")
            ],
            1,
        )
        algo = similarproduct.SimilarALSAlgorithm(
            similarproduct.SimilarALSParams(rank=4, num_iterations=2)
        )
        td = similarproduct.SimilarProductDataSource().read_training(ctx)
        with pytest.raises(ValueError, match="\\$set"):
            algo.train(ctx, td)

    def test_category_and_blacklist_filters(self, registry, ctx):
        ingest_similarproduct(registry)
        algo = similarproduct.SimilarALSAlgorithm(
            similarproduct.SimilarALSParams(rank=8, num_iterations=10, seed=1)
        )
        td = similarproduct.SimilarProductDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        res = algo.predict(
            model,
            similarproduct.Query(
                items=("a0",), num=10, categories=("beta",)
            ),
        )
        assert all(s.item.startswith("b") for s in res.item_scores)
        res = algo.predict(
            model,
            similarproduct.Query(items=("a0",), num=10, black_list=("a1", "a2")),
        )
        assert not {"a1", "a2"}.intersection(s.item for s in res.item_scores)

    def test_unknown_query_item_empty(self, registry, ctx):
        ingest_similarproduct(registry)
        algo = similarproduct.SimilarALSAlgorithm()
        td = similarproduct.SimilarProductDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        res = algo.predict(model, similarproduct.Query(items=("nope",)))
        assert res.item_scores == ()

    def test_ensemble_serving_zscore_sum(self, registry, ctx):
        ingest_similarproduct(registry)
        engine = similarproduct.engine_factory()
        ep = EngineParams(
            data_source_params=("", similarproduct.SimilarProductDataSourceParams()),
            algorithm_params_list=[
                ("als", similarproduct.SimilarALSParams(
                    rank=8, num_iterations=10, seed=1)),
                ("likealgo", similarproduct.SimilarALSParams(
                    rank=8, num_iterations=10, seed=2)),
            ],
        )
        models = engine.train(ctx, ep)
        assert len(models) == 2
        algos = engine._algorithms(ep)
        serving = engine._serving(ep)
        q = similarproduct.Query(items=("a0", "a1"), num=4)
        preds = [a.predict(m, q) for a, m in zip(algos, models)]
        combined = serving.serve(q, preds)
        assert 0 < len(combined.item_scores) <= 4
        # scores are standardized sums, descending
        scores = [s.score for s in combined.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_serving_zero_std_returns_zero(self):
        serving = similarproduct.SimilarProductServing()
        pr = similarproduct.PredictedResult(
            item_scores=(
                similarproduct.ItemScore("x", 2.0),
                similarproduct.ItemScore("y", 2.0),
            )
        )
        out = serving.serve(similarproduct.Query(items=("q",), num=2), [pr])
        assert all(s.score == 0.0 for s in out.item_scores)


# ---------------------------------------------------------------------------
# ecommerce
# ---------------------------------------------------------------------------


def ingest_ecommerce(reg):
    store = reg.get_events()
    items = [f"i{i}" for i in range(8)]
    for it in items:
        store.insert(
            Event(event="$set", entity_type="item", entity_id=it,
                  properties={"categories": ["cat1" if int(it[1:]) < 4 else "cat2"]},
                  event_time=_t(0)),
            APP_ID,
        )
    rng = np.random.default_rng(5)
    minute = 1
    for u in range(12):
        uid = f"u{u}"
        store.insert(
            Event(event="$set", entity_type="user", entity_id=uid,
                  event_time=_t(0)),
            APP_ID,
        )
        likes_low = u % 2 == 0
        for it in items:
            pref = int(it[1:]) < 4
            rating = 5.0 if pref == likes_low else 1.0
            rating += float(rng.normal(0, 0.2))
            store.insert(
                Event(event="rate", entity_type="user", entity_id=uid,
                      target_entity_type="item", target_entity_id=it,
                      properties={"rating": rating}, event_time=_t(minute)),
                APP_ID,
            )
            minute += 1
    return items


class TestECommerceTemplate:
    def _algo(self, unseen_only=False, **kw):
        kw.setdefault("rank", 8)
        kw.setdefault("num_iterations", 15)
        kw.setdefault("seed", 1)
        return ecommerce.ECommerceALSAlgorithm(
            ecommerce.ECommerceALSParams(
                app_id=APP_ID, unseen_only=unseen_only, **kw,
            )
        )

    def test_known_user_recommendations(self, registry, ctx):
        ingest_ecommerce(registry)
        algo = self._algo()
        td = ecommerce.ECommerceDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        res = algo.predict(model, ecommerce.Query(user="u0", num=3))
        assert len(res.item_scores) == 3
        # u0 likes low-numbered items
        assert sum(int(s.item[1:]) < 4 for s in res.item_scores) >= 2

    def test_unseen_only_filters_rated(self, registry, ctx):
        ingest_ecommerce(registry)
        store = registry.get_events()
        # u0 has "seen" (bought) i0 and i1
        for it in ("i0", "i1"):
            store.insert(
                Event(event="buy", entity_type="user", entity_id="u0",
                      target_entity_type="item", target_entity_id=it,
                      event_time=_t(500)),
                APP_ID,
            )
        algo = self._algo(unseen_only=True)
        td = ecommerce.ECommerceDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        res = algo.predict(model, ecommerce.Query(user="u0", num=8))
        assert not {"i0", "i1"}.intersection(s.item for s in res.item_scores)

    def test_unavailable_items_constraint(self, registry, ctx):
        ingest_ecommerce(registry)
        store = registry.get_events()
        store.insert(
            Event(event="$set", entity_type="constraint",
                  entity_id="unavailableItems",
                  properties={"items": ["i2", "i3"]}, event_time=_t(600)),
            APP_ID,
        )
        algo = self._algo()
        td = ecommerce.ECommerceDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        res = algo.predict(model, ecommerce.Query(user="u0", num=8))
        assert not {"i2", "i3"}.intersection(s.item for s in res.item_scores)
        # a newer $set supersedes the old constraint (latest wins)
        store.insert(
            Event(event="$set", entity_type="constraint",
                  entity_id="unavailableItems",
                  properties={"items": []}, event_time=_t(700)),
            APP_ID,
        )
        res = algo.predict(model, ecommerce.Query(user="u0", num=8))
        items = {s.item for s in res.item_scores}
        assert {"i2", "i3"}.intersection(items) or len(items) > 0

    def test_new_user_falls_back_to_recent_views(self, registry, ctx):
        ingest_ecommerce(registry)
        store = registry.get_events()
        algo = self._algo()
        td = ecommerce.ECommerceDataSource().read_training(ctx)
        model = algo.train(ctx, td)
        # unknown user with no views → empty
        res = algo.predict(model, ecommerce.Query(user="ghost", num=3))
        assert res.item_scores == ()
        # unknown user with recent views of low-numbered items
        for it in ("i0", "i1"):
            store.insert(
                Event(event="view", entity_type="user", entity_id="ghost",
                      target_entity_type="item", target_entity_id=it,
                      event_time=_t(800)),
                APP_ID,
            )
        res = algo.predict(model, ecommerce.Query(user="ghost", num=3))
        assert len(res.item_scores) > 0

    def test_latest_rating_wins(self, registry, ctx):
        store = registry.get_events()
        for eid in ("u0", "u1"):
            store.insert(
                Event(event="$set", entity_type="user", entity_id=eid,
                      event_time=_t(0)),
                APP_ID,
            )
        for eid in ("i0", "i1"):
            store.insert(
                Event(event="$set", entity_type="item", entity_id=eid,
                      event_time=_t(0)),
                APP_ID,
            )
        # u0 rates i0 twice: 1.0 then 5.0 — the 5.0 must win
        store.insert(
            Event(event="rate", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id="i0",
                  properties={"rating": 1.0}, event_time=_t(1)),
            APP_ID,
        )
        store.insert(
            Event(event="rate", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id="i0",
                  properties={"rating": 5.0}, event_time=_t(2)),
            APP_ID,
        )
        store.insert(
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties={"rating": 3.0}, event_time=_t(3)),
            APP_ID,
        )
        algo = self._algo(num_iterations=5)
        td = ecommerce.ECommerceDataSource().read_training(ctx)
        assert len(td.rate_events) == 3
        latest = {}
        for r in td.rate_events:
            key = (r.user, r.item)
            if key not in latest or r.t > latest[key].t:
                latest[key] = r
        assert latest[("u0", "i0")].rating == 5.0
