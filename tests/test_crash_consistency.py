"""Crash-consistency proofs over the storage plane (ISSUE 3).

``testing/crashsim.py`` interposes on a workload's file mutations and
enumerates every post-crash directory state its crash model allows
(prefix cuts, plus single-victim truncation of any write never fsync'd
before the cut — the power-loss reordering behind write-then-rename
bugs). Each test asserts one recovery invariant over *every* state:

- model stores: ``get`` returns the whole old blob or the whole new
  blob, never garbage (the ``LocalFSModelStore.insert`` durability gap
  this PR fixed — without the fsync-before-rename, a state with a torn
  blob under the final name exists and this suite fails);
- checkpoints: ``restore`` always loads a complete step, including when
  the crash hits mid-prune (markers are dropped before ``rmtree``);
- the replication op log: reopening truncates any torn tail to a
  consistent, gap-free prefix.

All deterministic, CPU-only, no wall-clock sleeps — tier-1.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.storage.model_store import (
    LocalFSModelStore,
    Model,
    SqliteModelStore,
)
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.testing.crashsim import CrashSim
from predictionio_tpu.workflow.checkpoint import CheckpointManager

OLD = b"OLD-" * 64
NEW = b"NEW-" * 64


def _states(sim):
    states = sim.crash_states()
    assert len(states) > 2, "crashsim recorded no meaningful ops"
    return states


class TestLocalFSModelStore:
    def test_overwrite_never_torn(self, tmp_path):
        root = str(tmp_path / "models")
        store = LocalFSModelStore(root)
        store.insert(Model(id="m", models=OLD))
        sim = CrashSim()
        with sim.record(root):
            store.insert(Model(id="m", models=NEW))
        for i, state in enumerate(_states(sim)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            got = LocalFSModelStore(crashed).get("m")
            assert got is not None, f"model vanished: {state.describe()}"
            assert got.models in (OLD, NEW), f"torn blob: {state.describe()}"

    def test_first_insert_all_or_nothing(self, tmp_path):
        root = str(tmp_path / "models")
        store = LocalFSModelStore(root)
        sim = CrashSim()
        with sim.record(root):
            store.insert(Model(id="m", models=NEW))
        for i, state in enumerate(_states(sim)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            got = LocalFSModelStore(crashed).get("m")
            # absent (crash before the rename) or whole — never torn
            assert got is None or got.models == NEW, state.describe()


class TestSqliteModelStore:
    def test_commit_boundaries_old_or_new(self, tmp_path):
        """SQLite writes from C, invisible to the interposer — snapshot
        mode captures each commit boundary and asserts old-or-new there
        (sub-commit atomicity is SQLite's own journal's contract)."""
        root = str(tmp_path / "db")
        os.makedirs(root)
        path = os.path.join(root, "models.db")
        store = SqliteModelStore(path)
        sim = CrashSim()
        sim.mark(root)  # empty store
        store.insert(Model(id="m", models=OLD))
        sim.mark(root)
        store.insert(Model(id="m", models=NEW))
        sim.mark(root)
        store.delete("m")
        sim.mark(root)
        states = sim.snapshot_states()
        assert len(states) == 4
        expected = [None, OLD, NEW, None]
        for i, (state, want) in enumerate(zip(states, expected)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            got = SqliteModelStore(os.path.join(crashed, "models.db")).get("m")
            assert (got.models if got else None) == want


class TestCheckpointCrash:
    def test_save_over_existing_always_restorable(self, tmp_path):
        root = str(tmp_path / "ck")
        cm = CheckpointManager(root)
        cm.save(1, {"x": np.full(4, 1.0)})
        sim = CrashSim()
        with sim.record(root):
            cm.save(2, {"x": np.full(4, 2.0)})
        for i, state in enumerate(_states(sim)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            step, tree, _ = CheckpointManager(crashed).restore(like={"x": 0})
            assert (step, float(tree["x"][0])) in ((1, 1.0), (2, 2.0)), (
                state.describe()
            )

    def test_prune_mid_delete_keeps_newest_loadable(self, tmp_path):
        """The retention satellite's contract: a crash at ANY point of a
        pruning save (including mid-rmtree of an old step) leaves the
        newest checkpoint complete and loadable, and never leaves a
        half-deleted directory that still claims _COMPLETE."""
        root = str(tmp_path / "ck")
        cm = CheckpointManager(root, keep_last=2)
        cm.save(1, {"x": np.full(4, 1.0)})
        cm.save(2, {"x": np.full(4, 2.0)})
        sim = CrashSim()
        with sim.record(root):
            cm.save(3, {"x": np.full(4, 3.0)})  # prunes step 1
        for i, state in enumerate(_states(sim)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            mgr = CheckpointManager(crashed)
            step, tree, _ = mgr.restore(like={"x": 0})
            assert float(tree["x"][0]) == float(step)
            # every step listed complete must actually restore
            for s in mgr.all_steps():
                s2, t2, _ = mgr.restore(s, like={"x": 0})
                assert float(t2["x"][0]) == float(s2)

    def test_retention_prunes_and_default_is_unlimited(self, tmp_path):
        unlimited = CheckpointManager(str(tmp_path / "u"))
        for s in (1, 2, 3, 4, 5):
            unlimited.save(s, {"x": np.ones(2)})
        assert unlimited.all_steps() == [1, 2, 3, 4, 5]
        bounded = CheckpointManager(str(tmp_path / "b"), keep_last=2)
        for s in (1, 2, 3, 4, 5):
            bounded.save(s, {"x": np.ones(2)})
        assert bounded.all_steps() == [4, 5]


class TestOpLogCrash:
    def test_every_torn_prefix_reopens_consistent(self, tmp_path):
        root = str(tmp_path / "oplog")
        sim = CrashSim()
        with sim.record(root):
            log = OpLog(root, sync_every=4)
            for i in range(10):
                log.append({"i": i})
            log.close()
        checked = 0
        for i, state in enumerate(_states(sim)):
            crashed = state.materialize(str(tmp_path / f"s{i}"))
            if not os.path.exists(os.path.join(crashed, "oplog.meta.json")):
                continue  # crashed before the log was born
            checked += 1
            reopened = OpLog(crashed)
            entries, last = reopened.read_since(0, limit=100)
            # a consistent dense prefix: seqs 1..last, payloads intact
            assert [s for s, _ in entries] == list(range(1, last + 1))
            assert all(op == {"i": s - 1} for s, op in entries)
            reopened.close()
        assert checked > 5

    def test_generation_survives_and_seq_resumes(self, tmp_path):
        log = OpLog(str(tmp_path), sync_every=2)
        generation = log.generation
        for i in range(5):
            log.append({"i": i})
        log.close()
        reopened = OpLog(str(tmp_path))
        assert reopened.generation == generation
        assert reopened.last_seq == 5
        assert reopened.append({"i": 5}) == 6
        reopened.close()


class TestCrashSimSelf:
    """The simulator itself must catch the bug class it exists for."""

    def test_unfsynced_rename_produces_torn_state(self, tmp_path):
        root = str(tmp_path / "w")
        os.makedirs(root)
        final = os.path.join(root, "blob.bin")
        with open(final, "wb") as fh:
            fh.write(OLD)
        sim = CrashSim()
        with sim.record(root):
            tmp = final + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(NEW)  # never fsync'd
            os.replace(tmp, final)
        torn = 0
        for i, state in enumerate(sim.crash_states()):
            data = state.tree().files.get("blob.bin")
            if data is not None and data not in (OLD, NEW):
                torn += 1
        assert torn > 0, (
            "crash model must generate rename-over-unsynced-data states"
        )

    def test_fsynced_rename_is_atomic(self, tmp_path):
        root = str(tmp_path / "w")
        os.makedirs(root)
        final = os.path.join(root, "blob.bin")
        with open(final, "wb") as fh:
            fh.write(OLD)
        sim = CrashSim()
        with sim.record(root):
            tmp = final + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(NEW)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, final)
        for state in sim.crash_states():
            data = state.tree().files.get("blob.bin")
            assert data in (OLD, NEW), state.describe()
