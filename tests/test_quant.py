"""The quantization subsystem (``predictionio_tpu/quant``,
docs/quantization.md).

Four layers, mirroring the package's contract:

1. **Table codec**: symmetric-absmax int8 encode/decode properties,
   zero-row safety, the fp8 capability probe's loud CPU fallback, and
   the byte model (``estimate_table_bytes`` == the bytes a real table
   holds).
2. **Ragged gather**: bit-identical to ``table[ids]`` — with
   duplicates, 2-D id blocks, empty ids, and under jit — the contract
   that lets BOTH adoption sites (sharded trainer slab fetch, fused
   serve top-k) keep their existing equivalence pins.
3. **The exactness gate**: an exactly-representable table passes at
   match rate 1.0 and serves ids identical to f32 end to end; a
   tampered table is REFUSED loudly (``QuantGateError``) and counted —
   never a silent quality slide. The trained-model sweep rides the
   ``test_sharded_train`` train-once recipe, so tier-1 pays no second
   training run.
4. **Ledger records**: ``quant_records`` keys are disjoint from every
   other record family, and the ``bytes`` unit genuinely gates (a
   grown table flags as a regression).
"""

from __future__ import annotations

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.quant import (
    QuantGateError,
    QuantizedTable,
    default_probe_idx,
    dequantize_rows,
    estimate_table_bytes,
    gate_counts,
    quantize_serving_table,
    quantize_table,
    ragged_gather,
    resolve_quantized_serving,
    top_k_quantized,
    topk_match_gate,
)


def _exact_grid(n, rank, seed=0):
    """A table symmetric-absmax int8 round-trips within f32 rounding:
    integer codes in [-126, 127], one entry per row forced to 127 (the
    absmax must land exactly on code 127), times a per-row scale."""
    rng = np.random.default_rng(seed)
    k = rng.integers(-126, 127, size=(n, rank))
    k[np.arange(n), rng.integers(0, rank, size=n)] = 127
    scale = rng.uniform(0.01, 2.0, size=(n, 1))
    return (k * scale).astype(np.float32)


class TestTableCodec:
    def test_int8_roundtrip_on_exact_grid(self):
        table = _exact_grid(40, 8)
        qtable = quantize_table(table)
        assert qtable.dtype == "int8"
        assert qtable.codes.dtype == np.int8
        approx = np.asarray(dequantize_rows(qtable, np.arange(40)))
        # 127 division is inexact in f32: tiny rounding, not exact bits
        denom = np.maximum(np.abs(table).max(axis=1, keepdims=True), 1e-9)
        assert np.max(np.abs(approx - table) / denom) < 1e-4

    def test_quantization_error_bounded_by_half_step(self):
        rng = np.random.default_rng(3)
        table = rng.normal(size=(64, 16)).astype(np.float32)
        qtable = quantize_table(table)
        approx = np.asarray(dequantize_rows(qtable, np.arange(64)))
        step = np.asarray(qtable.scales)[:, None]
        assert np.all(np.abs(approx - table) <= 0.5 * step + 1e-6)

    def test_zero_rows_are_safe(self):
        table = np.zeros((4, 6), dtype=np.float32)
        table[2] = 1.0
        qtable = quantize_table(table)
        assert np.asarray(qtable.scales)[0] == 0.0
        approx = np.asarray(dequantize_rows(qtable, np.arange(4)))
        assert np.all(approx[0] == 0.0) and np.all(approx[1] == 0.0)

    def test_unknown_dtype_is_loud(self):
        with pytest.raises(ValueError, match="dtype"):
            quantize_table(np.ones((2, 2), dtype=np.float32), dtype="int4")

    def test_non_2d_table_is_loud(self):
        with pytest.raises(ValueError, match="2-D"):
            quantize_table(np.ones(8, dtype=np.float32))

    def test_fp8_falls_back_loudly_off_accelerator(self):
        from predictionio_tpu.quant import fp8_supported

        table = _exact_grid(8, 4)
        if fp8_supported():  # pragma: no cover - accelerator-only
            qtable = quantize_table(table, dtype="fp8")
            assert qtable.dtype == "fp8" and qtable.fallback is None
            return
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            qtable = quantize_table(table, dtype="fp8")
        assert qtable.dtype == "int8"
        assert qtable.fallback and "fp8" in qtable.fallback
        assert any("fp8" in str(w.message) for w in caught)
        assert qtable.status()["fallback"] == qtable.fallback

    def test_estimate_matches_real_table_bytes(self):
        table = _exact_grid(50, 8)
        qtable = quantize_table(table)
        assert estimate_table_bytes(50, 8, "int8") == qtable.table_bytes
        assert estimate_table_bytes(50, 8, "f32") == qtable.f32_bytes
        assert qtable.f32_bytes == 50 * 8 * 4
        with pytest.raises(ValueError, match="dtype"):
            estimate_table_bytes(50, 8, "int7")

    def test_bench_recipe_compression_clears_3x(self):
        """The acceptance floor: at the bench recipe's rank 50 the int8
        table is 200n/54n = 3.7x smaller than its f32 twin."""
        f32 = estimate_table_bytes(1000, 50, "f32")
        int8 = estimate_table_bytes(1000, 50, "int8")
        assert f32 / int8 >= 3.0
        qtable = quantize_table(_exact_grid(100, 50))
        assert qtable.compression_ratio >= 3.0


class TestRaggedGather:
    @pytest.mark.parametrize(
        "ids",
        [
            np.array([3, 1, 3, 3, 0, 7, 1], dtype=np.int32),
            np.array([[5, 5, 2], [0, 9, 9]], dtype=np.int32),
            np.zeros((4,), dtype=np.int32),
        ],
        ids=["dups-1d", "block-2d", "all-zero"],
    )
    def test_bit_identical_to_dense_gather(self, ids):
        rng = np.random.default_rng(11)
        table = rng.normal(size=(10, 6)).astype(np.float32)
        got = np.asarray(ragged_gather(table, ids))
        assert np.array_equal(got, table[ids])

    def test_empty_ids(self):
        table = np.ones((5, 3), dtype=np.float32)
        out = np.asarray(ragged_gather(table, np.zeros(0, dtype=np.int32)))
        assert out.shape == (0, 3)

    def test_bit_identical_under_jit(self):
        rng = np.random.default_rng(13)
        table = rng.normal(size=(32, 4)).astype(np.float32)
        ids = rng.integers(0, 32, size=(3, 5)).astype(np.int32)
        jitted = jax.jit(ragged_gather)
        assert np.array_equal(np.asarray(jitted(table, ids)), table[ids])

    def test_dequantize_rows_matches_full_dequant(self):
        table = _exact_grid(20, 5)
        qtable = quantize_table(table)
        ids = np.array([7, 7, 1, 19, 7], dtype=np.int32)
        full = np.asarray(qtable.codes, dtype=np.float32) * np.asarray(
            qtable.scales
        )[:, None]
        got = np.asarray(dequantize_rows(qtable, ids))
        assert np.allclose(got, full[ids], rtol=0, atol=1e-6)


class TestServingLever:
    def test_explicit_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("PIO_SERVE_QUANT", "1")
        assert resolve_quantized_serving(False) is False
        monkeypatch.setenv("PIO_SERVE_QUANT", "0")
        assert resolve_quantized_serving(True) is True

    def test_env_resolves_when_unset_explicitly(self, monkeypatch):
        monkeypatch.delenv("PIO_SERVE_QUANT", raising=False)
        assert resolve_quantized_serving(None) is False
        monkeypatch.setenv("PIO_SERVE_QUANT", "1")
        assert resolve_quantized_serving(None) is True
        monkeypatch.setenv("PIO_SERVE_QUANT", "0")
        assert resolve_quantized_serving(None) is False

    def test_invalid_env_is_loud(self, monkeypatch):
        monkeypatch.setenv("PIO_SERVE_QUANT", "yes")
        with pytest.raises(ValueError, match="PIO_SERVE_QUANT"):
            resolve_quantized_serving(None)


class TestExactnessGate:
    def test_exact_grid_gates_at_full_match(self):
        items = _exact_grid(60, 8, seed=5)
        rng = np.random.default_rng(6)
        users = rng.normal(size=(30, 8)).astype(np.float32)
        qtable, status = quantize_serving_table(items, users, k=10)
        assert status["matchRate"] == 1.0
        assert status["dtype"] == "int8"
        assert status["tableBytes"] == qtable.table_bytes
        assert status["compression"] == round(qtable.compression_ratio, 2)

    def test_quant_topk_ids_match_f32_end_to_end(self):
        from predictionio_tpu.ops.scoring import top_k_for_users_fused

        items = _exact_grid(60, 8, seed=7)
        rng = np.random.default_rng(8)
        users = rng.normal(size=(20, 8)).astype(np.float32)
        qtable, _ = quantize_serving_table(items, users, k=5)
        idx = np.arange(20, dtype=np.int32)
        _, ref_ids = top_k_for_users_fused(users, items, idx, k=5,
                                           mode="never")
        _, got_ids = top_k_quantized(users, qtable, idx, k=5)
        assert np.array_equal(
            np.sort(np.asarray(ref_ids), axis=1),
            np.sort(np.asarray(got_ids), axis=1),
        )

    def test_near_tie_model_refused_loudly_and_counted(self):
        """A generic gaussian table genuinely flips near-ties under
        int8 (the trained-model failure mode, deterministic under the
        fixed seed): the strict default gate must REFUSE it — loudly
        and counted — never serve it silently degraded."""
        rng = np.random.default_rng(5)
        items = rng.normal(size=(100, 8)).astype(np.float32)
        users = rng.normal(size=(64, 8)).astype(np.float32)
        qtable = quantize_table(items)
        rate = topk_match_gate(users, items, qtable,
                               default_probe_idx(64), 10)
        assert rate < 1.0  # the near-ties really flip on this recipe
        before = gate_counts()
        with pytest.raises(QuantGateError, match="REFUSED"):
            quantize_serving_table(items, users, k=10)
        after = gate_counts()
        assert after["refusals"] == before["refusals"] + 1
        assert after["runs"] == before["runs"] + 1
        # shuffled codes are the tamper detector's floor: a table whose
        # rows no longer correspond to the items collapses the rate
        shuffled = QuantizedTable(
            codes=np.asarray(qtable.codes)[::-1].copy(),
            scales=np.asarray(qtable.scales)[::-1].copy(),
            dtype="int8",
        )
        tampered_rate = topk_match_gate(users, items, shuffled,
                                        default_probe_idx(64), 10)
        assert tampered_rate < rate

    def test_probe_idx_is_deterministic_and_bounded(self):
        idx = default_probe_idx(1000)
        assert idx.size <= 64
        assert np.array_equal(idx, default_probe_idx(1000))
        assert default_probe_idx(3).size == 3


class TestTrainedModelSweep:
    """The gate on a REAL trained model, riding test_sharded_train's
    train-once recipe (module-level cache: one training run per session
    no matter which module triggers it)."""

    def _factors(self):
        import test_sharded_train

        uf, itf = test_sharded_train.sweep(0)
        return uf, itf

    def test_trained_model_match_rate_measured(self):
        uf, itf = self._factors()
        qtable = quantize_table(itf)
        rate = topk_match_gate(uf, itf, qtable,
                               default_probe_idx(uf.shape[0]), 10)
        # tiny rank-8 models genuinely flip near-ties under int8: the
        # measured rate sits ~0.9, well above collapse but below the
        # strict default — exactly why the default gate REFUSES and the
        # operator must lower min_match deliberately
        assert 0.75 <= rate <= 1.0

    def test_strict_default_refuses_and_explicit_floor_admits(self):
        uf, itf = self._factors()
        try:
            _, status = quantize_serving_table(itf, uf, k=10)
            # a lucky grid CAN pass strict; if so the status must say so
            assert status["matchRate"] == 1.0
        except QuantGateError:
            pass  # the expected strict-default outcome on this recipe
        _, status = quantize_serving_table(itf, uf, k=10, min_match=0.75)
        assert status["matchRate"] >= 0.75

    def test_end_to_end_quantized_serving_via_model(self):
        """The ALSAlgorithm lever end to end: explicit opt-in with an
        operator floor serves through the quant path and reports it,
        with ids identical to the f32 path on the same queries."""
        import test_sharded_train
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm, ALSAlgorithmParams, ALSModel, Query,
        )
        from predictionio_tpu.storage import BiMap

        uf, itf = self._factors()
        model = ALSModel(
            rank=test_sharded_train._CFG.rank,
            user_factors=uf,
            item_factors=itf,
            user_map=BiMap({f"u{i}": i for i in range(uf.shape[0])}),
            item_map=BiMap({f"i{i}": i for i in range(itf.shape[0])}),
        )
        queries = [(0, Query(user="u0", num=5)), (1, Query(user="u3", num=5))]
        quant_algo = ALSAlgorithm(ALSAlgorithmParams(
            rank=model.rank,
            quantized_serving=True,
            quant_gate_min_match=0.5,
        ))
        quant_out = dict(quant_algo.batch_predict(model, queries))
        assert quant_algo.topk_path == "quant"
        assert quant_algo.quant_status is not None
        assert quant_algo.quant_status["dtype"] == "int8"
        assert len(quant_out[0].item_scores) == 5
        f32_algo = ALSAlgorithm(ALSAlgorithmParams(
            rank=model.rank, quantized_serving=False,
        ))
        f32_out = dict(f32_algo.batch_predict(model, queries))
        assert f32_algo.topk_path != "quant"
        for i in quant_out:
            quant_ids = {s.item for s in quant_out[i].item_scores}
            f32_ids = {s.item for s in f32_out[i].item_scores}
            # id-SET agreement on the probe queries the gate admitted
            # is not guaranteed per-query at min_match=0.5 — but both
            # paths must return real, k-sized answers
            assert len(quant_ids) == 5 and len(f32_ids) == 5


class TestQuantRecords:
    _BENCH = {
        "metric": "als_train_s",
        "value": 10.0,
        "device": "cpu",
        "quantServe": {
            "ok": True,
            "tableBytes": 54000,
            "f32Bytes": 200000,
            "ratio": 3.7,
            "tableDtype": "int8",
            "matchRate": 0.98,
            "probes": 64,
            "k": 10,
            "rank": 50,
            "nItems": 1000,
        },
    }

    def test_records_shape(self):
        from predictionio_tpu.obs.perfledger import quant_records

        by_metric = {r["metric"]: r for r in quant_records(self._BENCH)}
        assert set(by_metric) == {
            "serve_table_bytes", "quant_topk_match_rate",
        }
        table = by_metric["serve_table_bytes"]
        assert table["unit"] == "bytes" and table["value"] == 54000.0
        assert table["extra"]["ratio"] == 3.7
        assert table["extra"]["f32Bytes"] == 200000
        rate = by_metric["quant_topk_match_rate"]
        assert rate["unit"] == "ratio" and rate["value"] == 0.98
        assert rate["extra"]["k"] == 10

    def test_missing_or_failed_block_records_nothing(self):
        from predictionio_tpu.obs.perfledger import quant_records

        assert quant_records({"metric": "x", "value": 1.0}) == []
        assert quant_records({"quantServe": {"error": "boom"}}) == []
        assert quant_records({"quantServe": {"ok": False}}) == []

    def test_keys_disjoint_from_other_record_families(self):
        from predictionio_tpu.obs.perfledger import (
            comparable_key,
            fleet_records,
            quant_records,
            shared_cache_records,
            sharded_records,
        )

        bench = dict(self._BENCH)
        bench["servingFleet"] = {
            "ok": True, "servedP50Ms": 5.0, "servedP99Ms": 9.0,
            "replicas": 2, "qps": 100.0,
        }
        bench["sharedCache"] = {
            "ok": True, "hedgedP99Ms": 7.0, "sharedHitRate": 0.5,
        }
        bench["shardedTrain"] = {
            "ok": True, "counts": {"4": {"trainS": 3.0}},
        }
        quant_keys = {comparable_key(r) for r in quant_records(bench)}
        other = []
        for fn in (fleet_records, shared_cache_records, sharded_records):
            other.extend(fn(bench))
        other_keys = {comparable_key(r) for r in other}
        assert other  # the fixtures actually produced records
        assert quant_keys and quant_keys.isdisjoint(other_keys)

    def test_bytes_unit_genuinely_gates(self):
        from predictionio_tpu.obs.perfledger import (
            detect_regressions,
            quant_records,
        )

        history = []
        for _ in range(3):
            history.extend(quant_records(self._BENCH))
        grown = {**self._BENCH, "quantServe": {
            **self._BENCH["quantServe"], "tableBytes": 108000,
        }}
        history.extend(quant_records(grown))
        flagged = detect_regressions(history)
        assert any(
            f["latest"] == 108000.0 for f in flagged
        ), f"a doubled table must flag: {flagged}"
        # the match-rate twin (unit=ratio) never gates
        assert all("match" not in str(f["key"]) for f in flagged)

    def test_bench_extra_carries_quant_block(self):
        from predictionio_tpu.obs.perfledger import bench_to_record

        record = bench_to_record(self._BENCH)
        assert record["extra"]["quantServe"]["ratio"] == 3.7

    def test_bench_helper_measures_without_refusing(self):
        """bench.run_quant_serve MEASURES the gate margin — it must
        produce a record (ok, bytes, rate) even on a table the strict
        serving gate would refuse."""
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench

        rng = np.random.default_rng(5)  # the refused near-tie recipe
        items = rng.normal(size=(100, 8)).astype(np.float32)
        users = rng.normal(size=(64, 8)).astype(np.float32)
        out = bench.run_quant_serve(users, items, k=10)
        assert out["ok"] is True
        assert out["tableDtype"] == "int8"
        assert out["tableBytes"] == estimate_table_bytes(100, 8, "int8")
        assert out["estTableBytes"] == out["tableBytes"]
        assert out["f32Bytes"] == 100 * 8 * 4
        assert 0.0 <= out["matchRate"] < 1.0
        assert out["topkS"] > 0
