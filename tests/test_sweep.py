"""Hyperparameter-sweep parallelism: mesh slicing, mesh-sliced batch_eval,
concurrent FastEval memoization counts, and parallel metric scoring
(the ``.par`` parity of ``MetricEvaluator.scala:202-211`` + SURVEY §2.8
row 5's sweep-over-mesh-slices mapping)."""

import jax
import pytest

from predictionio_tpu.controller import (
    Engine,
    FastEvalEngine,
    MetricEvaluator,
    WorkflowParams,
)
from predictionio_tpu.parallel.mesh import MeshConfig, create_mesh, slice_mesh
from predictionio_tpu.workflow.context import WorkflowContext

from sample_engine import (
    Algo0,
    DataSource0,
    Preparator0,
    Serving0,
    reset_all_counts,
)
from test_engine import IdSumMetric, make_params


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()


@pytest.fixture()
def ctx():
    return WorkflowContext(mode="Evaluation", batch="sweep-test")


class TestSliceMesh:
    def test_even_split(self):
        mesh = create_mesh(MeshConfig((("data", 8),)))
        slices = slice_mesh(mesh, 4)
        assert len(slices) == 4
        assert all(s.shape["data"] == 2 for s in slices)
        seen = [d for s in slices for d in s.devices.flat]
        assert len(set(seen)) == 8  # disjoint cover

    def test_uneven_request_rounds_down(self):
        mesh = create_mesh(MeshConfig((("data", 8),)))
        slices = slice_mesh(mesh, 3)  # 8 % 3 != 0 -> 2 slices of 4
        assert len(slices) == 2
        assert all(s.shape["data"] == 4 for s in slices)

    def test_oversubscribed_clamps(self):
        mesh = create_mesh(MeshConfig((("data", 4),)), jax.devices()[:4])
        slices = slice_mesh(mesh, 16)
        assert len(slices) == 4

    def test_keeps_secondary_axes(self):
        mesh = create_mesh(MeshConfig((("data", 4), ("model", 2))))
        slices = slice_mesh(mesh, 4)
        assert len(slices) == 4
        assert all(s.shape["model"] == 2 for s in slices)

    def test_missing_axis_returns_whole_mesh(self):
        """A mesh without the slicing axis must fall back to shared-mesh
        serial-equivalent behavior, not crash the evaluation."""
        mesh = create_mesh(MeshConfig((("model", 8),)))
        assert slice_mesh(mesh, 4) == [mesh]

    def test_oversubscription_reuses_free_slices(self, ctx):
        """More candidates than slices: a finishing slice is reused; no two
        concurrent tasks ever hold the same slice."""
        import threading
        from predictionio_tpu.parallel.sweep import run_sliced

        in_use = set()
        lock = threading.Lock()

        def task(sliced):
            key = tuple(d.id for d in sliced.mesh.devices.flat)
            with lock:
                assert key not in in_use, "two tasks on one slice"
                in_use.add(key)
            try:
                import time

                time.sleep(0.02)
                return key
            finally:
                with lock:
                    in_use.discard(key)

        results = run_sliced(ctx, [task] * 12, parallelism=4)
        assert len(results) == 12
        assert len({r for r in results}) == 4  # all four slices used

    def test_context_slices(self, ctx):
        children = ctx.slices(4)
        assert len(children) == 4
        assert children[0].batch == ctx.batch
        assert children[0].mesh.shape["data"] == 2


def fast_engine():
    return FastEvalEngine(
        {"": DataSource0}, {"": Preparator0}, {"": Algo0}, {"": Serving0}
    )


class TestParallelSweep:
    def test_fast_eval_4_slices_counts_unchanged(self, ctx):
        """The VERDICT round-1 'done' criterion: a 4-params sweep over 4
        mesh slices with FastEval memoization counts identical to serial."""
        engine = fast_engine()
        eps = [make_params(algo_ids=(i,), n_eval_sets=1) for i in range(4)]
        results = engine.batch_eval(ctx, eps, parallelism=4)
        assert len(results) == 4
        assert DataSource0.count == 1  # read once across the whole sweep
        assert Preparator0.count == 1  # prepared once
        assert Algo0.count == 4  # one train per distinct algo params

        # and the results match a fresh serial sweep exactly
        reset_all_counts()
        serial = fast_engine().batch_eval(ctx, eps, parallelism=1)
        assert [r for _, r in results] == [r for _, r in serial]
        assert DataSource0.count == 1 and Algo0.count == 4

    def test_fast_eval_duplicate_params_computed_once_in_parallel(self, ctx):
        engine = fast_engine()
        ep = make_params(n_eval_sets=1)
        engine.batch_eval(ctx, [ep, ep, ep, ep], parallelism=4)
        assert DataSource0.count == 1
        assert Algo0.count == 1  # exactly-once under concurrency

    def test_plain_engine_parallel_matches_serial(self, ctx):
        eps = [make_params(algo_ids=(i,), n_eval_sets=1) for i in range(4)]
        eng = Engine(
            {"": DataSource0}, {"": Preparator0}, {"": Algo0}, {"": Serving0}
        )
        par = eng.batch_eval(ctx, eps, parallelism=4)
        ser = eng.batch_eval(ctx, eps, parallelism=1)
        assert [r for _, r in par] == [r for _, r in ser]

    def test_parallel_eval_errors_propagate(self, ctx):
        class ExplodingDS(DataSource0):
            def read_eval(self, c):
                if self.params.id == 1:
                    raise RuntimeError("bad split")
                return super().read_eval(c)

        eps = [
            make_params(ds_id=0, n_eval_sets=1),
            make_params(ds_id=1, n_eval_sets=1),
        ]
        eng = Engine(
            {"": ExplodingDS}, {"": Preparator0}, {"": Algo0}, {"": Serving0}
        )
        with pytest.raises(RuntimeError, match="bad split"):
            eng.batch_eval(ctx, eps, parallelism=2)


class TestParallelMetricScoring:
    def test_parallel_matches_serial_best(self, ctx):
        engine = fast_engine()
        eps = [make_params(algo_ids=(i,), n_eval_sets=1) for i in range(4)]
        data = engine.batch_eval(ctx, eps, parallelism=4)
        me = MetricEvaluator(IdSumMetric())
        par = me.evaluate_base(ctx, None, data, parallelism=4)
        ser = me.evaluate_base(ctx, None, data, parallelism=1)
        assert par.best_idx == ser.best_idx
        assert par.best_score == ser.best_score
        assert par.engine_params_scores == ser.engine_params_scores


class TestWorkflowWiring:
    def test_run_evaluation_uses_parallelism(self, tmp_path):
        """pio eval → mesh: the default eval path slices the mesh."""
        from predictionio_tpu.controller.evaluation import (
            Evaluation,
            EngineParamsGenerator,
        )
        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.workflow.core_workflow import run_evaluation

        registry = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
        ev = Evaluation()
        ev.engine_metric = (fast_engine(), IdSumMetric())
        gen = EngineParamsGenerator(
            [make_params(algo_ids=(i,), n_eval_sets=1) for i in range(4)]
        )
        instance_id = run_evaluation(
            ev, gen, registry, WorkflowParams(batch="wired-sweep")
        )
        inst = registry.get_metadata().evaluation_instance_get(instance_id)
        assert inst is not None and inst.status == "EVALCOMPLETED"
        assert DataSource0.count == 1  # memoization intact through wiring
        assert Algo0.count == 4
