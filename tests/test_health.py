"""Fleet-health plane tests (ISSUE 11, docs/slo.md).

1. **SLO engine** (`obs/slo.py`): burn-rate math over status counters /
   latency histograms / gauges on injected clocks, the multi-window
   fire+clear state machine, the explicit-abstention contract ("no
   data is never a verdict" — absent series, the ``-1`` gauge
   sentinel, thin windows, counter resets; a FIRING alert never clears
   on data loss), and the durable fsynced alert ledger.
2. **Flight recorder + stall watchdog** (`obs/flight.py`): bounded
   ring, the ZERO-COST disabled path (counting clock — the PR 8
   profiler contract), durable dumps, in-flight-request and
   wedged-tick stall detection with forensic dumps naming the site.
3. **Wiring**: every server answers ``/health.json`` +
   ``/blackbox.json``; breaker transitions land in the process flight
   recorder; ``pio top`` grows the HEALTH column.
4. **CLIs** (`tools/health.py`): `pio health` / `pio alerts` /
   `pio blackbox` with the pinned 0/1/2 exit codes, driven in-process.
5. **The `loadgen --brownout` drill** (tier-1 acceptance): module-
   scoped — ONE drill run (on the process-cached toy-train workspace),
   many cheap assertions, the PR 9 `sweep_factors` pattern.
6. **Metric-catalog golden test**: every `pio_*` instrument registered
   at server boot is pinned against the table in
   docs/observability.md#metric-catalog.

Everything engine-side runs on injected clocks with zero wall-clock
sleeps; the wiring tests use a handful of real loopback round trips.
"""

from __future__ import annotations

import json
import os
import re
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")

from predictionio_tpu.obs.flight import (  # noqa: E402
    FlightRecorder,
    StallWatchdog,
    load_dump,
)
from predictionio_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from predictionio_tpu.obs.slo import (  # noqa: E402
    SLOEngine,
    SLOObjective,
    default_objectives,
    load_alerts,
)
from predictionio_tpu.testing.clock import FakeClock  # noqa: E402


def _ratio_objectives(**overrides):
    base = dict(
        target=0.999, burn_threshold=8.0, min_window_events=10,
        fast_window_s=300.0, slow_window_s=3600.0,
    )
    base.update(overrides)
    return (
        SLOObjective(
            name="availability", kind="ratio",
            metric="pio_http_responses_total", **base,
        ),
    )


class _Plant:
    """One registry + engine + traffic pump on a fake clock."""

    def __init__(self, objectives=None, ledger=None):
        self.clock = FakeClock()
        self.metrics = MetricsRegistry(clock=self.clock)
        self.resp = self.metrics.counter(
            "pio_http_responses_total", labelnames=("status",)
        )
        self.hist = self.metrics.histogram("pio_serving_request_seconds")
        self.engine = SLOEngine(
            self.metrics,
            objectives if objectives is not None else _ratio_objectives(),
            clock=self.clock,
            ledger_path=ledger,
        )

    def pump(self, rounds, good=20, bad=0, latency=0.005, advance=60.0):
        summary = None
        for _ in range(rounds):
            for _ in range(good):
                self.resp.inc(1, status=200)
                self.hist.observe(latency)
            for _ in range(bad):
                self.resp.inc(1, status=500)
                self.hist.observe(latency)
            self.clock.advance(advance)
            summary = self.engine.evaluate()
        return summary

    def state(self, name="availability"):
        return next(
            o for o in self.engine.summary()["objectives"]
            if o["name"] == name
        )


class TestSLOEngine:
    def test_clean_traffic_never_fires(self):
        plant = _Plant()
        summary = plant.pump(8)
        assert summary["firing"] == 0
        state = plant.state()
        assert state["state"] == "OK" and not state["abstaining"]
        assert state["burnFast"] == 0.0

    def test_fires_only_when_both_windows_burn(self, tmp_path):
        ledger = str(tmp_path / "alerts.jsonl")
        plant = _Plant(ledger=ledger)
        plant.pump(6)  # a clean hour of history
        # one bad minute: the fast window burns, the slow window is
        # still diluted below threshold -> must NOT fire
        # fast: 10/30 = 0.33/0.001 = 333; slow: 10/(6*20+30) ~ 0.066
        # -> 66 >= 8 ... both exceed with budget 0.001. Use a milder
        # burn that only the fast window exceeds:
        plant.resp.inc(0, status=200)
        summary = plant.pump(1, good=997, bad=3)  # 0.3% bad
        # fast burn = 3/(1000)/0.001 = 3 < 8: no fire
        assert summary["firing"] == 0
        summary = plant.pump(2, good=10, bad=10)  # 50% bad, sustained
        assert summary["firing"] == 1
        state = plant.state()
        assert state["burnFast"] >= 8.0 and state["burnSlow"] >= 8.0
        # exactly one durable FIRING line
        states = [a["state"] for a in load_alerts(ledger)]
        assert states == ["FIRING"]

    def test_clears_when_fast_window_drains_durably(self, tmp_path):
        ledger = str(tmp_path / "alerts.jsonl")
        plant = _Plant(ledger=ledger)
        plant.pump(6)
        plant.pump(2, good=10, bad=10)
        assert plant.state()["state"] == "FIRING"
        summary = plant.pump(7, good=30)  # > fast window of clean traffic
        assert summary["firing"] == 0
        assert plant.state()["cleared"] == 1
        alerts = load_alerts(ledger)
        assert [a["state"] for a in alerts] == ["FIRING", "CLEARED"]
        assert all(a["schema"] == 1 and a["kind"] == "alert"
                   for a in alerts)

    def test_latency_objective_over_histogram(self):
        objectives = (
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_serving_request_seconds",
                latency_threshold_s=0.128, target=0.99,
                burn_threshold=8.0, min_window_events=10,
            ),
        )
        plant = _Plant(objectives=objectives)
        plant.pump(6, latency=0.005)
        assert plant.state("latency")["state"] == "OK"
        plant.pump(2, good=10, latency=0.3)  # every answer slow
        assert plant.state("latency")["state"] == "FIRING"

    def test_absent_series_abstains_not_ok(self):
        clock = FakeClock()
        metrics = MetricsRegistry(clock=clock)
        engine = SLOEngine(
            metrics, _ratio_objectives(), clock=clock
        )
        summary = engine.evaluate()
        state = summary["objectives"][0]
        assert state["abstaining"] and state["state"] == "OK"
        # exported as -1, never 0 ("no data" must not read healthy)
        gauge = metrics.instrument("pio_slo_alert_state")
        assert gauge.value(objective="availability") == -1.0

    def test_thin_window_abstains(self):
        plant = _Plant()
        plant.resp.inc(1, status=500)  # 1 bad of 2: 50% "error rate"
        plant.resp.inc(1, status=200)
        plant.clock.advance(60)
        plant.engine.evaluate()
        plant.clock.advance(60)
        plant.engine.evaluate()
        state = plant.state()
        assert state["abstaining"]  # < min_window_events: no verdict

    def test_gauge_sentinel_reads_absent_and_firing_holds_on_data_loss(
        self,
    ):
        clock = FakeClock()
        metrics = MetricsRegistry(clock=clock)
        gauge = metrics.gauge(
            "pio_quality_score_psi", labelnames=("variant",)
        )
        obj = SLOObjective(
            name="drift", kind="gauge",
            metric="pio_quality_score_psi",
            labels=(("variant", "baseline"),),
            max_value=0.25, burn_threshold=1.0,
            fast_window_s=300.0, slow_window_s=3600.0,
        )
        engine = SLOEngine(metrics, (obj,), clock=clock)
        gauge.set(-1.0, variant="baseline")  # the abstention sentinel
        state = engine.evaluate()["objectives"][0]
        assert state["abstaining"]
        gauge.set(0.6, variant="baseline")
        clock.advance(60)
        state = engine.evaluate()["objectives"][0]
        assert state["state"] == "FIRING"
        # data loss while firing: the alert HOLDS, export stays 1
        gauge.set(-1.0, variant="baseline")
        clock.advance(60)
        state = engine.evaluate()["objectives"][0]
        assert state["state"] == "FIRING" and state["abstaining"]
        alert_state = metrics.instrument("pio_slo_alert_state")
        assert alert_state.value(objective="drift") == 1.0

    def test_counter_reset_abstains_instead_of_false_firing(self):
        plant = _Plant()
        plant.pump(6)
        # "restart": a fresh registry value below the last sample would
        # make the delta negative — the window must abstain
        plant.resp._children.clear()  # simulate the process restart
        plant.resp.inc(1, status=200)
        plant.clock.advance(60)
        plant.engine.evaluate()
        assert plant.state()["abstaining"]

    def test_torn_ledger_lines_skipped(self, tmp_path):
        ledger = tmp_path / "alerts.jsonl"
        ledger.write_text(
            json.dumps(
                {"schema": 1, "kind": "alert", "objective": "x",
                 "state": "FIRING"}
            )
            + "\n{torn"
        )
        alerts = load_alerts(str(ledger))
        assert len(alerts) == 1 and alerts[0]["objective"] == "x"

    def test_default_objectives_cover_every_server_kind(self):
        for kind in ("query", "router", "event", "storage", "dashboard"):
            objectives = default_objectives(kind)
            assert any(o.name == "availability" for o in objectives)
            for obj in objectives:  # constructable = validated
                assert obj.kind in ("ratio", "gauge")
        assert any(
            o.name == "drift" for o in default_objectives("query")
        )
        assert any(
            o.name == "freshness" for o in default_objectives("storage")
        )


class _CountingClock:
    def __init__(self):
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return 0.0


class TestFlightRecorder:
    def test_disabled_path_is_zero_cost(self):
        clock = _CountingClock()
        recorder = FlightRecorder(enabled=False, clock=clock)
        for _ in range(256):
            recorder.record("rollout", "rollout.stage", to="CANARY")
        assert clock.calls == 0  # the clock was NEVER touched
        assert len(recorder) == 0

    def test_ring_is_bounded(self):
        recorder = FlightRecorder(capacity=8, enabled=True,
                                  clock=FakeClock())
        for i in range(32):
            recorder.record("k", "s", i=i)
        events = recorder.dump()
        assert len(events) == 8
        assert events[-1]["details"] == {"i": 31}  # newest survive

    def test_ambient_trace_id_tagged(self):
        from predictionio_tpu.obs.trace import Tracer

        recorder = FlightRecorder(enabled=True, clock=FakeClock())
        tracer = Tracer("t", clock=FakeClock())
        with tracer.server_span("x", header_value="trace42"):
            recorder.record("k", "s")
        assert recorder.dump()[-1]["trace"] == "trace42"

    def test_dump_to_roundtrip(self, tmp_path):
        recorder = FlightRecorder(enabled=True, clock=FakeClock())
        recorder.record("breaker", "breaker.es", state="open")
        path = str(tmp_path / "flight.jsonl")
        recorder.dump_to(path, reason="test")
        doc = load_dump(path)
        assert doc["header"]["reason"] == "test"
        assert doc["events"][0]["site"] == "breaker.es"
        assert load_dump(str(tmp_path / "missing.jsonl")) is None

    def test_breaker_transitions_land_in_process_recorder(self):
        from predictionio_tpu.obs.flight import default_recorder
        from predictionio_tpu.utils.resilience import CircuitBreaker

        recorder = default_recorder()
        before = len(recorder.dump())
        clock = FakeClock()
        breaker = CircuitBreaker(
            name="health-test", failure_threshold=1, clock=clock
        )
        with pytest.raises(RuntimeError):
            breaker.call(lambda: (_ for _ in ()).throw(RuntimeError()))
        events = recorder.dump()[before:]
        assert any(
            e["kind"] == "breaker"
            and e["site"] == "breaker.health-test"
            and e["details"]["state"] == "open"
            for e in events
        )


class TestStallWatchdog:
    def _watchdog(self, tmp_path=None):
        clock = FakeClock()
        metrics = MetricsRegistry(clock=clock)
        flight = FlightRecorder(enabled=True, clock=clock)
        watchdog = StallWatchdog(
            metrics, clock=clock, flight=flight,
            dump_dir=str(tmp_path) if tmp_path else None,
        )
        return watchdog, clock, metrics

    def test_inflight_stall_fires_once_then_recovers(self, tmp_path):
        watchdog, clock, metrics = self._watchdog(tmp_path)
        token = watchdog.enter("serving.request", budget_s=1.0)
        clock.advance(2.0)
        assert watchdog.check() == []  # under 4x budget (and min floor)
        clock.advance(10.0)
        stalls = watchdog.check()
        assert [s["site"] for s in stalls] == ["serving.request"]
        assert stalls[0]["stallKind"] == "request"
        assert watchdog.check() == []  # fires ONCE per episode
        counter = metrics.instrument("pio_stall_detected_total")
        assert counter.value(site="serving.request") == 1.0
        # durable dump names the site
        dump_path = watchdog.summary()["lastDump"]
        assert dump_path and os.path.exists(dump_path)
        doc = load_dump(dump_path)
        assert doc["header"]["reason"] == "stall:serving.request"
        watchdog.exit(token)
        watchdog.check()
        assert watchdog.summary()["active"] == []

    def test_missing_deadline_gets_default_budget(self):
        watchdog, clock, _ = self._watchdog()
        watchdog.enter("serving.request", budget_s=None)
        clock.advance(39.0)
        assert watchdog.check() == []  # 4 x 10 s default
        clock.advance(2.0)
        assert watchdog.check()

    def test_wedged_tick_detected_and_unexpect_clears(self):
        watchdog, clock, metrics = self._watchdog()
        watchdog.expect("continuous.tick", max_gap_s=30.0)
        watchdog.beat("continuous.tick")
        clock.advance(20.0)
        assert watchdog.check() == []
        watchdog.beat("continuous.tick")
        clock.advance(31.0)
        stalls = watchdog.check()
        assert stalls and stalls[0]["stallKind"] == "tick"
        watchdog.unexpect("continuous.tick")
        assert watchdog.check() == []
        summary = watchdog.summary()
        assert summary["watched"] == [] and summary["detected"] == 1


# ---------------------------------------------------------------------------
# server wiring: every server answers /health.json + /blackbox.json
# ---------------------------------------------------------------------------


def _get_json(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode()
        return resp.status, json.loads(body)
    finally:
        conn.close()


class TestServerWiring:
    @pytest.fixture()
    def event_server(self, tmp_path):
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.storage import StorageRegistry

        registry = StorageRegistry(
            env={"PIO_FS_BASEDIR": str(tmp_path)}
        )
        server = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=registry.get_events(),
            metadata=registry.get_metadata(),
        )
        server.start_background()
        yield server
        server.server_close()

    def test_health_and_blackbox_routes(self, event_server):
        status, doc = _get_json(event_server.bound_port, "/health.json")
        assert status == 200
        assert doc["kind"] == "event"
        names = {o["name"] for o in doc["objectives"]}
        assert "availability" in names
        # fresh server: every objective is abstaining, none firing
        assert doc["firing"] == 0
        assert all(o["abstaining"] for o in doc["objectives"])
        assert "stalls" in doc
        status, doc = _get_json(event_server.bound_port, "/blackbox.json")
        assert status == 200
        assert "events" in doc and isinstance(doc["events"], list)

    def test_slo_families_on_metrics_and_top_health_column(
        self, event_server
    ):
        from predictionio_tpu.obs.top import FLEET_COLUMNS, node_row

        node = f"127.0.0.1:{event_server.bound_port}"
        row = node_row(node)
        assert row["up"]
        # abstaining everywhere, no stalls -> 'ok' (the engine exists)
        assert row["health"] == "ok"
        assert any(key == "health" for _t, key, _f in FLEET_COLUMNS)

    def test_health_plane_ticker_stops_on_close(self, tmp_path):
        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.storage.storage_server import StorageServer

        registry = StorageRegistry(
            env={"PIO_FS_BASEDIR": str(tmp_path)}
        )
        server = StorageServer(
            "127.0.0.1", 0, registry.get_events(),
            registry.get_metadata(), registry.get_models(),
        )
        plane = server.health
        assert plane is not None and plane.kind == "storage"
        port = server.bound_port
        # a FAILED construction (port already bound) must not leak a
        # ticking thread: the ticker starts only after the bind
        import threading

        before = threading.active_count()
        with pytest.raises(OSError):
            StorageServer(
                "127.0.0.1", port, registry.get_events(),
                registry.get_metadata(), registry.get_models(),
            )
        assert threading.active_count() == before
        server.server_close()
        assert plane._thread is None  # ticker joined, not leaked

    def test_dashboard_health_panel_renders_down_rows(self, tmp_path):
        import http.client

        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.tools.dashboard import (
            DashboardConfig,
            DashboardServer,
        )

        registry = StorageRegistry(
            env={"PIO_FS_BASEDIR": str(tmp_path)}
        )
        server = DashboardServer(
            DashboardConfig(
                ip="127.0.0.1", port=0, nodes="127.0.0.1:9",
                scrape_timeout_s=0.5,
            ),
            registry,
        )
        server.start_background()
        try:
            status, doc = _get_json(server.bound_port, "/health.json")
            assert status == 200
            # the uniform per-node contract holds (a dict with the
            # dashboard's OWN objectives — `pio health` must not read a
            # live dashboard as DOWN), fleet rows ride along
            assert doc["kind"] == "dashboard"
            assert any(
                o["name"] == "availability" for o in doc["objectives"]
            )
            assert doc["fleet"] == [{"node": "127.0.0.1:9", "up": False}]
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=10
            )
            conn.request("GET", "/health")
            resp = conn.getresponse()
            body = resp.read().decode()
            conn.close()
            assert resp.status == 200 and "DOWN" in body
        finally:
            server.server_close()


# ---------------------------------------------------------------------------
# the CLIs (in-process, pinned exit codes)
# ---------------------------------------------------------------------------


class TestHealthCLI:
    def _main(self, *argv):
        from predictionio_tpu.tools import health

        return health.main(list(argv))

    def test_health_no_nodes_reachable_is_engine_error(self, capsys):
        rc = self._main(
            "health", "--nodes", "127.0.0.1:9", "--timeout", "0.5"
        )
        assert rc == 2
        assert "DOWN" in capsys.readouterr().out

    def test_alerts_ledger_exit_codes(self, tmp_path, capsys):
        missing = str(tmp_path / "missing.jsonl")
        assert self._main("alerts", "--ledger", missing) == 2
        # existing-but-unreadable (a directory) is an error too, never
        # a silent "everything cleared"
        assert self._main("alerts", "--ledger", str(tmp_path)) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert self._main("alerts", "--ledger", str(empty)) == 0
        from predictionio_tpu.obs.perfledger import append_record

        ledger = str(tmp_path / "alerts.jsonl")
        fire = {
            "schema": 1, "kind": "alert", "objective": "availability",
            "metric": "m", "state": "FIRING", "burnFast": 12.0,
            "burnSlow": 9.0, "node": "query", "at": 1000.0,
        }
        append_record(ledger, fire)
        assert self._main("alerts", "--ledger", ledger) == 1  # firing
        append_record(ledger, dict(fire, state="CLEARED", burnFast=0.1))
        assert self._main("alerts", "--ledger", ledger) == 0  # cleared
        out = capsys.readouterr().out
        assert "FIRING" in out and "CLEARED" in out

    def test_blackbox_show_and_errors(self, tmp_path, capsys):
        recorder = FlightRecorder(enabled=True, clock=FakeClock())
        recorder.record("rollout", "rollout.stage", to="CANARY")
        path = str(tmp_path / "flight.jsonl")
        recorder.dump_to(path)
        assert self._main("blackbox", "show", "--file", path) == 0
        assert "rollout.stage" in capsys.readouterr().out
        assert self._main(
            "blackbox", "show", "--file", str(tmp_path / "nope.jsonl")
        ) == 2
        assert self._main(
            "blackbox", "dump", "--node", "127.0.0.1:9",
            "--timeout", "0.5",
        ) == 2

    def test_console_forwards_health_family(self, tmp_path, capsys):
        from predictionio_tpu.tools import console

        ledger = str(tmp_path / "alerts.jsonl")
        from predictionio_tpu.obs.perfledger import append_record

        append_record(
            ledger,
            {"schema": 1, "kind": "alert", "objective": "o",
             "metric": "m", "state": "CLEARED", "at": 1.0,
             "node": "n"},
        )
        assert console.main(["alerts", "--ledger", ledger]) == 0

    def test_live_scrape_health_and_blackbox(self, tmp_path, capsys):
        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )

        registry = StorageRegistry(
            env={"PIO_FS_BASEDIR": str(tmp_path)}
        )
        server = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=registry.get_events(),
            metadata=registry.get_metadata(),
        )
        server.start_background()
        try:
            node = f"127.0.0.1:{server.bound_port}"
            assert self._main("health", "--nodes", node) == 0
            out = capsys.readouterr().out
            assert "event" in out
            out_file = str(tmp_path / "bb.jsonl")
            assert self._main(
                "blackbox", "dump", "--node", node, "--out", out_file
            ) == 0
            assert os.path.exists(out_file)
            assert self._main("alerts", "--node", node) == 0
        finally:
            server.server_close()


# ---------------------------------------------------------------------------
# lint: obs-swallowed-observer fixture twins
# ---------------------------------------------------------------------------


class TestSwallowedObserverLint:
    def _unsuppressed(self, path):
        from predictionio_tpu.lint import lint_file

        return [f for f in lint_file(path) if not f.suppressed]

    def test_bad_fixture_fires_exactly_intended_rule(self):
        path = os.path.join(FIXTURES, "swallowed_observer_bad.py")
        findings = self._unsuppressed(path)
        assert [f.rule_id for f in findings] == (
            ["obs-swallowed-observer"] * 3
        ), [(f.rule_id, f.line) for f in findings]

    def test_clean_twin_has_no_findings(self):
        findings = self._unsuppressed(
            os.path.join(FIXTURES, "swallowed_observer_clean.py")
        )
        assert findings == [], [(f.rule_id, f.line) for f in findings]


# ---------------------------------------------------------------------------
# perfledger: the alert-noisiness trend records
# ---------------------------------------------------------------------------


class TestAlertLedgerRecords:
    def test_alert_records_shape_and_gating(self):
        from predictionio_tpu.obs import perfledger

        bench = {
            "device": "cpu", "alerts": {
                "ok": True, "fired": 2, "cleared": 2,
                "falsePositives": 0,
            },
        }
        records = perfledger.alert_records(bench)
        assert len(records) == 1
        record = records[0]
        assert record["metric"] == "alert_false_positives"
        assert record["unit"] == "count"  # trend-only: never gates
        assert record["value"] == 0.0
        # a failed drill records NOTHING
        assert perfledger.alert_records(
            {"alerts": {"ok": False, "falsePositives": 3}}
        ) == []
        assert perfledger.alert_records({}) == []
        # unit != "s" means detect_regressions ignores it even at 100x
        history = [
            dict(record, value=0.0), dict(record, value=0.0),
            dict(record, value=100.0),
        ]
        assert perfledger.detect_regressions(history) == []


# ---------------------------------------------------------------------------
# the brownout drill (tier-1 acceptance) — ONE run, many assertions
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def brownout_report():
    from predictionio_tpu.tools.loadgen import run_brownout

    return run_brownout()


class TestBrownoutDrill:
    def test_drill_accepts(self, brownout_report):
        assert brownout_report["ok"], brownout_report

    def test_control_run_fires_zero_alerts(self, brownout_report):
        assert brownout_report["controlAlertsFired"] == 0
        assert brownout_report["falsePositives"] == 0

    def test_stall_watchdog_dump_names_the_wedged_site(
        self, brownout_report
    ):
        assert brownout_report["stallsDetected"] >= 1
        assert brownout_report["stallDumpNamesSite"]
        # the drill's dump dir may already be cleaned (tmp workspace);
        # the parsed verdict above is the contract

    def test_alerts_fire_and_clear_durably(self, brownout_report):
        ledger = {
            (a["objective"], a["state"])
            for a in brownout_report["ledger"]
        }
        assert {
            ("availability", "FIRING"), ("availability", "CLEARED"),
            ("latency", "FIRING"), ("latency", "CLEARED"),
        } <= ledger
        assert brownout_report["firingAfterRecovery"] == 0
        for stats in brownout_report["alerts"].values():
            assert stats["fired"] == 1 and stats["cleared"] == 1


class TestWorkspaceCache:
    def test_builder_runs_once_per_tag(self, tmp_path):
        from predictionio_tpu.tools import loadgen

        calls = []

        def build(registry):
            calls.append(1)
            return {"id": "X"}

        a = str(tmp_path / "a")
        b = str(tmp_path / "b")
        tag = "cache-test-health"
        info_a = loadgen._prepared_workspace(tag, build, a)
        info_b = loadgen._prepared_workspace(tag, build, b)
        assert calls == [1]  # trained ONCE
        assert info_a == info_b == {"id": "X"}
        assert os.path.isdir(a) and os.path.isdir(b)


# ---------------------------------------------------------------------------
# metric-catalog golden test: boot-registered pio_* vs docs
# ---------------------------------------------------------------------------


def _parse_catalog():
    """docs/observability.md#metric-catalog rows →
    {name: (kind, frozenset(labels))}; `runtime:`-marked and
    bracketed rows are documentation-only (not boot-registered)."""
    path = os.path.join(REPO, "docs", "observability.md")
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    section = text.split("## Metric catalog", 1)[1]
    catalog = {}
    for line in section.splitlines():
        match = re.match(
            r"\|\s*`(pio_[a-z0-9_]+)`\s*\|\s*(\w+)\s*\|\s*([^|]+)\|",
            line,
        )
        if not match:
            continue
        name, kind, labels_text = match.groups()
        labels_text = labels_text.strip()
        if labels_text.startswith("runtime:") or "[" in labels_text:
            catalog[name] = (kind, None)  # documented, schema unpinned
            continue
        labels = frozenset(
            part.strip()
            for part in labels_text.split(",")
            if part.strip() and part.strip() != "-"
        )
        catalog[name] = (kind, labels)
    return catalog


def _boot_instruments(server):
    return {
        inst.name: (inst.kind, frozenset(inst.labelnames))
        for inst in server.metrics.collect()
        if inst.name.startswith("pio_")
    }


@pytest.fixture(scope="module")
def boot_metrics(tmp_path_factory):
    """Every server type booted in-process; their boot-registered
    pio_* instruments, merged (schemas are pinned registry-wide, so a
    name can never disagree between servers)."""
    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.controller import Engine, WorkflowParams
    from predictionio_tpu.fleet.router import RouterConfig, RouterServer
    from predictionio_tpu.storage import StorageRegistry
    from predictionio_tpu.storage.storage_server import StorageServer
    from predictionio_tpu.tools.dashboard import (
        DashboardConfig,
        DashboardServer,
    )
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.serving import (
        QueryServer,
        ServerConfig,
    )

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from sample_engine import (  # noqa: E402
        Algo0,
        DataSource0,
        Preparator0,
        Query,
        Serving0,
    )
    from test_engine import make_params  # noqa: E402

    tmp = tmp_path_factory.mktemp("catalog")
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp)})
    merged = {}
    servers = []
    try:
        servers.append(
            EventServer(
                EventServerConfig(ip="127.0.0.1", port=0),
                events=registry.get_events(),
                metadata=registry.get_metadata(),
            )
        )
        servers.append(
            StorageServer(
                "127.0.0.1", 0, registry.get_events(),
                registry.get_metadata(), registry.get_models(),
            )
        )
        servers.append(
            RouterServer(
                RouterConfig(
                    ip="127.0.0.1", port=0, backends=("127.0.0.1:9",)
                )
            )
        )
        servers.append(
            DashboardServer(
                DashboardConfig(ip="127.0.0.1", port=0), registry
            )
        )
        from predictionio_tpu.fleet.sharedcache import SharedCacheServer

        servers.append(SharedCacheServer(ip="127.0.0.1", port=0))

        class TypedAlgo(Algo0):
            def query_class(self):
                return Query

        engine = Engine(
            {"": DataSource0}, {"": Preparator0},
            {"": TypedAlgo}, {"": Serving0},
        )
        run_train(
            engine, make_params(algo_ids=(11,)), registry,
            engine_id="default", engine_version="1",
            workflow_params=WorkflowParams(batch="catalog"),
        )
        servers.append(
            QueryServer(
                ServerConfig(ip="127.0.0.1", port=0, batch_wait_ms=0.0),
                engine, registry,
            )
        )
        for server in servers:
            merged.update(_boot_instruments(server))
    finally:
        for server in servers:
            try:
                server.server_close()
            except Exception:
                pass
    return merged


class TestMetricCatalog:
    def test_every_boot_metric_is_documented_with_exact_schema(
        self, boot_metrics
    ):
        catalog = _parse_catalog()
        assert len(catalog) > 40  # the parse actually found the table
        missing = sorted(set(boot_metrics) - set(catalog))
        assert not missing, (
            "metrics registered at server boot but absent from "
            f"docs/observability.md#metric-catalog: {missing} — "
            "update the table (the docs are the pinned schema)"
        )
        mismatched = {
            name: (boot_metrics[name], catalog[name])
            for name in boot_metrics
            if catalog[name][1] is not None
            and boot_metrics[name] != catalog[name]
        }
        assert not mismatched, (
            "metric kind/label schema drifted from the documented "
            f"catalog: {mismatched}"
        )

    def test_catalog_kinds_are_valid(self):
        for name, (kind, _labels) in _parse_catalog().items():
            assert kind in ("counter", "gauge", "histogram"), (
                name, kind,
            )
