"""End-to-end recommendation template test: events → train → persist →
deploy → predict (the "one model" milestone of SURVEY §7 step 5)."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams,
    Query,
    RecDataSourceParams,
    engine_factory,
)
from predictionio_tpu.storage import DataMap, Event, StorageRegistry
from predictionio_tpu.workflow import load_models, run_train
from predictionio_tpu.workflow.context import WorkflowContext


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    reg = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
    # route the module-level get_registry() to this test's registry
    import predictionio_tpu.storage.registry as regmod

    monkeypatch.setattr(regmod, "_default_registry", reg)
    return reg


def ingest_ratings(reg, app_id=1, n_users=12, n_items=8, seed=0):
    """Two-cohort preference structure so recommendations are predictable:
    even users love even items, odd users love odd items."""
    rng = np.random.default_rng(seed)
    ev = reg.get_events()
    ev.init(app_id)
    events = []
    for u in range(n_users):
        for i in range(n_items):
            aligned = (u % 2) == (i % 2)
            if rng.random() < 0.8:
                rating = 5.0 if aligned else 1.0
                events.append(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": rating}),
                    )
                )
    # a few buy events (implicit rating 4.0)
    events.append(
        Event(event="buy", entity_type="user", entity_id="u0",
              target_entity_type="item", target_entity_id="i2")
    )
    ev.write(events, app_id)
    return len(events)


def engine_params(rank=4, iters=6):
    return EngineParams(
        data_source_params=("", RecDataSourceParams(app_id=1)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=rank, num_iterations=iters,
                                       lambda_=0.05))
        ],
    )


class TestEndToEnd:
    def test_train_persist_deploy_predict(self, registry):
        n = ingest_ratings(registry)
        assert n > 50
        engine = engine_factory()
        iid = run_train(
            engine, engine_params(), registry,
            engine_id="rec", engine_factory="predictionio_tpu.models.recommendation:engine_factory",
        )
        # deploy path: reload from blobs
        ctx = WorkflowContext("Serving")
        ep = engine.engine_instance_to_engine_params(
            registry.get_metadata().engine_instance_get(iid)
        )
        models = engine.prepare_deploy(ctx, ep, iid, load_models(registry, iid))
        algo = engine._algorithms(ep)[0]

        result = algo.predict(models[0], Query(user="u0", num=3))
        assert len(result.item_scores) == 3
        # even user should prefer even items
        top = result.item_scores[0].item
        assert int(top[1:]) % 2 == 0, f"u0 got odd item {top}"
        # scores descending
        scores = [s.score for s in result.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty_result(self, registry):
        ingest_ratings(registry)
        engine = engine_factory()
        ctx = WorkflowContext("Training")
        models = engine.train(ctx, engine_params())
        algo = engine._algorithms(engine_params())[0]
        result = algo.predict(models[0], Query(user="ghost", num=3))
        assert result.item_scores == ()

    def test_batch_predict_matches_single(self, registry):
        ingest_ratings(registry)
        engine = engine_factory()
        ctx = WorkflowContext("Training")
        models = engine.train(ctx, engine_params())
        algo = engine._algorithms(engine_params())[0]
        queries = [(i, Query(user=f"u{i}", num=2)) for i in range(4)]
        batch = dict(algo.batch_predict(models[0], queries))
        for i, q in queries:
            single = algo.predict(models[0], q)
            # same items; scores equal up to matmul tiling noise
            assert [s.item for s in batch[i].item_scores] == [
                s.item for s in single.item_scores
            ]
            np.testing.assert_allclose(
                [s.score for s in batch[i].item_scores],
                [s.score for s in single.item_scores],
                rtol=1e-5,
            )

    def test_json_query_roundtrip(self, registry):
        """Wire-format compatibility of the predicted result."""
        ingest_ratings(registry)
        engine = engine_factory()
        ctx = WorkflowContext("Training")
        models = engine.train(ctx, engine_params())
        algo = engine._algorithms(engine_params())[0]
        result = algo.predict(models[0], Query(user="u1", num=2))
        js = result.to_json_dict()
        assert set(js) == {"itemScores"}
        assert all(set(s) == {"item", "score"} for s in js["itemScores"])

    def test_eval_split(self, registry):
        ingest_ratings(registry)
        engine = engine_factory()
        ctx = WorkflowContext("Evaluation")
        results = engine.eval(ctx, engine_params())
        assert len(results) == 1
        _, qpa = results[0]
        assert len(qpa) > 5
        q, p, a = qpa[0]
        assert isinstance(q, Query)

    def test_eval_train_split_excludes_test_only_entities(self, registry):
        """A user whose every rating fell in the test split must be absent
        from the train-split maps, so predict() returns the unknown-user
        empty result instead of scoring a never-solved zero factor row."""
        ingest_ratings(registry)
        from predictionio_tpu.models.recommendation import RecDataSource

        ds = RecDataSource(RecDataSourceParams(app_id=1))
        [(train_td, _, qa)] = ds.read_eval(None)
        # maps contain exactly the train split's entities
        full = ds.read_training(None)
        test_mask = np.arange(len(full.users)) % 4 == 0
        u_inv = full.user_map.inverse
        train_users = {u_inv[int(u)] for u in full.users[~test_mask]}
        assert set(train_td.user_map) == train_users
        # indices are dense and consistent with the arrays
        assert train_td.users.max() == len(train_td.user_map) - 1
        assert train_td.items.max() == len(train_td.item_map) - 1

    def test_empty_events_fails_sanity(self, registry):
        registry.get_events().init(1)
        engine = engine_factory()
        ctx = WorkflowContext("Training")
        with pytest.raises(ValueError, match="No rating events"):
            engine.train(ctx, engine_params())


class TestStreamingTopKServing:
    """The streaming serving path must produce the same results as the
    dense path (forced via streaming_top_k="always"; on CPU the kernel
    runs in interpret mode)."""

    def test_streaming_matches_dense(self, registry):
        """One trained model served through both paths — streaming_top_k
        is serving-only, so the model is shared."""
        from predictionio_tpu.models.recommendation import ALSAlgorithm

        ingest_ratings(registry)
        engine = engine_factory()
        params = EngineParams(
            data_source_params=("", RecDataSourceParams(app_id=1)),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(rank=4, num_iterations=4,
                                           lambda_=0.05))
            ],
        )
        iid = run_train(engine, params, registry, engine_id="stream")
        model = load_models(registry, iid)[0]
        results = {}
        for mode in ("never", "always"):
            algo = ALSAlgorithm(
                ALSAlgorithmParams(rank=4, streaming_top_k=mode)
            )
            out = algo.batch_predict(
                model,
                [(0, Query(user="u0", num=4)), (1, Query(user="u3", num=4))],
            )
            results[mode] = {
                i: [s.item for s in r.item_scores] for i, r in out
            }
        assert results["never"] == results["always"]

    def test_bad_mode_fails_loudly_at_train_time(self, registry):
        ingest_ratings(registry)
        engine = engine_factory()
        params = EngineParams(
            data_source_params=("", RecDataSourceParams(app_id=1)),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(streaming_top_k="bogus"))
            ],
        )
        with pytest.raises(ValueError, match="streaming_top_k"):
            run_train(engine, params, registry, engine_id="bad-mode")


class TestGatherLeverParams:
    """The round-3/4 training levers (sort_gather_indices, fused_gather)
    must be reachable from engine.json via ALSAlgorithmParams and
    reproduce the default path's factors."""

    @pytest.mark.slow  # ~90 s: three full trainings; outside tier-1 budget
    def test_levers_reproduce_default_model(self, registry):
        ingest_ratings(registry)
        engine = engine_factory()

        def params(**kw):
            return EngineParams(
                data_source_params=("", RecDataSourceParams(app_id=1)),
                algorithm_params_list=[
                    ("als", ALSAlgorithmParams(
                        rank=4, num_iterations=4, lambda_=0.05, **kw
                    ))
                ],
            )

        base = run_train(engine, params(), registry, engine_id="lv0")
        levered = run_train(
            engine,
            params(sort_gather_indices=True, fused_gather=True,
                   solve_mode="pallas"),
            registry, engine_id="lv1",
        )
        m0 = load_models(registry, base)[0]
        m1 = load_models(registry, levered)[0]
        np.testing.assert_allclose(
            m0.user_factors, m1.user_factors, rtol=5e-3, atol=5e-4
        )
        np.testing.assert_allclose(
            m0.item_factors, m1.item_factors, rtol=5e-3, atol=5e-4
        )
