"""Robustness bug class 1: a network call with no explicit timeout.

The pre-ISSUE-2 serving feedback path was one stalled Event Server away
from wedging its delivery pool forever, because nothing bounded the
socket wait. ``robust-no-timeout`` must flag the POST below (and nothing
else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import requests


def deliver_feedback(url, data):
    resp = requests.post(url, json=data)  # no timeout: BAD
    return resp.status_code == 201
