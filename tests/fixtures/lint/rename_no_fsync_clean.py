"""Clean twin of ``rename_no_fsync_bad.py``: the tmp file is fsync'd
before the rename and the parent directory after it (the
``utils/durability.atomic_write_bytes`` sequence), so a crash at any
point leaves whole-old or whole-new bytes under the final name. The
linter must report NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import os


def fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_blob(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())  # data durable BEFORE the name flips
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def save_via_helper(path, data):
    # a helper whose name carries the fsync contract also satisfies the
    # rule (the package's durability helpers)
    write_and_fsync(path + ".tmp", data)
    os.replace(path + ".tmp", path)


def write_and_fsync(path, data):
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
