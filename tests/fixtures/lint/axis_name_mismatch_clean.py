"""Clean twin: collectives reduce over bound mesh axes — including a
replicated axis the specs never mention (legal, and the false positive
the rule must not produce)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def reduce_rows(x, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        lambda s: jax.lax.psum(s, "data"),
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P(None, None),
    )
    return f(x)


def replicated_axis_reduce(x, devices):
    mesh = Mesh(devices, ("data", "model"))
    f = shard_map(
        # "model" never appears in the specs, but the mesh binds it:
        # a replicated-axis reduction, perfectly legal
        lambda s: jax.lax.psum(s, "model"),
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P("data", None),
    )
    return f(x)
