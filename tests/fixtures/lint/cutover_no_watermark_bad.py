"""Fixture: robust-cutover-no-watermark MUST fire on both flip shapes."""


class Layouts:
    def __init__(self, old_store, new_store):
        self.old_store = old_store
        self.new_store = new_store
        self.active = old_store
        self.flipped = False

    def cutover(self):
        # flips reads between two layouts with no drain/watermark
        # evidence anywhere in scope — in-flight mirror writes are
        # stranded on the retired path the moment this returns
        self.flipped = True
        if self.flipped:  # BAD: branch flip without a barrier
            self.active = self.new_store
        else:
            self.active = self.old_store
        return self.active


def switch_layout(use_new, old_store, new_store):
    active = new_store if use_new else old_store  # BAD: bare IfExp flip
    return active
