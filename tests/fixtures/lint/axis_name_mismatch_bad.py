"""Family F fixture: collective names an axis the mesh does not bind."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def reduce_rows(x, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        lambda s: jax.lax.psum(s, "batch"),  # BAD: the mesh binds "data"
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P(None, None),
    )
    return f(x)
