"""Fixture: robust-nonatomic-checkpoint MUST fire on raw writes in
checkpoint-marked scopes."""

import json
import os

import numpy as np


def save_checkpoint(path, arrays, meta):
    # writes land on the final names directly: a crash mid-loop leaves
    # torn .npy bytes the next run trusts as a valid checkpoint
    for name, arr in arrays.items():
        np.save(os.path.join(path, name + ".npy"), arr)  # BAD: direct save
    with open(os.path.join(path, "meta.json"), "w") as fh:  # BAD: open w
        json.dump(meta, fh)  # BAD: dump through the raw handle


class Trainer:
    def persist_state(self, path, state):
        with open(path, "wb") as fh:  # BAD: open wb, no atomic evidence
            fh.write(state)
