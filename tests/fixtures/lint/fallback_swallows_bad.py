"""Fixture: robust-fallback-swallows MUST fire on both swallow shapes."""


class TieredReader:
    def __init__(self, primary, cache):
        self.primary = primary
        self.cache = cache
        self.degraded = False

    def read_with_fallback(self, key):
        # shape 1: the function NAME advertises the degrade path, yet
        # the handler drops the primary's exception on the floor — the
        # fallback works, nothing pages, the primary is silently dead
        try:
            return self.primary.read(key)
        except Exception:  # BAD: fallback handler swallows the failure
            return self.cache.read(key)

    def read(self, key):
        # shape 2: the handler body itself advertises the degrade (the
        # `degraded` flag) but still records nothing about WHY
        try:
            return self.primary.read(key)
        except Exception:  # BAD: degrade flagged, failure unrecorded
            self.degraded = True
            return self.cache.read(key)
