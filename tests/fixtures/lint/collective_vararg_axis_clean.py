"""Clean twin: the helpers forward ``*args``/``**kwargs`` into their
collectives' axis slots AND every mapped call site provably feeds one —
an extra positional, or ``axis_name=`` riding the ``**kwargs``."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _reduce(x, *args):
    return jax.lax.psum(x, *args)


def _gather(x, **kwargs):
    return jax.lax.all_gather(x, **kwargs)


def _body(x):
    r = _reduce(x, "data")  # extra positional feeds the axis slot
    return _gather(r, axis_name="data", tiled=True)


def train(y, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
    )
    return f(y)
