"""Clean twin: one spec per mapped operand."""

from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def sharded_matmul(a, b, mesh):
    f = shard_map(
        lambda sa, sb: sa @ sb,
        mesh=mesh,
        in_specs=(P("x", None), P(None, None)),
        out_specs=P("x", None),
    )
    return f(a, b)
