"""Family F fixture: in/out spec literals disagree on rank for a
rank-preserving collective body."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def reduce_rows(x, mesh):
    f = shard_map(  # BAD: psum preserves rank; the out spec lost a dim
        lambda s: jax.lax.psum(s, "data"),
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P(None),
    )
    return f(x)
