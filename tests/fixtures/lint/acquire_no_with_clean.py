"""Clean twin: finally-release, `with`, and the semaphore hand-off
exemption (acquired here, released by the worker — that is what a
semaphore is for)."""

import threading

_LOCK = threading.Lock()
_SLOTS = threading.Semaphore(2)


def update(registry, key, value):
    _LOCK.acquire()
    try:
        registry[key] = value
    finally:
        _LOCK.release()


def read(registry, key):
    with _LOCK:
        return registry.get(key)


def take_slot():
    _SLOTS.acquire()
