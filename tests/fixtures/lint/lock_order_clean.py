"""Clean twin: one pinned acquisition order everywhere."""

import threading

_ROUTES = threading.Lock()
_MODELS = threading.Lock()


def swap_model(routes, models):
    with _ROUTES:
        with _MODELS:
            models.update(routes)


def reroute(routes, models):
    with _ROUTES:
        with _MODELS:
            routes.update(models)
