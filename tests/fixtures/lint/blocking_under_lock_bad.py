"""Family E fixture: sleeping while holding the registry lock."""

import threading
import time


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def refresh(self, debounce_s):
        with self._lock:
            time.sleep(debounce_s)  # BAD: every reader waits out the sleep
            self._state["refreshed"] = True
