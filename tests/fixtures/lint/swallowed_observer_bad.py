"""Observability bug class: an observer path swallowing uncounted.

The swallow itself is correct — a quality monitor must never fail the
query it observes — but without a counter bump the monitor can be
broken on EVERY call (schema change, corrupt state) and look exactly
like a healthy one. ``obs-swallowed-observer`` must flag the three
handlers below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import logging

logger = logging.getLogger(__name__)


def serve(server, variant, payload, result):
    # observer call in the try body, handler only logs: BAD
    try:
        server.quality.observe_result(variant, payload, result)
    except Exception:
        logger.debug("quality observe failed", exc_info=True)


def _observe_quality(self, app_id, event):
    # observer-named function, bare-pass swallow: BAD
    try:
        self.quality.record_event(app_id, event)
    except Exception:
        pass


def drain(watcher, event):
    # logger.error is still a LOG, not a counter: BAD
    try:
        watcher.on_event(event)
    except Exception:
        logger.error("tap failed")
