"""Family E fixture: module-level registry mutated at request time."""

import threading

_HANDLERS = {}
_LOCK = threading.Lock()


def register(name, handler):
    _HANDLERS[name] = handler  # BAD: server threads race the registry


def lookup(name):
    with _LOCK:
        return _HANDLERS.get(name)
