"""Family E fixture: lock-guarded attr read bare on the scrape thread."""

import threading


class ShadowPool:
    def __init__(self, metrics):
        self._lock = threading.Lock()
        self._pending = 0
        metrics.gauge_callback("pool_pending", self._depth, "queue depth")

    def submit(self, item):
        with self._lock:
            self._pending += 1
        return item

    def _depth(self):
        return self._pending  # BAD: guarded attr, bare read on scrape thread
