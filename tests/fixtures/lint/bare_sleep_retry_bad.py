"""Robustness bug class 2: a retry loop sleeping a constant.

Every client that hit the same failure wakes at the same instant and
stampedes the recovering dependency — the thundering-herd shape
full-jitter backoff exists to kill. ``robust-bare-sleep-retry`` must
flag the sleep below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import time


def fetch_with_retry(fetch):
    for _attempt in range(5):
        try:
            return fetch()
        except ConnectionError:
            time.sleep(2.0)  # constant backoff, no jitter: BAD
    raise RuntimeError("gave up")
