"""Clean twin of ``obs_label_bad.py``: label values come from a closed
vocabulary (route templates, outcome kinds, dependency names) and the
per-request detail rides a span tag, not a label. The linter must
report NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

_ROUTES = {"/queries.json": "POST /queries.json"}


def record_request(counter, tracer, span_ctx, path, user_id):
    # bounded: the label is a route *template* from a fixed mapping
    counter.inc(1, route=_ROUTES.get(path, "other"))
    # the unbounded value goes in a span tag — ring-buffered, not a
    # permanent time series (f-strings outside label positions are fine)
    tracer.record(
        f"request user-{user_id}",
        span_ctx,
        None,
        start_wall=0.0,
        duration_s=0.0,
        tags={"user": user_id},
    )


def breaker_gauge(registry, breaker):
    # constant label values on a callback gauge: bounded
    registry.gauge_callback(
        "pio_breaker_state",
        lambda: breaker.state_value,
        labels={"dep": "event-server"},
    )
