"""Family E fixture: two locks nested in opposite orders."""

import threading

_ROUTES = threading.Lock()
_MODELS = threading.Lock()


def swap_model(routes, models):
    with _ROUTES:
        with _MODELS:
            models.update(routes)


def reroute(routes, models):
    with _MODELS:
        with _ROUTES:  # BAD: reversed nesting deadlocks against swap_model
            routes.update(models)
