"""Round-5 Mosaic bug class 3: the fused-yty implicit-mode pattern
(``gramian_fused``, PERF.md round-3 weakness). One ``make_async_copy``
per loop iteration gathers a single factor row — each DMA moves one
sublane row, well below the 128-lane floor, and serializes on DMA issue
rate. ``mosaic-per-row-dma`` must flag the copy below (and nothing else
in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fused_yty_kernel(idx_ref, y_ref, yty_ref, out_ref, gbuf, sem):
    def one(k, _):
        dma = pltpu.make_async_copy(  # one row per DMA: BAD
            y_ref.at[pl.ds(idx_ref[0, k], 1), :],
            gbuf.at[pl.ds(k, 1), :],
            sem,
        )
        dma.start()
        dma.wait()
        return 0

    jax.lax.fori_loop(0, 16, one, 0)
    g = gbuf[:]
    out_ref[:] = jax.lax.dot_general(
        g, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + yty_ref[:]


def run(idx, y, yty, out_shape, scratch_shapes):
    return pl.pallas_call(
        _fused_yty_kernel,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
    )(idx, y, yty)
