"""Clean twin of ``rank3_compare_bad.py``: the post-fix formulation —
one excluded id per ``fori_loop`` step, each step a single 2-D compare
(total compare work identical: E x [B, T]). The linter must report
NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float("-inf")


def _mask_kernel(scores_ref, excl_ref, out_ref):
    scores = scores_ref[:]
    gidx = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)

    def body(e, sc):
        ex = excl_ref[pl.ds(e, 1), :]  # one [1, B] sublane row per step
        hit = gidx == ex[0][:, None]  # 2-D compare: OK
        return jnp.where(hit, _NEG_INF, sc)

    out_ref[:] = jax.lax.fori_loop(0, 8, body, scores)


def run(scores, excl, out_shape):
    return pl.pallas_call(_mask_kernel, out_shape=out_shape)(scores, excl)
