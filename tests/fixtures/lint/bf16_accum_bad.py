"""Fixture: bf16-cast operands reaching contractions WITHOUT
preferred_element_type — the accumulator silently follows the operand
dtype down to bf16 (mosaic-bf16-accum)."""
import jax
import jax.numpy as jnp


def direct_cast_einsum(y, idx, mask):
    g = y.astype(jnp.bfloat16)[idx] * mask
    # BAD: bf16 operands, accumulator defaults to bf16
    return jnp.einsum("bkr,bks->brs", g, g)


def conditional_dtype_dot(y, val, reduced):
    gdt = jnp.bfloat16 if reduced else jnp.float32
    y_g = y.astype(gdt)
    # BAD: possibly-bf16 via the conditional-dtype idiom, kwarg missing
    return jax.lax.dot_general(
        y_g, val.astype(y_g.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
    )


def one_hop_matmul(table, q):
    low = table.astype("bfloat16")
    padded = jnp.pad(low, ((0, 0), (0, 8)))
    # BAD: taint survives the pad (still bf16 data)
    return jnp.matmul(q, padded.T)


def operator_matmul(table, q):
    low = table.astype(jnp.bfloat16)
    # BAD: the @ operator cannot pin an accumulator dtype at all
    return q @ low.T


def tuple_unpacked_einsum(yu, yi, reduced):
    gdt = jnp.bfloat16 if reduced else jnp.float32
    g1, g2 = yu.astype(gdt), yi.astype(gdt)
    # BAD: taint flows through the tuple-unpacking assignment
    return jnp.einsum("bkr,bks->brs", g1, g2)
