"""Robustness bug class 3: write-then-rename without fsync.

``LocalFSModelStore.insert`` shipped exactly this shape before ISSUE 3:
the tmp file's data blocks may still be in flight when the rename's
metadata journals, so a power loss leaves the *final* name holding torn
bytes — and nothing ever notices, because the name exists.
``robust-rename-no-fsync`` must flag the replace below (and nothing
else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import os


def put_blob(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
    os.replace(tmp, path)  # no fsync before the rename: BAD
