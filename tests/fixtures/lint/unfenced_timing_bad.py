"""Perf bug class: timing a jitted call without fencing the result.

JAX dispatch is asynchronous — ``solve(x)`` returns the moment the work
is *enqueued*, so the stop read below measures dispatch overhead, not
device time, and the resulting "measurement" feeds perf decisions while
measuring nothing. ``perf-unfenced-timing`` must flag the stop read
below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import time

import jax

solve = jax.jit(lambda x: x * 2.0)


def measure(x):
    t0 = time.monotonic()
    y = solve(x)
    return y, time.monotonic() - t0  # unfenced stop: BAD
