"""Family F fixture: hash-ordered set iteration feeding device placement."""

import jax


def place_shards(shards):
    out = []
    for s in set(shards):
        out.append(jax.device_put(s))  # BAD: hosts disagree on the order
    return out
