"""Clean twin: every deadline-scoped call forwards the budget — by
keyword, or by the callee reading the ambient ``current_deadline()``
itself (the contextvar idiom ``storage/remote.py`` uses) — and a callee
with no deadline/timeout parameter has nothing to forward."""

from predictionio_tpu.utils.resilience import current_deadline


def fetch_rows(shard, deadline=None):
    return shard.read(deadline=deadline)


def tail_rows(shard, deadline=None):
    if deadline is None:
        deadline = current_deadline()
    return shard.read(deadline=deadline)


def count_rows(shard):
    return len(shard)


def query(shards, deadline):
    out = []
    for shard in shards:
        out.append(fetch_rows(shard, deadline=deadline))  # forwarded by keyword
        out.append(tail_rows(shard))  # callee reads the ambient deadline itself
        out.append(count_rows(shard))  # not deadline-capable: nothing to forward
    return out
