"""Round-5 Mosaic bug class 1 (commit 093d7d2): the exclusion top-k
sliced its [B, E] exclusion buffer at 16-lane offsets in the lane dim.
Mosaic rejects unaligned lane slices outright — the serving query did
not compile on TPU at all. ``mosaic-unaligned-lane-slice`` must flag the
``pl.ds`` below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float("-inf")


def _excl_kernel(scores_ref, excl_ref, out_ref):
    scores = scores_ref[:]

    def body(c, sc):
        chunk = excl_ref[:, pl.ds(c * 16, 16)]  # 16-lane slice: BAD
        hit = sc[:, None] == chunk[:, :1]
        return jnp.where(hit[:, 0], _NEG_INF, sc)

    out_ref[:] = jax.lax.fori_loop(0, 4, body, scores)


def run(scores, excl, out_shape):
    return pl.pallas_call(_excl_kernel, out_shape=out_shape)(scores, excl)
