"""Clean twin of ``bare_sleep_retry_bad.py``: the retry schedule comes
from the shared full-jitter policy (and a pacing sleep outside any
except handler stays legal). The linter must report NOTHING for this
file.

Fixture only: parsed by the linter, never imported or executed.
"""

import time

from predictionio_tpu.utils.resilience import RetryPolicy


def fetch_with_retry(fetch):
    policy = RetryPolicy(attempts=5, base_delay_s=0.1, max_delay_s=2.0)
    return policy.call(fetch, should_retry=lambda e: isinstance(e, ConnectionError))


def drain(pending):
    while pending():  # pacing loop, no retry/except: sleeps stay legal
        time.sleep(0.005)
