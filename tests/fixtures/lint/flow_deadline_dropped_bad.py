"""Family G fixture: a deadline-scoped caller invokes a package callee
that accepts ``deadline=`` without forwarding it — that leg runs
unbounded while the caller's budget ticks away."""


def fetch_rows(shard, deadline=None):
    return shard.read(deadline=deadline)


def query(shards, deadline):
    out = []
    for shard in shards:
        out.append(fetch_rows(shard))  # BAD: deadline in hand, not forwarded
    return out
