"""Fixture: robust-unbounded-cache MUST fire on both container shapes."""

import threading
from collections import OrderedDict

_RESPONSE_CACHE = {}
_CACHE_LOCK = threading.Lock()


def lookup(key, compute):
    # module-global dict cache: get-then-set on a request-derived key,
    # properly locked — but nothing in the module ever evicts
    with _CACHE_LOCK:
        hit = _RESPONSE_CACHE.get(key)
    if hit is None:
        hit = compute(key)
        with _CACHE_LOCK:
            _RESPONSE_CACHE[key] = hit  # BAD: grows with every distinct key
    return hit


class PlanMirror:
    def __init__(self):
        self.plan_cache = OrderedDict()

    def plan_for(self, engine_key, load):
        # attribute cache over the whole class: ordered, but order
        # without popitem is not an LRU — nothing bounds it
        if engine_key in self.plan_cache:
            return self.plan_cache[engine_key]
        plan = load(engine_key)
        self.plan_cache[engine_key] = plan  # BAD: unbounded attribute cache
        return plan
