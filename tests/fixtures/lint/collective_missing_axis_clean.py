"""Clean twin: collectives inside mapped bodies carry their axis —
positionally or as axis_name= — and an axis-less call OUTSIDE any mapped
body is not this rule's business (the first unit test catches it)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _gramian_body(y_local):
    local = jax.numpy.einsum("nr,ns->rs", y_local, y_local)
    return jax.lax.psum(local, "data")  # positional axis


def _gather_body(y_local):
    # axis via keyword: equally statically provable
    return jax.lax.all_gather(y_local, axis_name="data", tiled=True)


def sharded_gramian(y, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        _gramian_body,
        mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
    )
    g = shard_map(
        _gather_body,
        mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
    )
    return f(y), g(y)


def unmapped_helper(x):
    # not inside any shard_map/pmap body: out of this rule's scope (and
    # the first direct call would raise immediately anyway)
    return jax.lax.psum(x)
