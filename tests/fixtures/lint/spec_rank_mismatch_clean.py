"""Clean twin: specs agree on rank."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def reduce_rows(x, mesh):
    f = shard_map(
        lambda s: jax.lax.psum(s, "data"),
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P(None, None),
    )
    return f(x)
