"""Family F fixture: RNG seeded from the wall clock."""

import time

import jax


def init_factors(shape):
    key = jax.random.PRNGKey(int(time.time()))  # BAD: differs per host/run
    return jax.random.normal(key, shape)
