"""Clean twin: sorted iteration gives every host the same order."""

import jax


def place_shards(shards):
    out = []
    for s in sorted(set(shards)):
        out.append(jax.device_put(s))
    return out
