"""Clean twin: the narrowing cast and the check that licenses it share
one scope — and an unmarked helper may narrow freely (index packing,
encode internals) because nothing it returns is served unmeasured."""

import jax.numpy as jnp


def topk_match_gate(codes, scales, table):
    approx = codes.astype(jnp.float32) * scales[:, None]
    return float(jnp.mean(jnp.abs(approx - table)))


def build_serving_table(table):
    scales = jnp.max(jnp.abs(table), axis=1) / 127.0
    codes = (table / scales[:, None]).astype(jnp.int8)
    if topk_match_gate(codes, scales, table) > 1.0:
        raise ValueError("quantized table refused")
    return codes, scales


def pack_ids(ids):
    # unmarked scope: narrowing an id below the table size is lossless
    return ids.astype(jnp.uint8)
