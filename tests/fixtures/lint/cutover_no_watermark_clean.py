"""Fixture twin: the same flips behind a verified barrier stay clean."""


class Layouts:
    def __init__(self, old_store, new_store):
        self.old_store = old_store
        self.new_store = new_store
        self.active = old_store
        self.flipped = False

    def drain_queue(self):
        return 0

    def watermark(self):
        return {"ok": True}

    def cutover(self):
        # CLEAN: drain + watermark checked before the flip.
        self.drain_queue()
        if not self.watermark()["ok"]:
            raise RuntimeError("backfill not caught up")
        self.flipped = True
        if self.flipped:
            self.active = self.new_store
        else:
            self.active = self.old_store
        return self.active


def switch_layout(use_new, old_store, new_store, pending):
    # CLEAN: waits for the lagging side to drain before choosing.
    pending.drain()
    active = new_store if use_new else old_store
    return active
