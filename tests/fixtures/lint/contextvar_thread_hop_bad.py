"""Family E fixture: ambient contextvar read after the thread hop."""

import contextvars

_REQUEST = contextvars.ContextVar("request", default=None)


def handle(pool, payload):
    def deliver():
        ctx = _REQUEST.get()  # BAD: the worker thread's context is empty
        return (ctx, payload)

    return pool.submit(deliver)
