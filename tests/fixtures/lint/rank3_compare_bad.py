"""Round-5 Mosaic bug class 2 (commit 093d7d2): widening the exclusion
compare to an aligned ``[B, T, C]`` rank-3 broadcast "fixed" the slice
alignment but made Mosaic compile pathologically — the kernel was
aborted after 15+ minutes of compile time. ``mosaic-rank3-compare``
must flag the broadcast compare below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float("-inf")


def _mask_kernel(scores_ref, excl_ref, out_ref):
    scores = scores_ref[:]
    gidx = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    excl = excl_ref[:]
    hit = gidx[:, :, None] == excl[:, None, :]  # [B, T, C] compare: BAD
    out_ref[:] = jnp.where(hit.any(axis=2), _NEG_INF, scores)


def run(scores, excl, out_shape):
    return pl.pallas_call(_mask_kernel, out_shape=out_shape)(scores, excl)
