"""Clean twin: the seed comes from configuration."""

import jax


def init_factors(shape, seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.normal(key, shape)
