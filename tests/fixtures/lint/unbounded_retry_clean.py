"""Fixture: robust-unbounded-retry must NOT fire on any of these."""

import random
import time


def fetch_bounded(client):
    # clean: attempt cap (a for loop IS the cap) + jittered backoff
    for attempt in range(5):
        try:
            return client.fetch()
        except ConnectionError:
            if attempt == 4:
                raise
            time.sleep(random.uniform(0.0, 0.05 * 2 ** attempt))


def fetch_with_policy(client, policy):
    # clean: RetryPolicy owns the schedule (bounded, jittered)
    return policy.call(client.fetch)


def fetch_until_deadline(client, deadline):
    # clean: conditional exit — the deadline check bounds the loop
    while True:
        try:
            return client.fetch()
        except ConnectionError:
            if deadline.expired:
                raise


def fetch_reraising(client):
    # clean: the handler re-raises — no silent re-iteration
    while True:
        try:
            return client.fetch()
        except ConnectionError:
            raise


def poll_until_stopped(client, stop_event):
    # clean: a real loop condition is the exit, and the failure path
    # waits (backoff) instead of spinning
    while not stop_event.is_set():
        try:
            client.poll()
        except ConnectionError:
            stop_event.wait(0.5)
