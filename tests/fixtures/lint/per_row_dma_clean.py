"""Clean twin of ``per_row_dma_bad.py``: the gather block arrives as one
batched, tiling-aligned copy (8 sublanes x full lane width) before the
compute — no per-iteration DMA. The linter must report NOTHING for this
file.

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fused_yty_kernel(idx_ref, y_ref, yty_ref, out_ref, gbuf, sem):
    dma = pltpu.make_async_copy(
        y_ref.at[pl.ds(0, 8), :],  # one aligned 8-sublane block: OK
        gbuf.at[pl.ds(0, 8), :],
        sem,
    )
    dma.start()
    dma.wait()
    g = gbuf[:]
    out_ref[:] = jax.lax.dot_general(
        g, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + yty_ref[:]


def run(idx, y, yty, out_shape, scratch_shapes):
    return pl.pallas_call(
        _fused_yty_kernel,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
    )(idx, y, yty)
