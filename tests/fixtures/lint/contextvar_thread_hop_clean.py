"""Clean twin: the context is captured at submit time and passed in."""

import contextvars

_REQUEST = contextvars.ContextVar("request", default=None)


def handle(pool, payload):
    ctx = _REQUEST.get()  # captured on the request thread

    def deliver():
        return (ctx, payload)

    return pool.submit(deliver)
