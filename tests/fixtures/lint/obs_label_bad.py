"""Observability bug class: a metric label interpolated from request
data.

Every distinct label value is a new time series the scraper stores
forever; a per-user value grows without bound until the registry's
cardinality cap folds it into ``{user="_overflow"}`` — the metric is
destroyed either way. ``obs-unbounded-label`` must flag the ``inc``
below (and nothing else in this file).

Fixture only: parsed by the linter, never imported or executed.
"""


def record_request(counter, user_id):
    counter.inc(1, user=f"user-{user_id}")  # unbounded label: BAD
