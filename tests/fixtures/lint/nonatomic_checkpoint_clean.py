"""Fixture twin: the same persistence shapes with atomic evidence stay
clean — the packaged helper, or the manual tmp+fsync+rename sequence."""

import json
import os

import numpy as np


def atomic_write_bytes(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def save_checkpoint(path, arrays, meta):
    # CLEAN: every write goes through the atomic helper
    for name, arr in arrays.items():
        atomic_write_bytes(os.path.join(path, name + ".npy"), arr.tobytes())
    atomic_write_bytes(
        os.path.join(path, "meta.json"), json.dumps(meta).encode()
    )


class Trainer:
    def persist_state(self, path, state):
        # CLEAN: the manual sequence — tmp write, fsync, rename — in scope
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(state)
            os.fsync(fh.fileno())
        os.replace(tmp, path)


def save_report(path, rows):
    # CLEAN: a read in a save-marked scope is not write evidence
    with open(path) as fh:
        prior = json.load(fh)
    return prior + rows
