"""Clean twin: every host issues the collective; only host-local I/O
branches on the rank."""

import jax


def global_norm(x, axis, log):
    total = jax.lax.psum(x, axis)
    if jax.process_index() == 0:
        log(total)
    return total
