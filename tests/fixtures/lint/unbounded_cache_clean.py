"""Fixture: robust-unbounded-cache must NOT fire on any of these."""

import functools
import threading
from collections import OrderedDict

_LIMIT = 128

_lru_cache = OrderedDict()
_LRU_LOCK = threading.Lock()


def lookup_lru(key, compute):
    # clean: a real LRU — the popitem under the len() check is the bound
    with _LRU_LOCK:
        hit = _lru_cache.get(key)
    if hit is None:
        hit = compute(key)
        with _LRU_LOCK:
            _lru_cache[key] = hit
            _lru_cache.move_to_end(key)
            while len(_lru_cache) > _LIMIT:
                _lru_cache.popitem(last=False)
    return hit


@functools.lru_cache(maxsize=256)
def lookup_decorated(key):
    # clean: functools.lru_cache owns the bound
    return key.upper()


_config_cache = {}
_CONFIG_LOCK = threading.Lock()


def configured(name):
    # clean: constant keys only — configuration, not a per-request cache
    with _CONFIG_LOCK:
        if "mode" not in _config_cache:
            _config_cache["mode"] = name
        return _config_cache["mode"]


class EvictingMirror:
    def __init__(self):
        self.row_cache = {}

    def row_for(self, key, load):
        # clean: the del under a size check is eviction evidence
        if key in self.row_cache:
            return self.row_cache[key]
        if len(self.row_cache) >= _LIMIT:
            victim = next(iter(self.row_cache))
            del self.row_cache[victim]
        value = load(key)
        self.row_cache[key] = value
        return value


def plain_index(rows):
    # clean: not named a cache — an ordinary build-once index
    index = {}
    for row in rows:
        index[row.key] = row
    return index
