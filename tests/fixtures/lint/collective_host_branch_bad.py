"""Family F fixture: a collective only some hosts ever issue."""

import jax


def global_norm(x, axis):
    if jax.process_index() == 0:
        return jax.lax.psum(x, axis)  # BAD: other hosts hang in their psum
    return x
