"""Family F fixture: in_specs drifted from the mapped function arity."""

from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def sharded_matmul(a, b, mesh):
    f = shard_map(  # BAD: 3 specs for a 2-argument body
        lambda sa, sb: sa @ sb,
        mesh=mesh,
        in_specs=(P("x", None), P(None, None), P(None, None)),
        out_specs=P("x", None),
    )
    return f(a, b)
