"""Family F fixture: collective inside a mapped body with no axis
argument — a trace-time TypeError that only fires when the sharded path
actually runs (the mesh-gated trainer's hardware-day failure mode)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _gramian_body(y_local):
    local = jax.numpy.einsum("nr,ns->rs", y_local, y_local)
    return jax.lax.psum(local)  # BAD: no axis argument


def sharded_gramian(y, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        _gramian_body,
        mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
    )
    return f(y)
