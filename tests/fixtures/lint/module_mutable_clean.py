"""Clean twin: every mutation holds the module lock."""

import threading

_HANDLERS = {}
_LOCK = threading.Lock()


def register(name, handler):
    with _LOCK:
        _HANDLERS[name] = handler


def lookup(name):
    with _LOCK:
        return _HANDLERS.get(name)
