"""Family G extension of the collective-axis twins: the helper forwards
its own ``*args`` into the collective's axis slot, and the mapped call
site feeds nothing extra — the missing axis is a static fact one hop
deep (the per-file rule's documented ``*args/**kwargs calls pass``
skip, now judged through the call graph)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _reduce(x, *args):
    return jax.lax.psum(x, *args)


def _body(x):
    return _reduce(x)  # BAD: nothing fed into the helper's axis slot


def train(y, devices):
    mesh = Mesh(devices, ("data",))
    f = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
    )
    return f(y)
