"""Family G fixture: a worker thread started and stored on ``self``
with no stop/join reachable from any lifecycle method — ``close()``
does not exist, so the worker outlives the object."""

import threading
import time


class MetricsPusher:
    def __init__(self):
        self._worker = threading.Thread(target=self._run, daemon=True)  # BAD: no lifecycle method stops this thread
        self._worker.start()

    def _run(self):
        while True:
            time.sleep(60)

    def push(self, sample):
        return sample
