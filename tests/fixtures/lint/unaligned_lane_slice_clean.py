"""Clean twin of ``unaligned_lane_slice_bad.py``: identical structure,
but the lane-dim slice rides 128-aligned offsets and sizes (the post-fix
formulation). The linter must report NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float("-inf")


def _excl_kernel(scores_ref, excl_ref, out_ref):
    scores = scores_ref[:]

    def body(c, sc):
        chunk = excl_ref[:, pl.ds(c * 128, 128)]  # lane-aligned: OK
        hit = sc[:, None] == chunk[:, :1]
        return jnp.where(hit[:, 0], _NEG_INF, sc)

    out_ref[:] = jax.lax.fori_loop(0, 4, body, scores)


def run(scores, excl, out_shape):
    return pl.pallas_call(_excl_kernel, out_shape=out_shape)(scores, excl)
