"""Clean twin of ``swallowed_observer_bad.py``: every swallowed
observer failure is *counted* — a handler counter bump, an error hook,
or an outcome counter in the try's ``finally``. The linter must report
NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import logging

logger = logging.getLogger(__name__)


def serve(server, variant, payload, result):
    # swallowed but counted: the canonical pattern
    try:
        server.quality.observe_result(variant, payload, result)
    except Exception:
        server._observer_errors.inc(1, site="serving.quality")
        logger.debug("quality observe failed", exc_info=True)


def drain(watcher, event):
    # hook-shaped accounting: the object has no registry of its own,
    # the owner wired an error hook that does the counting
    try:
        watcher.on_event(event)
    except Exception:
        if watcher.on_event_error is not None:
            watcher.on_event_error()
        logger.debug("tap failed", exc_info=True)


def shadow(manager, quality, scores, events_counter, elapsed):
    # accounting in the finally: the outcome counter records ok/error
    # for every path through the try, handler included
    ok = False
    try:
        quality.record_scores("candidate", scores)
        ok = True
    except Exception:
        logger.debug("shadow record failed", exc_info=True)
    finally:
        events_counter.inc(1, kind="shadow_ok" if ok else "shadow_error")


def unrelated(store, row):
    # not an observer path at all: a storage write may swallow-and-log
    # under its own rules without this family firing
    try:
        store.insert(row)
    except Exception:
        logger.warning("insert failed", exc_info=True)
