"""Clean twin: the blocking work happens outside the critical section."""

import threading
import time


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def refresh(self, debounce_s):
        time.sleep(debounce_s)
        with self._lock:
            self._state["refreshed"] = True
