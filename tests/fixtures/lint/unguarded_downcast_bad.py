"""Family F fixture: serve-path table narrowed to int8, nothing
measures what the cut cost."""

import jax.numpy as jnp


def build_serving_table(table):
    scales = jnp.max(jnp.abs(table), axis=1) / 127.0
    codes = (table / scales[:, None]).astype(jnp.int8)  # BAD: no gate
    return codes, scales
