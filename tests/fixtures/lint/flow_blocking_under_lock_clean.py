"""Clean twin: the helper still sleeps, but every call happens outside
the critical section — snapshot under the lock, do the slow work after
release — and a non-blocking helper under the lock is fine."""

import threading
import time


def _refresh_from_disk():
    time.sleep(0.05)
    return 1


def _pure_default():
    return 0


class ModelCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._model = None

    def get(self):
        with self._lock:
            cached = self._model
        if cached is not None:
            return cached
        fresh = _refresh_from_disk()  # slow path outside the lock
        with self._lock:
            if self._model is None:
                self._model = fresh
            return self._model

    def reset(self):
        with self._lock:
            self._model = _pure_default()  # non-blocking helper under the lock
