"""Clean twin: every started worker has a stop story a lifecycle
method can reach — a join, a stop Event the run loop watches, or a
sentinel pushed through the queue the workers drain (the
``_ShardLegPool`` idiom: referencing the thread list counts)."""

import queue
import threading


class JoinedPusher:
    def __init__(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        pass

    def close(self):
        self._worker.join(timeout=5)


class EventStopped:
    def __init__(self):
        self._stop = threading.Event()
        self._ticker = threading.Thread(target=self._run, daemon=True)
        self._ticker.start()

    def _run(self):
        while not self._stop.wait(1.0):
            pass

    def stop(self):
        self._stop.set()  # loop-flag idiom: the run loop exits on the event


class SentinelDrained:
    _STOP = object()

    def __init__(self):
        self._q = queue.Queue()
        self._workers = [
            threading.Thread(target=self._drain, daemon=True)
            for _ in range(2)
        ]
        for t in self._workers:
            t.start()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is self._STOP:
                return

    def close(self):
        for _ in self._workers:  # one sentinel per worker
            self._q.put(self._STOP)
