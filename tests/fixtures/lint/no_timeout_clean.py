"""Clean twin of ``no_timeout_bad.py``: every network call carries an
explicit timeout (kwarg or the API's positional timeout slot). The
linter must report NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import http.client
import socket
import urllib.request

import requests


def deliver_feedback(url, data):
    resp = requests.post(url, json=data, timeout=10)  # bounded: OK
    return resp.status_code == 201


def probe(host, port):
    conn = http.client.HTTPConnection(host, port, timeout=5)
    conn.request("GET", "/")
    return conn.getresponse().status


def fetch(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read()


def raw(addr):
    return socket.create_connection(addr, 2.0)  # positional timeout slot
