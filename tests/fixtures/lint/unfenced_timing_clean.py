"""Clean twin of ``unfenced_timing_bad.py``: every timing bracket around
a jitted call fences with ``block_until_ready`` (or materializes via
``np.asarray``) before the stop read, and timing a plain host function
needs no fence at all. The linter must report NOTHING for this file.

Fixture only: parsed by the linter, never imported or executed.
"""

import time

import jax
import numpy as np

solve = jax.jit(lambda x: x * 2.0)


def measure_fenced(x):
    t0 = time.monotonic()
    y = solve(x)
    jax.block_until_ready(y)
    return y, time.monotonic() - t0


def measure_materialized(x):
    t0 = time.perf_counter()
    y = np.asarray(solve(x))
    return y, time.perf_counter() - t0


def measure_host_work(records):
    # no jitted call in the bracket: plain host timing is fine unfenced
    t0 = time.monotonic()
    total = sum(len(r) for r in records)
    return total, time.monotonic() - t0
