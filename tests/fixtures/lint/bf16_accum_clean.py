"""Fixture twin: the same bf16-cast shapes with f32 accumulation pinned
(or the operand explicitly upcast) — mosaic-bf16-accum stays quiet."""
import jax
import jax.numpy as jnp


def direct_cast_einsum(y, idx, mask):
    g = y.astype(jnp.bfloat16)[idx] * mask
    # clean: accumulation forced to f32 (the als.py exemplar shape)
    return jnp.einsum(
        "bkr,bks->brs", g, g, preferred_element_type=jnp.float32
    )


def conditional_dtype_dot(y, val, reduced):
    gdt = jnp.bfloat16 if reduced else jnp.float32
    y_g = y.astype(gdt)
    return jax.lax.dot_general(
        y_g, val.astype(y_g.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def one_hop_matmul(table, q):
    low = table.astype("bfloat16")
    # clean: explicit upcast clears the reduced-precision taint
    wide = low.astype(jnp.float32)
    return jnp.matmul(q, wide.T)


def f32_only_matmul(a, b):
    # clean: no bf16 anywhere near it
    return jnp.matmul(a, b)


def nested_upcast_in_expression(table, w, q):
    low = table.astype(jnp.bfloat16)
    # clean: the upcast clears the taint even nested inside the
    # operand expression — no redundant preferred_element_type needed
    return jnp.matmul(q, low.astype(jnp.float32) * w)


def operator_matmul_upcast(table, q):
    low = table.astype(jnp.bfloat16)
    # clean: explicit upcast before the operator form
    return q @ low.astype(jnp.float32).T


def tuple_unpacked_einsum(yu, yi, reduced):
    gdt = jnp.bfloat16 if reduced else jnp.float32
    g1, g2 = yu.astype(gdt), yi.astype(gdt)
    # clean: tuple-unpacked bf16 operands with f32 accumulation pinned
    return jnp.einsum(
        "bkr,bks->brs", g1, g2, preferred_element_type=jnp.float32
    )
