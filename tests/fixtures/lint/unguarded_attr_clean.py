"""Clean twin: the scrape-thread read takes the same lock."""

import threading


class ShadowPool:
    def __init__(self, metrics):
        self._lock = threading.Lock()
        self._pending = 0
        metrics.gauge_callback("pool_pending", self._depth, "queue depth")

    def submit(self, item):
        with self._lock:
            self._pending += 1
        return item

    def _depth(self):
        with self._lock:
            return self._pending
