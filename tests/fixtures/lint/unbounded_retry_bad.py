"""Fixture: robust-unbounded-retry MUST fire on both loops."""

import logging

logger = logging.getLogger(__name__)


def fetch_forever(client):
    # BAD: no cap, no deadline, no backoff — a dead client pins this
    # thread at full speed forever
    while True:
        try:
            return client.fetch()
        except ConnectionError:
            continue


def drain_forever(queue, sink):
    # BAD: the swallowed handler just logs; the loop re-iterates
    # immediately against the same failing sink
    while True:
        item = queue.peek()
        try:
            sink.send(item)
            queue.pop()
        except OSError as exc:
            logger.warning("send failed: %s", exc)
