"""Clean twin: degrade paths that RECORD the primary failure do not fire."""

import logging

logger = logging.getLogger(__name__)


class TieredReader:
    def __init__(self, primary, cache):
        self.primary = primary
        self.cache = cache
        self.degraded = False
        self.last_error = None

    def read_with_fallback(self, key):
        # the degrade leaves a trace: the exception is kept and logged
        # before the fallback answers (fleet/sharedcache.py's
        # _record_degrade shape)
        try:
            return self.primary.read(key)
        except Exception as exc:
            self.last_error = f"{type(exc).__name__}: {exc}"
            logger.warning("primary read failed; serving from cache: %s", exc)
            return self.cache.read(key)

    def read(self, key):
        # counting the degrade is recording too — the counter IS the
        # page-able signal
        try:
            return self.primary.read(key)
        except Exception:
            self.degraded = True
            self.count_degrade("primary_error")
            return self.cache.read(key)

    def count_degrade(self, outcome):
        logger.debug("degrade outcome: %s", outcome)
