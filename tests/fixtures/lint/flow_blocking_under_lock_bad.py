"""Family G fixture: the blocking work was refactored into a helper —
lexically clean for conc-blocking-under-lock, but every thread that
wants the lock still waits out the sleep."""

import threading
import time


def _refresh_from_disk():
    time.sleep(0.05)  # stand-in for the slow I/O
    return 1


class ModelCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._model = None

    def get(self):
        with self._lock:
            if self._model is None:
                self._model = _refresh_from_disk()  # BAD: blocking helper under self._lock
            return self._model
