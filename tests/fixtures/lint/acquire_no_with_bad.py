"""Family E fixture: lock.acquire() leaked on the exception path."""

import threading

_LOCK = threading.Lock()


def update(registry, key, value):
    _LOCK.acquire()  # BAD: an exception below leaks the lock forever
    registry[key] = value
    _LOCK.release()
