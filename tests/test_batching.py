"""Micro-batching aggregator: correctness under concurrency, fan-out
alignment, error isolation, and the batched serving path end-to-end
(the accelerator replacement for per-request predictBase,
``CreateServer.scala:479-485``)."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest
import requests

from predictionio_tpu.workflow.batching import MicroBatcher


class TestMicroBatcher:
    def test_single_item_roundtrip(self):
        mb = MicroBatcher(lambda items: [x * 2 for x in items], max_wait_ms=1.0)
        try:
            assert mb.submit(21) == 42
        finally:
            mb.close()

    def test_results_index_aligned_under_concurrency(self):
        mb = MicroBatcher(
            lambda items: [x * 10 for x in items],
            max_batch=16,
            max_wait_ms=5.0,
        )
        try:
            with ThreadPoolExecutor(max_workers=32) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(200)]
                results = [f.result(timeout=30) for f in futs]
            assert results == [i * 10 for i in range(200)]
            # concurrency must actually aggregate: far fewer batches than items
            assert mb.stats["batches"] < 200
            assert mb.stats["avg_batch"] > 1.0
        finally:
            mb.close()

    def test_max_batch_respected(self):
        seen = []

        def process(items):
            seen.append(len(items))
            return list(items)

        mb = MicroBatcher(process, max_batch=8, max_wait_ms=20.0)
        try:
            with ThreadPoolExecutor(max_workers=24) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(64)]
                [f.result(timeout=30) for f in futs]
            assert max(seen) <= 8
        finally:
            mb.close()

    def test_processor_exception_fails_only_that_batch(self):
        calls = []

        def process(items):
            calls.append(list(items))
            if "boom" in items:
                raise ValueError("boom batch")
            return list(items)

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0)
        try:
            with pytest.raises(ValueError, match="boom batch"):
                mb.submit("boom")
            assert mb.submit("ok") == "ok"  # batcher still alive
        finally:
            mb.close()

    def test_length_mismatch_is_an_error(self):
        mb = MicroBatcher(lambda items: [1], max_batch=4, max_wait_ms=5.0)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(2)]
                time.sleep(0.05)
                failures = 0
                for f in futs:
                    try:
                        f.result(timeout=10)
                    except RuntimeError:
                        failures += 1
                # at least the 2-item batch fails; a lone 1-item batch passes
                assert failures >= 1
        finally:
            mb.close()

    def test_submit_after_close_raises(self):
        mb = MicroBatcher(lambda items: list(items))
        mb.close()
        with pytest.raises(RuntimeError, match="closed"):
            mb.submit(1)


class TestPipelinedDispatch:
    """Round-3 pipelining: up to pipeline_depth batches in flight at once
    so the next batch dispatches while the previous one's results are
    still traveling back from the device (the round-2 single-in-flight
    dispatcher capped QPS at max_batch / round_trip)."""

    def test_batches_overlap_up_to_depth(self):
        """With a slow processor and depth 2, two batches must be observed
        running concurrently — the whole point of the pipeline."""
        running = []
        peak = []
        lock = threading.Lock()
        entered = threading.Barrier(2, timeout=10)

        def process(items):
            with lock:
                running.append(1)
                peak.append(len(running))
            try:
                entered.wait()  # both batches provably inside process()
            except threading.BrokenBarrierError:
                pass
            time.sleep(0.02)
            with lock:
                running.pop()
            return list(items)

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0,
                          pipeline_depth=2)
        try:
            with ThreadPoolExecutor(max_workers=4) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(4)]
                assert sorted(f.result(timeout=30) for f in futs) == [0, 1, 2, 3]
            assert max(peak) == 2
            assert mb.stats["inflight_hwm"] == 2
        finally:
            mb.close()

    def test_depth_bounds_concurrency(self):
        """Never more than pipeline_depth batches in process() at once,
        regardless of queue pressure."""
        concurrent = []
        count = [0]
        lock = threading.Lock()

        def process(items):
            with lock:
                count[0] += 1
                concurrent.append(count[0])
            time.sleep(0.005)
            with lock:
                count[0] -= 1
            return list(items)

        mb = MicroBatcher(process, max_batch=2, max_wait_ms=0.0,
                          pipeline_depth=3)
        try:
            with ThreadPoolExecutor(max_workers=24) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(96)]
                [f.result(timeout=30) for f in futs]
            assert max(concurrent) <= 3
        finally:
            mb.close()

    def test_depth_one_is_strictly_serial(self):
        """pipeline_depth=1 reproduces the round-2 contract: batches never
        overlap."""
        concurrent = []
        count = [0]
        lock = threading.Lock()

        def process(items):
            with lock:
                count[0] += 1
                concurrent.append(count[0])
            time.sleep(0.002)
            with lock:
                count[0] -= 1
            return list(items)

        mb = MicroBatcher(process, max_batch=4, max_wait_ms=0.0,
                          pipeline_depth=1)
        try:
            with ThreadPoolExecutor(max_workers=16) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(64)]
                [f.result(timeout=30) for f in futs]
            assert max(concurrent) == 1
        finally:
            mb.close()

    def test_out_of_order_completion_resolves_correct_futures(self):
        """A later batch finishing before an earlier one must deliver each
        item to its own submitter (futures are per-item, not positional
        across batches)."""
        first_batch_gate = threading.Event()
        batch_no = [0]
        batch_lock = threading.Lock()

        def process(items):
            with batch_lock:
                batch_no[0] += 1
                mine = batch_no[0]
            if mine == 1:
                # stall batch 1 until batch 2 has finished
                first_batch_gate.wait(timeout=10)
            return [x * 100 for x in items]

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0,
                          pipeline_depth=2)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                f1 = pool.submit(mb.submit, 1)
                time.sleep(0.05)  # ensure 1 is taken as its own batch first
                f2 = pool.submit(mb.submit, 2)
                assert f2.result(timeout=10) == 200  # batch 2 completes first
                assert not f1.done()
                first_batch_gate.set()
                assert f1.result(timeout=10) == 100
        finally:
            first_batch_gate.set()
            mb.close()

    def test_error_in_one_inflight_batch_spares_the_other(self):
        gate = threading.Event()

        def process(items):
            if "bad" in items:
                raise ValueError("bad batch")
            gate.wait(timeout=10)
            return list(items)

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0,
                          pipeline_depth=2)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                f_ok = pool.submit(mb.submit, "ok")
                time.sleep(0.05)
                f_bad = pool.submit(mb.submit, "bad")
                with pytest.raises(ValueError, match="bad batch"):
                    f_bad.result(timeout=10)
                gate.set()
                assert f_ok.result(timeout=10) == "ok"
        finally:
            gate.set()
            mb.close()

    def test_close_is_bounded_with_hung_batch(self):
        """A batch hung on a dead device must not hang close() (the /stop
        and hot-swap path) forever: close returns after its grace period,
        leaving the daemon worker behind."""
        hang = threading.Event()

        def process(items):
            hang.wait(timeout=60)  # simulates a wedged device dispatch
            return list(items)

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0,
                          pipeline_depth=2)
        try:
            with ThreadPoolExecutor(max_workers=1) as pool:
                fut = pool.submit(mb.submit, 1, 30)
                time.sleep(0.1)  # batch is in flight and hung
                t0 = time.monotonic()
                mb.close(grace_s=0.3)
                assert time.monotonic() - t0 < 5.0
                hang.set()  # release the "device"; submitter completes
                assert fut.result(timeout=10) == 1
        finally:
            hang.set()

    def test_close_with_inflight_batches_completes_them(self):
        """close() must let in-flight batches finish (their callers are
        blocked on the result), then fail whatever never dispatched."""
        release = threading.Event()

        def process(items):
            release.wait(timeout=10)
            return list(items)

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0,
                          pipeline_depth=2)
        try:
            with ThreadPoolExecutor(max_workers=3) as pool:
                futs = [pool.submit(mb.submit, i) for i in range(2)]
                time.sleep(0.1)  # both in flight
                closer = pool.submit(mb.close)
                time.sleep(0.05)
                release.set()
                closer.result(timeout=10)
                assert sorted(f.result(timeout=10) for f in futs) == [0, 1]
        finally:
            release.set()


class TestQueueDepthGauge:
    """Regression tests for the ISSUE-6 ``conc-unguarded-attr`` sweep
    finding: the queue-depth gauge callback read ``self._items`` from
    the scrape thread without the batcher lock."""

    def test_gauge_is_registered_and_reads_zero_when_idle(self):
        from predictionio_tpu.obs.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        mb = MicroBatcher(
            lambda items: list(items), max_wait_ms=0.0, metrics=metrics
        )
        try:
            metrics.collect()  # refresh callback gauges
            assert metrics.gauge("pio_batch_queue_depth").value() == 0.0
        finally:
            mb.close()

    def test_queue_depth_reads_under_the_batcher_lock(self):
        mb = MicroBatcher(lambda items: list(items), max_wait_ms=0.0)
        try:
            got = []
            mb._lock.acquire()
            try:
                t = threading.Thread(
                    target=lambda: got.append(mb._queue_depth())
                )
                t.start()
                t.join(timeout=0.05)
                assert t.is_alive(), (
                    "queue-depth callback returned while the batcher "
                    "lock was held — it reads _items without the lock"
                )
            finally:
                mb._lock.release()
            t.join(timeout=30)
            assert got == [0]
        finally:
            mb.close()


class TestBatchedServing:
    def test_batched_and_unbatched_agree(self, registry):
        from predictionio_tpu.workflow.serving import QueryServer, ServerConfig
        from test_query_server import _train, _typed_engine

        engine = _typed_engine()
        _train(registry, engine, algo_ids=(11, 13))

        batched = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=True,
                         batch_wait_ms=2.0),
            engine, registry,
        )
        unbatched = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry,
        )
        try:
            rb, sb = batched.handle_query({"id": 7})
            ru, su = unbatched.handle_query({"id": 7})
            assert sb == su == 200
            assert rb == ru
        finally:
            for s in (batched, unbatched):
                s.server_close()

    def test_poison_query_fails_alone(self, registry):
        """One bad query in a micro-batch must not 500 its batchmates."""
        from predictionio_tpu.controller import Engine
        from predictionio_tpu.workflow.serving import QueryServer, ServerConfig
        from sample_engine import Algo0, DataSource0, Preparator0, Serving0
        from test_query_server import _train, TypedQueryAlgoMixin

        class PoisonAlgo(TypedQueryAlgoMixin, Algo0):
            def predict(self, model, query):
                if query.id == 666:
                    raise ValueError("poison")
                return super().predict(model, query)

        engine = Engine(
            {"": DataSource0}, {"": Preparator0},
            {"": PoisonAlgo}, {"": Serving0},
        )
        _train(registry, engine, algo_ids=(11,))
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=True,
                         batch_max=8, batch_wait_ms=30.0),
            engine, registry,
        )
        try:
            with ThreadPoolExecutor(max_workers=4) as pool:
                futs = {
                    qid: pool.submit(srv.handle_query, {"id": qid})
                    for qid in (1, 666, 2, 3)
                }
                for qid, fut in futs.items():
                    if qid == 666:
                        with pytest.raises(ValueError, match="poison"):
                            fut.result(timeout=30)
                    else:
                        _result, status = fut.result(timeout=30)
                        assert status == 200
        finally:
            srv.server_close()

    def test_concurrent_http_queries_aggregate(self, registry):
        from predictionio_tpu.workflow.serving import QueryServer, ServerConfig
        from test_query_server import _train, _typed_engine

        engine = _typed_engine()
        _train(registry, engine, algo_ids=(11,))
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=True,
                         batch_max=32, batch_wait_ms=150.0),
            engine, registry,
        )
        srv.start_background()
        base = f"http://127.0.0.1:{srv.bound_port}"
        try:
            with ThreadPoolExecutor(max_workers=16) as pool:
                futs = [
                    pool.submit(
                        requests.post, f"{base}/queries.json",
                        json={"id": i}, timeout=30,
                    )
                    for i in range(64)
                ]
                codes = [f.result().status_code for f in futs]
            assert codes == [200] * 64
            stats = srv._batcher.stats
            assert stats["submitted"] == 64
            # fewer dispatches than requests = aggregation happened. The
            # bound is deliberately loose (48, not 32): on a loaded 1-core
            # CI host the 16 client threads can trickle in slowly enough
            # that several batches close near-empty despite the 150 ms
            # linger — the test proves aggregation, not a batching ratio.
            assert stats["batches"] <= 48
        finally:
            srv.shutdown()
            srv.server_close()


class TestObsRecordedBeforeFanout:
    """Regression pin for the PR-8/9 e2e batch-span flake: metrics and
    spans for a batch were recorded in ``_execute``'s finally block,
    AFTER ``set_result`` unblocked the submitting thread — so a client
    (or a test) that answered and immediately read ``/traces.json``
    raced the recording. The fix records before the fan-out on both the
    success and failure paths; these tests make the old ordering fail
    deterministically instead of flakily."""

    def test_obs_complete_when_submit_returns(self, monkeypatch):
        from predictionio_tpu.obs.metrics import MetricsRegistry

        recorded = threading.Event()
        orig = MicroBatcher._record_obs

        def slow_record(self, *args, **kwargs):
            # widen the historical race window: under the OLD ordering
            # the submitter returns while this sleeps, turning a
            # sometimes-flake into a certain failure
            time.sleep(0.05)
            orig(self, *args, **kwargs)
            recorded.set()

        monkeypatch.setattr(MicroBatcher, "_record_obs", slow_record)
        metrics = MetricsRegistry()
        mb = MicroBatcher(
            lambda items: list(items), max_batch=1, max_wait_ms=0.0,
            metrics=metrics,
        )
        flush = metrics.counter(
            "pio_batch_flush_total", "Batch flushes by trigger",
            labelnames=("reason",),
        )
        try:
            for i in range(3):
                recorded.clear()
                assert mb.submit(i) == i
                # the moment submit() returns, this batch's obs must
                # already be on the registry — no drain, no sleep
                assert recorded.is_set()
            assert flush.value(reason="full") == 3  # max_batch=1 fills
        finally:
            mb.close()

    def test_failed_batch_also_records_before_fanout(self, monkeypatch):
        recorded = threading.Event()
        orig = MicroBatcher._record_obs

        def slow_record(self, *args, **kwargs):
            time.sleep(0.05)
            orig(self, *args, **kwargs)
            recorded.set()

        monkeypatch.setattr(MicroBatcher, "_record_obs", slow_record)

        def process(items):
            raise ValueError("device died")

        mb = MicroBatcher(process, max_batch=1, max_wait_ms=0.0)
        try:
            with pytest.raises(ValueError, match="device died"):
                mb.submit("x")
            assert recorded.is_set()
        finally:
            mb.close()


@pytest.fixture()
def registry(tmp_path):
    from predictionio_tpu.storage import StorageRegistry

    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
