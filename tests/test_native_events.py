"""Native (C++) event-log backend specifics.

The shared EventStore contract is covered by the parametrized fixture in
``test_storage_core.py``; here: durability across reopen, torn-tail crash
recovery, tombstone persistence, and scan-capacity growth — the behaviors the
reference delegates to HBase (WAL + region scans) and this backend owns.
"""

import os
import struct

import pytest

from predictionio_tpu.storage.data_map import DataMap
from predictionio_tpu.storage.event import Event
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.storage.native_events import NativeEventStore


def ts(i):
    import datetime as dt

    return dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(hours=i)


@pytest.fixture()
def root(tmp_path):
    return str(tmp_path / "evnative")


def test_persistence_across_reopen(root):
    s = NativeEventStore(root)
    eid = s.insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              properties=DataMap({"r": 1.5}), event_time=ts(0)),
        1,
    )
    s.close()

    s2 = NativeEventStore(root)
    got = s2.get(eid, 1)
    assert got is not None and got.properties.get_as("r", float) == 1.5
    assert len(list(s2.find(1))) == 1
    s2.close()


def test_tombstone_survives_reopen(root):
    s = NativeEventStore(root)
    eid = s.insert(Event(event="a", entity_type="t", entity_id="1"), 1)
    keep = s.insert(Event(event="b", entity_type="t", entity_id="2"), 1)
    assert s.delete(eid, 1)
    s.close()

    s2 = NativeEventStore(root)
    assert s2.get(eid, 1) is None
    assert s2.get(keep, 1) is not None
    assert [e.event for e in s2.find(1)] == ["b"]
    s2.close()


def test_torn_tail_truncated_on_reopen(root):
    s = NativeEventStore(root)
    for i in range(3):
        s.insert(Event(event="e", entity_type="t", entity_id=str(i),
                       event_time=ts(i)), 1)
    path = s._log_path(1)
    s.close()

    # simulate a crash mid-append: a half-written header at the tail
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 160, 0) + b"\x00" * 20)

    s2 = NativeEventStore(root)
    events = list(s2.find(1))
    assert len(events) == 3
    # the torn bytes are gone; a fresh insert lands on a valid boundary
    s2.insert(Event(event="new", entity_type="t", entity_id="9"), 1)
    assert len(list(s2.find(1))) == 4
    s2.close()


def test_scan_cap_growth(root):
    # more records than the initial 1024 scan capacity
    s = NativeEventStore(root)
    n = 1500
    events = [
        Event(event="rate", entity_type="u", entity_id=str(i % 7),
              event_time=ts(i % 50))
        for i in range(n)
    ]
    s.write(events, 1)
    assert len(list(s.find(1))) == n
    f = EventFilter(entity_type="u", entity_id="0")
    assert len(list(s.find(1, f))) == len([e for e in events if e.entity_id == "0"])
    s.close()


def test_time_ordering_and_reverse(root):
    s = NativeEventStore(root)
    # inserted out of time order — scan must sort by event time
    for i in [3, 0, 2, 1]:
        s.insert(Event(event=f"e{i}", entity_type="t", entity_id="x",
                       event_time=ts(i)), 1)
    assert [e.event for e in s.find(1)] == ["e0", "e1", "e2", "e3"]
    assert [e.event for e in s.find(1, EventFilter(reversed=True))] == [
        "e3", "e2", "e1", "e0"
    ]
    assert [e.event for e in s.find(1, EventFilter(reversed=True, limit=2))] == [
        "e3", "e2"
    ]
    s.close()


def test_reinsert_after_delete_is_live(root):
    # order-sensitive tombstones: an id re-inserted after a delete must be
    # visible to BOTH get() and find()
    s = NativeEventStore(root)
    e = Event(event="a", entity_type="t", entity_id="1", event_time=ts(0))
    eid = s.insert(e, 1)
    assert s.delete(eid, 1)
    import dataclasses

    s.insert(dataclasses.replace(e, event_id=eid), 1)
    assert s.get(eid, 1) is not None
    assert [ev.event_id for ev in s.find(1)] == [eid]
    s.close()


def test_explicit_id_upserts(root):
    # SQLite backend semantics: re-inserting with the same event_id replaces
    s = NativeEventStore(root)
    e1 = Event(event="a", entity_type="t", entity_id="1", event_time=ts(0),
               properties=DataMap({"v": 1}))
    eid = s.insert(e1, 1)
    import dataclasses

    e2 = dataclasses.replace(e1, properties=DataMap({"v": 2}), event_id=eid)
    assert s.insert(e2, 1) == eid
    found = list(s.find(1))
    assert len(found) == 1
    assert found[0].properties.get_as("v", int) == 2
    assert s.get(eid, 1).properties.get_as("v", int) == 2
    s.close()


def test_two_handles_same_log(root):
    # cross-handle visibility: a long-lived server handle must see records
    # appended through a second handle (the `pio import` coexistence case)
    s1 = NativeEventStore(root)
    s1.init(1)
    assert list(s1.find(1)) == []
    s2 = NativeEventStore(root)
    s2.insert(Event(event="imported", entity_type="t", entity_id="1",
                    event_time=ts(0)), 1)
    assert [e.event for e in s1.find(1)] == ["imported"]
    eid = s1.insert(Event(event="own", entity_type="t", entity_id="2",
                          event_time=ts(1)), 1)
    assert [e.event for e in s2.find(1)] == ["imported", "own"]
    assert s2.get(eid, 1) is not None
    s1.close()
    s2.close()


def test_scan_columnar_matches_sqlite_contract(root):
    s = NativeEventStore(root)
    for i in range(5):
        s.insert(
            Event(event="rate", entity_type="user", entity_id=f"u{i % 2}",
                  target_entity_type="item", target_entity_id=f"i{i}",
                  properties=DataMap({"rating": float(i)}), event_time=ts(i)),
            1,
        )
    cols = s.scan_columnar(1, EventFilter(event_names=["rate"]))
    assert cols["entity_id"] == ["u0", "u1", "u0", "u1", "u0"]
    assert [p["rating"] for p in cols["properties"]] == [0, 1, 2, 3, 4]
    assert cols["event_time_ms"].tolist() == [
        1577836800000 + i * 3600_000 for i in range(5)
    ]
    rev = s.scan_columnar(1, EventFilter(reversed=True, limit=2))
    assert rev["target_entity_id"] == ["i4", "i3"]
    s.close()


def test_registry_native_type(tmp_path):
    from predictionio_tpu.storage.registry import StorageRegistry

    env = {
        "PIO_STORAGE_SOURCES_N_TYPE": "native",
        "PIO_STORAGE_SOURCES_N_PATH": str(tmp_path),
    }
    reg = StorageRegistry(env)
    ev = reg.get_events()
    assert isinstance(ev, NativeEventStore)
    ev.init(1)
    eid = ev.insert(Event(event="x", entity_type="t", entity_id="1"), 1)
    assert ev.get(eid, 1) is not None
    assert os.path.isdir(str(tmp_path / "events_native"))


def test_concurrent_cross_process_appends(root):
    """Two OS processes hammer the same log concurrently: the advisory
    flock serialization (eventlog.cc append path) must keep every record
    intact — no torn/corrupt records, no lost appends."""
    import subprocess
    import sys
    import textwrap

    worker = textwrap.dedent(
        """
        import sys
        sys.path.insert(0, sys.argv[3])
        from predictionio_tpu.storage.native_events import NativeEventStore
        from predictionio_tpu.storage.event import Event, utcnow

        store = NativeEventStore(sys.argv[1])
        store.init(1)
        tag = sys.argv[2]
        for j in range(300):
            store.insert(
                Event(event="rate", entity_type="user",
                      entity_id=f"{tag}-u{j}",
                      target_entity_type="item", target_entity_id=f"i{j%7}",
                      properties={"rating": 1.0}, event_time=utcnow()),
                1,
            )
        store.close()
        print("DONE", tag)
        """
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker, str(root), f"p{k}", repo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for k in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err[-1500:]
        assert "DONE" in out

    from predictionio_tpu.storage.native_events import NativeEventStore

    store = NativeEventStore(str(root))
    events = list(store.find(1))
    ids = {e.entity_id for e in events}
    assert len(events) == 600
    assert sum(1 for i in ids if i.startswith("p0-")) == 300
    assert sum(1 for i in ids if i.startswith("p1-")) == 300
    store.close()
