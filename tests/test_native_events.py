"""Native (C++) event-log backend specifics.

The shared EventStore contract is covered by the parametrized fixture in
``test_storage_core.py``; here: durability across reopen, torn-tail crash
recovery, tombstone persistence, and scan-capacity growth — the behaviors the
reference delegates to HBase (WAL + region scans) and this backend owns.
"""

import os
import struct

import pytest

from predictionio_tpu.storage.data_map import DataMap
from predictionio_tpu.storage.event import Event
from predictionio_tpu.storage.events import EventFilter
from predictionio_tpu.storage.native_events import NativeEventStore


def ts(i):
    import datetime as dt

    return dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(hours=i)


@pytest.fixture()
def root(tmp_path):
    return str(tmp_path / "evnative")


def test_persistence_across_reopen(root):
    s = NativeEventStore(root)
    eid = s.insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              properties=DataMap({"r": 1.5}), event_time=ts(0)),
        1,
    )
    s.close()

    s2 = NativeEventStore(root)
    got = s2.get(eid, 1)
    assert got is not None and got.properties.get_as("r", float) == 1.5
    assert len(list(s2.find(1))) == 1
    s2.close()


def test_tombstone_survives_reopen(root):
    s = NativeEventStore(root)
    eid = s.insert(Event(event="a", entity_type="t", entity_id="1"), 1)
    keep = s.insert(Event(event="b", entity_type="t", entity_id="2"), 1)
    assert s.delete(eid, 1)
    s.close()

    s2 = NativeEventStore(root)
    assert s2.get(eid, 1) is None
    assert s2.get(keep, 1) is not None
    assert [e.event for e in s2.find(1)] == ["b"]
    s2.close()


def test_torn_tail_truncated_on_reopen(root):
    s = NativeEventStore(root)
    for i in range(3):
        s.insert(Event(event="e", entity_type="t", entity_id=str(i),
                       event_time=ts(i)), 1)
    path = s._log_path(1)
    s.close()

    # simulate a crash mid-append: a half-written header at the tail
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 160, 0) + b"\x00" * 20)

    s2 = NativeEventStore(root)
    events = list(s2.find(1))
    assert len(events) == 3
    # the torn bytes are gone; a fresh insert lands on a valid boundary
    s2.insert(Event(event="new", entity_type="t", entity_id="9"), 1)
    assert len(list(s2.find(1))) == 4
    s2.close()


def test_scan_cap_growth(root):
    # more records than the initial 1024 scan capacity
    s = NativeEventStore(root)
    n = 1500
    events = [
        Event(event="rate", entity_type="u", entity_id=str(i % 7),
              event_time=ts(i % 50))
        for i in range(n)
    ]
    s.write(events, 1)
    assert len(list(s.find(1))) == n
    f = EventFilter(entity_type="u", entity_id="0")
    assert len(list(s.find(1, f))) == len([e for e in events if e.entity_id == "0"])
    s.close()


def test_time_ordering_and_reverse(root):
    s = NativeEventStore(root)
    # inserted out of time order — scan must sort by event time
    for i in [3, 0, 2, 1]:
        s.insert(Event(event=f"e{i}", entity_type="t", entity_id="x",
                       event_time=ts(i)), 1)
    assert [e.event for e in s.find(1)] == ["e0", "e1", "e2", "e3"]
    assert [e.event for e in s.find(1, EventFilter(reversed=True))] == [
        "e3", "e2", "e1", "e0"
    ]
    assert [e.event for e in s.find(1, EventFilter(reversed=True, limit=2))] == [
        "e3", "e2"
    ]
    s.close()


def test_reinsert_after_delete_is_live(root):
    # order-sensitive tombstones: an id re-inserted after a delete must be
    # visible to BOTH get() and find()
    s = NativeEventStore(root)
    e = Event(event="a", entity_type="t", entity_id="1", event_time=ts(0))
    eid = s.insert(e, 1)
    assert s.delete(eid, 1)
    import dataclasses

    s.insert(dataclasses.replace(e, event_id=eid), 1)
    assert s.get(eid, 1) is not None
    assert [ev.event_id for ev in s.find(1)] == [eid]
    s.close()


def test_explicit_id_upserts(root):
    # SQLite backend semantics: re-inserting with the same event_id replaces
    s = NativeEventStore(root)
    e1 = Event(event="a", entity_type="t", entity_id="1", event_time=ts(0),
               properties=DataMap({"v": 1}))
    eid = s.insert(e1, 1)
    import dataclasses

    e2 = dataclasses.replace(e1, properties=DataMap({"v": 2}), event_id=eid)
    assert s.insert(e2, 1) == eid
    found = list(s.find(1))
    assert len(found) == 1
    assert found[0].properties.get_as("v", int) == 2
    assert s.get(eid, 1).properties.get_as("v", int) == 2
    s.close()


def test_two_handles_same_log(root):
    # cross-handle visibility: a long-lived server handle must see records
    # appended through a second handle (the `pio import` coexistence case)
    s1 = NativeEventStore(root)
    s1.init(1)
    assert list(s1.find(1)) == []
    s2 = NativeEventStore(root)
    s2.insert(Event(event="imported", entity_type="t", entity_id="1",
                    event_time=ts(0)), 1)
    assert [e.event for e in s1.find(1)] == ["imported"]
    eid = s1.insert(Event(event="own", entity_type="t", entity_id="2",
                          event_time=ts(1)), 1)
    assert [e.event for e in s2.find(1)] == ["imported", "own"]
    assert s2.get(eid, 1) is not None
    s1.close()
    s2.close()


def test_scan_columnar_matches_sqlite_contract(root):
    s = NativeEventStore(root)
    for i in range(5):
        s.insert(
            Event(event="rate", entity_type="user", entity_id=f"u{i % 2}",
                  target_entity_type="item", target_entity_id=f"i{i}",
                  properties=DataMap({"rating": float(i)}), event_time=ts(i)),
            1,
        )
    cols = s.scan_columnar(1, EventFilter(event_names=["rate"]))
    assert cols["entity_id"] == ["u0", "u1", "u0", "u1", "u0"]
    assert [p["rating"] for p in cols["properties"]] == [0, 1, 2, 3, 4]
    assert cols["event_time_ms"].tolist() == [
        1577836800000 + i * 3600_000 for i in range(5)
    ]
    rev = s.scan_columnar(1, EventFilter(reversed=True, limit=2))
    assert rev["target_entity_id"] == ["i4", "i3"]
    s.close()


def test_registry_native_type(tmp_path):
    from predictionio_tpu.storage.registry import StorageRegistry

    env = {
        "PIO_STORAGE_SOURCES_N_TYPE": "native",
        "PIO_STORAGE_SOURCES_N_PATH": str(tmp_path),
    }
    reg = StorageRegistry(env)
    ev = reg.get_events()
    assert isinstance(ev, NativeEventStore)
    ev.init(1)
    eid = ev.insert(Event(event="x", entity_type="t", entity_id="1"), 1)
    assert ev.get(eid, 1) is not None
    assert os.path.isdir(str(tmp_path / "events_native"))


def test_concurrent_cross_process_appends(root):
    """Two OS processes hammer the same log concurrently: the advisory
    flock serialization (eventlog.cc append path) must keep every record
    intact — no torn/corrupt records, no lost appends."""
    import subprocess
    import sys
    import textwrap

    worker = textwrap.dedent(
        """
        import sys
        sys.path.insert(0, sys.argv[3])
        from predictionio_tpu.storage.native_events import NativeEventStore
        from predictionio_tpu.storage.event import Event, utcnow

        store = NativeEventStore(sys.argv[1])
        store.init(1)
        tag = sys.argv[2]
        for j in range(300):
            store.insert(
                Event(event="rate", entity_type="user",
                      entity_id=f"{tag}-u{j}",
                      target_entity_type="item", target_entity_id=f"i{j%7}",
                      properties={"rating": 1.0}, event_time=utcnow()),
                1,
            )
        store.close()
        print("DONE", tag)
        """
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker, str(root), f"p{k}", repo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for k in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err[-1500:]
        assert "DONE" in out

    from predictionio_tpu.storage.native_events import NativeEventStore

    store = NativeEventStore(str(root))
    events = list(store.find(1))
    ids = {e.entity_id for e in events}
    assert len(events) == 600
    assert sum(1 for i in ids if i.startswith("p0-")) == 300
    assert sum(1 for i in ids if i.startswith("p1-")) == 300
    store.close()


# -- multi-writer segments ---------------------------------------------------
# N ingest processes each append to a private segment file (no flock
# contention); reads merge segments. Tombstones/upserts live only in the
# primary log, which makes cross-segment delete filtering exact.


def _ev(i, user="u", item="i", event="rate", val=None, event_id=None):
    return Event(
        event=event, entity_type="user", entity_id=f"{user}{i}",
        target_entity_type="item", target_entity_id=f"{item}{i % 7}",
        properties=DataMap({"rating": float(val if val is not None else i % 5 + 1)}),
        event_time=ts(i), event_id=event_id,
    )


class TestWriterSegments:
    def test_writers_append_to_private_segments(self, root):
        w1 = NativeEventStore(root, writer_id="w1")
        w2 = NativeEventStore(root, writer_id="w2")
        w1.init(1)
        w1.write([_ev(i) for i in range(0, 10)], 1)
        w2.write([_ev(i) for i in range(10, 20)], 1)
        app_dir = os.path.join(root, "app_1")
        names = sorted(os.listdir(app_dir))
        assert "events.w-w1.log" in names and "events.w-w2.log" in names
        w1.close()
        w2.close()

    def test_merged_find_sees_all_segments_in_time_order(self, root):
        w1 = NativeEventStore(root, writer_id="w1")
        w2 = NativeEventStore(root, writer_id="w2")
        reader = NativeEventStore(root)
        w1.init(1)
        w1.write([_ev(i) for i in range(0, 20, 2)], 1)   # even hours
        w2.write([_ev(i) for i in range(1, 20, 2)], 1)   # odd hours
        got = list(reader.find(1, EventFilter(event_names=["rate"])))
        assert len(got) == 20
        times = [e.event_time for e in got]
        assert times == sorted(times)  # merged across segments by time
        assert {e.entity_id for e in got} == {f"u{i}" for i in range(20)}
        for s in (w1, w2, reader):
            s.close()

    def test_single_event_insert_goes_to_segment(self, root):
        w = NativeEventStore(root, writer_id="ingest1")
        w.init(3)
        eid = w.insert(_ev(0), 3)  # fresh id -> private segment
        assert os.path.exists(os.path.join(root, "app_3", "events.w-ingest1.log"))
        # readable via merged get() from a plain reader
        reader = NativeEventStore(root)
        assert reader.get(eid, 3) is not None
        w.close()
        reader.close()

    def test_delete_kills_segment_record(self, root):
        w = NativeEventStore(root, writer_id="w1")
        reader = NativeEventStore(root)
        w.init(1)
        w.write([_ev(i) for i in range(5)], 1)
        victim = list(reader.find(1))[2]
        # delete through a store with NO writer id: tombstone -> primary
        assert reader.delete(victim.event_id, 1)
        assert reader.get(victim.event_id, 1) is None
        left = list(reader.find(1))
        assert len(left) == 4
        assert victim.event_id not in {e.event_id for e in left}
        # and through a WRITER store the tombstone also goes to primary
        victim2 = left[0]
        assert w.delete(victim2.event_id, 1)
        assert len(list(reader.find(1))) == 3
        for s in (w, reader):
            s.close()

    def test_upsert_replaces_segment_record(self, root):
        w = NativeEventStore(root, writer_id="w1")
        reader = NativeEventStore(root)
        w.init(1)
        w.write([_ev(i) for i in range(3)], 1)
        old = list(reader.find(1))[0]
        updated = _ev(0, val=9.0, event_id=old.event_id)
        # explicit-id insert (upsert) must route to the primary log
        w.insert(updated, 1)
        got = reader.get(old.event_id, 1)
        assert got is not None and got.properties["rating"] == 9.0
        # merged scans show exactly one record for the id
        matching = [
            e for e in reader.find(1) if e.event_id == old.event_id
        ]
        assert len(matching) == 1 and matching[0].properties["rating"] == 9.0
        for s in (w, reader):
            s.close()

    def test_columnar_scan_merges_segments(self, root):
        w1 = NativeEventStore(root, writer_id="w1")
        w2 = NativeEventStore(root, writer_id="w2")
        reader = NativeEventStore(root)
        w1.init(1)
        w1.write([_ev(i) for i in range(0, 50, 2)], 1)
        w2.write([_ev(i) for i in range(1, 50, 2)], 1)
        cols = reader.scan_columnar(1, EventFilter(event_names=["rate"]))
        assert len(cols["event"]) == 50
        t = cols["event_time_ms"]
        assert (t[1:] >= t[:-1]).all()
        for s in (w1, w2, reader):
            s.close()

    def test_ratings_scan_merges_segments(self, root):
        w1 = NativeEventStore(root, writer_id="w1")
        w2 = NativeEventStore(root, writer_id="w2")
        single = NativeEventStore(str(root) + "_single")
        for s in (w1, single):
            s.init(1)
        evs_a = [_ev(i) for i in range(0, 30, 2)]
        evs_b = [_ev(i) for i in range(1, 30, 2)]
        w1.write(evs_a, 1)
        w2.write(evs_b, 1)
        single.write(evs_a + evs_b, 1)
        reader = NativeEventStore(root)
        u, it, v, uids, iids = reader.scan_ratings(1, {"rate": "rating"})
        su, sit, sv, suids, siids = single.scan_ratings(1, {"rate": "rating"})
        # same triples regardless of segmentation (index labels may differ)
        def triples(us, its, vs, upool, ipool):
            return sorted(
                (upool[a], ipool[b], float(c))
                for a, b, c in zip(us.tolist(), its.tolist(), vs.tolist())
            )
        assert triples(u, it, v, uids, iids) == triples(su, sit, sv, suids, siids)
        for s in (w1, w2, reader, single):
            s.close()

    def test_ratings_scan_declines_segments_with_deletes(self, root):
        from predictionio_tpu.storage.native_events import NativeScanUnsupported

        w = NativeEventStore(root, writer_id="w1")
        reader = NativeEventStore(root)
        w.init(1)
        w.write([_ev(i) for i in range(6)], 1)
        victim = list(reader.find(1))[0]
        reader.delete(victim.event_id, 1)
        with pytest.raises(NativeScanUnsupported):
            reader.scan_ratings(1, {"rate": "rating"})
        # the generic path (stream_ratings fallback) stays exact
        from predictionio_tpu.workflow.infeed import stream_ratings

        batch = stream_ratings(reader, 1, {"rate": "rating"})
        assert len(batch.ratings) == 5
        for s in (w, reader):
            s.close()

    def test_bad_writer_id_rejected(self, root):
        with pytest.raises(ValueError, match="writer_id"):
            NativeEventStore(root, writer_id="../evil")

    def test_segment_torn_tail_truncated_on_reopen(self, root):
        w = NativeEventStore(root, writer_id="w1")
        w.init(1)
        w.write([_ev(i) for i in range(4)], 1)
        w.close()
        seg = os.path.join(root, "app_1", "events.w-w1.log")
        with open(seg, "ab") as f:
            f.write(b"\x55" * 13)  # torn partial record
        reader = NativeEventStore(root)
        assert len(list(reader.find(1))) == 4
        reader.close()
