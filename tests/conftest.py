"""Test fixtures.

Tests run on the CPU backend with 8 virtual devices — the analogue of the
reference's ``local[4]`` Spark test contexts
(``core/src/test/scala/io/prediction/workflow/BaseTest.scala``): multi-device
sharding semantics are exercised without TPU hardware. Env vars must be set
before the first ``import jax`` anywhere in the test process.
"""

import os
import sys

# Force CPU: the session environment pins JAX_PLATFORMS to the axon TPU
# tunnel (via sitecustomize), but tests must run on the 8-device virtual
# CPU mesh. The config.update overrides any platform the boot hook set.
os.environ["JAX_PLATFORMS"] = "cpu"
# No phone-home threads from the train/eval/deploy/build call sites under
# test; the version-check tests drive the mechanism directly.
os.environ["PIO_NO_UPGRADE_CHECK"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy compile/AOT/interpret-mode suites excluded from the "
        "tier-1 time budget (`-m 'not slow'`); run them explicitly with "
        "`pytest -m slow`",
    )


@pytest.fixture(params=["sqlite", "native", "remote"])
def event_store(request, tmp_path):
    """Every event-store test runs against the SQLite backend, the native
    (C++) append-only log backend, and the remote (HTTP server-mode)
    backend — the analogue of the reference running its EventsSpec against
    each configured storage source."""
    server = None
    if request.param == "sqlite":
        from predictionio_tpu.storage import SqliteEventStore

        store = SqliteEventStore(":memory:")
    elif request.param == "remote":
        from predictionio_tpu.storage import MetadataStore, SqliteEventStore
        from predictionio_tpu.storage.model_store import SqliteModelStore
        from predictionio_tpu.storage.remote import RemoteEventStore
        from predictionio_tpu.storage.storage_server import StorageServer

        server = StorageServer(
            "127.0.0.1",
            0,
            SqliteEventStore(":memory:"),
            MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
        )
        server.start_background()
        store = RemoteEventStore(f"http://127.0.0.1:{server.bound_port}")
    else:
        from predictionio_tpu.native import NativeBuildError

        try:
            from predictionio_tpu.storage.native_events import NativeEventStore

            store = NativeEventStore(str(tmp_path / "events_native"))
        except NativeBuildError as exc:  # toolchain-less host only
            pytest.skip(f"native event log unavailable: {exc}")
    store.init(1)
    yield store
    store.close()
    if server is not None:
        server.shutdown()
        server.server_close()


@pytest.fixture()
def metadata_store():
    from predictionio_tpu.storage import MetadataStore

    store = MetadataStore(":memory:")
    yield store
    store.close()
