"""Event Server REST tests.

Analogue of the reference's ``EventServiceSpec``
(``data/src/test/scala/io/prediction/data/api/EventServiceSpec.scala:31-42``)
but exercising the full HTTP surface over a live socket (the spray-testkit
route tests become requests against a ThreadingHTTPServer on an ephemeral
port), plus the stats bookkeeping of ``StatsActor``
(``EventAPI.scala:354-395``).
"""

import datetime as dt

import pytest
import requests

from predictionio_tpu.api import EventServer, EventServerConfig, StatsTracker
from predictionio_tpu.storage import (
    AccessKey,
    App,
    Event,
    MetadataStore,
    SqliteEventStore,
)


@pytest.fixture()
def server():
    events = SqliteEventStore(":memory:")
    metadata = MetadataStore(":memory:")
    app_id = metadata.app_insert(App(id=0, name="testapp"))
    metadata.access_key_insert(AccessKey(key="SECRET", appid=app_id, events=[]))
    events.init(app_id)
    srv = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, stats=True), events, metadata
    )
    srv.start_background()
    base = f"http://127.0.0.1:{srv.bound_port}"
    yield base, app_id
    srv.shutdown()
    srv.server_close()


def _event_payload(**overrides):
    payload = {
        "event": "rate",
        "entityType": "user",
        "entityId": "u1",
        "targetEntityType": "item",
        "targetEntityId": "i1",
        "properties": {"rating": 4.5},
        "eventTime": "2026-01-02T03:04:05.000Z",
    }
    payload.update(overrides)
    return payload


def test_root_alive(server):
    base, _ = server
    r = requests.get(f"{base}/")
    assert r.status_code == 200
    assert r.json() == {"status": "alive"}


def test_post_requires_access_key(server):
    base, _ = server
    r = requests.post(f"{base}/events.json", json=_event_payload())
    assert r.status_code == 401
    assert r.json() == {"message": "Invalid accessKey."}
    r = requests.post(
        f"{base}/events.json?accessKey=WRONG", json=_event_payload()
    )
    assert r.status_code == 401


def test_post_get_delete_roundtrip(server):
    base, _ = server
    r = requests.post(
        f"{base}/events.json?accessKey=SECRET", json=_event_payload()
    )
    assert r.status_code == 201
    event_id = r.json()["eventId"]
    assert event_id

    r = requests.get(f"{base}/events/{event_id}.json?accessKey=SECRET")
    assert r.status_code == 200
    body = r.json()
    assert body["event"] == "rate"
    assert body["entityId"] == "u1"
    assert body["targetEntityId"] == "i1"
    assert body["properties"]["rating"] == 4.5
    assert body["eventTime"].startswith("2026-01-02T03:04:05")

    r = requests.delete(f"{base}/events/{event_id}.json?accessKey=SECRET")
    assert r.status_code == 200
    assert r.json() == {"message": "Found"}

    r = requests.get(f"{base}/events/{event_id}.json?accessKey=SECRET")
    assert r.status_code == 404
    r = requests.delete(f"{base}/events/{event_id}.json?accessKey=SECRET")
    assert r.status_code == 404
    assert r.json() == {"message": "Not Found"}


def test_post_malformed_body_is_400(server):
    base, _ = server
    r = requests.post(
        f"{base}/events.json?accessKey=SECRET",
        data="{not json",
        headers={"Content-Type": "application/json"},
    )
    assert r.status_code == 400

    # validation failure: $set requires no targetEntity (Event.scala:70-99)
    r = requests.post(
        f"{base}/events.json?accessKey=SECRET",
        json=_event_payload(event="$set"),
    )
    assert r.status_code == 400


@pytest.mark.parametrize(
    "field,value",
    [
        ("targetEntityType", 0),
        ("targetEntityType", False),
        ("entityType", 5),
        ("event", None),
    ],
)
def test_non_string_type_fields_are_400_not_500(server, field, value):
    """Wrong-typed JSON for name/type fields must be a clean 400 — falsy
    or numeric values once slipped past validation and crashed deeper in
    the pipeline as a 500."""
    base, _ = server
    r = requests.post(
        f"{base}/events.json?accessKey=SECRET",
        json=_event_payload(**{field: value}),
    )
    assert r.status_code == 400, r.text
    assert "message" in r.json()


def test_find_with_filters(server):
    base, _ = server
    for i in range(5):
        requests.post(
            f"{base}/events.json?accessKey=SECRET",
            json=_event_payload(
                entityId=f"u{i % 2}",
                eventTime=f"2026-01-0{i + 1}T00:00:00.000Z",
            ),
        )

    r = requests.get(f"{base}/events.json?accessKey=SECRET")
    assert r.status_code == 200
    assert len(r.json()) == 5

    r = requests.get(f"{base}/events.json?accessKey=SECRET&entityId=u0")
    assert len(r.json()) == 3

    r = requests.get(
        f"{base}/events.json?accessKey=SECRET"
        "&startTime=2026-01-02T00:00:00.000Z&untilTime=2026-01-04T00:00:00.000Z"
    )
    assert len(r.json()) == 2

    r = requests.get(f"{base}/events.json?accessKey=SECRET&limit=2")
    assert len(r.json()) == 2

    # reversed=true returns latest first (LEvents.scala:139)
    r = requests.get(f"{base}/events.json?accessKey=SECRET&reversed=true")
    times = [e["eventTime"] for e in r.json()]
    assert times == sorted(times, reverse=True)

    r = requests.get(f"{base}/events.json?accessKey=SECRET&event=nonexistent")
    assert r.status_code == 404


def test_stats_counts_by_app(server):
    base, _ = server
    requests.post(f"{base}/events.json?accessKey=SECRET", json=_event_payload())
    requests.post(
        f"{base}/events.json?accessKey=SECRET",
        json=_event_payload(event="buy"),
    )
    # no-target event: snapshot sort must handle targetEntityType=None
    no_target = _event_payload(event="view")
    del no_target["targetEntityType"], no_target["targetEntityId"]
    requests.post(f"{base}/events.json?accessKey=SECRET", json=no_target)
    r = requests.get(f"{base}/stats.json?accessKey=SECRET")
    assert r.status_code == 200
    snap = r.json()
    assert set(snap) == {"time", "currentHour", "prevHour", "longLive"}
    long_live = snap["longLive"]
    events_counted = {
        kv["key"]["event"]: kv["value"] for kv in long_live["basic"]
    }
    assert events_counted == {"rate": 1, "buy": 1, "view": 1}
    assert long_live["statusCode"] == [{"key": 201, "value": 3}]


def test_stats_disabled_is_404():
    events = SqliteEventStore(":memory:")
    metadata = MetadataStore(":memory:")
    app_id = metadata.app_insert(App(id=0, name="nostats"))
    metadata.access_key_insert(AccessKey(key="K", appid=app_id, events=[]))
    events.init(app_id)
    srv = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, stats=False), events, metadata
    )
    srv.start_background()
    try:
        r = requests.get(
            f"http://127.0.0.1:{srv.bound_port}/stats.json?accessKey=K"
        )
        assert r.status_code == 404
        assert "stats" in r.json()["message"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_keepalive_survives_rejected_post(server):
    # A 401 sent before the body is read must not desync the next request
    # on the same persistent connection.
    base, _ = server
    with requests.Session() as s:
        r = s.post(f"{base}/events.json", json=_event_payload())
        assert r.status_code == 401
        r = s.post(f"{base}/events.json?accessKey=SECRET", json=_event_payload())
        assert r.status_code == 201


def test_stats_tracker_hour_rollover():
    tracker = StatsTracker()
    e = Event(event="rate", entity_type="user", entity_id="u1")
    tracker.bookkeeping(7, 201, e)
    snap = tracker.get(7)
    assert snap["currentHour"]["statusCode"] == [{"key": 201, "value": 1}]
    # force rollover by back-dating the hourly window
    tracker.hourly.start_time = tracker.hourly.start_time - dt.timedelta(hours=2)
    tracker.bookkeeping(7, 201, e)
    snap = tracker.get(7)
    assert snap["currentHour"]["statusCode"] == [{"key": 201, "value": 1}]
    assert snap["longLive"]["statusCode"] == [{"key": 201, "value": 2}]
    # other app sees nothing
    assert tracker.get(8)["longLive"]["basic"] == []


def test_batch_events_route(server):
    """POST /batches/events.json — bulk ingestion with per-event results
    (valid events succeed even when the batch contains invalid ones)."""
    base, app_id = server
    batch = [
        _event_payload(entityId=f"b{i}") for i in range(5)
    ] + [
        {"event": "", "entityType": "user", "entityId": "bad"},  # invalid
        _event_payload(entityId="b-last", eventId="client-chosen-id"),
    ]
    r = requests.post(f"{base}/batches/events.json?accessKey=SECRET", json=batch)
    assert r.status_code == 200
    results = r.json()
    assert len(results) == 7
    assert [x["status"] for x in results] == [201] * 5 + [400, 201]
    assert "message" in results[5]
    assert results[6]["eventId"] == "client-chosen-id"
    # every accepted event is durably findable
    found = requests.get(
        f"{base}/events.json?accessKey=SECRET&limit=-1"
    ).json()
    ids = {e["entityId"] for e in found}
    assert {f"b{i}" for i in range(5)} <= ids and "b-last" in ids
    assert "bad" not in ids
    # returned eventIds resolve via point GET
    eid = results[0]["eventId"]
    got = requests.get(f"{base}/events/{eid}.json?accessKey=SECRET")
    assert got.status_code == 200


def test_non_object_events_get_400_not_500(server):
    """A non-mapping body (or batch element) is a client error: the
    single route 400s with a clear message and a batch element only
    fails its own slot — never the whole batch via a 500."""
    base, _ = server
    r = requests.post(f"{base}/events.json?accessKey=SECRET", json=[5])
    assert r.status_code == 400
    assert "JSON object" in r.json()["message"]
    r = requests.post(
        f"{base}/batches/events.json?accessKey=SECRET",
        json=[5, _event_payload(entityId="after-bad")],
    )
    assert r.status_code == 200
    results = r.json()
    assert [x["status"] for x in results] == [400, 201]


def test_batch_events_rejects_non_array(server):
    base, _ = server
    r = requests.post(
        f"{base}/batches/events.json?accessKey=SECRET", json={"not": "array"}
    )
    assert r.status_code == 400


def test_batch_events_requires_auth(server):
    base, _ = server
    r = requests.post(
        f"{base}/batches/events.json?accessKey=WRONG", json=[_event_payload()]
    )
    assert r.status_code == 401
