"""Quality-observability plane (docs/observability.md#quality): sketch
golden tests vs numpy, PSI identity/shift, monitors on injected clocks,
the ``pio quality`` CLI exit-code contract, and the score-drift chaos
drill — the ISSUE 10 acceptance proof. Zero wall-clock sleeps in any
decision path; the one sleep in this file exists to *widen* a historical
race into a deterministic ordering assertion."""

import json
import math
import os

import numpy as np
import pytest

from predictionio_tpu.obs import expo
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.quality import (
    IngestQualityMonitor,
    QualityConfig,
    QualityMonitor,
    feedback_key,
    load_snapshots,
    scores_from_result,
    snapshot_psi,
)
from predictionio_tpu.obs.sketch import (
    QuantileSketch,
    categorical_psi,
    psi,
)
from predictionio_tpu.testing.clock import FakeClock

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "quality")


# ---------------------------------------------------------------------------
# sketch correctness (golden vs numpy)
# ---------------------------------------------------------------------------


class TestQuantileSketch:
    # The documented bound (obs/sketch.py): quantile() is within rel_err
    # RELATIVE error of the exact sample quantile for |v| > min_magnitude.
    # The assertions allow 2*rel_err: one rel_err for the bucket
    # representative, one for the discrete-rank walk vs numpy's linear
    # interpolation between order statistics. Fixed rng => deterministic.
    BOUND = 2 * 0.02

    def _assert_close(self, sketch, values, quantiles):
        for q in quantiles:
            exact = float(np.quantile(values, q))
            got = sketch.quantile(q)
            assert abs(got - exact) <= self.BOUND * abs(exact) + 1e-9, (
                f"q={q}: sketch {got} vs numpy {exact}"
            )

    def test_golden_quantiles_within_documented_bound(self):
        # one shared sweep over three distribution shapes (tier-1 budget:
        # one rng, no per-case fixtures)
        rng = np.random.default_rng(7)
        cases = [
            ("lognormal", rng.lognormal(0.0, 1.0, 4000)),
            ("uniform", rng.uniform(0.5, 100.0, 4000)),
            ("negated", -rng.lognormal(1.0, 0.5, 4000)),
        ]
        for _name, values in cases:
            s = QuantileSketch()
            s.extend(values.tolist())
            assert s.count == len(values)
            self._assert_close(s, values, (0.01, 0.1, 0.5, 0.9, 0.99))

    def test_mixed_sign_walk_order(self):
        # negative store walks descending index (most-negative first):
        # quantiles must be monotone across the sign boundary
        rng = np.random.default_rng(11)
        values = rng.normal(0.0, 10.0, 4000)
        s = QuantileSketch()
        s.extend(values.tolist())
        qs = [s.quantile(q) for q in (0.05, 0.25, 0.5, 0.75, 0.95)]
        assert qs == sorted(qs)
        # tails are far from zero: the relative bound applies there
        self._assert_close(s, values, (0.05, 0.95))

    def test_merge_is_lossless_bucket_addition(self):
        rng = np.random.default_rng(3)
        values = rng.lognormal(0.0, 1.0, 2000)
        whole = QuantileSketch()
        whole.extend(values.tolist())
        a, b = QuantileSketch(), QuantileSketch()
        a.extend(values[:700].tolist())
        b.extend(values[700:].tolist())
        merged = a.merge(b)
        assert merged.count == whole.count
        assert merged.sum == pytest.approx(whole.sum)
        for q in (0.1, 0.5, 0.9, 0.99):
            assert merged.quantile(q) == whole.quantile(q)

    def test_merge_rejects_mismatched_accuracy(self):
        with pytest.raises(ValueError, match="accuracy"):
            QuantileSketch(rel_err=0.02).merge(QuantileSketch(rel_err=0.05))
        a, b = QuantileSketch(rel_err=0.02), QuantileSketch(rel_err=0.05)
        a.add(1.0)
        b.add(1.0)
        with pytest.raises(ValueError, match="accuracy"):
            psi(a, b)  # (empty sketches abstain before the param check)

    def test_bounded_memory_keeps_the_tail_accurate(self):
        # 6 decades of magnitudes through a 16-bucket cap: memory stays
        # bounded and the HIGH-magnitude tail stays within the bound
        # (collapse folds low-magnitude buckets only)
        values = np.logspace(-3, 3, 5000)
        s = QuantileSketch(max_buckets=16)
        s.extend(values.tolist())
        assert len(s._pos) <= 16
        exact = float(np.quantile(values, 0.99))
        assert abs(s.quantile(0.99) - exact) <= self.BOUND * exact

    def test_nan_skipped_inf_clamped_zero_bucketed(self):
        s = QuantileSketch()
        s.extend([1.0, 2.0, float("nan"), 0.0, 1e-12, math.inf])
        assert s.count == 5  # NaN contributes nothing
        assert s.quantile(0.0) == 0.0  # zero bucket holds 0.0 and 1e-12
        assert s.quantile(1.0) >= 2.0 * (1 - 0.02)  # inf clamped, not lost

    def test_inf_into_empty_store_ranks_as_the_extreme(self):
        # review pin: an inf clamped into a FRESH store used to land in
        # bucket 0 (representative ~1.0) — the overflow score read as
        # the distribution's MINIMUM, skewing PSI the wrong way
        s = QuantileSketch()
        s.add(math.inf)
        s.extend([1000.0] * 9)
        assert s.quantile(0.05) == pytest.approx(1000.0, rel=0.05)
        assert s.quantile(1.0) > 1e300  # finite, huge, never overflows
        # review pin: the clamp covers sum/min/max too — one inf must
        # not poison mean() or write "Infinity" (non-RFC JSON) into the
        # durable snapshot line
        assert math.isfinite(s.sum) and math.isfinite(s.max)
        assert math.isfinite(s.mean())
        json.loads(json.dumps(s.to_dict(), allow_nan=False))
        # review pin: the RUNNING SUM saturates — several clamped
        # extremes (or near-max finites, clamped at intake too) must
        # not overflow sum to inf between them, nor across a merge
        s.extend([math.inf, math.inf, 1.7e308])
        other = QuantileSketch()
        other.extend([1.7e308, math.inf])
        s.merge(other)
        assert math.isfinite(s.sum) and math.isfinite(s.mean())
        json.loads(json.dumps(s.to_dict(), allow_nan=False))

    def test_near_max_finite_scores_never_overflow_reads(self):
        # review pin (confirmed by execution): a FINITE near-max-float
        # score used to land in a bucket whose representative value
        # raised OverflowError in quantile() — the intake clamp now
        # covers huge finite magnitudes, not just infinities
        s = QuantileSketch()
        s.extend([1.7976e308, 1.5e308, -1.7e308, 1000.0])
        for q in (0.0, 0.25, 0.5, 0.75, 1.0):
            assert math.isfinite(s.quantile(q))
        assert s.quantile(1.0) > 1e300
        assert s.quantile(0.0) < -1e300

    def test_serialization_roundtrip_preserves_quantiles(self):
        rng = np.random.default_rng(5)
        s = QuantileSketch()
        s.extend(rng.lognormal(0.0, 1.0, 1000).tolist())
        doc = json.loads(json.dumps(s.to_dict()))  # through real JSON
        back = QuantileSketch.from_dict(doc)
        assert back.count == s.count
        for q in (0.1, 0.5, 0.99):
            assert back.quantile(q) == s.quantile(q)
        assert psi(s, back) == pytest.approx(0.0, abs=1e-12)


class TestPSI:
    def test_identity_is_zero(self):
        s = QuantileSketch()
        s.extend(np.random.default_rng(2).lognormal(0, 1, 500).tolist())
        assert psi(s, s.copy()) == pytest.approx(0.0, abs=1e-12)

    def test_resampled_same_distribution_stays_stable(self):
        rng = np.random.default_rng(9)
        a, b = QuantileSketch(), QuantileSketch()
        a.extend(rng.lognormal(0.0, 1.0, 3000).tolist())
        b.extend(rng.lognormal(0.0, 1.0, 3000).tolist())
        assert psi(a, b) < 0.1  # conventional "stable" reading

    def test_coarsened_bins_keep_small_samples_stable(self):
        # the PSI_COARSEN rationale pinned: at the gate's sample floor a
        # same-distribution resample must read stable — over the raw 2%
        # buckets it reads past the 0.25 "real change" bar on epsilon
        # noise alone, which would make the rollout gate a coin flip
        rng = np.random.default_rng(9)
        values = rng.lognormal(0.0, 0.5, 640)
        small, big = QuantileSketch(), QuantileSketch()
        small.extend(values[:120].tolist())
        big.extend(values.tolist())
        assert psi(small, big) < 0.1
        assert psi(small, big, coarsen=1) > 0.25  # the noise floor it fixes

    def test_scale_shift_exceeds_the_drift_threshold(self):
        rng = np.random.default_rng(9)
        values = rng.lognormal(0.0, 1.0, 2000)
        a, b = QuantileSketch(), QuantileSketch()
        a.extend(values.tolist())
        b.extend((values * 4.0).tolist())  # the drill's skew shape
        assert psi(a, b) > 0.25

    def test_empty_side_abstains(self):
        s = QuantileSketch()
        s.add(1.0)
        assert psi(s, QuantileSketch()) is None
        assert psi(QuantileSketch(), s) is None

    def test_categorical_identity_shift_and_empty(self):
        mix = {"rate": 800, "buy": 150, "view": 50}
        assert categorical_psi(mix, dict(mix)) == pytest.approx(
            0.0, abs=1e-12
        )
        # scaled counts, same mix: still zero (PSI is over proportions)
        doubled = {k: 2 * v for k, v in mix.items()}
        assert categorical_psi(mix, doubled) == pytest.approx(
            0.0, abs=1e-12
        )
        skewed = {"rate": 50, "buy": 150, "view": 800}
        assert categorical_psi(mix, skewed) > 0.25
        assert categorical_psi({}, mix) is None
        assert categorical_psi(mix, {}) is None


# ---------------------------------------------------------------------------
# monitors (injected clocks)
# ---------------------------------------------------------------------------


def _scores(rng, n, scale=1.0):
    return (rng.lognormal(0.0, 0.5, n) * scale).tolist()


class TestQualityMonitor:
    def _monitor(self, tmp_path=None, **overrides):
        clock = FakeClock()
        cfg = QualityConfig(
            pin_min_samples=overrides.pop("pin_min_samples", 100),
            min_psi_samples=overrides.pop("min_psi_samples", 100),
            snapshot_path=(
                str(tmp_path / "quality.jsonl") if tmp_path else None
            ),
            **overrides,
        )
        registry = MetricsRegistry(clock=clock)
        return QualityMonitor(registry, clock=clock, config=cfg), (
            registry,
            clock,
        )

    def test_baseline_pins_then_reads_stable(self):
        monitor, (registry, _clock) = self._monitor()
        rng = np.random.default_rng(1)
        assert not monitor.pinned()
        assert monitor.score_psi("baseline") is None  # nothing to drift from
        monitor.record_scores("baseline", _scores(rng, 120))
        assert monitor.pinned()
        monitor.record_scores("baseline", _scores(rng, 400))
        value = monitor.score_psi("baseline")
        assert value is not None and value < 0.1
        # the gauge renders on /metrics with the variant label
        text = expo.render(registry)
        assert 'pio_quality_score_psi{variant="baseline"}' in text

    def test_candidate_drift_detected_and_floors_respected(self):
        monitor, _ = self._monitor()
        rng = np.random.default_rng(4)
        monitor.record_scores("baseline", _scores(rng, 300))
        monitor.record_scores("candidate", _scores(rng, 50, scale=4.0))
        # below min_psi_samples: abstain, never a coin-flip verdict
        assert monitor.score_psi("candidate") is None
        monitor.record_scores("candidate", _scores(rng, 100, scale=4.0))
        assert monitor.score_psi("candidate") > 0.25

    def test_window_rotation_ages_samples_out(self):
        monitor, (_registry, clock) = self._monitor(window_s=60.0)
        rng = np.random.default_rng(6)
        monitor.record_scores("baseline", _scores(rng, 300))
        assert monitor.summary()["samples"]["baseline"] == 300
        clock.advance(200.0)  # > 2 windows idle: both epochs stale
        assert monitor.summary()["samples"]["baseline"] == 0
        # the pin survives rotation — it is a snapshot, not a window
        assert monitor.pinned()

    def test_feedback_join_hit_miss_and_rank(self):
        monitor, (registry, _clock) = self._monitor()
        monitor.record_served("u1", ["i3", "i7", "i9"])
        assert monitor.record_feedback("u1", "i7") == 2  # 1-based rank
        assert monitor.record_feedback("u1", "i0") is None  # not served
        assert monitor.record_feedback("ghost", "i7") is None  # unknown user
        # the unknown user is UNJOINED, not a miss: historical-backlog
        # feedback (or an evicted user) must not dilute the hit-rate
        assert monitor.feedback_hit_rate() == pytest.approx(1 / 2)
        online = monitor.online_quality()
        assert online["feedbackSamples"] == 2
        assert online["meanServedRank"] == 2.0
        counter = registry.counter(
            "pio_quality_feedback_events_total",
            "Feedback events joined to served lists, by outcome",
            labelnames=("outcome",),
        )
        assert counter.value(outcome="hit") == 1
        assert counter.value(outcome="miss") == 1
        assert counter.value(outcome="unjoined") == 1

    def test_served_lru_is_bounded(self):
        monitor, _ = self._monitor(served_capacity=4)
        for i in range(10):
            monitor.record_served(f"u{i}", ["a"])
        assert len(monitor._served) == 4
        assert monitor.record_feedback("u0", "a") is None  # evicted
        assert monitor.record_feedback("u9", "a") == 1

    def test_reset_variant_drops_a_stale_candidate_window(self):
        # review pin: the rollout manager resets the candidate window at
        # every rollout START — without it, a rolled-back candidate's
        # skewed scores contaminate the NEXT candidate's PSI for up to
        # 2x window_s (spurious-rollback livelock)
        monitor, _ = self._monitor()
        rng = np.random.default_rng(12)
        monitor.record_scores("baseline", _scores(rng, 300))
        monitor.record_scores("candidate", _scores(rng, 200, scale=4.0))
        assert monitor.score_psi("candidate") > 0.25  # the OLD candidate
        monitor.reset_variant("candidate")
        assert monitor.summary()["samples"]["candidate"] == 0
        assert monitor.score_psi("candidate") is None  # abstains, fresh
        monitor.record_scores("candidate", _scores(rng, 200))  # healthy
        assert monitor.score_psi("candidate") < 0.25
        monitor.reset_variant("nonsense")  # unknown variant: no-op

    def test_model_live_repins_and_persists_snapshots(self, tmp_path):
        monitor, _ = self._monitor(tmp_path)
        rng = np.random.default_rng(8)
        monitor.record_scores("baseline", _scores(rng, 300))
        assert monitor.pinned()
        monitor.model_live("EI-42")
        assert not monitor.pinned()  # the NEW model's traffic must re-pin
        assert monitor.summary()["samples"]["baseline"] == 0
        snaps = load_snapshots(str(tmp_path / "quality.jsonl"))
        # auto-pin wrote one, model_live wrote the closing one
        assert [s["source"] for s in snaps] == [
            "baseline-pin", "model-live:EI-42",
        ]
        # the persisted sketch round-trips into a PSI comparison
        assert snapshot_psi(snaps[0], snaps[1]) == pytest.approx(
            0.0, abs=1e-6
        )

    def test_abstaining_monitor_reads_as_unknown_not_stable(
        self, monkeypatch
    ):
        # review pin: a fresh (or just-reloaded) monitor has no PSI to
        # report — the gauge exports the -1 sentinel and every scrape
        # consumer maps it back to unknown, so an operator never reads
        # "measured stable / zero hit-rate" off an abstaining window
        from predictionio_tpu.obs import top as top_mod
        from predictionio_tpu.tools.quality import node_report

        monitor, (registry, _clock) = self._monitor()
        text = expo.render(registry)
        assert 'pio_quality_score_psi{variant="baseline"} -1' in text
        assert "pio_quality_feedback_hit_rate -1" in text
        parsed = top_mod.parse_text(text)
        monkeypatch.setattr(
            top_mod, "fetch_metrics", lambda node, timeout=5.0: parsed
        )
        row = top_mod.node_row("fake:1")
        assert row["score_psi"] is None  # DRIFT renders "-"
        assert row["hit_rate"] is None  # HITRATE renders "-"
        report = node_report("fake:1")
        assert "scorePsi" not in report
        assert "hitRate" not in report.get("feedback", {})
        # an unjoined backlog (watcher replay before anyone was served)
        # is still not a measured 0.00 hit-rate — only hit/miss join
        monitor.record_feedback("nobody", "a")
        parsed = top_mod.parse_text(expo.render(registry))
        assert top_mod.node_row("fake:1")["hit_rate"] is None
        assert "hitRate" not in node_report("fake:1")["feedback"]
        # and once real data lands, the same consumers read the number
        rng = np.random.default_rng(5)
        monitor.record_scores("baseline", _scores(rng, 300))
        monitor.record_served("u1", ["a", "b"])
        monitor.record_feedback("u1", "a")
        parsed = top_mod.parse_text(expo.render(registry))
        row = top_mod.node_row("fake:1")
        assert row["score_psi"] is not None and row["score_psi"] >= 0
        assert row["hit_rate"] == 1.0
        assert node_report("fake:1")["scorePsi"]["baseline"] >= 0

    def test_snapshot_psi_abstains_on_corrupt_sketch_fields(self):
        # review pin: a torn/hand-edited snapshot whose sketch carries a
        # non-scalar numeric (TypeError at float(), not ValueError) must
        # abstain like any other unreadable sketch, so `pio quality
        # --diff` reports exit 2 (error) instead of crashing as exit 1
        rng = np.random.default_rng(3)
        sketch = QuantileSketch()
        sketch.extend(_scores(rng, 300))
        good = {"serving": {"baseline": sketch.to_dict()}}
        corrupt = {"serving": {"baseline": dict(sketch.to_dict())}}
        corrupt["serving"]["baseline"]["relErr"] = {}
        assert snapshot_psi(good, corrupt) is None
        assert snapshot_psi(corrupt, good) is None

    def test_snapshot_psi_applies_the_live_sample_floor(self):
        # review pin: `pio quality --diff` must apply the same
        # min-sample floor as every live PSI read — a model-live
        # closing snapshot written after a handful of queries is
        # sampling noise, not a CI drift verdict (exit 1)
        rng = np.random.default_rng(9)
        big, small = QuantileSketch(), QuantileSketch()
        big.extend(_scores(rng, 300))
        small.extend(_scores(rng, 10))
        pin = {"serving": {"baseline": big.to_dict()}}
        thin = {"serving": {"baseline": small.to_dict()}}
        assert snapshot_psi(pin, thin) is None
        assert snapshot_psi(thin, pin) is None
        assert snapshot_psi(pin, thin, min_samples=5) is not None

    def test_scores_from_result_shapes(self):
        items, scores = scores_from_result(
            {"itemScores": [
                {"item": "a", "score": 1.5},
                {"item": "b", "score": 2},
                {"item": "c", "score": "bad"},
            ]}
        )
        assert items == ["a", "b"] and scores == [1.5, 2.0]
        assert scores_from_result({"score": 0.7}) == ([None], [0.7])
        assert scores_from_result({"label": "spam"}) == ([], [])
        assert scores_from_result("not a dict") == ([], [])

    def test_feedback_key_field_preference(self):
        assert feedback_key({"user": "u1", "num": 5}) == "u1"
        assert feedback_key({"entityId": 7}) == "7"
        assert feedback_key("raw") == "raw"


class TestIngestQualityMonitor:
    class _Props:
        def __init__(self, d):
            self._d = d

        def to_dict(self):
            return self._d

    class _Event:
        def __init__(self, name, props=None):
            self.event = name
            self.properties = (
                TestIngestQualityMonitor._Props(props)
                if props is not None
                else None
            )

    def _monitor(self, baseline_dir=None, **overrides):
        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)
        cfg = QualityConfig(
            baseline_min_events=overrides.pop("baseline_min_events", 20),
            **overrides,
        )
        return (
            IngestQualityMonitor(
                registry, clock=clock, config=cfg,
                baseline_dir=baseline_dir,
            ),
            registry,
        )

    def test_violation_kinds_counted_not_rejected(self):
        monitor, registry = self._monitor()
        monitor.record_event(1, self._Event("rate", {"rating": 3.0}))  # ok
        monitor.record_event(1, self._Event("rate", {"rating": 42.0}))
        monitor.record_event(1, self._Event("rate", {}))  # no rating
        monitor.record_event(1, self._Event("rate", {"rating": True}))
        monitor.record_rejected(1)
        counter = registry.counter(
            "pio_quality_ingest_violations_total",
            "Ingest data-quality violations by app and kind "
            "(schema / range / poison)",
            labelnames=("app", "kind"),
        )
        assert counter.value(app="1", kind="range") == 1
        assert counter.value(app="1", kind="poison") == 2
        assert counter.value(app="1", kind="schema") == 1
        # every accepted event still counted (rejected ones are not)
        assert monitor.summary()["1"]["events"] == 4

    def test_mix_baseline_pins_then_flags_drift(self):
        monitor, registry = self._monitor()
        monitor.record_event(7, self._Event("view"))
        # review pin: below the pin floor the gauge exports the -1
        # abstention sentinel, never a measured-looking 0.0
        assert 'pio_quality_event_mix_psi{app="7"} -1' in expo.render(
            registry
        )
        for _ in range(14):
            monitor.record_event(7, self._Event("view"))
        for _ in range(5):
            monitor.record_event(7, self._Event("buy"))
        assert monitor.summary()["7"]["baselinePinned"]
        stable = monitor.mix_psi(7)
        assert stable is not None and stable < 0.1
        for _ in range(200):  # the mix rots: buys vanish, rates flood in
            monitor.record_event(7, self._Event("rate", {"rating": 1.0}))
        assert monitor.mix_psi(7) > 0.25
        assert 'pio_quality_event_mix_psi{app="7"}' in expo.render(registry)

    def test_baseline_survives_restart_via_durable_file(self, tmp_path):
        first, _ = self._monitor(baseline_dir=str(tmp_path))
        for _ in range(25):
            first.record_event(3, self._Event("view"))
        assert first.summary()["3"]["baselinePinned"]
        # a fresh monitor (restarted server) loads the pin from disk:
        # one event is enough to see drift vs the durable baseline
        second, _ = self._monitor(baseline_dir=str(tmp_path))
        second.record_event(3, self._Event("buy"))
        assert second.summary()["3"]["baselinePinned"]
        assert second.mix_psi(3) > 0.25


# ---------------------------------------------------------------------------
# `pio quality` CLI — exit-code contract + report rendering
# ---------------------------------------------------------------------------


class TestQualityCLI:
    STABLE = os.path.join(FIXTURES, "snapshots_stable.jsonl")
    DRIFT = os.path.join(FIXTURES, "snapshots_drift.jsonl")

    def _main(self, *argv):
        from predictionio_tpu.tools import quality as quality_mod

        return quality_mod.main(list(argv))

    def test_diff_exit_codes_pinned_0_1_2(self, tmp_path, capsys):
        # the satellite contract: 0 stable / 1 drift / 2 engine error,
        # self-tested against the checked-in snapshot pair
        assert self._main("--diff", "--snapshots", self.STABLE) == 0
        assert self._main("--diff", "--snapshots", self.DRIFT) == 1
        assert (
            self._main("--diff", "--snapshots", str(tmp_path / "none.jsonl"))
            == 2
        )
        single = tmp_path / "single.jsonl"
        with open(self.STABLE) as fh:
            single.write_text(fh.readline())
        assert self._main("--diff", "--snapshots", str(single)) == 2
        out = capsys.readouterr()
        assert "DRIFT" in out.out and "error" in out.err

    def test_diff_against_baseline_file_and_json(self, capsys):
        assert (
            self._main(
                "--diff", "--snapshots", self.DRIFT,
                "--baseline", self.STABLE, "--json",
            )
            == 1
        )
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["drift"] is True
        assert verdict["psi"]["baseline"] > 0.25

    def test_raised_bar_turns_drift_into_ok(self):
        assert (
            self._main(
                "--diff", "--snapshots", self.DRIFT, "--max-psi", "1e6"
            )
            == 0
        )

    def test_diff_honors_the_snapshots_recorded_sample_floor(
        self, tmp_path
    ):
        # review pin: a deployment configured below the default floor
        # records minPsiSamples in its snapshots; --diff must judge at
        # THAT bar (not hard-coded 50), and --min-samples overrides
        rng = np.random.default_rng(11)
        path = tmp_path / "thin.jsonl"
        s = QuantileSketch()
        s.extend(_scores(rng, 20))
        with open(path, "w") as fh:
            for _ in range(2):  # identical 20-sample sketches: psi ~ 0
                fh.write(json.dumps({
                    "kind": "quality", "source": "t",
                    "minPsiSamples": 10,
                    "serving": {"baseline": s.to_dict()},
                }) + "\n")
        assert self._main("--diff", "--snapshots", str(path)) == 0
        # overriding above the sketch size abstains both variants -> 2
        assert (
            self._main(
                "--diff", "--snapshots", str(path), "--min-samples", "50"
            )
            == 2
        )

    def test_snapshot_report_renders(self, capsys):
        assert self._main("--snapshots", self.STABLE) == 0
        out = capsys.readouterr().out
        assert "baseline" in out and "hits=" in out

    def test_console_forwards_verbatim(self, capsys):
        from predictionio_tpu.tools.console import main as console_main

        assert (
            console_main(["quality", "--diff", "--snapshots", self.STABLE])
            == 0
        )
        assert "ok baseline" in capsys.readouterr().out

    def test_no_source_is_an_error(self, monkeypatch, capsys):
        monkeypatch.delenv("PIO_QUALITY_SNAPSHOTS", raising=False)
        assert self._main() == 2
        assert "nothing to report" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# perf-ledger integration (bench's quality block → trend records)
# ---------------------------------------------------------------------------


class TestPerfLedgerIntegration:
    def test_quality_block_becomes_trend_records(self):
        from predictionio_tpu.obs import perfledger

        bench = {
            "metric": "als_train_s", "value": 2.0, "device": "cpu",
            "quality": {
                "ok": True, "scorePsi": 0.07,
                "feedbackHitRate": 0.55, "feedbackSamples": 20,
            },
        }
        records = {
            r["metric"]: r for r in perfledger.quality_records(bench)
        }
        assert records["quality_score_psi"]["value"] == 0.07
        assert records["quality_score_psi"]["unit"] == "psi"  # trend-only:
        # the ledger's regression gate compares unit "s" records only
        assert records["quality_feedback_hitrate"]["unit"] == "ratio"
        assert records["quality_feedback_hitrate"]["extra"]["samples"] == 20
        # a failed drill records nothing — no trend point beats a lie
        assert perfledger.quality_records(
            {"quality": {"ok": False, "scorePsi": 9.0}}
        ) == []
        # the headline bench record carries the block through `extra`
        rec = perfledger.bench_to_record(bench)
        assert rec["extra"]["quality"]["scorePsi"] == 0.07


# ---------------------------------------------------------------------------
# dashboard /quality panel
# ---------------------------------------------------------------------------


class TestDashboardQualityPanel:
    def test_quality_routes_render_with_fleet_down(self, tmp_path):
        # connection-refused nodes resolve instantly (no timeout wait):
        # the panel must render DOWN rows, never error
        import urllib.request

        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.tools.dashboard import (
            DashboardConfig,
            create_dashboard,
        )

        registry = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})
        server = create_dashboard(
            DashboardConfig(port=0, nodes="127.0.0.1:9", scrape_timeout_s=0.5),
            registry, block=False,
        )
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            with urllib.request.urlopen(f"{base}/quality.json", timeout=10) as r:
                rows = json.loads(r.read())
            assert rows == [{"node": "127.0.0.1:9", "up": False}]
            with urllib.request.urlopen(f"{base}/quality", timeout=10) as r:
                page = r.read().decode()
            assert "DOWN" in page and "Quality" in page
        finally:
            server.stop_async()
            server.server_close()

    def test_render_quality_live_rows(self):
        from predictionio_tpu.tools.dashboard import render_quality

        page = render_quality([
            {
                "node": "q1:8000", "up": True,
                "scorePsi": {"baseline": 0.02, "candidate": 0.41},
                "feedback": {"hitRate": 0.55},
                "ingest": {"1": {"mixPsi": 0.01, "violations": {"range": 2}}},
            },
        ])
        assert "0.4100" in page and "0.550" in page and "1:0.0100" in page


# ---------------------------------------------------------------------------
# the acceptance drill: score-skewed candidate auto-rolled-back by PSI
# ---------------------------------------------------------------------------


class TestScoreDriftDrill:
    def test_psi_gate_rolls_back_skewed_candidate(self, capsys):
        """ISSUE 10 acceptance: a candidate whose scores are a pure
        distribution shift (well-formed, fast, error-free — invisible to
        every pre-existing gate) is auto-rolled-back by max_score_psi
        with zero client failures, a durable ROLLED_BACK plan, and
        restart quarantine; `pio quality` renders the drift from a live
        /metrics scrape while the server is still up."""
        from predictionio_tpu.tools import quality as quality_mod
        from predictionio_tpu.tools.loadgen import run_score_drift

        live: dict = {}

        def scrape(server):
            node = f"127.0.0.1:{server.bound_port}"
            live["report"] = quality_mod.node_report(node)
            live["exit"] = quality_mod.main(["--node", node])

        report = run_score_drift(on_live=scrape)
        assert report["ok"], report
        assert report["clientFailures"] == 0
        assert report["rolledBack"] and report["durableStage"] == "ROLLED_BACK"
        assert report["postRollbackCandidateServed"] == 0
        assert report["quarantined"]
        assert report["candidatePsi"] > 0.25
        assert "score PSI" in report["rollbackReason"]
        # the live scrape saw the same drift the gate acted on
        scraped = live["report"]
        assert scraped["scorePsi"]["candidate"] > 0.25
        assert scraped["scorePsi"]["baseline"] < 0.1
        assert scraped["scoreSamples"]["candidate"] > 0
        assert live["exit"] == 0
        rendered = capsys.readouterr().out
        assert "candidate" in rendered and "psi=" in rendered
