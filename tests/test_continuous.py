"""Continuous-learning plane tests (``predictionio_tpu/continuous``,
docs/continuous.md).

Covers the ISSUE-7 acceptance contract on injected clocks with zero
wall-clock sleeps on any decision path:

- fold-in math: a held-out slice of users folds back in to within the
  documented tolerance of a full retrain (RMSE ratio <= 1.25), untouched
  rows stay byte-identical, zero delta is a no-op;
- escalation policy: delta fraction / new-entity fraction / RMSE drift
  all force a full retrain;
- the feed watcher: changefeed filtering, durable-cursor resume,
  FeedGap on sequence gaps and generation changes, resync;
- the controller state machine end to end on the cheap sample engine:
  delta -> candidate -> auto-submit -> monitor -> LIVE commit, rollout
  busy backoff, gate-rollback quarantine + forced full retrain, offline
  scoring quarantine, pause/trigger, the /continuous HTTP surface and
  the `pio continuous` CLI;
- the ALS closed loop: feedback events posted to the event server
  produce an auto-promoted live model with no manual step and zero
  client-visible failures (the loadgen --feedback-stream scenario), and
  a restart mid-cycle resumes the persisted cursor AND the in-flight
  rollout instead of replaying either.
"""

import json
import os

import numpy as np
import pytest

from predictionio_tpu.continuous.controller import (
    ContinuousConfig,
    ContinuousController,
)
from predictionio_tpu.continuous.foldin import (
    FOLD_IN,
    FULL_RETRAIN,
    FoldInPolicy,
    decide_mode,
    fold_in_factors,
    seeded_rows,
)
from predictionio_tpu.continuous.watcher import (
    FeedGap,
    FeedWatcher,
    LocalFeed,
)
from predictionio_tpu.controller import WorkflowParams
from predictionio_tpu.storage import DataMap, Event, StorageRegistry
from predictionio_tpu.storage.changefeed import Changefeed
from predictionio_tpu.storage.metadata import (
    ROLLOUT_CANARY,
    ROLLOUT_LIVE,
    ROLLOUT_ROLLED_BACK,
    ROLLOUT_SHADOW,
)
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.testing import faults
from predictionio_tpu.workflow.core_workflow import run_train
from predictionio_tpu.workflow.serving import QueryServer, ServerConfig

from predictionio_tpu.testing.clock import FakeClock

from sample_engine import reset_all_counts
from test_engine import make_engine, make_params


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()
    faults.deactivate()
    yield
    faults.deactivate()


@pytest.fixture()
def registry(tmp_path):
    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})


# ---------------------------------------------------------------------------
# escalation policy (pure)
# ---------------------------------------------------------------------------


class TestDecideMode:
    def test_within_policy_folds(self):
        mode, reason = decide_mode(
            FoldInPolicy(),
            total_events=1000, delta_events=50,
            known_entities=200, new_entities=10,
        )
        assert mode == FOLD_IN
        assert "within fold-in policy" in reason

    def test_delta_fraction_escalates(self):
        mode, reason = decide_mode(
            FoldInPolicy(max_delta_fraction=0.1),
            total_events=100, delta_events=50,
            known_entities=200, new_entities=0,
        )
        assert mode == FULL_RETRAIN
        assert "delta fraction" in reason

    def test_new_entity_fraction_escalates(self):
        mode, reason = decide_mode(
            FoldInPolicy(max_new_entity_fraction=0.1),
            total_events=1000, delta_events=10,
            known_entities=100, new_entities=50,
        )
        assert mode == FULL_RETRAIN
        assert "new-entity fraction" in reason

    def test_unavailable_or_empty_baseline_escalates(self):
        assert decide_mode(
            FoldInPolicy(), total_events=10, delta_events=1,
            known_entities=10, new_entities=0, fold_in_available=False,
        )[0] == FULL_RETRAIN
        assert decide_mode(
            FoldInPolicy(), total_events=0, delta_events=1,
            known_entities=0, new_entities=1,
        )[0] == FULL_RETRAIN


# ---------------------------------------------------------------------------
# fold-in math
# ---------------------------------------------------------------------------


def _synth_matrix(seed=0, n_u=60, n_i=40, rank=6, nnz=3000):
    rng = np.random.default_rng(seed)
    gu = rng.normal(size=(n_u, rank)).astype(np.float32)
    gi = rng.normal(size=(n_i, rank)).astype(np.float32)
    u = rng.integers(0, n_u, nnz).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = (gu[u] * gi[i]).sum(-1).astype(np.float32)
    return u, i, v, n_u, n_i, rank


class TestFoldInMath:
    #: documented tolerance (docs/continuous.md#fold-in): fold-in RMSE on
    #: the full matrix stays within 1.25x the full-retrain RMSE
    RMSE_RATIO = 1.25

    def test_heldout_users_converge_to_full_retrain(self):
        from predictionio_tpu.ops.als import (
            ALSConfig, ALSFactors, als_train_coo, rmse,
        )

        u, i, v, n_u, n_i, rank = _synth_matrix()
        held = u >= n_u - 10  # every rating of the last 10 users
        cfg = ALSConfig(rank=rank, iterations=8, lambda_=0.05, seed=0)
        base = als_train_coo(u[~held], i[~held], v[~held], n_u - 10, n_i, cfg)
        full = als_train_coo(u, i, v, n_u, n_i, cfg)

        uf = np.concatenate([
            np.asarray(base.user_factors),
            seeded_rows(10, rank, 0, offset=n_u - 10),
        ])
        itf = np.asarray(base.item_factors)
        changed_u = list(range(n_u - 10, n_u))
        changed_i = sorted(set(i[held].tolist()))
        uf2, itf2, counts = fold_in_factors(
            uf, itf, u, i, v, changed_u, changed_i, lambda_=0.05,
            policy=FoldInPolicy(fold_iterations=2),
        )
        assert counts["solved_users"] == 10
        r_full = rmse(full, u, i, v)
        r_fold = rmse(ALSFactors(uf2, itf2, rank), u, i, v)
        assert r_fold <= r_full * self.RMSE_RATIO + 0.05
        # untouched user rows are BYTE-identical (the no-op guarantee
        # that makes fold-in an incremental step, not a retrain)
        untouched = np.setdiff1d(np.arange(n_u - 10), changed_u)
        np.testing.assert_array_equal(uf2[untouched], uf[untouched])

    def test_zero_delta_is_identity(self):
        u, i, v, n_u, n_i, rank = _synth_matrix(seed=3, nnz=800)
        uf = np.random.default_rng(1).normal(size=(n_u, rank)).astype(np.float32)
        itf = np.random.default_rng(2).normal(size=(n_i, rank)).astype(np.float32)
        uf2, itf2, counts = fold_in_factors(
            uf, itf, u, i, v, [], [], lambda_=0.05,
        )
        np.testing.assert_array_equal(uf2, uf)
        np.testing.assert_array_equal(itf2, itf)
        assert counts == {"solved_users": 0, "solved_items": 0}

    def test_als_algorithm_fold_in_zero_events_identical_factors(self):
        """ALSAlgorithm.fold_in with an empty changed set returns a model
        whose factors are identical (same maps, same rows)."""
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm, ALSAlgorithmParams, ALSModel, PreparedData,
        )
        from predictionio_tpu.storage import BiMap

        u, i, v, n_u, n_i, rank = _synth_matrix(seed=5, n_u=12, n_i=8, nnz=200)
        user_map = BiMap({f"u{k}": k for k in range(n_u)})
        item_map = BiMap({f"i{k}": k for k in range(n_i)})
        rng = np.random.default_rng(0)
        model = ALSModel(
            rank=rank,
            user_factors=rng.normal(size=(n_u, rank)).astype(np.float32),
            item_factors=rng.normal(size=(n_i, rank)).astype(np.float32),
            user_map=user_map,
            item_map=item_map,
        )
        pd = PreparedData(
            user_map=user_map, item_map=item_map, users=u, items=i, ratings=v
        )
        algo = ALSAlgorithm(ALSAlgorithmParams(rank=rank))
        folded, stats = algo.fold_in(None, model, pd, [], [])
        np.testing.assert_array_equal(folded.user_factors, model.user_factors)
        np.testing.assert_array_equal(folded.item_factors, model.item_factors)
        assert folded.user_map == model.user_map
        assert stats.new_users == 0 and stats.new_items == 0

    def test_implicit_prefs_model_cannot_fold_in(self):
        """Fold-in solves the EXPLICIT normal equations; an
        implicit-prefs ALS must refuse (the controller then escalates to
        a full retrain instead of folding with the wrong objective)."""
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm, ALSAlgorithmParams, ALSModel, PreparedData,
        )
        from predictionio_tpu.storage import BiMap

        implicit = ALSAlgorithm(ALSAlgorithmParams(implicit_prefs=True))
        assert implicit.fold_in_supported is False
        assert ALSAlgorithm(ALSAlgorithmParams()).fold_in_supported is True
        rank = 4
        m = BiMap({"u0": 0})
        model = ALSModel(
            rank=rank,
            user_factors=np.zeros((1, rank), dtype=np.float32),
            item_factors=np.zeros((1, rank), dtype=np.float32),
            user_map=m, item_map=BiMap({"i0": 0}),
        )
        pd = PreparedData(
            user_map=model.user_map, item_map=model.item_map,
            users=np.array([0], dtype=np.int32),
            items=np.array([0], dtype=np.int32),
            ratings=np.array([1.0], dtype=np.float32),
        )
        with pytest.raises(ValueError, match="implicit"):
            implicit.fold_in(None, model, pd, [], [])

    def test_fold_in_new_entities_extend_maps_stably(self):
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm, ALSAlgorithmParams, ALSModel, PreparedData,
        )
        from predictionio_tpu.storage import BiMap

        rank = 4
        base_users = {f"u{k}": k for k in range(5)}
        base_items = {f"i{k}": k for k in range(4)}
        rng = np.random.default_rng(0)
        model = ALSModel(
            rank=rank,
            user_factors=rng.normal(size=(5, rank)).astype(np.float32),
            item_factors=rng.normal(size=(4, rank)).astype(np.float32),
            user_map=BiMap(base_users),
            item_map=BiMap(base_items),
        )
        # fresh data read whose maps arrived in a DIFFERENT order and
        # include one new user
        pd_users = {"u3": 0, "u0": 1, "u9": 2}
        pd_items = {"i1": 0, "i0": 1}
        pd = PreparedData(
            user_map=BiMap(pd_users),
            item_map=BiMap(pd_items),
            users=np.array([0, 1, 2, 2], dtype=np.int32),
            items=np.array([0, 1, 0, 1], dtype=np.int32),
            ratings=np.array([5, 4, 3, 2], dtype=np.float32),
        )
        algo = ALSAlgorithm(ALSAlgorithmParams(rank=rank))
        folded, stats = algo.fold_in(None, model, pd, ["u9"], [])
        # existing ids keep their indices; the new user appended at the end
        assert folded.user_map["u0"] == 0 and folded.user_map["u3"] == 3
        assert folded.user_map["u9"] == 5
        assert stats.new_users == 1
        # untouched rows byte-identical
        np.testing.assert_array_equal(
            folded.user_factors[:5][[0, 1, 2, 4]],
            model.user_factors[[0, 1, 2, 4]],
        )
        # the new user's row was actually solved (not left at its seed)
        assert not np.array_equal(
            folded.user_factors[5], seeded_rows(1, rank, algo.params.seed, 5)[0]
        )


# ---------------------------------------------------------------------------
# feed watcher
# ---------------------------------------------------------------------------


def _rate(user, item, rating, name="rate"):
    return Event(
        event=name, entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties=DataMap({"rating": rating} if name == "rate" else {}),
    )


class TestFeedWatcher:
    def _feed(self, registry, tmp_path):
        cf = Changefeed(
            OpLog(str(tmp_path / "oplog")),
            registry.get_events(), registry.get_metadata(),
            registry.get_models(),
        )
        registry.get_events().init(1)
        registry.get_events().init(2)
        return cf, LocalFeed(cf.oplog)

    def test_filters_app_and_event_names(self, registry, tmp_path):
        cf, feed = self._feed(registry, tmp_path)
        w = FeedWatcher(
            feed, 1, {"rate": "rating", "buy": 4.0}, str(tmp_path / "st")
        )
        cf.insert_event(_rate("u1", "i1", 5.0), 1)
        cf.insert_event(_rate("u2", "i2", 3.0), 2)  # other app
        cf.insert_event(_rate("u3", "i3", 0, name="view"), 1)  # unwatched
        cf.insert_event(_rate("u4", "i4", 0, name="buy"), 1)  # fixed value
        cf.write_events([_rate("u5", "i5", 2.0)], 1, fresh=True)
        assert w.poll() == 3
        batch = w.take_batch()
        assert [(e.user, e.item, e.value) for e in batch.events] == [
            ("u1", "i1", 5.0), ("u4", "i4", 4.0), ("u5", "i5", 2.0),
        ]
        assert w.feed_lag() == 0
        assert batch.upto_seq == cf.last_seq

    def test_commit_is_durable_and_restart_resumes_exact(
        self, registry, tmp_path
    ):
        cf, feed = self._feed(registry, tmp_path)
        state = str(tmp_path / "st")
        w = FeedWatcher(feed, 1, {"rate": "rating"}, state)
        cf.insert_event(_rate("u1", "i1", 5.0), 1)
        cf.insert_event(_rate("u2", "i1", 4.0), 1)
        w.poll()
        batch = w.take_batch()
        assert len(batch.events) == 2
        # crash BEFORE commit: a new watcher re-reads the whole suffix
        w2 = FeedWatcher(feed, 1, {"rate": "rating"}, state)
        assert w2.cursor_seq == 0
        assert w2.poll() == 2
        # commit, then restart: the suffix is consumed exactly once
        w2.commit(batch.upto_seq)
        assert w2.pending_count() == 0
        w3 = FeedWatcher(feed, 1, {"rate": "rating"}, state)
        assert w3.cursor_seq == batch.upto_seq
        assert w3.poll() == 0
        cf.insert_event(_rate("u3", "i2", 1.0), 1)
        assert w3.poll() == 1  # only the new event, never a replay

    def test_poison_event_skipped_not_fatal(self, registry, tmp_path):
        cf, feed = self._feed(registry, tmp_path)
        w = FeedWatcher(feed, 1, {"rate": "rating"}, str(tmp_path / "st"))
        cf.insert_event(_rate("u1", "i1", 5.0), 1)
        cf.insert_event(  # "rate" without the required rating property
            Event(event="rate", entity_type="user", entity_id="u2",
                  target_entity_type="item", target_entity_id="i2",
                  properties=DataMap({})), 1,
        )
        assert w.poll() == 1
        assert w.skipped_events == 1

    def test_sequence_gap_raises_feedgap_and_resync_recovers(
        self, registry, tmp_path
    ):
        # a log that starts at base_seq 5 cannot serve a cursor at 0
        oplog = OpLog(str(tmp_path / "oplog"), base_seq=5)
        feed = LocalFeed(oplog)
        w = FeedWatcher(feed, 1, {"rate": "rating"}, str(tmp_path / "st"))
        with pytest.raises(FeedGap):
            w.poll()
        w.resync()
        assert w.cursor_seq == 5
        assert w.poll() == 0  # tailing works again from the head

    def test_generation_change_raises_feedgap(self, registry, tmp_path):
        cf, feed = self._feed(registry, tmp_path)
        w = FeedWatcher(feed, 1, {"rate": "rating"}, str(tmp_path / "st"))
        cf.insert_event(_rate("u1", "i1", 5.0), 1)
        assert w.poll() == 1
        # the primary store is wiped and replaced: fresh oplog, new
        # generation, same URL
        feed2 = LocalFeed(OpLog(str(tmp_path / "oplog2")))
        w._feed = feed2
        with pytest.raises(FeedGap, match="generation"):
            w.poll()


# ---------------------------------------------------------------------------
# controller state machine (sample engine: no device math, ms-cheap)
# ---------------------------------------------------------------------------


def _gates(**overrides):
    g = {
        "min_samples": 5,
        "window_s": 100_000.0,
        "shadow_hold_s": 10.0,
        "canary_hold_s": 10.0,
        "max_divergence": 1.0,
        "max_p99_latency_ratio": 1_000.0,
    }
    g.update(overrides)
    return g


class _Loop:
    """One assembled continuous loop over the sample engine."""

    def __init__(self, registry, tmp_path, **cfg_kw):
        self.registry = registry
        self.engine = make_engine()
        self.baseline_id = run_train(
            self.engine, make_params(algo_ids=(11,)), registry,
            workflow_params=WorkflowParams(batch="continuous-test"),
        )
        registry.get_events().init(1)
        self.changefeed = Changefeed(
            OpLog(str(tmp_path / "oplog")),
            registry.get_events(), registry.get_metadata(),
            registry.get_models(),
        )
        self.clock = FakeClock()
        self.server = QueryServer(
            ServerConfig(
                ip="127.0.0.1", port=0, batching=False,
                engine_instance_id=self.baseline_id,
            ),
            self.engine, registry, clock=self.clock,
        )
        defaults = dict(
            app_id=1,
            min_events=3,
            max_staleness_s=1e9,
            rollout_gates=_gates(),
            quarantine_backoff_s=60.0,
            score_window=50,
            state_dir=str(tmp_path / "cstate"),
        )
        defaults.update(cfg_kw)
        self.ctl = ContinuousController(
            self.server,
            ContinuousConfig(**defaults),
            feed=LocalFeed(self.changefeed.oplog),
            clock=self.clock,
        )
        self.server.continuous = self.ctl  # status embeds + routes

    def post(self, n, start=0):
        for k in range(start, start + n):
            self.changefeed.insert_event(_rate(f"u{k}", f"i{k % 3}", 4.0), 1)

    def drive(self, n, start=0):
        for k in range(start, start + n):
            _result, status = self.server.handle_query({"id": k})
            assert status == 200
        self.server.rollout.drain_shadow()

    def promote_to_live(self):
        """Feed the gates until the in-flight candidate goes LIVE."""
        for _round in range(6):
            if not self.server.rollout.active:
                break
            self.drive(8, start=1000 + _round * 100)
            self.clock.advance(11.0)
            self.drive(2, start=2000 + _round * 100)
            self.server.rollout.drain_shadow()
        self.ctl.tick()

    def close(self):
        self.server.server_close()


class TestContinuousController:
    def test_no_delta_no_candidate(self, registry, tmp_path):
        loop = _Loop(registry, tmp_path)
        try:
            status = loop.ctl.tick()
            assert status["state"] == "WATCHING"
            assert status["cycles"] == 0
            assert "candidate" not in status
            assert not loop.server.rollout.active
        finally:
            loop.close()

    def test_delta_below_min_events_waits(self, registry, tmp_path):
        loop = _Loop(registry, tmp_path, min_events=5)
        try:
            loop.post(3)
            status = loop.ctl.tick()
            assert status["cycles"] == 0
            assert status["pendingEvents"] == 3
        finally:
            loop.close()

    def test_full_cycle_auto_submits_and_commits_on_live(
        self, registry, tmp_path
    ):
        loop = _Loop(registry, tmp_path)
        try:
            loop.post(4)
            status = loop.ctl.tick()
            # sample engine has no fold_in -> full retrain through the
            # existing run_train path
            assert status["lastCycle"]["mode"] == FULL_RETRAIN
            assert status["state"] == "MONITORING"
            cand_id = status["candidate"]["instanceId"]
            assert cand_id != loop.baseline_id
            plan = loop.server.rollout.plan
            assert plan.stage == ROLLOUT_SHADOW
            assert plan.candidate_instance_id == cand_id
            assert plan.history[0]["reason"] == (
                "continuous controller auto-submit"
            )
            assert status["cursorSeq"] == 0  # nothing committed yet
            loop.promote_to_live()
            status = loop.ctl.status()
            assert loop.server.rollout.plan.stage == ROLLOUT_LIVE
            assert loop.server.deployment.instance.id == cand_id
            assert status["state"] == "WATCHING"
            assert status["cursorSeq"] == loop.changefeed.last_seq
            assert status["lastCycle"]["outcome"] == "live"
            assert status["lastFreshnessS"] is not None
            # metrics: the loop's outcomes are counted
            assert loop.ctl._folds.value(kind=FULL_RETRAIN) == 1
            assert loop.ctl._folds.value(kind="promoted") == 1
        finally:
            loop.close()

    def test_busy_rollout_backs_off_then_submits(self, registry, tmp_path):
        loop = _Loop(registry, tmp_path)
        try:
            # an operator rollout is already in flight
            op_cand = run_train(
                loop.engine, make_params(algo_ids=(13,)), registry,
                workflow_params=WorkflowParams(batch="operator"),
            )
            loop.server.rollout.start(
                candidate_instance_id=op_cand, gates=_gates()
            )
            loop.post(4)
            status = loop.ctl.tick()
            assert status["state"] == "SUBMIT_PENDING"
            assert "rollout busy" in status["lastError"]
            # still pending while the operator's rollout runs
            loop.ctl.tick()
            assert loop.server.rollout.plan.candidate_instance_id == op_cand
            loop.server.rollout.abort("operator done")
            loop.clock.advance(120.0)  # past the backoff delay
            status = loop.ctl.tick()
            assert status["state"] == "MONITORING"
            assert loop.server.rollout.plan.candidate_instance_id == (
                status["candidate"]["instanceId"]
            )
        finally:
            loop.close()

    def test_gate_rollback_quarantines_and_forces_full_retrain(
        self, registry, tmp_path
    ):
        loop = _Loop(
            registry, tmp_path,
            rollout_gates=_gates(canary_hold_s=100_000.0),
        )
        try:
            loop.post(4)
            status = loop.ctl.tick()
            cand_id = status["candidate"]["instanceId"]
            loop.drive(6)
            loop.clock.advance(11.0)
            loop.drive(1, start=50)
            self_stage = loop.server.rollout.stage
            assert self_stage == ROLLOUT_CANARY
            # the candidate dies in canary; the error gate rolls back
            with faults.inject(
                faults.FaultSpec(site="serving.candidate", kind="refuse")
            ):
                loop.drive(100, start=100)
            assert loop.server.rollout.stage == ROLLOUT_ROLLED_BACK
            status = loop.ctl.tick()
            assert cand_id in status["quarantined"]
            assert status["state"] == "COOLDOWN"
            assert status["lastCycle"]["outcome"] == "rolled_back"
            # cooldown holds the loop even with a fresh delta
            loop.post(5, start=100)
            status = loop.ctl.tick()
            assert status["cycles"] == 1
            # ...and after the cooldown the next cycle is a FULL retrain
            loop.clock.advance(61.0)
            status = loop.ctl.tick()
            assert status["cycles"] == 2
            assert status["lastCycle"]["mode"] == FULL_RETRAIN
            assert "forced" in status["lastCycle"]["reason"]
        finally:
            loop.close()

    def test_offline_divergence_quarantines_before_submission(
        self, registry, tmp_path
    ):
        loop = _Loop(registry, tmp_path, max_offline_divergence=0.5,
                     min_score_samples=3)
        try:
            # feedback whose SERVED predictions look nothing like what the
            # candidate will produce -> divergence ~1.0 over every replay
            store = registry.get_events()
            for k in range(6):
                store.insert(
                    Event(
                        event="predict", entity_type="pio_pr",
                        entity_id=f"pr{k}",
                        properties=DataMap({
                            "engineInstanceId": loop.baseline_id,
                            "query": {"id": k},
                            "prediction": {"totally": "different"},
                            "variant": "baseline",
                        }),
                    ), 1,
                )
            loop.post(4)
            status = loop.ctl.tick()
            assert status["lastCycle"]["outcome"] == "offline_quarantined"
            assert not loop.server.rollout.active
            assert status["quarantined"]
            score = status["lastCycle"]["offlineScore"]
            assert score["samples"] == 6
            assert score["meanDivergence"] > 0.5
            # the rejected candidate's delta must NOT simply re-fold into
            # a byte-identical candidate after the cooldown: the next
            # cycle is a forced full retrain (quarantine livelock guard)
            loop.clock.advance(61.0)
            status = loop.ctl.tick()
            assert status["cycles"] == 2
            assert status["lastCycle"]["mode"] == FULL_RETRAIN
            assert "forced" in status["lastCycle"]["reason"]
        finally:
            loop.close()

    def test_pause_and_trigger(self, registry, tmp_path):
        loop = _Loop(registry, tmp_path, min_events=1000)
        try:
            loop.ctl.pause()
            loop.post(5)
            status = loop.ctl.tick()
            assert status["state"] == "PAUSED"
            assert status["cycles"] == 0
            loop.ctl.resume_watching()
            status = loop.ctl.tick()
            assert status["cycles"] == 0  # below min_events
            loop.ctl.trigger()
            status = loop.ctl.tick()
            assert status["cycles"] == 1  # trigger overrides the threshold
        finally:
            loop.close()

    def test_http_surface_and_status_embed(self, registry, tmp_path):
        import requests

        loop = _Loop(registry, tmp_path, min_events=1000)
        try:
            loop.server.start_background()
            base = f"http://127.0.0.1:{loop.server.bound_port}"
            r = requests.get(f"{base}/continuous.json", timeout=10)
            assert r.status_code == 200
            assert r.json()["enabled"] is True
            assert r.json()["state"] == "WATCHING"
            r = requests.post(f"{base}/continuous/pause", timeout=10)
            assert r.json()["state"] == "PAUSED"
            r = requests.post(
                f"{base}/continuous/start", json={}, timeout=10
            )
            assert r.json()["state"] == "WATCHING"
            r = requests.post(
                f"{base}/continuous/trigger", json={"full": True}, timeout=10
            )
            assert r.status_code == 200
            status = requests.get(f"{base}/status.json", timeout=10).json()
            assert status["continuous"]["enabled"] is True
        finally:
            loop.ctl.stop()
            loop.close()

    def test_routes_409_without_controller(self, registry):
        import requests

        engine = make_engine()
        run_train(engine, make_params(algo_ids=(11,)), registry,
                  workflow_params=WorkflowParams(batch="plain"))
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry, clock=FakeClock(),
        )
        try:
            srv.start_background()
            base = f"http://127.0.0.1:{srv.bound_port}"
            r = requests.get(f"{base}/continuous.json", timeout=10)
            assert r.json() == {"enabled": False}
            r = requests.post(f"{base}/continuous/trigger", timeout=10)
            assert r.status_code == 409
        finally:
            srv.server_close()

    def test_cli_status_and_pause(self, registry, tmp_path, capsys):
        from predictionio_tpu.tools.console import main as console_main

        loop = _Loop(registry, tmp_path, min_events=1000)
        try:
            loop.server.start_background()
            port = str(loop.server.bound_port)
            assert console_main(
                ["continuous", "status", "--ip", "127.0.0.1", "--port", port],
                registry=registry,
            ) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["state"] == "WATCHING"
            assert console_main(
                ["continuous", "pause", "--ip", "127.0.0.1", "--port", port],
                registry=registry,
            ) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["state"] == "PAUSED"
        finally:
            loop.close()

    def test_feed_gap_forces_retrain_then_resyncs_at_live(
        self, registry, tmp_path
    ):
        """A feed gap (here: the primary replaced — new generation) must
        produce ONE covering full retrain whose LIVE resyncs the cursor
        to the new feed's head — not an endless gap→retrain loop."""
        loop = _Loop(registry, tmp_path)
        try:
            loop.post(2)  # below min_events: just moves the read position
            loop.ctl.tick()
            fresh_oplog = OpLog(str(tmp_path / "oplog2"))
            loop.ctl.watcher._feed = LocalFeed(fresh_oplog)
            status = loop.ctl.tick()  # FeedGap -> forced retrain cycle
            assert status["lastCycle"]["mode"] == FULL_RETRAIN
            assert status["candidate"]["resync"] is True
            loop.promote_to_live()
            status = loop.ctl.status()
            assert status["lastCycle"]["outcome"] == "live"
            # the cursor jumped to the NEW feed's identity/head...
            assert loop.ctl.watcher.generation == fresh_oplog.generation
            assert status["pendingEvents"] == 0
            # ...and tailing works again: no gap, fresh events arrive
            cf2 = Changefeed(
                fresh_oplog, registry.get_events(),
                registry.get_metadata(), registry.get_models(),
            )
            cf2.insert_event(_rate("u77", "i1", 5.0), 1)
            status = loop.ctl.tick()
            assert "feed gap" not in (status.get("lastError") or "")
            assert status["pendingEvents"] == 1
        finally:
            loop.close()

    def test_restart_mid_cycle_resumes_cursor_and_rollout(
        self, registry, tmp_path
    ):
        """The restart acceptance proof: a controller killed with a
        candidate mid-rollout comes back (a) monitoring the SAME rollout
        (not submitting a second candidate), (b) with the durable cursor
        still uncommitted (the delta replays into nothing — the candidate
        already carries it), and the eventual LIVE commits exactly once."""
        loop = _Loop(registry, tmp_path)
        try:
            loop.post(4)
            status = loop.ctl.tick()
            cand_id = status["candidate"]["instanceId"]
            assert loop.server.rollout.stage == ROLLOUT_SHADOW
            n_instances = len(
                registry.get_metadata().engine_instance_get_all()
            )
        finally:
            loop.close()
        # --- restart: fresh server + controller over the same durable state
        clock2 = FakeClock()
        engine2 = make_engine()
        srv2 = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine2, registry, clock=clock2,
        )
        try:
            # the rollout plane resumed the in-flight plan on its own
            assert srv2.rollout.stage == ROLLOUT_SHADOW
            assert srv2.rollout.plan.candidate_instance_id == cand_id
            ctl2 = ContinuousController(
                srv2,
                ContinuousConfig(
                    app_id=1, min_events=3, max_staleness_s=1e9,
                    rollout_gates=_gates(),
                    state_dir=str(tmp_path / "cstate"),
                ),
                feed=LocalFeed(loop.changefeed.oplog),
                clock=clock2,
            )
            srv2.continuous = ctl2
            status = ctl2.tick()
            # resumed, not replayed: same candidate, no new training run
            assert status["state"] == "MONITORING"
            assert status["candidate"]["instanceId"] == cand_id
            assert status["cursorSeq"] == 0
            assert len(
                registry.get_metadata().engine_instance_get_all()
            ) == n_instances
            # drive the resumed rollout to LIVE; the cursor commits now
            for _round in range(6):
                if not srv2.rollout.active:
                    break
                for k in range(8):
                    _r, code = srv2.handle_query({"id": 1000 + k})
                    assert code == 200
                srv2.rollout.drain_shadow()
                clock2.advance(11.0)
                _r, code = srv2.handle_query({"id": 2000 + _round})
                assert code == 200
                srv2.rollout.drain_shadow()
            status = ctl2.tick()
            assert srv2.rollout.plan.stage == ROLLOUT_LIVE
            assert srv2.deployment.instance.id == cand_id
            assert status["cursorSeq"] == loop.changefeed.last_seq
            assert status["lastCycle"]["outcome"] == "live"
        finally:
            srv2.server_close()


# ---------------------------------------------------------------------------
# the ALS closed loop (events -> event server -> changefeed -> fold-in ->
# shadow -> canary -> live), via the loadgen scenario
# ---------------------------------------------------------------------------


class TestClosedLoopE2E:
    def test_feedback_stream_scenario_promotes_fold_in_candidate(
        self, tmp_path
    ):
        from predictionio_tpu.tools.loadgen import run_feedback_stream

        report = run_feedback_stream(base_dir=str(tmp_path))
        assert report["ok"], report
        assert report["clientFailures"] == 0
        assert report["freshnessS"] is not None
        assert report["lastCycle"]["mode"] == FOLD_IN
        assert report["lastCycle"]["outcome"] == "live"
        # the fold actually moved the model toward the fresh feedback
        assert report["lastCycle"]["foldIn"]["newUsers"] > 0

    def test_als_delta_fraction_escalates_to_full_retrain(
        self, registry, tmp_path, monkeypatch
    ):
        """Acceptance: crossing a fold-in policy threshold triggers a
        full retrain on the REAL ALS engine (not just decide_mode)."""
        import predictionio_tpu.storage.registry as regmod

        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithmParams, RecDataSourceParams, engine_factory,
        )

        monkeypatch.setattr(regmod, "_default_registry", registry)
        store = registry.get_events()
        store.init(1)
        seed = [
            _rate(f"u{u}", f"i{i}", 4.0)
            for u in range(6) for i in range(4)
        ]
        store.write(seed, 1)
        engine = engine_factory()
        ep = EngineParams(
            data_source_params=("", RecDataSourceParams(app_id=1)),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(rank=4, num_iterations=2)),
            ],
        )
        run_train(engine, ep, registry,
                  workflow_params=WorkflowParams(batch="als-base"))
        changefeed = Changefeed(
            OpLog(str(tmp_path / "oplog")),
            store, registry.get_metadata(), registry.get_models(),
        )
        clock = FakeClock()
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry, clock=clock,
        )
        try:
            ctl = ContinuousController(
                srv,
                ContinuousConfig(
                    app_id=1, min_events=3, max_staleness_s=1e9,
                    rollout_gates=_gates(),
                    # a delta this large vs the 24-event corpus crosses
                    # any honest fraction threshold
                    policy=FoldInPolicy(max_delta_fraction=0.05),
                    state_dir=str(tmp_path / "cstate"),
                ),
                feed=LocalFeed(changefeed.oplog),
                clock=clock,
            )
            srv.continuous = ctl
            for k in range(8):
                changefeed.insert_event(_rate(f"nu{k}", f"i{k % 4}", 5.0), 1)
            status = ctl.tick()
            assert status["lastCycle"]["mode"] == FULL_RETRAIN
            assert "delta fraction" in status["lastCycle"]["reason"]
            assert status["state"] == "MONITORING"  # still auto-submitted
        finally:
            srv.server_close()

    def test_classification_fold_drives_through_generic_controller(
        self, registry, tmp_path, monkeypatch
    ):
        """A SECOND template (classification / multinomial NB) folds
        through the REAL controller: the fold protocol is duck-typed
        (``fold_in`` + ``fold_in_supported`` + ``user_map``/``item_map``),
        so no controller change is needed to onboard a new engine —
        pinned structurally by the companion test below."""
        import predictionio_tpu.storage.registry as regmod

        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.models import classification

        monkeypatch.setattr(regmod, "_default_registry", registry)
        store = registry.get_events()
        store.init(1)
        rng = np.random.default_rng(7)
        base = {0.0: [20, 2, 2], 1.0: [2, 20, 2], 2.0: [2, 2, 20]}
        plans = (0.0, 1.0, 2.0)

        def _profile(uid, plan):
            attrs = rng.poisson(base[plan]).astype(float)
            return Event(
                event="$set", entity_type="user", entity_id=uid,
                properties=DataMap({
                    "plan": plan,
                    "attr0": float(attrs[0]),
                    "attr1": float(attrs[1]),
                    "attr2": float(attrs[2]),
                }),
            )

        def _signup(uid, plan):
            # $set cannot carry a target entity (reserved-event rule),
            # and the watcher keys deltas on (entity, target) pairs — so
            # the domain emits a signup marker alongside the profile
            # write. Pure event_values config; the controller is unchanged.
            return Event(
                event="signup", entity_type="user", entity_id=uid,
                target_entity_type="plan",
                target_entity_id=f"plan{int(plan)}",
                properties=DataMap({}),
            )

        store.write([_profile(f"u{k}", plans[k % 3]) for k in range(36)], 1)
        engine = classification.engine_factory()
        ep = EngineParams(
            data_source_params=(
                "", classification.ClassificationDataSourceParams(),
            ),
            # naive only: randomforest has no fold_in, and the controller
            # rightly refuses to fold a deployment it can only half-fold
            algorithm_params_list=[
                ("naive", classification.NaiveBayesParams(lam=1.0)),
            ],
        )
        run_train(engine, ep, registry,
                  workflow_params=WorkflowParams(batch="clf-base"))
        changefeed = Changefeed(
            OpLog(str(tmp_path / "oplog")),
            store, registry.get_metadata(), registry.get_models(),
        )
        clock = FakeClock()
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry, clock=clock,
        )
        try:
            ctl = ContinuousController(
                srv,
                ContinuousConfig(
                    app_id=1, min_events=3, max_staleness_s=1e9,
                    rollout_gates=_gates(),
                    event_values={"signup": 1.0},
                    state_dir=str(tmp_path / "cstate"),
                ),
                feed=LocalFeed(changefeed.oplog),
                clock=clock,
            )
            srv.continuous = ctl
            for k, plan in enumerate(plans):
                changefeed.insert_event(_profile(f"nu{k}", plan), 1)
                changefeed.insert_event(_signup(f"nu{k}", plan), 1)
            status = ctl.tick()
            last = status["lastCycle"]
            assert last["mode"] == FOLD_IN, last
            assert last["outcome"] == "submitted", last
            # all three new users folded; the plan-marker target ids are
            # not entity rows and are harmlessly ignored by the fold
            assert last["foldIn"]["newUsers"] == 3
            assert last["foldIn"]["foldedUsers"] == 3
            # NB statistics are additive: folding fresh labeled rows must
            # not degrade the full-data error rate beyond noise
            assert (last["foldIn"]["rmseAfter"]
                    <= last["foldIn"]["rmseBefore"] + 1e-9)
        finally:
            srv.server_close()

    def test_controller_layer_has_no_template_specific_code(self):
        """The pin for the satellite above: onboarding the second
        template required ZERO layer-specific controller changes. Any
        future classification special-case in the continuous layer
        breaks this, forcing the discussion back to the duck-typed
        protocol."""
        import inspect

        from predictionio_tpu.continuous import controller, watcher

        for mod in (controller, watcher):
            src = inspect.getsource(mod).lower()
            for word in ("classif", "naive", "bayes", "randomforest"):
                assert word not in src, (mod.__name__, word)


# ---------------------------------------------------------------------------
# ISSUE-15 satellite: per-partition fold-in parallelism
# ---------------------------------------------------------------------------


class TestPartitionedFoldParallelism:
    """The controller folds per-partition deltas CONCURRENTLY on a
    bounded pool (docs/continuous.md#partitioned-folds): a slow
    partition is skipped — its cursor stays put and its delta re-folds
    next cycle — so it never blocks another partition's commit, and no
    folded event is ever lost or double-committed."""

    def _als_loop(self, registry, tmp_path, monkeypatch, **cfg_kw):
        import predictionio_tpu.storage.registry as regmod

        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithmParams,
            RecDataSourceParams,
            engine_factory,
        )

        # RecDataSource reads the process-default registry
        monkeypatch.setattr(regmod, "_default_registry", registry)
        store = registry.get_events()
        store.init(1)
        seed = [
            _rate(f"u{u}", f"i{i}", 4.0)
            for u in range(8) for i in range(5)
        ]
        store.write(seed, 1)
        engine = engine_factory()
        ep = EngineParams(
            data_source_params=("", RecDataSourceParams(app_id=1)),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(rank=4, num_iterations=2)),
            ],
        )
        run_train(
            engine, ep, registry,
            workflow_params=WorkflowParams(batch="als-base"),
        )
        feeds, cfs = [], []
        for p in range(2):
            cf = Changefeed(
                OpLog(str(tmp_path / f"oplog{p}")),
                store, registry.get_metadata(), registry.get_models(),
            )
            cfs.append(cf)
            feeds.append(LocalFeed(cf.oplog))
        clock = FakeClock()
        srv = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry, clock=clock,
        )
        defaults = dict(
            app_id=1,
            min_events=2,
            max_staleness_s=1e9,
            rollout_gates=_gates(),
            state_dir=str(tmp_path / "cstate"),
        )
        defaults.update(cfg_kw)
        ctl = ContinuousController(
            srv, ContinuousConfig(**defaults), feed=feeds, clock=clock
        )
        srv.continuous = ctl
        return srv, ctl, cfs, clock

    def _promote(self, srv, ctl, clock):
        for _round in range(6):
            if not srv.rollout.active:
                break
            for k in range(8):
                _r, status = srv.handle_query(
                    {"user": f"u{k % 8}", "num": 2}
                )
                assert status == 200
            srv.rollout.drain_shadow()
            clock.advance(11.0)
            for k in range(3):
                _r, status = srv.handle_query(
                    {"user": f"u{(k + 3) % 8}", "num": 2}
                )
                assert status == 200
            srv.rollout.drain_shadow()
        ctl.tick()

    def test_partitions_fold_concurrently_and_both_commit(
        self, registry, tmp_path, monkeypatch
    ):
        srv, ctl, cfs, clock = self._als_loop(registry, tmp_path, monkeypatch)
        try:
            cfs[0].insert_event(_rate("u0", "i0", 5.0), 1)
            cfs[0].insert_event(_rate("u2", "i1", 5.0), 1)
            cfs[1].insert_event(_rate("u1", "i0", 5.0), 1)
            cfs[1].insert_event(_rate("u3", "i2", 5.0), 1)
            status = ctl.tick()
            assert status["lastCycle"]["mode"] == FOLD_IN
            parts = status["lastCycle"]["foldPartitions"]
            assert parts == {"completed": [0, 1], "skipped": []}
            # BOTH partitions' cursors ride the candidate
            assert set(status["candidate"]["uptoSeq"]) == {"0", "1"}
            assert status["lastCycle"]["deltaEvents"] == 4
            self._promote(srv, ctl, clock)
            status = ctl.status()
            assert status["lastCycle"]["outcome"] == "live"
            # every partition committed, nothing left pending
            assert status["pendingEvents"] == 0
            for w in ctl.watcher.watchers:
                assert w.cursor_seq > 0
        finally:
            srv.server_close()

    def test_slow_partition_skipped_never_blocks_commit(
        self, registry, tmp_path, monkeypatch
    ):
        import time as _time

        import predictionio_tpu.continuous.foldin as foldin_mod

        srv, ctl, cfs, clock = self._als_loop(
            registry, tmp_path, monkeypatch,
            fold_workers=2,
            fold_partition_timeout_s=0.5,
        )
        try:
            cfs[0].insert_event(_rate("u0", "i0", 5.0), 1)
            cfs[0].insert_event(_rate("u2", "i1", 5.0), 1)
            cfs[1].insert_event(_rate("u1", "i0", 5.0), 1)
            cfs[1].insert_event(_rate("u3", "i2", 5.0), 1)
            slow_row = srv.deployment.models[0].user_map["u0"]
            orig = foldin_mod.fold_in_factors

            def slow_p0(uf, itf, u, i, r, cu, ci, lam, policy=None):
                if slow_row in cu:  # partition 0 owns u0
                    _time.sleep(2.0)
                return orig(uf, itf, u, i, r, cu, ci, lam, policy=policy)

            monkeypatch.setattr(foldin_mod, "fold_in_factors", slow_p0)
            status = ctl.tick()
            parts = status["lastCycle"]["foldPartitions"]
            assert parts == {"completed": [1], "skipped": [0]}
            # ONLY the completed partition's cursor rides the candidate
            assert set(status["candidate"]["uptoSeq"]) == {"1"}
            assert status["lastCycle"]["deltaEvents"] == 2
            assert ctl._folds.value(kind="partition_skipped") == 1
            monkeypatch.setattr(foldin_mod, "fold_in_factors", orig)
            self._promote(srv, ctl, clock)
            status = ctl.status()
            # partition 1 committed; partition 0's delta is PENDING, not
            # lost — and partition 1 has nothing left to re-fold
            w0, w1 = ctl.watcher.watchers
            assert w1.cursor_seq > 0 and w1.pending_count() == 0
            assert w0.cursor_seq == 0 and w0.pending_count() == 2
            # the commit tick already started the NEXT cycle over the
            # still-pending delta: it re-folds ONLY the skipped
            # partition's 2 events — nothing re-folds partition 1, so no
            # folded event is ever duplicated. A single-partition delta
            # rides the merged fold path (no foldPartitions block).
            assert status["lastCycle"]["mode"] == FOLD_IN
            assert status["lastCycle"]["deltaEvents"] == 2
            assert "foldPartitions" not in status["lastCycle"]
            self._promote(srv, ctl, clock)
            status = ctl.status()
            assert status["lastCycle"]["outcome"] == "live"
            assert w0.cursor_seq > 0 and w0.pending_count() == 0
            assert status["pendingEvents"] == 0
        finally:
            srv.server_close()
