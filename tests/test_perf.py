"""Performance observability (ISSUE 8, docs/observability.md#profiling,
docs/performance.md#perf-ledger).

Five layers:

1. **Jit telemetry**: compile/retrace counting via cache-size probes on
   a fake jitted callable, replay-on-bind into a metrics registry,
   attribute-forwarding wrappers, and run deltas.
2. **Phase profiling**: the near-zero-cost contract when ``PIO_PROFILE``
   is off (the injected clock and fence are NEVER called), fenced
   device timing, and roofline math — all on injected clocks.
3. **Exposition round trip**: the new ``pio_jit_*`` metric families
   survive ``expo.render`` → ``expo.parse_text`` with values intact
   (the scrape path ``pio profile --node`` and ``pio top`` ride).
4. **Perf ledger**: append/load durability (torn lines skipped),
   bench-record normalization, comparability grouping (a CPU fallback
   never gates a TPU number), and the regression gate against the
   checked-in BENCH_r0*.json history — flat ⇒ clean, an injected
   20%-worse synthetic record ⇒ flagged (the ISSUE 8 acceptance).
5. **CLIs**: ``pio perf diff|trend`` and ``pio profile`` driven
   in-process through the console, including the smoke-train report
   (per-phase wall/device time, compile counts, retrace counts, a
   roofline estimate) and the fleet columns read through LIVE
   exposition (a real HTTP scrape of a server's ``/metrics``).

No wall-clock sleeps; the only waiting is loopback HTTP.
"""

from __future__ import annotations

import json
import os

import pytest

from predictionio_tpu.obs import expo
from predictionio_tpu.obs import perfledger
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.profile import (
    JitTelemetry,
    PhaseProfiler,
    render_profile_report,
    roofline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


class FakeJit:
    """Mimics a jitted callable: ``_cache_size`` grows on every new
    'signature' (argument) — exactly the probe JitTelemetry reads."""

    def __init__(self):
        self._signatures = set()
        self.calls = 0

    def _cache_size(self) -> int:
        return len(self._signatures)

    def __call__(self, signature, **kwargs):
        self.calls += 1
        self._signatures.add((signature, tuple(sorted(kwargs.items()))))
        return signature

    def lower(self):  # AOT-surface stand-in for wrapper forwarding
        return "lowered"


# ---------------------------------------------------------------------------
# 1. Jit telemetry
# ---------------------------------------------------------------------------


class TestJitTelemetry:
    def test_compile_and_retrace_counting(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("toy", fn, "a")  # first compile: warmup
        tel.call("toy", fn, "a")  # cache hit: nothing
        tel.call("toy", fn, "b")  # second compile: retrace
        tel.call("toy", fn, "c")  # third compile: retrace
        snap = tel.snapshot()
        assert snap["fns"]["toy"]["compiles"] == 3
        assert snap["fns"]["toy"]["retraces"] == 2
        assert fn.calls == 4

    def test_non_jitted_callable_passes_through(self):
        tel = JitTelemetry()
        assert tel.call("plain", lambda x: x + 1, 41) == 42
        assert tel.snapshot()["fns"] == {}

    def test_bind_replays_totals_and_counts_live(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("solve", fn, "a")
        tel.call("solve", fn, "b")
        reg = MetricsRegistry()
        tel.bind(reg)  # after the fact: totals must replay
        text = expo.render(reg)
        assert 'pio_jit_compiles_total{fn="solve"} 2' in text
        assert 'pio_jit_retraces_total{fn="solve"} 1' in text
        tel.call("solve", fn, "c")  # live after bind
        text = expo.render(reg)
        assert 'pio_jit_compiles_total{fn="solve"} 3' in text
        assert 'pio_jit_retraces_total{fn="solve"} 2' in text
        # cache gauges exist even with monitoring unattached
        assert "pio_jit_cache_hits 0" in text

    def test_bind_is_idempotent(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("f", fn, "a")
        reg = MetricsRegistry()
        tel.bind(reg)
        tel.bind(reg)  # second bind must not double-replay
        assert 'pio_jit_compiles_total{fn="f"} 1' in expo.render(reg)

    def test_wrap_counts_and_forwards_attributes(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        wrapped = tel.wrap("w", FakeJit())
        wrapped("a")
        wrapped("b")
        assert tel.snapshot()["fns"]["w"]["compiles"] == 2
        # AOT tooling reaches through the wrapper
        assert wrapped.lower() == "lowered"
        assert wrapped._cache_size() == 2

    def test_racing_first_compile_counted_once(self):
        """Two threads racing the same first compile both observe cache
        growth (the loser waits on jax's compile lock, then reads
        after > before); the high-water mark must credit ONE compile and
        no phantom retrace. Reproduced deterministically by scripting
        the cache-size reads the loser thread would see."""

        class ScriptedSizes:
            def __init__(self, sizes):
                self._sizes = list(sizes)

            def _cache_size(self):
                return self._sizes.pop(0)

            def __call__(self):
                return None

        # winner: before=0 after=1; loser replays before=0 after=1
        fn = ScriptedSizes([0, 1, 0, 1])
        tel = JitTelemetry(clock=lambda: 0.0)
        tel.call("raced", fn)
        tel.call("raced", fn)
        snap = tel.snapshot()["fns"]["raced"]
        assert snap["compiles"] == 1
        assert snap["retraces"] == 0
        # a REAL later retrace (cache grows past the mark) still counts
        fn._sizes = [1, 2]
        tel.call("raced", fn)
        snap = tel.snapshot()["fns"]["raced"]
        assert snap["compiles"] == 2
        assert snap["retraces"] == 1

    def test_delta_since_isolates_one_run(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("f", fn, "a")
        before = tel.snapshot()
        tel.call("f", fn, "b")
        tel.call("g", FakeJit(), "x")
        delta = tel.delta_since(before)
        assert delta["fns"]["f"] == {
            "compiles": 1, "retraces": 1, "compile_s": 0.0,
        }
        assert delta["fns"]["g"]["compiles"] == 1
        assert "retraces" in delta["fns"]["g"]


# ---------------------------------------------------------------------------
# 2. Phase profiling
# ---------------------------------------------------------------------------


class TestPhaseProfiler:
    def test_disabled_hooks_are_free(self):
        """The PIO_PROFILE-off contract: neither the clock nor the fence
        is EVER called, and nothing is recorded — production paths keep
        the hooks at (near) zero cost."""
        calls = {"clock": 0, "fence": 0}

        def clock():
            calls["clock"] += 1
            return float(calls["clock"])

        def fence(value):
            calls["fence"] += 1

        prof = PhaseProfiler(enabled=False, clock=clock, fence=fence)
        for _ in range(100):
            with prof.phase("hot", flops=1e12) as ph:
                ph.fence("result")
        prof.record("adopted", wall_s=1.0)
        assert calls == {"clock": 0, "fence": 0}
        assert prof.summary() == {}

    def test_enabled_respects_env_default(self, monkeypatch):
        monkeypatch.delenv("PIO_PROFILE", raising=False)
        assert PhaseProfiler().enabled is False
        monkeypatch.setenv("PIO_PROFILE", "1")
        assert PhaseProfiler().enabled is True

    def test_fenced_device_time_and_roofline(self):
        # injected clock: each read advances 1s, so wall and device
        # times are exact integers
        ticks = {"n": 0}

        def clock():
            ticks["n"] += 1
            return float(ticks["n"])

        fenced = []
        prof = PhaseProfiler(
            enabled=True, clock=clock, fence=fenced.append
        )
        with prof.phase("solve", flops=197e12, hbm_bytes=819e9) as ph:
            ph.fence("device-value")  # t0=1, fence read=2 → device 1s
        # exit read=3 → wall 2s
        summary = prof.summary()
        assert fenced == ["device-value"]
        st = summary["solve"]
        assert st["count"] == 1
        assert st["wall_s"] == pytest.approx(2.0)
        assert st["device_s"] == pytest.approx(1.0)
        # 197e12 flops over the 1s device time vs the 98.5e12 f32 peak
        assert st["mfu"] == pytest.approx(2.0)
        assert st["hbm_util"] == pytest.approx(1.0)
        assert st["tflops_per_s"] == pytest.approx(197.0)

    def test_unfenced_phase_device_equals_wall(self):
        ticks = {"n": 0}

        def clock():
            ticks["n"] += 1
            return float(ticks["n"])

        prof = PhaseProfiler(enabled=True, clock=clock, fence=lambda v: v)
        with prof.phase("host-only"):
            pass
        st = prof.summary()["host-only"]
        assert st["wall_s"] == st["device_s"] == pytest.approx(1.0)

    def test_roofline_zero_time(self):
        assert roofline(1e12, 1e9, 0.0) == {
            "tflops_per_s": 0.0, "mfu": 0.0, "hbm_util": 0.0,
        }

    def test_report_renders_all_sections(self):
        text = render_profile_report(
            "unit",
            phases={"train": {"count": 2, "wall_s": 3.0, "device_s": 2.5,
                              "tflops_per_s": 1.0, "mfu": 0.01,
                              "hbm_util": 0.02}},
            jit={"als_half": {"compiles": 2, "retraces": 1,
                              "compile_s": 3.5}},
            cache={"hits": 1, "misses": 2, "backend_compiles": 3,
                   "backend_compile_s": 4.0},
            device="TFRT_CPU_0",
        )
        for token in ("train", "als_half", "retraces", "mfu(v5e)",
                      "hits=1", "TFRT_CPU_0"):
            assert token in text, text


# ---------------------------------------------------------------------------
# 3. Exposition round trip over the profile families
# ---------------------------------------------------------------------------


class TestProfileExpositionRoundTrip:
    def test_jit_families_survive_render_parse(self):
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("als_half", fn, "a")
        tel.call("als_half", fn, "b")
        tel.call("serving.topk_users", FakeJit(), "q")
        reg = MetricsRegistry()
        tel.bind(reg)
        parsed = expo.parse_text(expo.render(reg))
        compiles = dict(
            (labels["fn"], value)
            for labels, value in parsed["pio_jit_compiles_total"]
        )
        assert compiles == {"als_half": 2.0, "serving.topk_users": 1.0}
        retraces = dict(
            (labels["fn"], value)
            for labels, value in parsed["pio_jit_retraces_total"]
        )
        assert retraces["als_half"] == 1.0
        # histogram family: _bucket/_sum/_count all present and coherent
        assert "pio_jit_compile_seconds_bucket" in parsed
        counts = {
            labels["fn"]: value
            for labels, value in parsed["pio_jit_compile_seconds_count"]
        }
        assert counts["als_half"] == 2.0
        assert parsed["pio_jit_cache_hits"][0][1] == 0.0
        assert parsed["pio_jit_cache_misses"][0][1] == 0.0

    def test_scraped_report_reconstruction(self):
        """The pio profile --node path: scrape text → report inputs."""
        from predictionio_tpu.tools.perf import _report_from_metrics

        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("fold_in.solve_rows", fn, "a")
        tel.call("fold_in.solve_rows", fn, "b")
        reg = MetricsRegistry()
        tel.bind(reg)
        reg.gauge(
            "pio_train_phase_seconds", labelnames=("phase",)
        ).set(4.5, phase="train[0]")
        data = _report_from_metrics(expo.parse_text(expo.render(reg)))
        assert data["jit"]["fold_in.solve_rows"]["compiles"] == 2.0
        assert data["jit"]["fold_in.solve_rows"]["retraces"] == 1.0
        assert data["phases"]["train[0]"]["wall_s"] == 4.5
        text = render_profile_report("node", **data)
        assert "fold_in.solve_rows" in text


# ---------------------------------------------------------------------------
# 4. Perf ledger + regression gate
# ---------------------------------------------------------------------------


def _bench_like(value: float, source: str = "bench", **over) -> dict:
    base = {
        "metric": "ml20m_als_rank50_train_s",
        "value": value,
        "unit": "s",
        "device": "TFRT_CPU_0",
        "scale": 0.01,
        "solve_mode": "chunked",
        "gather_dtype": "f32",
        "sort_gather": False,
        "fused_gather": False,
        "holdout_rmse": 0.53,
        "vs_baseline": 0.0,
    }
    base.update(over)
    return perfledger.bench_to_record(base, source=source)


class TestPerfLedger:
    def test_append_load_round_trip_skips_torn_line(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        first = _bench_like(12.0, source="r1")
        second = _bench_like(12.1, source="r2")
        perfledger.append_record(path, first)
        with open(path, "a") as fh:
            fh.write('{"torn": ')  # a crash mid-append
            fh.write("\n")
        perfledger.append_record(path, second)
        records = perfledger.load_ledger(path)
        assert [r["source"] for r in records] == ["r1", "r2"]
        assert records[0]["schema"] == perfledger.SCHEMA_VERSION
        assert records[0]["levers"]["solve_mode"] == "chunked"

    def test_load_missing_ledger_is_empty(self, tmp_path):
        assert perfledger.load_ledger(str(tmp_path / "none.jsonl")) == []

    def test_checked_in_history_loads_and_is_flat(self):
        history = perfledger.load_bench_history(REPO)
        # r01 failed bring-up (parsed null) and contributes nothing
        assert len(history) >= 4
        assert all(r["schema"] == 1 for r in history)
        assert perfledger.detect_regressions(history) == []

    def test_injected_regression_is_flagged(self):
        history = perfledger.load_bench_history(REPO)
        prior = [r["value"] for r in history]
        baseline = sorted(prior)[len(prior) // 2]
        worse = _bench_like(round(baseline * 1.25, 3), source="injected")
        flagged = perfledger.detect_regressions(history + [worse])
        assert len(flagged) == 1
        assert flagged[0]["latest_source"] == "injected"
        assert flagged[0]["ratio"] > 1.15

    def test_device_class_separates_groups(self):
        # a TPU record never gates (or is gated by) the CPU history
        records = [
            _bench_like(12.0, source="c1"),
            _bench_like(12.1, source="c2"),
            _bench_like(12.0, source="c3"),
            _bench_like(
                40.0, source="tpu1", device="TPU v5 lite0", scale=1.0
            ),
        ]
        assert perfledger.detect_regressions(records) == []
        assert perfledger.comparable_key(
            records[0]
        ) != perfledger.comparable_key(records[3])

    def test_lever_flags_separate_groups(self):
        records = [
            _bench_like(10.0, source="a"),
            _bench_like(10.0, source="b"),
            # 2x slower but under a different lever: not comparable
            _bench_like(20.0, source="c", gather_dtype="bf16"),
        ]
        assert perfledger.detect_regressions(records) == []

    def test_failed_runs_gate_nothing(self):
        records = [
            _bench_like(10.0, source="a"),
            _bench_like(10.0, source="b"),
            _bench_like(-1.0, source="failed"),
        ]
        assert perfledger.detect_regressions(records) == []

    def test_quality_gate_failures_gate_nothing(self):
        """A holdout-RMSE gate failure carries a real positive wall time
        but measured an invalid run: it must neither be flagged as the
        latest nor sit in the baseline median."""
        records = [
            _bench_like(10.0, source="a"),
            _bench_like(10.0, source="b"),
            _bench_like(10.1, source="c"),
            _bench_like(20.0, source="bad", error="rmse gate failed"),
        ]
        assert perfledger.detect_regressions(records) == []
        # ...and a later healthy regression is still judged against the
        # healthy baseline only
        flagged = perfledger.detect_regressions(
            records + [_bench_like(14.0, source="later")]
        )
        assert len(flagged) == 1
        assert flagged[0]["latest_source"] == "later"
        assert flagged[0]["baseline_median"] == pytest.approx(10.0)

    def test_trend_survives_non_numeric_fields(self):
        good = _bench_like(10.0, source="ok")
        bad = dict(_bench_like(10.0, source="garbled"))
        bad["value"] = "12.3"
        bad2 = dict(_bench_like(11.0, source="half-garbled"))
        bad2["rmse"] = "n/a"
        bad2["vs_baseline"] = None
        text = perfledger.render_trend([good, bad, bad2])
        assert "ok" in text
        assert "half-garbled" in text  # renders, minus the bad fields
        assert "12.3" not in text  # the string-valued record is skipped

    def test_min_history_required(self):
        records = [
            _bench_like(10.0, source="a"),
            _bench_like(20.0, source="b"),  # worse, but one prior point
        ]
        assert perfledger.detect_regressions(records) == []

    def test_bf16_gate_margin_rides_extra(self):
        """Satellite hygiene (round 12): the bench's bf16 RMSE-gate
        block travels into the ledger record's extra, so r06+ rounds
        are self-describing."""
        gate = {"rmse_f32": 0.53, "rmse_bf16": 0.5301, "margin": 0.0001,
                "gate": 0.01, "ok": True}
        record = _bench_like(10.0, source="gated", bf16_gate=gate)
        assert record["extra"]["bf16_gate"] == gate


class TestNoPriorReporting:
    """Flipping a lever default starts a FRESH comparable group (flags
    are part of the key) — the diff must say "no comparable prior"
    explicitly, never let an ungated group read as "stable"."""

    def test_flipped_levers_reported_as_no_prior(self):
        history = perfledger.load_bench_history(REPO)
        flipped = _bench_like(5.0, source="flip", sort_gather=True)
        verdicts = perfledger.find_no_prior(history + [flipped])
        assert len(verdicts) == 1
        v = verdicts[0]
        assert v["latest_source"] == "flip"
        assert v["history"] == 0
        assert v["needed"] == perfledger.MIN_HISTORY
        assert v["key"]["sort_gather"] is True
        # ...and the flipped record is NOT a regression either
        assert perfledger.detect_regressions(history + [flipped]) == []

    def test_established_history_has_no_no_prior(self):
        history = perfledger.load_bench_history(REPO)
        assert perfledger.find_no_prior(history) == []

    def test_failed_runs_do_not_count_as_measurements(self):
        failed = _bench_like(-1.0, source="failed",
                             sort_gather=True)
        assert perfledger.find_no_prior([failed]) == []

    def test_stale_experiment_ages_out_of_report(self):
        """A one-off lever experiment must not print 'no comparable
        prior' forever: once enough newer gate-able evidence lands, the
        stale group drops out of the report."""
        stale = _bench_like(9.0, source="oneoff", gather_dtype="bf16")
        newer = [
            _bench_like(10.0 + i * 0.01, source=f"r{i}")
            for i in range(perfledger.NO_PRIOR_RECENT_WINDOW + 1)
        ]
        verdicts = perfledger.find_no_prior([stale] + newer)
        assert [v["latest_source"] for v in verdicts] == []
        # ...but while it is still recent, it IS reported
        recent = perfledger.find_no_prior([stale] + newer[:3])
        assert [v["latest_source"] for v in recent] == ["oneoff"]

    def test_trend_renders_lever_flags(self):
        """The trend output must name the levers so two short disjoint
        histories across a default flip read as what they are."""
        text = perfledger.render_trend(
            [
                _bench_like(12.0, source="old"),
                _bench_like(5.0, source="new", sort_gather=True,
                            gather_dtype="bf16"),
            ]
        )
        assert "solve=chunked gather=f32" in text
        assert "solve=chunked gather=bf16 sort" in text


# ---------------------------------------------------------------------------
# 5. CLIs (in-process through the console, tier-1-budget style)
# ---------------------------------------------------------------------------


class TestPerfCLI:
    def _main(self, argv):
        from predictionio_tpu.tools.console import main

        return main(argv)

    def test_perf_diff_clean_on_checked_in_history(self, capsys):
        assert self._main(["perf", "diff"]) == 0
        assert "no regressions" in capsys.readouterr().out

    def test_perf_diff_flags_injected_regression(self, tmp_path, capsys):
        history = perfledger.load_bench_history(REPO)
        baseline = sorted(r["value"] for r in history)[len(history) // 2]
        ledger = str(tmp_path / "ledger.jsonl")
        perfledger.append_record(
            ledger, _bench_like(round(baseline * 1.25, 3), source="pr")
        )
        rc = self._main(["perf", "diff", "--ledger", ledger])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION" in out

    def test_perf_diff_json_shape(self, capsys):
        assert self._main(["perf", "diff", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == []
        assert doc["records"] >= 4

    def test_perf_diff_no_records_is_engine_error(self, tmp_path, capsys):
        rc = self._main(
            ["perf", "diff", "--history-dir", str(tmp_path)]
        )
        assert rc == 2

    def test_perf_trend_renders_history(self, capsys):
        assert self._main(["perf", "trend"]) == 0
        out = capsys.readouterr().out
        assert "ml20m_als_rank50_train_s" in out
        assert "bench_r05" in out

    def test_perf_diff_reports_no_prior_distinct_from_stable(
        self, tmp_path, capsys
    ):
        """A flipped-lever record exits 0 but is called out as
        unestablished — wording distinct from the clean-history line —
        while an empty ledger run says plain "no regressions"."""
        ledger = str(tmp_path / "ledger.jsonl")
        perfledger.append_record(
            ledger, _bench_like(5.0, source="flip", sort_gather=True)
        )
        rc = self._main(["perf", "diff", "--ledger", ledger])
        out = capsys.readouterr().out
        assert rc == 0
        assert "NO COMPARABLE PRIOR" in out
        assert "sort" in out  # the levers that opened the new group
        assert "await comparable history" in out
        # the stable leg: same history, no unestablished groups
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        rc = self._main(["perf", "diff", "--ledger", str(empty)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "NO COMPARABLE PRIOR" not in out
        assert "no regressions" in out
        assert "await comparable history" not in out

    def test_perf_diff_json_carries_no_prior(self, tmp_path, capsys):
        ledger = str(tmp_path / "ledger.jsonl")
        perfledger.append_record(
            ledger, _bench_like(5.0, source="flip", sort_gather=True)
        )
        rc = self._main(["perf", "diff", "--json", "--ledger", ledger])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["regressions"] == []
        assert [v["latest_source"] for v in doc["noPrior"]] == ["flip"]

    def test_profile_smoke_train_reports_everything(self, capsys):
        """The ISSUE 8 acceptance drive: a smoke-scale in-process train
        reports per-phase wall/device time, compile counts, retrace
        counts, and a roofline estimate."""
        rc = self._main(["profile", "--train-smoke", "--iterations", "1"])
        out = capsys.readouterr().out
        assert rc == 0
        for token in (
            "phase", "wall_s", "device_s",  # per-phase wall/device time
            "bucketize", "train",
            "compiles", "retraces", "als_half",  # compile/retrace counts
            "mfu(v5e)", "hbm_util",  # the roofline estimate
        ):
            assert token in out, out
        # the telemetry saw the two half-solves: one warmup compile,
        # the second half (different shapes) is a retrace
        import re as _re

        match = _re.search(r"als_half\s+(\d+)\s+(\d+)", out)
        assert match is not None, out
        assert int(match.group(1)) >= 2
        assert int(match.group(2)) >= 1

    def test_profile_smoke_train_json(self, capsys):
        rc = self._main(
            ["profile", "--train-smoke", "--iterations", "1", "--json"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        # the jit delta may be empty here: an earlier smoke run in this
        # process already compiled these shapes (the cache is process-
        # global), and a warm run compiling nothing is exactly what the
        # delta should say
        assert isinstance(doc["jit"], dict)
        assert "train" in doc["phases"]
        assert doc["phases"]["train"]["wall_s"] > 0
        assert "device" in doc


class TestInstanceProfile:
    """The persisted-profile path: run_train writes PIO_TRAIN_PHASES +
    PIO_TRAIN_PROFILE into the instance env; pio profile reads them back
    long after the training process died."""

    def test_env_round_trip(self):
        from predictionio_tpu.utils.profiling import (
            TRAIN_PROFILE_ENV_KEY,
            profile_from_env,
            profile_to_env,
        )

        snapshot = {
            "fns": {"als_half": {"compiles": 2, "retraces": 1,
                                 "compile_s": 3.2}},
            "cache": {"hits": 1, "misses": 2, "backend_compiles": 3,
                      "backend_compile_s": 4.0},
            "train_wall_s": 9.5,
        }
        env = {TRAIN_PROFILE_ENV_KEY: profile_to_env(snapshot)}
        assert profile_from_env(env) == snapshot
        assert profile_from_env({}) == {}
        assert profile_from_env({TRAIN_PROFILE_ENV_KEY: "not json"}) == {}

    def test_report_from_instance(self):
        import types

        from predictionio_tpu.tools.perf import _report_from_instance
        from predictionio_tpu.utils.profiling import (
            TRAIN_PHASES_ENV_KEY,
            TRAIN_PROFILE_ENV_KEY,
            profile_to_env,
        )

        instance = types.SimpleNamespace(
            id="AB12",
            env={
                TRAIN_PHASES_ENV_KEY: '{"train[0]": 5.5, "read": 0.5}',
                TRAIN_PROFILE_ENV_KEY: profile_to_env(
                    {
                        "fns": {"als_iteration": {"compiles": 1,
                                                  "retraces": 0,
                                                  "compile_s": 2.0}},
                        "cache": {"hits": 0, "misses": 1,
                                  "backend_compiles": 1,
                                  "backend_compile_s": 2.0},
                    }
                ),
            },
        )
        data = _report_from_instance(instance)
        assert data["phases"]["train[0]"]["wall_s"] == 5.5
        assert data["jit"]["als_iteration"]["compiles"] == 1
        text = render_profile_report("instance AB12", **{
            k: data[k] for k in ("phases", "jit", "cache")
        })
        assert "als_iteration" in text and "train[0]" in text


class TestFleetExposition:
    """The PR-7 leftover: continuous freshness (and the new jit
    counters) must be readable fleet-wide through LIVE exposition —
    a real HTTP scrape, not registry poking."""

    @pytest.fixture()
    def live_node(self):
        from predictionio_tpu.api.http import BackgroundHTTPServer
        from predictionio_tpu.api.http import JsonHTTPHandler

        class _Handler(JsonHTTPHandler):
            def do_GET(self):  # noqa: N802
                if not self.serve_obs(self.path):
                    self.respond(404, {"message": "not found"})

        server = BackgroundHTTPServer(("127.0.0.1", 0), _Handler)
        reg = server.metrics
        reg.gauge(
            "pio_continuous_feed_lag_ops", "feed lag"
        ).set(7)
        reg.gauge(
            "pio_continuous_candidate_age_seconds", "candidate age"
        ).set(42)
        tel = JitTelemetry(clock=lambda: 0.0)
        fn = FakeJit()
        tel.call("als_half", fn, "a")
        tel.call("als_half", fn, "b")
        tel.bind(reg)
        server.start_background()
        try:
            yield f"127.0.0.1:{server.bound_port}"
        finally:
            server.shutdown()
            server.server_close()

    def test_top_row_reads_freshness_and_jit_columns(self, live_node):
        from predictionio_tpu.obs.top import node_row, render_table

        row = node_row(live_node)
        assert row["up"] is True
        assert row["feed_lag"] == 7.0
        assert row["cand_age"] == 42.0
        assert row["jit_compiles"] == 2.0
        assert row["jit_retraces"] == 1.0
        table = render_table([row])
        header, data = table.splitlines()[:2]
        for column in ("FEEDLAG", "CANDAGE", "JITC", "RETRACE"):
            assert column in header
        assert "42" in data and "7" in data

    def test_dashboard_fleet_panel(self, live_node, tmp_path):
        from predictionio_tpu.storage import StorageRegistry
        from predictionio_tpu.tools.dashboard import (
            DashboardConfig,
            DashboardServer,
        )
        import requests

        srv = DashboardServer(
            DashboardConfig(ip="127.0.0.1", port=0, nodes=live_node),
            StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)}),
        )
        srv.start_background()
        try:
            base = f"http://127.0.0.1:{srv.bound_port}"
            rows = requests.get(base + "/fleet.json", timeout=10).json()
            assert rows[0]["feed_lag"] == 7.0
            assert rows[0]["jit_retraces"] == 1.0
            html_page = requests.get(base + "/fleet", timeout=10).text
            assert "FEEDLAG" in html_page and "RETRACE" in html_page
            assert "42" in html_page
        finally:
            srv.shutdown()
            srv.server_close()


# ---------------------------------------------------------------------------
# perf-unfenced-timing lint fixtures (family D, the fixture-twin
# discipline of tests/test_lint.py)
# ---------------------------------------------------------------------------


class TestPerfLintFixtures:
    def _unsuppressed(self, path):
        from predictionio_tpu.lint import lint_file

        return [f for f in lint_file(path) if not f.suppressed]

    def test_bad_fixture_fires_exactly_intended_rule(self):
        path = os.path.join(FIXTURES, "unfenced_timing_bad.py")
        findings = self._unsuppressed(path)
        assert [f.rule_id for f in findings] == ["perf-unfenced-timing"], [
            (f.rule_id, f.line) for f in findings
        ]
        with open(path) as fh:
            marked = next(
                i for i, line in enumerate(fh, 1) if "BAD" in line
            )
        assert findings[0].line == marked

    def test_clean_twin_has_no_findings(self):
        findings = self._unsuppressed(
            os.path.join(FIXTURES, "unfenced_timing_clean.py")
        )
        assert findings == [], [(f.rule_id, f.line) for f in findings]

    def test_factory_and_alias_and_wrapper_shapes_flagged(self):
        """The resolution hops the rule must see: jit factories, one-hop
        aliases, and telemetry-wrapper call sites."""
        from predictionio_tpu.lint import lint_file

        src = (
            "import functools, time\n"
            "import jax\n"
            "def make():\n"
            "    return jax.jit(lambda x: x)\n"
            "g = make()\n"
            "h = g\n"
            "direct = functools.partial(jax.jit, static_argnames=())(abs)\n"
            "def a(x):\n"
            "    t0 = time.monotonic()\n"
            "    y = h(x)\n"
            "    return time.monotonic() - t0\n"
            "def b(tel, x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = tel.call('n', direct, x)\n"
            "    return time.perf_counter() - t0\n"
        )
        findings = [
            f
            for f in lint_file("x.py", source=src)
            if f.rule_id == "perf-unfenced-timing"
        ]
        assert len(findings) == 2, findings

    def test_local_shadowing_not_flagged(self):
        """Jitted names resolve per scope: a function's own binding (or
        parameter) named like a module-level jitted fn is NOT a jitted
        call — honest host timing must not need a suppression."""
        from predictionio_tpu.lint import lint_file

        src = (
            "import time\n"
            "import jax\n"
            "f = jax.jit(lambda x: x)\n"
            "def host_timing(path):\n"
            "    f = open(path)\n"
            "    t0 = time.monotonic()\n"
            "    data = f.read()\n"
            "    return data, time.monotonic() - t0\n"
            "def param_shadow(f, x):\n"
            "    t0 = time.monotonic()\n"
            "    y = f(x)\n"
            "    return y, time.monotonic() - t0\n"
            "def still_flagged(x):\n"
            "    t0 = time.monotonic()\n"
            "    y = f(x)\n"
            "    return y, time.monotonic() - t0\n"
        )
        findings = [
            finding
            for finding in lint_file("x.py", source=src)
            if finding.rule_id == "perf-unfenced-timing"
        ]
        assert len(findings) == 1, findings
        assert findings[0].line == 16  # only the true module-jit bracket

    def test_fence_between_clears(self):
        from predictionio_tpu.lint import lint_file

        src = (
            "import time\n"
            "import jax\n"
            "f = jax.jit(lambda x: x)\n"
            "def a(x):\n"
            "    t0 = time.monotonic()\n"
            "    y = f(x)\n"
            "    jax.block_until_ready(y)\n"
            "    return time.monotonic() - t0\n"
        )
        findings = [
            f
            for f in lint_file("x.py", source=src)
            if f.rule_id == "perf-unfenced-timing"
        ]
        assert findings == []
