"""Replicated storage plane (ISSUE 3): changefeed, replica, failover.

Layers under test:

1. **Changefeed** — every mutating storage-server op gets a dense seq,
   lands in the durable op log resolved (ids shipped, not re-minted),
   and the seq rides back in ``X-PIO-Seq``.
2. **Replica** — ``StorageReplica`` tails the feed idempotently, serves
   reads, rejects writes with 409 + primary hint, gates reads on
   ``X-PIO-Min-Seq`` (wait-or-reject), reports lag on ``/status.json``.
3. **Client failover** — ``pio+ha://`` endpoint sets: writes → primary,
   read-your-writes seq token threaded through all three stores, reads
   failing over to the freshest replica once the primary breaker opens.
4. **The chaos proof** — primary hard-killed mid-run (live connections
   severed), replica promoted from the changefeed, every previously
   acked event/metadata/model read served with correct token semantics.

Deterministic: replicas are driven by explicit ``step``/``catch_up``
(no background polling), breaker thresholds pinned via env, zero
wall-clock sleeps. Tier-1.
"""

import json
import urllib.request

import pytest

from predictionio_tpu.storage import MetadataStore, SqliteEventStore
from predictionio_tpu.storage import remote
from predictionio_tpu.storage.changefeed import (
    Changefeed,
    METADATA_MUTATING_METHODS,
    MIN_SEQ_HEADER,
    SEQ_HEADER,
    apply_op,
)
from predictionio_tpu.storage.event import Event
from predictionio_tpu.storage.metadata import App
from predictionio_tpu.storage.model_store import Model, SqliteModelStore
from predictionio_tpu.storage.oplog import OpLog, OpLogGap
from predictionio_tpu.storage.replica import ReplicationError, StorageReplica
from predictionio_tpu.storage.storage_server import (
    METADATA_READ_METHODS,
    METADATA_RPC_METHODS,
    StorageServer,
)


def _stores():
    return (
        SqliteEventStore(":memory:"),
        MetadataStore(":memory:"),
        SqliteModelStore(":memory:"),
    )


@pytest.fixture()
def primary(tmp_path):
    events, metadata, models = _stores()
    changefeed = Changefeed(
        OpLog(str(tmp_path / "oplog")), events, metadata, models
    )
    server = StorageServer(
        "127.0.0.1", 0, events, metadata, models, changefeed=changefeed
    )
    server.start_background()
    yield server
    try:
        server.shutdown()
        server.server_close()
    except OSError:
        pass  # already killed by the test


@pytest.fixture()
def primary_url(primary):
    return f"http://127.0.0.1:{primary.bound_port}"


@pytest.fixture()
def replica(tmp_path, primary_url):
    events, metadata, models = _stores()
    server = StorageReplica(
        "127.0.0.1", 0, events, metadata, models, primary_url,
        str(tmp_path / "replica_state"), catchup_wait_s=0.0,
    )
    server.start_background()
    yield server
    try:
        server.shutdown()
        server.server_close()
    except OSError:
        pass


@pytest.fixture(autouse=True)
def _fresh_breakers(monkeypatch):
    # threshold 1: the first post-kill read trips the breaker and fails
    # over in-call — no wasted failures, no wall-clock cooldown waits
    monkeypatch.setenv("PIO_BREAKER_FAILURES", "1")
    remote.reset_resilience(clock=lambda: 0.0)
    yield
    remote.reset_resilience()


def _status(url: str) -> dict:
    with urllib.request.urlopen(f"{url}/status.json") as resp:
        return json.load(resp)


# -- method partition ------------------------------------------------------


def test_rpc_methods_partition_into_reads_and_mutations():
    assert METADATA_READ_METHODS | METADATA_MUTATING_METHODS == METADATA_RPC_METHODS
    assert not METADATA_READ_METHODS & METADATA_MUTATING_METHODS


# -- changefeed recording --------------------------------------------------


class TestChangefeed:
    def test_mutations_are_sequenced_and_resolved(self, primary, primary_url):
        store = remote.RemoteEventStore(primary_url)
        store.init(7)
        eid = store.insert(Event(event="rate", entity_type="u", entity_id="1"), 7)
        store.write([Event(event="rate", entity_type="u", entity_id=str(i))
                     for i in range(3)], 7)
        entries, last = primary.changefeed.oplog.read_since(0)
        assert [seq for seq, _ in entries] == list(range(1, last + 1))
        kinds = [op["kind"] for _, op in entries]
        assert kinds == ["event_init", "event_insert", "event_write"]
        # resolved: the insert op carries the acked id, batch events all
        # carry ids (replay must not re-mint random ids)
        assert entries[1][1]["event"]["eventId"] == eid
        assert all(d.get("eventId") for d in entries[2][1]["events"])

    def test_noop_mutations_are_not_logged(self, primary, primary_url):
        md = remote.RemoteMetadataStore(primary_url)
        app_id = md.app_insert(App(id=0, name="a"))
        before = primary.changefeed.last_seq
        assert md.app_insert(App(id=0, name="a")) is None  # duplicate
        assert md.app_delete(app_id + 99) is False  # no row
        assert primary.changefeed.last_seq == before

    def test_seq_header_on_writes(self, primary_url):
        body = json.dumps(
            {"event": "rate", "entityType": "u", "entityId": "1"}
        ).encode()
        with remote._request(f"{primary_url}/events/1", "POST", body) as resp:
            assert int(resp.getheader(SEQ_HEADER)) >= 1

    def test_gen_next_replays_idempotently(self, tmp_path):
        events, metadata, models = _stores()
        cf = Changefeed(OpLog(str(tmp_path / "log")), events, metadata, models)
        for _ in range(3):
            cf.metadata_rpc("gen_next", ["ids"])
        entries, _ = cf.oplog.read_since(0)
        r_events, r_md, r_models = _stores()
        for _, op in entries:
            apply_op(op, r_events, r_md, r_models)
        # re-apply the whole suffix: the advance-to semantics absorb it
        for _, op in entries:
            apply_op(op, r_events, r_md, r_models)
        assert r_md.gen_next("ids") == 4


# -- replica behavior ------------------------------------------------------


class TestReplica:
    def test_tails_all_three_stores(self, primary, primary_url, replica):
        es = remote.RemoteEventStore(primary_url)
        md = remote.RemoteMetadataStore(primary_url)
        ms = remote.RemoteModelStore(primary_url)
        es.init(1)
        eid = es.insert(Event(event="rate", entity_type="u", entity_id="1"), 1)
        app_id = md.app_insert(App(id=0, name="rep-app"))
        ms.insert(Model(id="m1", models=b"blob"))
        replica.catch_up()
        assert replica.applied_seq() == primary.changefeed.last_seq
        # read the replica's local stores through its own HTTP surface
        rurl = f"http://127.0.0.1:{replica.bound_port}"
        r_es = remote.RemoteEventStore(rurl)
        r_md = remote.RemoteMetadataStore(rurl)
        r_ms = remote.RemoteModelStore(rurl)
        assert r_es.get(eid, 1).event == "rate"
        assert r_md.app_get(app_id).name == "rep-app"
        assert r_ms.get("m1").models == b"blob"

    def test_replay_is_idempotent_after_progress_loss(
        self, primary, primary_url, replica
    ):
        es = remote.RemoteEventStore(primary_url)
        es.init(1)
        for i in range(5):
            es.insert(Event(event="rate", entity_type="u", entity_id=str(i)), 1)
        replica.catch_up()
        # simulate the crash window: progress marker lost, stores kept
        replica.tailer.applied_seq = 0
        replica.catch_up()  # re-applies everything
        flt_events = list(replica.events.find(1))
        assert len(flt_events) == 5  # upsert replay: no duplicates

    def test_rejects_writes_with_primary_hint(
        self, primary, primary_url, replica
    ):
        rurl = f"http://127.0.0.1:{replica.bound_port}"
        store = remote.RemoteEventStore(rurl)
        with pytest.raises(remote.RemoteStorageError) as err:
            store.insert(Event(event="x", entity_type="u", entity_id="1"), 1)
        assert err.value.code == 409
        assert primary_url in str(err.value)
        md = remote.RemoteMetadataStore(rurl)
        with pytest.raises(remote.RemoteStorageError) as err:
            md.app_insert(App(id=0, name="nope"))
        assert err.value.code == 409

    def test_min_seq_gate_wait_or_reject(self, primary, primary_url, replica):
        es = remote.RemoteEventStore(primary_url)
        es.init(1)
        eid = es.insert(Event(event="rate", entity_type="u", entity_id="1"), 1)
        acked = primary.changefeed.last_seq
        replica.catch_up()
        rurl = f"http://127.0.0.1:{replica.bound_port}"
        # satisfied token: served
        with remote._request(
            f"{rurl}/events/1/{eid}", headers={MIN_SEQ_HEADER: str(acked)}
        ) as resp:
            assert json.loads(resp.read())["eventId"] == eid
        # future token: 409 with the applied seq and primary hint
        with pytest.raises(remote.RemoteStorageError) as err:
            remote._request(
                f"{rurl}/events/1/{eid}",
                headers={MIN_SEQ_HEADER: str(acked + 10)},
            )
        assert err.value.code == 409

    def test_status_reports_lag(self, primary, primary_url, replica):
        es = remote.RemoteEventStore(primary_url)
        es.init(1)
        for i in range(3):
            es.insert(Event(event="rate", entity_type="u", entity_id=str(i)), 1)
        replica.step()  # observes primary seq while applying
        status = _status(f"http://127.0.0.1:{replica.bound_port}")
        assert status["role"] == "replica"
        assert status["appliedSeq"] == primary.changefeed.last_seq
        assert status["lag"] == 0
        assert _status(primary_url)["role"] == "primary"

    def test_generation_mismatch_stops_tailing(
        self, tmp_path, primary, primary_url, replica
    ):
        remote.RemoteEventStore(primary_url).init(1)
        replica.catch_up()
        # primary store replaced: new changefeed, new generation
        events, metadata, models = _stores()
        primary.changefeed = Changefeed(
            OpLog(str(tmp_path / "oplog2")), events, metadata, models
        )
        primary.events, primary.metadata, primary.models = (
            events, metadata, models,
        )
        remote.RemoteEventStore(primary_url).init(1)
        with pytest.raises(ReplicationError):
            replica.catch_up()

    def test_oplog_gap_is_loud(self, tmp_path):
        log = OpLog(str(tmp_path), base_seq=50)
        with pytest.raises(OpLogGap):
            log.read_since(10)

    def test_checkpoint_probe_answers_on_replica(
        self, primary, primary_url, replica
    ):
        """The HA client's freshness probe hits /replicate/checkpoint on
        REPLICAS — they must answer from applied state, not 404 (a 404
        would silently degrade failover to listed order)."""
        es = remote.RemoteEventStore(primary_url)
        es.init(1)
        replica.catch_up()
        rurl = f"http://127.0.0.1:{replica.bound_port}"
        with remote._request(f"{rurl}/replicate/checkpoint") as resp:
            ck = json.loads(resp.read())
        assert ck["seq"] == replica.applied_seq() == 1
        assert ck["generation"] == primary.changefeed.oplog.generation

    def test_primary_seq_rewind_is_loud(self, tmp_path, primary, primary_url, replica):
        """A primary whose history ends BEFORE the replica's applied seq
        (post-power-loss truncation under the same generation) must stop
        tailing with ReplicationError, never silently diverge."""
        es = remote.RemoteEventStore(primary_url)
        es.init(1)
        for i in range(4):
            es.insert(Event(event="rate", entity_type="u", entity_id=str(i)), 1)
        replica.catch_up()
        # rebuild the primary's oplog at the same generation, shorter
        generation = primary.changefeed.oplog.generation
        short = OpLog(str(tmp_path / "rewound"))
        short.generation = generation
        short.append({"kind": "event_init", "app": 1})
        primary.changefeed = Changefeed(
            short, primary.events, primary.metadata, primary.models
        )
        with pytest.raises(ReplicationError, match="rewound"):
            replica.step()


# -- client failover -------------------------------------------------------


class TestFailover:
    def _ha_store(self, primary, replica, timeout=10.0):
        return remote.RemoteEventStore(
            f"pio+ha://127.0.0.1:{primary.bound_port},"
            f"127.0.0.1:{replica.bound_port}",
            timeout=timeout,
        )

    def test_writes_ack_the_seq_token(self, primary, replica):
        store = self._ha_store(primary, replica)
        store.init(1)
        store.insert(Event(event="rate", entity_type="u", entity_id="1"), 1)
        assert store._ep.token.last == primary.changefeed.last_seq

    def test_seq_token_shared_across_store_kinds(self, primary, replica):
        ha = (
            f"pio+ha://127.0.0.1:{primary.bound_port},"
            f"127.0.0.1:{replica.bound_port}"
        )
        es = remote.RemoteEventStore(ha)
        ms = remote.RemoteModelStore(ha)
        es.init(1)
        ms.insert(Model(id="m", models=b"x"))
        assert es._ep.token.last == ms._ep.token.last == 2

    def test_chaos_kill_primary_promote_replica(self, primary, replica):
        """The acceptance-criteria chaos proof: primary hard-killed
        mid-stream, every previously-acked event/metadata/model read is
        served by the (then promoted) replica with correct seq-token
        semantics."""
        ha = (
            f"pio+ha://127.0.0.1:{primary.bound_port},"
            f"127.0.0.1:{replica.bound_port}"
        )
        es = remote.RemoteEventStore(ha, timeout=10.0)
        md = remote.RemoteMetadataStore(ha, timeout=10.0)
        ms = remote.RemoteModelStore(ha, timeout=10.0)
        es.init(1)
        acked_ids = [
            es.insert(Event(event="rate", entity_type="u",
                            entity_id=str(i)), 1)
            for i in range(10)
        ]
        app_id = md.app_insert(App(id=0, name="chaos-app"))
        ms.insert(Model(id="m1", models=b"weights"))
        acked_seq = es._ep.token.last
        assert acked_seq == primary.changefeed.last_seq
        replica.catch_up()

        primary.kill()  # hard kill: live connections severed

        # every acked read is served via failover, carrying the token
        for eid in acked_ids:
            got = es.get(eid, 1)
            assert got is not None and got.event_id == eid
        assert md.app_get(app_id).name == "chaos-app"
        assert ms.get("m1").models == b"weights"
        # correctness of the gate itself: a token beyond anything acked
        # is rejected, not silently served stale
        rurl = f"http://127.0.0.1:{replica.bound_port}"
        with pytest.raises(remote.RemoteStorageError) as err:
            remote._request(
                f"{rurl}/events/1/{acked_ids[0]}",
                headers={MIN_SEQ_HEADER: str(acked_seq + 1)},
            )
        assert err.value.code == 409

        # promote: numbering continues, writes flow again
        status = replica.promote()
        assert status["role"] == "primary"
        assert status["seq"] == acked_seq
        promoted = remote.RemoteEventStore(rurl, timeout=10.0)
        new_id = promoted.insert(
            Event(event="rate", entity_type="u", entity_id="post"), 1
        )
        assert promoted.get(new_id, 1) is not None
        assert replica.changefeed.last_seq == acked_seq + 1
        # the promoted node satisfies the old token on its own now
        with remote._request(
            f"{rurl}/events/1/{new_id}",
            headers={MIN_SEQ_HEADER: str(acked_seq + 1)},
        ) as resp:
            assert json.loads(resp.read())["eventId"] == new_id

    def test_tokenless_client_fails_over_to_freshest(
        self, tmp_path, primary, primary_url, replica
    ):
        """A client with NO acked writes (seq token 0, so no min-seq
        header protects it) must still reach the caught-up replica:
        the checkpoint-probe ordering alone has to pick freshest-first,
        even with the lagging replica listed before it."""
        events, metadata, models = _stores()
        stale = StorageReplica(
            "127.0.0.1", 0, events, metadata, models, primary_url,
            str(tmp_path / "stale2_state"), catchup_wait_s=0.0,
        )
        stale.start_background()
        try:
            writer = remote.RemoteEventStore(primary_url)
            writer.init(1)
            eid = writer.insert(
                Event(event="rate", entity_type="u", entity_id="1"), 1
            )
            replica.catch_up()  # fresh one caught up; stale stays at 0
            # reader process analogue: fresh endpoints object, token 0
            reader = remote.RemoteEventStore(
                f"pio+ha://127.0.0.1:{primary.bound_port},"
                f"127.0.0.1:{stale.bound_port},"
                f"127.0.0.1:{replica.bound_port}",
                timeout=10.0,
            )
            assert reader._ep.token.last == 0
            primary.kill()
            got = reader.get(eid, 1)
            assert got is not None and got.event_id == eid
        finally:
            stale.kill()

    def test_behind_replica_skipped_for_fresher_one(
        self, tmp_path, primary, primary_url, replica
    ):
        """Two replicas, one lagging: failover must pick the fresh one
        (checkpoint probe ordering + min-seq rejection both protect)."""
        events, metadata, models = _stores()
        stale = StorageReplica(
            "127.0.0.1", 0, events, metadata, models, primary_url,
            str(tmp_path / "stale_state"), catchup_wait_s=0.0,
        )
        stale.start_background()
        try:
            ha = (
                f"pio+ha://127.0.0.1:{primary.bound_port},"
                f"127.0.0.1:{stale.bound_port},"
                f"127.0.0.1:{replica.bound_port}"
            )
            es = remote.RemoteEventStore(ha, timeout=10.0)
            es.init(1)
            eid = es.insert(
                Event(event="rate", entity_type="u", entity_id="1"), 1
            )
            replica.catch_up()  # fresh replica caught up; stale did not
            primary.kill()
            got = es.get(eid, 1)
            assert got is not None and got.event_id == eid
        finally:
            stale.shutdown()
            stale.server_close()

    def test_loadgen_chaos_scenario(self, tmp_path):
        from predictionio_tpu.tools.loadgen import run_storage_chaos

        report = run_storage_chaos(
            total_ops=40, kill_at=20, state_root=str(tmp_path / "chaos")
        )
        assert report["failedReads"] == 0
        assert report["lostAckedWrites"] == 0
        assert report["postPromoteWriteOk"] is True
        assert report["ackedWrites"] == 20


# -- HA URL parsing --------------------------------------------------------


class TestHAConfig:
    def test_split_endpoints(self):
        urls = remote._split_endpoints("pio+ha://a:1, b:2 ,http://c:3/")
        assert urls == ["http://a:1", "http://b:2", "http://c:3"]
        assert remote._split_endpoints("http://x:9") == ["http://x:9"]

    def test_base_url_conf_forms(self):
        assert remote._base_url({"url": "pio+ha://a:1,b:2"}) == "pio+ha://a:1,b:2"
        assert remote._base_url({"nodes": "a:1,b:2"}) == "pio+ha://a:1,b:2"
        assert remote._base_url({"host": "h", "port": "99"}) == "http://h:99"

    def test_empty_ha_url_rejected(self):
        with pytest.raises(remote.RemoteStorageError):
            remote._split_endpoints("pio+ha://")
