"""Deviceless Mosaic validation of every Pallas kernel (VERDICT r4 item 3).

``jax.experimental.topologies.get_topology_desc`` builds a compile-only
TPU topology from libtpu with NO device attached (works with the
accelerator tunnel down), and ``jit(fn).lower(avals).compile()`` against
its devices runs the full XLA:TPU + Mosaic pipeline. These tests convert
the single worst hardware-day risk — a Mosaic lowering error discovered
mid-window — into an offline check that runs in the ordinary CPU suite.

The argument-format key (the round-4 probe failed here):
``chips_per_host_bounds`` must be a TUPLE OF INTS, e.g. ``(1, 1, 1)``;
string forms are rejected by libtpu with a mangled type error.

On landing day this file's compiles found two real bugs in
``gramian_fused`` that interpret-mode equality testing could not see:
a 1×56 row-slice DMA violating the 128-lane tiling, and a 1-D→2-D
shape cast unsupported for bf16 vectors (see ops/pallas_kernels.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# deviceless AOT compile of every Pallas kernel: minutes of XLA/Mosaic work
pytestmark = pytest.mark.slow

from predictionio_tpu.ops.attention import flash_attention_pallas
from predictionio_tpu.ops.pallas_kernels import (
    gramian_fused,
    spd_solve_t,
    top_k_streaming,
)


def _topology(name: str, **kwargs):
    """Deviceless topology or skip — the lockfile retry lives in the
    shared helper (a concurrent watcher probe or prewarm run holds
    libtpu's machine-wide lockfile transiently)."""
    from predictionio_tpu.utils.topology import get_deviceless_topology

    try:
        return get_deviceless_topology(name, **kwargs)
    except Exception as exc:  # no libtpu, or sustained contention
        pytest.skip(f"deviceless TPU topology unavailable: {exc}")


@pytest.fixture(scope="module")
def topo1():
    return _topology("v5e:1x1", chips_per_host_bounds=(1, 1, 1))


def _sds(topo, shape, dtype):
    from jax.sharding import SingleDeviceSharding

    return jax.ShapeDtypeStruct(
        shape, dtype, sharding=SingleDeviceSharding(topo.devices[0])
    )


def _compile(fn, *avals):
    compiled = jax.jit(fn).lower(*avals).compile()
    assert compiled.memory_analysis().generated_code_size_in_bytes > 0
    return compiled


class TestMosaicAOT:
    def test_spd_solve_single_device(self, topo1):
        _compile(
            functools.partial(spd_solve_t, interpret=False),
            _sds(topo1, (56, 56, 512), jnp.float32),
            _sds(topo1, (56, 512), jnp.float32),
        )

    def test_spd_solve_under_shard_map(self):
        # the exact embedding ops/als.py uses under a mesh: per-device
        # pallas blocks inside shard_map, compiled for a 4-chip slice
        from jax.experimental import topologies

        from predictionio_tpu.parallel.collectives import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        topo4 = _topology("v5e:2x2")
        mesh = topologies.make_mesh(topo4, (4,), ("data",))
        ns = NamedSharding(mesh, P("data"))
        fn = shard_map(
            functools.partial(spd_solve_t, interpret=False), mesh=mesh,
            in_specs=(P("data"), P("data")), out_specs=P("data"),
            check_vma=False,
        )
        compiled = jax.jit(fn).lower(
            jax.ShapeDtypeStruct((4 * 56, 56, 512), jnp.float32, sharding=ns),
            jax.ShapeDtypeStruct((4 * 56, 512), jnp.float32, sharding=ns),
        ).compile()
        assert compiled.memory_analysis().generated_code_size_in_bytes > 0

    @pytest.mark.parametrize(
        "n,b,k",
        [
            (27_000, 4, 8192),   # bench-realistic wide bucket, SMEM cap
            (300, 32, 512),      # small table (VMEM-resident y)
            (200, 2, 32_768),    # K-slice split path
        ],
    )
    def test_gramian_fused_f32(self, topo1, n, b, k):
        _compile(
            functools.partial(gramian_fused, interpret=False),
            _sds(topo1, (n, 56), jnp.float32),
            _sds(topo1, (b, k), jnp.int32),
            _sds(topo1, (b, k), jnp.float32),
            _sds(topo1, (b, k), jnp.float32),
            _sds(topo1, (b,), jnp.float32),
        )

    def test_gramian_fused_bf16_table(self, topo1):
        # bf16 tables upcast inside the kernel entry (per-row DMA floor
        # is 128 lanes × 32 bits); the flag combination must still lower
        _compile(
            functools.partial(gramian_fused, interpret=False),
            _sds(topo1, (27_000, 56), jnp.bfloat16),
            _sds(topo1, (4, 8192), jnp.int32),
            _sds(topo1, (4, 8192), jnp.float32),
            _sds(topo1, (4, 8192), jnp.float32),
            _sds(topo1, (4,), jnp.float32),
        )

    def test_flash_attention_forward(self, topo1):
        _compile(
            functools.partial(
                flash_attention_pallas, causal=True, interpret=False
            ),
            _sds(topo1, (2, 8, 1024, 64), jnp.float32),
            _sds(topo1, (2, 8, 1024, 64), jnp.float32),
            _sds(topo1, (2, 8, 1024, 64), jnp.float32),
        )

    def test_flash_attention_grad(self, topo1):
        def loss(q, k, v):
            return flash_attention_pallas(
                q, k, v, causal=True, interpret=False
            ).sum()

        _compile(
            jax.grad(loss, argnums=(0, 1, 2)),
            _sds(topo1, (2, 4, 512, 64), jnp.float32),
            _sds(topo1, (2, 4, 512, 64), jnp.float32),
            _sds(topo1, (2, 4, 512, 64), jnp.float32),
        )

    def test_top_k_streaming(self, topo1):
        _compile(
            functools.partial(top_k_streaming, k=10, interpret=False),
            _sds(topo1, (512, 50), jnp.float32),
            _sds(topo1, (60_000, 50), jnp.float32),
        )

    def test_top_k_streaming_with_exclusions(self, topo1):
        # the similarproduct/ecommerce serving path: seen/blacklisted
        # items masked inside the kernel — a distinct program from the
        # plain top-k (extra SMEM block + compare loop)
        def with_excl(q, items, excl):
            return top_k_streaming(q, items, 10, exclude_idx=excl,
                                   interpret=False)

        _compile(
            with_excl,
            _sds(topo1, (512, 50), jnp.float32),
            _sds(topo1, (60_000, 50), jnp.float32),
            _sds(topo1, (512, 64), jnp.int32),
        )

    def test_flash_attention_bf16(self, topo1):
        _compile(
            functools.partial(
                flash_attention_pallas, causal=True, interpret=False
            ),
            _sds(topo1, (2, 4, 512, 64), jnp.bfloat16),
            _sds(topo1, (2, 4, 512, 64), jnp.bfloat16),
            _sds(topo1, (2, 4, 512, 64), jnp.bfloat16),
        )

    def test_gramian_fused_implicit_yty(self, topo1):
        # implicit mode (similarproduct's training): the yty base term
        # rides into the kernel — a distinct program from the explicit
        # yty=None path the other fused tests cover
        def with_yty(y, idx, w2, rhs, ridge, yty):
            return gramian_fused(y, idx, w2, rhs, ridge, yty=yty,
                                 interpret=False)

        _compile(
            with_yty,
            _sds(topo1, (27_000, 56), jnp.float32),
            _sds(topo1, (4, 8192), jnp.int32),
            _sds(topo1, (4, 8192), jnp.float32),
            _sds(topo1, (4, 8192), jnp.float32),
            _sds(topo1, (4,), jnp.float32),
            _sds(topo1, (56, 56), jnp.float32),
        )

    def test_implicit_als_iteration(self, topo1):
        # the full implicit-mode training program (Hu-Koren confidence
        # weighting: YᵀY einsums + c−1 gramian weights) at moderate
        # shapes with the pallas solver — what the implicit_gate queue
        # step will run on hardware
        from jax.sharding import SingleDeviceSharding

        from predictionio_tpu.ops import als
        from predictionio_tpu.tools.prewarm_cache import _stage_avals

        rng = np.random.default_rng(2)
        n_u, n_i, nnz = 2_000, 500, 40_000
        u = rng.integers(0, n_u, nnz)
        i = rng.integers(0, n_i, nnz)
        v = rng.integers(1, 5, nnz).astype(np.float32)
        bu = als.bucketize(u, i, v, n_u, n_i, pad_to_blocks=True)
        bi = als.bucketize(i, u, v, n_i, n_u, pad_to_blocks=True)
        sh = SingleDeviceSharding(topo1.devices[0])
        compiled = als._als_iteration.lower(
            _stage_avals(bu, sh), _stage_avals(bi, sh),
            jax.ShapeDtypeStruct((n_i, 32), jnp.float32, sharding=sh),
            jax.ShapeDtypeStruct((), jnp.float32, sharding=sh),
            jax.ShapeDtypeStruct((), jnp.float32, sharding=sh),
            n_users=n_u, n_items=n_i, rank=32, implicit=True,
            solve_mode="pallas", gather_dtype="f32", mesh=None,
            fused_gather=False,
        ).compile()
        assert compiled.memory_analysis().generated_code_size_in_bytes > 0
