"""Query server tests: deploy lifecycle + REST surface.

Covers the behaviors of ``CreateServer.scala``: latest-completed instance
selection, query decode → multi-algo predict → serving combine, the
``/reload`` hot swap, ``/stop``, the status page bookkeeping
(``:567-574``) and the feedback loop with prId stamping (``:505-565``).
"""

import time

import pytest
import requests

from predictionio_tpu.api import EventServer, EventServerConfig
from predictionio_tpu.controller import WorkflowParams
from predictionio_tpu.storage import (
    AccessKey,
    App,
    EventFilter,
    StorageRegistry,
)
from predictionio_tpu.workflow.core_workflow import run_train
from predictionio_tpu.workflow.serving import (
    QueryServer,
    ServerConfig,
    decode_query,
    encode_result,
    prepare_deployment,
)

from sample_engine import Query, reset_all_counts
from test_engine import make_engine, make_params


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()


@pytest.fixture()
def registry(tmp_path):
    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})


class TypedQueryAlgoMixin:
    def query_class(self):
        return Query


def _typed_engine():
    from sample_engine import Algo0, DataSource0, Preparator0, Serving0
    from predictionio_tpu.controller import Engine

    class TypedAlgo(TypedQueryAlgoMixin, Algo0):
        count = 0

    return Engine(
        {"": DataSource0},
        {"": Preparator0},
        {"": TypedAlgo, "second": TypedAlgo},
        {"": Serving0},
    )


def _train(registry, engine, algo_ids=(11,)):
    params = make_params(algo_ids=algo_ids)
    if len(algo_ids) > 1:
        import dataclasses as dc
        from sample_engine import IdParams

        params = dc.replace(
            params,
            algorithm_params_list=[
                ("" if i == 0 else "second", IdParams(id=a))
                for i, a in enumerate(algo_ids)
            ],
        )
    return run_train(
        engine, params, registry, engine_id="default", engine_version="1",
        workflow_params=WorkflowParams(batch="deploy-test"),
    )


@pytest.fixture()
def server(registry):
    engine = _typed_engine()
    _train(registry, engine, algo_ids=(11, 13))
    srv = QueryServer(
        ServerConfig(ip="127.0.0.1", port=0), engine, registry
    )
    srv.start_background()
    yield f"http://127.0.0.1:{srv.bound_port}", srv, registry, engine
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:
        pass


def test_prepare_deployment_picks_latest_completed(registry):
    engine = make_engine()
    _train(registry, engine)
    second = _train(registry, engine)
    dep = prepare_deployment(engine, registry, ServerConfig())
    assert dep.instance.id == second


def test_prepare_deployment_no_instance_raises(registry):
    with pytest.raises(RuntimeError, match="No completed engine instance"):
        prepare_deployment(make_engine(), registry, ServerConfig())


def test_query_roundtrip(server):
    base, srv, _, _ = server
    r = requests.post(f"{base}/queries.json", json={"id": 42})
    assert r.status_code == 200
    body = r.json()
    # Serving0 combines both algos' predictions
    assert body["combined"] == [11, 13]
    assert body["query"]["id"] == 42
    assert srv.request_count == 1
    assert srv.avg_serving_sec > 0


def test_query_malformed_json_400(server):
    base, _, _, _ = server
    r = requests.post(
        f"{base}/queries.json",
        data="{nope",
        headers={"Content-Type": "application/json"},
    )
    assert r.status_code == 400


def test_status_page(server):
    base, _, _, _ = server
    requests.post(f"{base}/queries.json", json={"id": 1})
    r = requests.get(f"{base}/")
    assert r.status_code == 200
    assert "Engine Server" in r.text
    assert "Request count" in r.text


def test_status_json_reports_resolved_topk_path(server):
    """/status.json surfaces each algorithm's RESOLVED serving top-k
    path ("streaming" | "dense") once it has served — the serve-side
    lever record (docs/performance.md#levers). Sample-engine algos
    don't expose one, so the block is absent here; an algo that does is
    picked up by name."""
    base, srv, _, _ = server
    requests.post(f"{base}/queries.json", json={"id": 1})
    doc = requests.get(f"{base}/status.json").json()
    assert "topkPath" not in doc  # sample algos carry no topk_path
    # graft a reporting algorithm in: the server reads the attribute
    srv.deployment.algorithms[0].topk_path = "dense"
    try:
        doc = requests.get(f"{base}/status.json").json()
        key = f"0:{type(srv.deployment.algorithms[0]).__name__}"
        assert doc["topkPath"] == {key: "dense"}
    finally:
        del srv.deployment.algorithms[0].topk_path


def test_reload_hot_swaps_to_latest(server):
    base, srv, registry, engine = server
    old_id = srv.deployment.instance.id
    new_id = _train(registry, engine, algo_ids=(11, 13))
    assert new_id != old_id
    r = requests.get(f"{base}/reload")
    assert r.status_code == 200
    assert srv.deployment.instance.id == new_id
    # still serves correctly after the swap
    r = requests.post(f"{base}/queries.json", json={"id": 7})
    assert r.status_code == 200


def test_reload_under_traffic(server):
    """Hot swap while queries are in flight: the micro-batcher may see a
    batch mixing deployments across the swap — the mixed-generation
    grouping in ``QueryServer._predict_batch`` must route every query to
    its own deployment and none may error (``GET /reload`` parity with
    the MasterActor swap, ``CreateServer.scala:250-372``)."""
    import threading

    base, srv, registry, engine = server
    stop = threading.Event()
    failures = []
    ok = [0]

    def hammer():
        while not stop.is_set():
            try:
                r = requests.post(f"{base}/queries.json", json={"id": 3},
                                  timeout=10)
                if r.status_code != 200 or r.json()["combined"] != [11, 13]:
                    failures.append(r.text[:200])
                else:
                    ok[0] += 1
            except Exception as exc:
                failures.append(repr(exc))

    workers = [threading.Thread(target=hammer) for _ in range(8)]
    for w in workers:
        w.start()
    try:
        for _ in range(3):  # three hot swaps under load
            new_id = _train(registry, engine, algo_ids=(11, 13))
            r = requests.get(f"{base}/reload", timeout=30)
            assert r.status_code == 200
            assert srv.deployment.instance.id == new_id
            time.sleep(0.2)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=30)
    assert not failures, failures[:3]
    assert ok[0] > 20  # real traffic flowed throughout


def test_repeated_reloads_drop_retired_model_references(server):
    """Swapping a ``Deployment`` must drop every server-side reference
    to the retired models so device buffers are reclaimable — a leak
    here grows resident HBM by one model table per retrain forever
    (docs/rollouts.md teardown contract)."""
    import gc
    import weakref

    base, srv, registry, engine = server
    retired = []
    for _ in range(3):
        retired.append(weakref.ref(srv.deployment.models[0]))
        _train(registry, engine, algo_ids=(11, 13))
        r = requests.post(f"{base}/reload")
        assert r.status_code == 200
    gc.collect()
    assert [ref() for ref in retired] == [None, None, None]


def test_stop_shuts_down(server):
    base, srv, _, _ = server
    r = requests.get(f"{base}/stop")
    assert r.status_code == 200
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            requests.get(f"{base}/", timeout=0.2)
            time.sleep(0.05)
        except (requests.ConnectionError, requests.Timeout):
            break
    else:
        pytest.fail("server did not shut down")


def test_feedback_loop(registry, tmp_path):
    # stand up an event server to receive feedback
    md = registry.get_metadata()
    app_id = md.app_insert(App(id=0, name="fbapp"))
    md.access_key_insert(AccessKey(key="FBKEY", appid=app_id, events=[]))
    registry.get_events().init(app_id)
    ev_srv = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, stats=False),
        registry.get_events(),
        md,
    )
    ev_srv.start_background()

    engine = _typed_engine()
    _train(registry, engine)
    q_srv = QueryServer(
        ServerConfig(
            ip="127.0.0.1",
            port=0,
            feedback=True,
            event_server_ip="127.0.0.1",
            event_server_port=ev_srv.bound_port,
            access_key="FBKEY",
        ),
        engine,
        registry,
    )
    q_srv.start_background()
    try:
        base = f"http://127.0.0.1:{q_srv.bound_port}"
        r = requests.post(f"{base}/queries.json", json={"id": 5})
        assert r.status_code == 200
        deadline = time.time() + 5
        events = []
        while time.time() < deadline and not events:
            events = list(
                registry.get_events().find(
                    app_id, EventFilter(event_names=["predict"])
                )
            )
            time.sleep(0.05)
        assert len(events) == 1
        fb = events[0]
        assert fb.entity_type == "pio_pr"
        assert len(fb.entity_id) == 64
        assert fb.properties.get("query")["id"] == 5
        assert fb.properties.get("prediction")["combined"] == [11]
    finally:
        q_srv.shutdown()
        q_srv.server_close()
        ev_srv.shutdown()
        ev_srv.server_close()


def test_decode_query_typed_and_untyped():
    class A:
        def query_class(self):
            return Query

    assert decode_query([A()], {"id": 9}) == Query(id=9)

    class B:
        def query_class(self):
            return None

    assert decode_query([B()], {"x": 1}) == {"x": 1}


def test_encode_result_nested():
    import dataclasses

    @dataclasses.dataclass
    class Inner:
        v: int

    @dataclasses.dataclass
    class Outer:
        inner: Inner
        xs: tuple

    import numpy as np

    assert encode_result(Outer(Inner(3), (1, np.float32(2.5)))) == {
        "inner": {"v": 3},
        "xs": [1, 2.5],
    }


def test_error_log_posted_to_log_url(registry):
    """Serving failures POST to --log-url (CreateServer.scala:409-420)."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    received = []
    got_one = threading.Event()

    class Sink(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received.append(_json.loads(body))
            got_one.set()
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    sink = ThreadingHTTPServer(("127.0.0.1", 0), Sink)
    threading.Thread(target=sink.serve_forever, daemon=True).start()

    engine = _typed_engine()
    _train(registry, engine, algo_ids=(11, 13))
    srv = QueryServer(
        ServerConfig(
            ip="127.0.0.1", port=0,
            log_url=f"http://127.0.0.1:{sink.server_address[1]}/log",
        ),
        engine, registry,
    )
    srv.start_background()
    base = f"http://127.0.0.1:{srv.bound_port}"
    try:
        # Serving0 raises on a poison query marker → 500 → error log POST
        import unittest.mock as mock

        with mock.patch.object(
            srv.deployment.serving, "serve",
            side_effect=RuntimeError("boom-for-log"),
        ):
            r = requests.post(f"{base}/queries.json", json={"id": 1})
        assert r.status_code == 500
        assert got_one.wait(timeout=10)
        assert received[0]["message"] == "boom-for-log"
        assert received[0]["query"] == {"id": 1}
    finally:
        srv.shutdown()
        srv.server_close()
        sink.shutdown()
        sink.server_close()
