"""Deviceless AOT compile of the MULTI-CHIP programs for real v5e
topologies.

``__graft_entry__.dryrun_multichip`` proves the sharded programs execute
on a virtual CPU mesh; these tests close the other half of the claim:
the same programs COMPILE for actual TPU hardware topologies — XLA
collectives over ICI, Mosaic kernels embedded per-device via shard_map —
using compile-only v5e topologies (2×2 for the distributed-ALS mesh,
2×4 for the 8-way sequence-parallel ring). No device or tunnel needed;
see tests/test_mosaic_aot.py for the single-chip kernel equivalents.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# multi-chip/multi-slice AOT compiles: minutes of XLA/Mosaic work
pytestmark = pytest.mark.slow

from predictionio_tpu.ops import als
from predictionio_tpu.ops.attention import ring_attention, ulysses_attention
from predictionio_tpu.tools.prewarm_cache import _stage_avals


def _mesh(topo_name, shape, names, **topo_kwargs):
    # skip-wrapper duplicated from test_mosaic_aot rather than imported:
    # cross-importing a test module double-executes it under two module
    # identities (tests/ is a namespace package)
    from jax.experimental import topologies

    from predictionio_tpu.utils.topology import get_deviceless_topology

    try:
        topo = get_deviceless_topology(topo_name, **topo_kwargs)
    except Exception as exc:
        pytest.skip(f"deviceless TPU topology unavailable: {exc}")
    return topologies.make_mesh(topo, shape, names)


class TestDistributedALSCompile:
    """One full sharded ALS iteration on a data×model v5e 2×2 mesh —
    solve rows over ``data``, factor tables over ``model`` (the
    production distributed path of ``ops/als.py:als_train``)."""

    @pytest.fixture(scope="class")
    def problem(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh("v5e:2x2", (2, 2), ("data", "model"))
        rng = np.random.default_rng(0)
        rows_u, rows_i, nnz = 64, 32, 2048
        u = rng.integers(0, rows_u, nnz)
        i = rng.integers(0, rows_i, nnz)
        v = rng.normal(3.5, 1.0, nnz).astype(np.float32)
        bu = als.bucketize(u, i, v, rows_u, rows_i, pad_to_blocks=True)
        bi = als.bucketize(i, u, v, rows_i, rows_u, pad_to_blocks=True)
        row_sh = NamedSharding(mesh, P(None, "data"))
        tbl = NamedSharding(mesh, P("model"))
        return dict(
            mesh=mesh,
            tbl=tbl,
            ub=_stage_avals(bu, row_sh, row_multiple=2),
            ib=_stage_avals(bi, row_sh, row_multiple=2),
            y=jax.ShapeDtypeStruct((rows_i, 8), jnp.float32, sharding=tbl),
            s=jax.ShapeDtypeStruct((), jnp.float32,
                                   sharding=NamedSharding(mesh, P())),
            rows=(rows_u, rows_i),
        )

    @pytest.mark.parametrize(
        "solve_mode,fused",
        [("chunked", False), ("pallas", False), ("pallas", True)],
        ids=["xla-collectives", "pallas-shard_map", "fused-shard_map"],
    )
    def test_sharded_iteration_compiles(self, problem, solve_mode, fused):
        rows_u, rows_i = problem["rows"]
        it = als._als_iteration_sharded(problem["tbl"])
        compiled = it.lower(
            problem["ub"], problem["ib"], problem["y"],
            problem["s"], problem["s"],
            n_users=rows_u, n_items=rows_i, rank=8, implicit=False,
            solve_mode=solve_mode, gather_dtype="f32",
            mesh=problem["mesh"] if solve_mode == "pallas" else None,
            fused_gather=fused,
        ).compile()
        assert compiled.memory_analysis().generated_code_size_in_bytes > 0


class TestMultiSliceCompile:
    """The multi-HOST analogue: programs spanning TWO v5e slices (4
    chips each), where cross-slice collectives ride DCN and intra-slice
    ones ride ICI — the reference's NCCL/MPI-backend scaling story
    (SURVEY §2.8 collective-communication row), compiled for real
    topology. ``num_slices`` builds the deviceless 2-slice system."""

    @pytest.fixture(scope="class")
    def mesh8(self):
        mesh = _mesh("v5e:2x2", (8,), ("data",), num_slices=2)
        slices = {getattr(d, "slice_index", 0) for d in
                  mesh.devices.flat}
        assert slices == {0, 1}, slices
        return mesh

    def test_als_data_parallel_across_slices(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.default_rng(1)
        rows_u, rows_i, nnz = 128, 64, 4096
        u = rng.integers(0, rows_u, nnz)
        i = rng.integers(0, rows_i, nnz)
        v = rng.normal(3.5, 1.0, nnz).astype(np.float32)
        bu = als.bucketize(u, i, v, rows_u, rows_i, pad_to_blocks=True)
        bi = als.bucketize(i, u, v, rows_i, rows_u, pad_to_blocks=True)
        row_sh = NamedSharding(mesh8, P(None, "data"))
        rep = NamedSharding(mesh8, P())
        it = als._als_iteration_sharded(rep)
        compiled = it.lower(
            _stage_avals(bu, row_sh, row_multiple=8),
            _stage_avals(bi, row_sh, row_multiple=8),
            jax.ShapeDtypeStruct((rows_i, 8), jnp.float32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.float32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.float32, sharding=rep),
            n_users=rows_u, n_items=rows_i, rank=8, implicit=False,
            solve_mode="chunked", gather_dtype="f32", mesh=None,
            fused_gather=False,
        ).compile()
        assert compiled.memory_analysis().generated_code_size_in_bytes > 0

    def test_ring_attention_across_slices(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh8, P(None, None, "data", None))
        av = jax.ShapeDtypeStruct((1, 4, 8 * 256, 32), jnp.float32,
                                  sharding=sh)
        jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh=mesh8, axis="data", causal=True
            )
        ).lower(av, av, av).compile()


class TestSequenceParallelCompile:
    """Ring and Ulysses attention — forward and gradient — over an
    8-chip ``seq`` axis (v5e 2×4): ppermute / all-to-all ride ICI."""

    @pytest.fixture(scope="class")
    def setup(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh("v5e:2x4", (8,), ("seq",))
        sh = NamedSharding(mesh, P(None, None, "seq", None))
        av = jax.ShapeDtypeStruct((2, 8, 8 * 512, 64), jnp.float32,
                                  sharding=sh)
        return mesh, av

    @pytest.mark.parametrize("impl", [ring_attention, ulysses_attention],
                             ids=["ring", "ulysses"])
    def test_forward_compiles(self, setup, impl):
        mesh, av = setup
        f = functools.partial(impl, mesh=mesh, causal=True)
        compiled = jax.jit(
            lambda q, k, v: f(q, k, v)
        ).lower(av, av, av).compile()
        assert compiled.memory_analysis().generated_code_size_in_bytes > 0

    @pytest.mark.parametrize("impl", [ring_attention, ulysses_attention],
                             ids=["ring", "ulysses"])
    def test_grad_compiles(self, setup, impl):
        mesh, av = setup

        def loss(q, k, v):
            return impl(
                q, k, v, mesh=mesh, causal=True
            ).astype(jnp.float32).sum()

        jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
            av, av, av
        ).compile()
