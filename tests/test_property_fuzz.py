"""Randomized property tests for the two correctness-critical folds.

1. The native ratings scan (C++ JSON walker + id interner,
   ``native/ratings.cc``) must agree with the pure-Python streaming path
   on arbitrary ids/properties — exercised over randomized unicode ids,
   escapes, rating values, and event mixes.
2. The $set/$unset/$delete aggregation monoid (``storage/aggregator.py``)
   must agree with a brute-force sequential interpreter over random event
   sequences (the reference pins these semantics in
   ``PEventAggregator.scala:87-188``).
"""

import datetime as dt
import random
import string

import numpy as np
import pytest

from predictionio_tpu.storage.aggregator import aggregate_properties
from predictionio_tpu.storage.event import Event

UTC = dt.timezone.utc


# -- 1. native ratings scan vs python path --------------------------------

_ID_ALPHABET = (
    string.ascii_letters + string.digits + ' _-./"\\\t\n' + "ñüß€🎉中"
)


def _rand_id(rng: random.Random) -> str:
    n = rng.randint(1, 24)
    return "".join(rng.choice(_ID_ALPHABET) for _ in range(n)) or "x"


def test_native_ratings_scan_fuzz_matches_python(tmp_path):
    from predictionio_tpu.native import NativeBuildError
    from predictionio_tpu.workflow.infeed import stream_ratings

    try:
        from predictionio_tpu.storage.native_events import NativeEventStore

        store = NativeEventStore(str(tmp_path / "ev"))
    except NativeBuildError as exc:
        pytest.skip(f"native event log unavailable: {exc}")
    store.init(1)

    rng = random.Random(42)
    users = [_rand_id(rng) for _ in range(40)]
    items = [_rand_id(rng) for _ in range(15)]
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    n = 400
    for j in range(n):
        ev_name = rng.choice(["rate", "rate", "rate", "buy"])
        props = {}
        if ev_name == "rate":
            props["rating"] = rng.choice(
                [0.5, 1.0, 2.5, 4.999, 1e-3, 123456.75, -2.25]
            )
            if rng.random() < 0.3:  # extra properties must be skipped over
                props["note"] = _rand_id(rng)
                props["nested"] = {"a": [1, {"b": _rand_id(rng)}]}
        store.insert(
            Event(
                event=ev_name,
                entity_type="user",
                entity_id=rng.choice(users),
                target_entity_type="item",
                target_entity_id=rng.choice(items),
                properties=props,
                event_time=t0 + dt.timedelta(seconds=j),
            ),
            1,
        )
    # a few deletions to exercise the tombstone-aware header walk
    all_events = list(store.find(1))
    for e in rng.sample(all_events, 10):
        store.delete(e.event_id, 1)

    rules = {"rate": "rating", "buy": 4.0}
    fast = stream_ratings(store, 1, rules)  # native path

    seen = []

    def grab(u, i, v):
        seen.append(len(u))

    slow = stream_ratings(store, 1, rules, chunk_rows=37, on_chunk=grab)

    assert np.array_equal(fast.users, slow.users)
    assert np.array_equal(fast.items, slow.items)
    assert np.array_equal(fast.ratings, slow.ratings)
    assert fast.user_map == slow.user_map
    assert fast.item_map == slow.item_map
    assert len(fast.users) == n - 10
    assert sum(seen) == n - 10


# -- 2. aggregation monoid vs brute force ---------------------------------


def _brute_force(events):
    """Sequential interpreter of the reference's special-event semantics:
    later event time wins per field; $unset removes a field; $delete
    removes the entity (a later $set recreates it). An entity whose
    fields were all $unset still EXISTS with an empty property map —
    ``toPropertyMap`` only yields None for never-$set or deleted entities
    (``PEventAggregator.scala:115-146``)."""
    state = {}  # entity -> fields dict (present = entity exists)
    for e in sorted(events, key=lambda e: e.event_time):
        ent = e.entity_id
        if e.event == "$delete":
            state.pop(ent, None)
        elif e.event == "$set":
            cur = state.setdefault(ent, {})
            for k, v in e.properties.to_dict().items():
                cur[k] = v
        elif e.event == "$unset":
            cur = state.get(ent)
            if cur is not None:
                for k in e.properties.to_dict():
                    cur.pop(k, None)
    return dict(state)


def test_aggregator_fuzz_matches_brute_force():
    rng = random.Random(7)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    for trial in range(20):
        events = []
        entities = [f"e{k}" for k in range(rng.randint(1, 5))]
        keys = ["a", "b", "c"]
        for j in range(rng.randint(5, 60)):
            name = rng.choice(["$set", "$set", "$set", "$unset", "$delete"])
            props = {}
            if name in ("$set", "$unset"):
                for k in rng.sample(keys, rng.randint(1, 3)):
                    props[k] = rng.randint(0, 9) if name == "$set" else ""
            events.append(
                Event(
                    event=name,
                    entity_type="user",
                    entity_id=rng.choice(entities),
                    properties=props,
                    # distinct times: the fold's tie rules are not the
                    # brute-force interpreter's concern
                    event_time=t0 + dt.timedelta(seconds=j),
                )
            )
        shuffled = events[:]
        rng.shuffle(shuffled)  # order-independence of the monoid fold
        got = {
            ent: pm.to_dict()
            for ent, pm in aggregate_properties(shuffled).items()
        }
        want = _brute_force(events)
        assert got == want, f"trial {trial}: {got} != {want}"
