"""Serving-fleet tier tests (docs/fleet.md).

Pure merge/shard arithmetic, router unit behavior (quotas, affinity,
deadline splits), live in-process fleets over real HTTP, and the tier-1
chaos acceptance drill: kill a backend mid-run behind the router and
prove zero client-visible failures with byte-identical variant
assignments — plus exact sharded top-k merge against the unsharded
answer. All in-process; the only clocks on decision paths are injected.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from predictionio_tpu.fleet.merge import merge_item_scores, merge_predictions
from predictionio_tpu.fleet.router import (
    APP_HEADER,
    RouterConfig,
    RouterServer,
)
from predictionio_tpu.rollout.plan import bucket_for_key
from predictionio_tpu.testing.clock import FakeClock
from predictionio_tpu.utils.resilience import Deadline


# ---------------------------------------------------------------------------
# pure merge
# ---------------------------------------------------------------------------


def _brute_topk(entries, k):
    return sorted(entries, key=lambda e: (-e["score"], e["item"]))[:k]


class TestMergeTopK:
    def test_exact_vs_brute_force(self):
        rng = np.random.default_rng(3)
        entries = [
            {"item": f"i{n}", "score": round(float(s), 6)}
            for n, s in enumerate(rng.normal(size=40))
        ]
        for shards in (3, 5):
            split = [entries[s::shards] for s in range(shards)]
            for k in (1, 5, 17, 40, 100):
                assert merge_item_scores(split, k) == _brute_topk(
                    entries, k
                )

    def test_ties_break_by_item_id(self):
        shards = [
            [{"item": "zz", "score": 1.0}],
            [{"item": "aa", "score": 1.0}, {"item": "mm", "score": 1.0}],
        ]
        merged = merge_item_scores(shards, 3)
        assert [e["item"] for e in merged] == ["aa", "mm", "zz"]

    def test_k_none_returns_all_and_empty_shards_ok(self):
        shards = [[], [{"item": "a", "score": 2.0}], []]
        assert merge_item_scores(shards, None) == [
            {"item": "a", "score": 2.0}
        ]
        assert merge_item_scores([], 5) == []

    def test_unsorted_shard_input_still_exact(self):
        # a misbehaving shard returning unsorted scores must degrade to
        # a sort, never to a wrong answer
        shards = [
            [{"item": "a", "score": 0.1}, {"item": "b", "score": 9.0}],
            [{"item": "c", "score": 5.0}],
        ]
        assert [e["item"] for e in merge_item_scores(shards, 2)] == [
            "b", "c",
        ]

    def test_merge_predictions_item_scores(self):
        bodies = [
            {"itemScores": [{"item": "a", "score": 3.0}]},
            {"itemScores": [{"item": "b", "score": 4.0}]},
        ]
        merged = merge_predictions(bodies, 1)
        assert merged == {"itemScores": [{"item": "b", "score": 4.0}]}

    def test_merge_predictions_passthrough_and_disagreement(self):
        same = {"label": "x"}
        assert merge_predictions([same, dict(same)]) == same
        with pytest.raises(ValueError, match="disagree"):
            merge_predictions([{"label": "x"}, {"label": "y"}])


# ---------------------------------------------------------------------------
# shard partition (model level, no training)
# ---------------------------------------------------------------------------


def _toy_model(n_items=10, n_users=6, rank=4, seed=0):
    from predictionio_tpu.models.recommendation import ALSModel
    from predictionio_tpu.storage import BiMap

    rng = np.random.default_rng(seed)
    return ALSModel(
        rank=rank,
        user_factors=rng.normal(size=(n_users, rank)).astype(np.float32),
        item_factors=rng.normal(size=(n_items, rank)).astype(np.float32),
        user_map=BiMap({f"u{i}": i for i in range(n_users)}),
        item_map=BiMap({f"i{i}": i for i in range(n_items)}),
    )


class TestShardModel:
    def test_partition_is_disjoint_and_covering(self):
        from predictionio_tpu.models.recommendation import ALSAlgorithm

        model = _toy_model()
        algo = ALSAlgorithm()
        shards = [algo.shard_model(model, s, 3) for s in range(3)]
        seen: dict = {}
        for s, shard in enumerate(shards):
            assert shard.user_factors is model.user_factors  # whole users
            for item_id in shard.item_map:
                assert item_id not in seen, "item on two shards"
                seen[item_id] = s
                # round-robin layout: item i lives on shard i % count
                assert int(item_id[1:]) % 3 == s
                # the factor row travelled intact
                np.testing.assert_array_equal(
                    shard.item_factors[shard.item_map[item_id]],
                    model.item_factors[model.item_map[item_id]],
                )
        assert set(seen) == set(model.item_map)

    def test_local_topk_union_contains_global(self):
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm,
            Query,
        )

        model = _toy_model()
        algo = ALSAlgorithm()
        k = 4
        full = algo.predict(model, Query(user="u1", num=k))
        union = set()
        for s in range(3):
            shard = algo.shard_model(model, s, 3)
            local = algo.predict(shard, Query(user="u1", num=k))
            union.update(i.item for i in local.item_scores)
        assert {i.item for i in full.item_scores} <= union

    def test_shard_spec_validated_at_deploy(self):
        from predictionio_tpu.workflow.serving import (
            ServerConfig,
            _shard_models,
        )

        class NoShard:
            pass

        cfg = ServerConfig(shard_index=0, shard_count=2)
        with pytest.raises(ValueError, match="shard_model"):
            _shard_models([NoShard()], [object()], cfg)
        bad = ServerConfig(shard_index=5, shard_count=2)
        with pytest.raises(ValueError, match="out of range"):
            _shard_models([], [], bad)


# ---------------------------------------------------------------------------
# router units (no live backends needed)
# ---------------------------------------------------------------------------


def _router(backends=("h1:1", "h2:1", "h3:1"), **kw) -> RouterServer:
    clock = kw.pop("clock", FakeClock())
    cfg = RouterConfig(ip="127.0.0.1", port=0, backends=backends, **kw)
    return RouterServer(cfg, clock=clock)


class TestRouterUnits:
    def test_needs_backends(self):
        with pytest.raises(ValueError, match="backend"):
            RouterServer(RouterConfig(port=0, backends=()))

    def test_quota_admit_release(self):
        router = _router(quotas={"gold": 2}, default_quota=1)
        try:
            assert router.admit("gold") and router.admit("gold")
            assert not router.admit("gold")  # at its cap
            assert router.admit("other")     # default quota
            assert not router.admit("other")
            router.release("gold")
            assert router.admit("gold")
            # unbounded app: default_quota 0 elsewhere
            unbounded = _router(default_quota=0)
            try:
                assert all(unbounded.admit("x") for _ in range(64))
            finally:
                unbounded.server_close()
        finally:
            router.server_close()

    def test_replica_affinity_is_pure_and_rotates(self):
        router = _router()
        try:
            payload = {"user": "u7"}
            order = router._ordered_replicas(payload)
            assert order == router._ordered_replicas(payload)  # pure
            start = bucket_for_key(
                router.config.routing_salt, "user=u7"
            ) % 3
            ring = list(router.backends[start:] + router.backends[:start])
            assert order == ring  # affinity-first, then ring order
            # an OPEN breaker leaves the rotation...
            router.breakers[order[0]]._trip()
            assert router._ordered_replicas(payload) == order[1:]
            # ...and with every breaker open, the full ring still tries
            for b in router.backends:
                router.breakers[b]._trip()
            assert router._ordered_replicas(payload) == ring
        finally:
            router.server_close()

    def test_leg_timeout_splits_deadline_across_attempts(self):
        clock = FakeClock()
        router = _router(clock=clock, timeout_s=10.0)
        try:
            deadline = Deadline.after_ms(900, clock=clock)
            # three sequential attempts share the 0.9 s budget evenly
            assert router._leg_timeout(deadline, 3) == pytest.approx(0.3)
            assert router._leg_timeout(deadline, 1) == pytest.approx(0.9)
            # config timeout caps the share, never the other way round
            assert router._leg_timeout(None, 3) == 10.0
            tight = Deadline.after_ms(50_000, clock=clock)
            assert router._leg_timeout(tight, 2) == 10.0
        finally:
            router.server_close()

    def test_all_replicas_shedding_relays_503(self):
        """Fleet-wide backpressure must surface as a shed (503 +
        Retry-After semantics via FleetOverloaded), never a generic 502
        that makes well-behaved clients retry straight into the
        overload. A mixed failure (one connect error) stays a 502."""
        from predictionio_tpu.fleet.router import FleetOverloaded

        router = _router()
        try:
            router._leg = lambda *a, **k: (503, {"message": "shed"}, {})
            with pytest.raises(FleetOverloaded) as exc_info:
                router.route_query(b'{"user": "u1"}', None)
            assert exc_info.value.retry_after_s >= 1

            calls = {"n": 0}

            def mixed(backend, *a, **k):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise OSError("connect refused")
                return (503, {"message": "shed"}, {})

            router._leg = mixed
            with pytest.raises(RuntimeError) as exc_info:
                router.route_query(b'{"user": "u1"}', None)
            assert not isinstance(exc_info.value, FleetOverloaded)
        finally:
            router.server_close()

    def test_variant_preview_none_without_registry(self):
        router = _router()
        try:
            assert router.variant_preview({"user": "u1"}) is None
            status = router.status_json()
            assert status["backendsUp"] == 3
            assert [b["backend"] for b in status["backends"]] == [
                "h1:1", "h2:1", "h3:1",
            ]
        finally:
            router.server_close()

    def test_router_cli_grammar(self):
        from predictionio_tpu.tools.console import build_parser

        args = build_parser().parse_args(
            [
                "router", "--backends", "a:1,b:2", "--sharded",
                "--quota", "gold=4", "--default-quota", "8",
            ]
        )
        assert args.command == "router"
        assert args.backends == "a:1,b:2"
        assert args.sharded and args.quota == ["gold=4"]
        assert args.default_quota == 8


# ---------------------------------------------------------------------------
# live in-process fleets
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    """One trained tiny recommendation model in a private registry,
    shared by every live-fleet test in this module."""
    import predictionio_tpu.storage.registry as regmod
    from predictionio_tpu.controller import WorkflowParams
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams,
        RecDataSourceParams,
        engine_factory,
    )
    from predictionio_tpu.storage import DataMap, Event, StorageRegistry
    from predictionio_tpu.workflow.core_workflow import run_train

    tmp = tmp_path_factory.mktemp("fleet")
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp)})
    app_id = 1
    store = registry.get_events()
    store.init(app_id)
    rng = np.random.default_rng(5)
    store.write(
        [
            Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
            )
            for u in range(12)
            for i in range(9)
            if rng.random() < 0.85
        ],
        app_id,
    )
    engine = engine_factory()
    ep = EngineParams(
        data_source_params=("", RecDataSourceParams(app_id=app_id)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=4, num_iterations=2)),
        ],
    )
    prev = regmod._default_registry
    regmod._default_registry = registry
    try:
        instance_id = run_train(
            engine, ep, registry,
            workflow_params=WorkflowParams(batch="fleet-test"),
        )
    finally:
        regmod._default_registry = prev
    return registry, engine, instance_id


def _backend(fleet_registry, shard_index=0, shard_count=1):
    from predictionio_tpu.workflow.serving import QueryServer, ServerConfig

    registry, engine, instance_id = fleet_registry
    server = QueryServer(
        ServerConfig(
            ip="127.0.0.1", port=0, batching=False,
            engine_instance_id=instance_id,
            shard_index=shard_index, shard_count=shard_count,
        ),
        engine, registry,
    )
    server.start_background()
    return server


def _post(port, payload, headers=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request(
            "POST", "/queries.json", body=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, (
            json.loads(body.decode()) if body else {}
        ), {k.lower(): v for k, v in resp.getheaders()}
    finally:
        conn.close()


def _get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


class TestReplicatedFleet:
    @pytest.fixture(scope="class")
    def fleet(self, fleet_registry):
        backends = [_backend(fleet_registry) for _ in range(3)]
        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=tuple(
                    f"127.0.0.1:{s.bound_port}" for s in backends
                ),
                quotas={"capped": 1},
            ),
            registry=fleet_registry[0],
        )
        router.start_background()
        yield backends, router
        for srv in [router, *backends]:
            try:
                srv.kill()
            except Exception:
                pass

    def test_routes_and_sticky_affinity(self, fleet):
        backends, router = fleet
        payload = {"user": "u3", "num": 3}
        home = bucket_for_key(router.config.routing_salt, "user=u3") % 3
        before = [s.stats.request_count for s in backends]
        for _ in range(5):
            status, body, _headers = _post(router.bound_port, payload)
            assert status == 200
            assert body["itemScores"]
        after = [s.stats.request_count for s in backends]
        served = [b - a for a, b in zip(before, after)]
        assert served[home] == 5  # every repeat landed on the home replica
        assert sum(served) == 5

    def test_dead_backend_read_retries_on_survivor(self, fleet):
        backends, router = fleet
        # find a key whose home replica we then kill
        key = next(
            f"u{n}" for n in range(100)
            if bucket_for_key(router.config.routing_salt, f"user=u{n}") % 3
            == 2
        )
        backends[2].kill()
        status, body, _headers = _post(
            router.bound_port, {"user": key, "num": 3}
        )
        assert status == 200 and body["itemScores"]
        from predictionio_tpu.obs.expo import parse_text, render

        scraped = parse_text(render(router.metrics))
        retried = sum(
            v for _l, v in scraped.get("pio_router_retries_total", [])
        )
        assert retried >= 1

    def test_quota_sheds_with_503(self, fleet):
        _backends, router = fleet
        assert router.admit("capped")  # occupy the single slot
        try:
            status, body, _headers = _post(
                router.bound_port, {"user": "u1"},
                headers={APP_HEADER: "capped"},
            )
            assert status == 503
            assert "quota" in body["message"]
        finally:
            router.release("capped")
        status, _body, _headers = _post(
            router.bound_port, {"user": "u1"}, headers={APP_HEADER: "capped"}
        )
        assert status == 200

    def test_expired_deadline_is_504_and_bad_json_400(self, fleet):
        _backends, router = fleet
        status, body, _headers = _post(
            router.bound_port, {"user": "u1"},
            headers={"X-PIO-Deadline-Ms": "0"},
        )
        assert status == 504 and "deadline" in body["message"]
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", router.bound_port, timeout=30
        )
        try:
            conn.request(
                "POST", "/queries.json", body=b"{not json",
                headers={"Content-Type": "application/json"},
            )
            assert conn.getresponse().status == 400
        finally:
            conn.close()

    def test_router_rows_in_fleet_table(self, fleet):
        """The router node through the LIVE exposition: pio top's
        scraper must digest pio_router_* into the fleet columns."""
        _backends, router = fleet
        from predictionio_tpu.obs.top import node_row, render_table

        row = node_row(f"127.0.0.1:{router.bound_port}")
        assert row["up"] is True
        assert row["backends_up"] is not None and row["backends_up"] >= 2
        assert row["requests"] and row["requests"] > 0
        table = render_table([row])
        assert "BACKENDS" in table and "RTRETRY" in table

    def test_status_json_shape(self, fleet):
        _backends, router = fleet
        status, body = _get(router.bound_port, "/router.json")
        assert status == 200
        doc = json.loads(body)
        assert doc["role"] == "router" and len(doc["backends"]) == 3
        assert doc["quotas"] == {"capped": 1}


class TestShardedFleet:
    @pytest.fixture(scope="class")
    def fleet(self, fleet_registry):
        shards = [
            _backend(fleet_registry, shard_index=i, shard_count=2)
            for i in range(2)
        ]
        reference = _backend(fleet_registry)  # unsharded twin
        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=tuple(
                    f"127.0.0.1:{s.bound_port}" for s in shards
                ),
                sharded=True,
            ),
        )
        router.start_background()
        yield shards, reference, router
        for srv in [router, reference, *shards]:
            try:
                srv.kill()
            except Exception:
                pass

    def test_shard_metadata_route(self, fleet):
        shards, _reference, _router = fleet
        status, body = _get(shards[1].bound_port, "/shard.json")
        assert status == 200
        doc = json.loads(body)
        assert doc["sharded"] is True
        assert doc["shardIndex"] == 1 and doc["shardCount"] == 2
        assert doc["models"][0]["items"] > 0
        # the two shards partition the catalog
        other = json.loads(_get(shards[0].bound_port, "/shard.json")[1])
        total = doc["models"][0]["items"] + other["models"][0]["items"]
        ref_doc = json.loads(
            _get(_reference.bound_port, "/shard.json")[1]
        )
        assert ref_doc["sharded"] is False
        assert total == ref_doc["models"][0]["items"]

    def test_merged_topk_equals_unsharded(self, fleet):
        """The exactness contract: identical item RANKING (the top-k
        itself and its order), scores to f32 reassociation tolerance —
        XLA's matmul accumulation order varies with matrix shape, so a
        shard's score can differ from the full catalog's in the last
        ulps (verified live with the rank-10 template; rank-4 happens to
        be bitwise-equal, which is luck, not contract)."""
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        _shards, reference, router = fleet
        for user in ("u0", "u3", "u7", "u11"):
            payload = {"user": user, "num": 4}
            expect, _status = reference.handle_query(dict(payload))
            status, merged, _h = _post(router.bound_port, payload)
            assert status == 200
            assert merged_matches_reference(merged, expect), (
                merged, expect,
            )
            # item ranking specifically is EXACT, not just close
            assert [e["item"] for e in merged["itemScores"]] == [
                e["item"] for e in expect["itemScores"]
            ]

    def test_merged_matches_reference_tolerances(self):
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        a = {"itemScores": [{"item": "x", "score": 1.0}]}
        ulp = {"itemScores": [{"item": "x", "score": 1.0 + 1e-7}]}
        far = {"itemScores": [{"item": "x", "score": 1.01}]}
        other = {"itemScores": [{"item": "y", "score": 1.0}]}
        assert merged_matches_reference(a, ulp)
        assert not merged_matches_reference(a, far)    # real drift fails
        assert not merged_matches_reference(a, other)  # different item
        assert merged_matches_reference({"n": 1}, {"n": 1})  # passthrough
        # near-TIED items may swap rank (the same f32 noise applied to a
        # tie) — accepted when the sets agree and scores align...
        tied = {"itemScores": [{"item": "p", "score": 2.0},
                               {"item": "q", "score": 2.0 + 1e-7}]}
        swapped = {"itemScores": [{"item": "q", "score": 2.0 + 1e-7},
                                  {"item": "p", "score": 2.0}]}
        assert merged_matches_reference(tied, swapped)
        # ...but a swap across a REAL score gap still fails (positionwise
        # scores no longer align)
        gap = {"itemScores": [{"item": "p", "score": 2.0},
                              {"item": "q", "score": 1.0}]}
        gap_swapped = {"itemScores": [{"item": "q", "score": 1.0},
                                      {"item": "p", "score": 2.0}]}
        assert not merged_matches_reference(gap, gap_swapped)

    def test_query_without_num_matches_unsharded(self, fleet):
        """Each shard fills the engine's Query.num default (10)
        independently; without router-side truncation the merged answer
        would be up to shard_count x the unsharded length. The router's
        default_num closes that (review finding)."""
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        _shards, reference, router = fleet
        payload = {"user": "u2"}  # no "num"
        expect, _status = reference.handle_query(dict(payload))
        status, merged, _h = _post(router.bound_port, payload)
        assert status == 200
        assert len(merged["itemScores"]) == len(expect["itemScores"])
        assert merged_matches_reference(merged, expect)

    def test_unknown_user_merges_empty(self, fleet):
        _shards, reference, router = fleet
        status, merged, _h = _post(
            router.bound_port, {"user": "nobody", "num": 4}
        )
        assert status == 200 and merged == {"itemScores": []}

    def test_missing_shard_fails_loudly(self, fleet):
        shards, _reference, router = fleet
        shards[0].kill()
        status, body, _h = _post(router.bound_port, {"user": "u0", "num": 4})
        assert status == 502
        assert "shard" in body["message"]


# ---------------------------------------------------------------------------
# the tier-1 acceptance drill (ISSUE 9)
# ---------------------------------------------------------------------------


class TestFleetChaosDrill:
    def test_kill_backend_zero_failures_identical_variants(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        report = run_fleet_chaos(replicas=3, kill_backend_at=1, queries=72)
        assert report["clientFailures"] == 0
        assert report["variantsIdentical"] is True
        assert report["inconsistentVariants"] == 0
        assert report["variantMismatches"] == 0
        assert report["backendStages"] == ["CANARY"] * 3
        # both variants actually served (the split is real, not 100/0)
        assert set(report["variantCounts"]) == {"baseline", "candidate"}
        assert report["servedQPS"] > 0 and report["servedP99Ms"] > 0
        assert report["ok"] is True

    def test_sharded_merge_matches_unsharded(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        report = run_fleet_chaos(replicas=2, sharded=True, queries=24)
        assert report["mergedEqualsUnsharded"] is True
        assert report["clientFailures"] == 0
        assert report["ok"] is True

    def test_cli_flag_validation(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        with pytest.raises(ValueError, match="at least 2"):
            run_fleet_chaos(replicas=1)
        with pytest.raises(ValueError, match="kill-backend-at"):
            run_fleet_chaos(replicas=2, kill_backend_at=5)


# ---------------------------------------------------------------------------
# perf-ledger wiring (the servedQPS/P99 satellite)
# ---------------------------------------------------------------------------


class TestFleetLedger:
    BENCH = {
        "metric": "ml20m_als_rank50_train_s",
        "value": 12.0,
        "unit": "s",
        "device": "TFRT_CPU_0",
        "scale": 0.01,
        "servingFleet": {
            "replicas": 2,
            "sharded": False,
            "servedQPS": 450.0,
            "servedP50Ms": 20.0,
            "servedP99Ms": 80.0,
            "ok": True,
        },
    }

    def test_fleet_records_shape(self):
        from predictionio_tpu.obs.perfledger import fleet_records

        records = fleet_records(self.BENCH)
        by_metric = {r["metric"]: r for r in records}
        p50 = by_metric["fleet_served_p50_s"]
        assert p50["unit"] == "s" and p50["value"] == pytest.approx(0.02)
        # both latency records declare their own noise bands: wall-clock
        # from an in-process drive on a possibly-contended box — the
        # stable median gets 0.25, the hiccup-prone small-sample p99
        # gets 0.5 (only a serving collapse should gate, not weather)
        assert p50["noise_band"] == pytest.approx(0.25)
        p99 = by_metric["fleet_served_p99_s"]
        assert p99["unit"] == "s" and p99["value"] == pytest.approx(0.08)
        assert p99["scale"] == 2  # replica count separates comparisons
        assert p99["noise_band"] == pytest.approx(0.5)
        qps = by_metric["fleet_served_qps"]
        assert qps["unit"] == "qps"  # trend-only: the gate compares "s"

    def test_sharded_drives_never_gate_replicated(self):
        from predictionio_tpu.obs.perfledger import (
            comparable_key,
            fleet_records,
        )

        sharded = dict(
            self.BENCH,
            servingFleet=dict(self.BENCH["servingFleet"], sharded=True),
        )
        names = {r["metric"] for r in fleet_records(sharded)}
        assert names == {
            "fleet_sharded_served_p50_s",
            "fleet_sharded_served_p99_s",
            "fleet_sharded_served_qps",
        }
        # distinct comparable keys: scatter/gather latency must never
        # flag a replicated drive as a regression (or vice versa)
        repl_keys = {comparable_key(r) for r in fleet_records(self.BENCH)}
        shard_keys = {comparable_key(r) for r in fleet_records(sharded)}
        assert repl_keys.isdisjoint(shard_keys)

    def test_failed_fleet_records_nothing(self):
        from predictionio_tpu.obs.perfledger import fleet_records

        bad = dict(self.BENCH, servingFleet={"ok": False, "servedP99Ms": 9})
        assert fleet_records(bad) == []
        assert fleet_records({"metric": "x", "value": 1.0}) == []

    def _history(self, rows):
        from predictionio_tpu.obs.perfledger import fleet_records

        out = []
        for p50, p99 in rows:
            bench = dict(
                self.BENCH,
                servingFleet=dict(self.BENCH["servingFleet"],
                                  servedP50Ms=p50, servedP99Ms=p99),
            )
            out.extend(fleet_records(bench))
        return out

    def test_serving_regressions_gate(self):
        from predictionio_tpu.obs.perfledger import detect_regressions

        flat = [(20.0, 80.0), (21.0, 82.0), (20.5, 81.0)]
        assert detect_regressions(self._history(flat)) == []
        # a CI-weather p99 spike (+37%) stays inside p99's declared
        # wide band — the gate the review asked not to make flaky...
        weather = self._history(flat + [(20.6, 110.0)])
        assert detect_regressions(weather) == []
        # ...but a serving collapse (p99 2.2x) fires it
        collapse = self._history(flat + [(20.6, 180.0)])
        flagged = detect_regressions(collapse)
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_served_p99_s"
        ]
        assert flagged[0]["noise_band"] == pytest.approx(0.5)
        # the median gates at its tighter (0.25) band: a real 1.5×
        # slowdown flags even while p99 sits inside its wide band...
        slower = self._history(flat + [(30.0, 82.0)])
        flagged = detect_regressions(slower)
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_served_p50_s"
        ]
        # ...and a +15% p50 wobble (box weather) stays quiet
        wobble = self._history(flat + [(23.6, 82.0)])
        assert detect_regressions(wobble) == []

    def test_bench_record_carries_fleet_block(self):
        from predictionio_tpu.obs.perfledger import bench_to_record

        record = bench_to_record(self.BENCH)
        assert record["extra"]["servingFleet"]["servedQPS"] == 450.0
