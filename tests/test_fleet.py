"""Serving-fleet tier tests (docs/fleet.md).

Pure merge/shard arithmetic, router unit behavior (quotas, affinity,
deadline splits), live in-process fleets over real HTTP, and the tier-1
chaos acceptance drill: kill a backend mid-run behind the router and
prove zero client-visible failures with byte-identical variant
assignments — plus exact sharded top-k merge against the unsharded
answer. All in-process; the only clocks on decision paths are injected.
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

from predictionio_tpu.fleet.merge import merge_item_scores, merge_predictions
from predictionio_tpu.fleet.router import (
    APP_HEADER,
    RouterConfig,
    RouterServer,
)
from predictionio_tpu.rollout.plan import bucket_for_key
from predictionio_tpu.testing.clock import FakeClock
from predictionio_tpu.utils.resilience import CircuitBreaker, Deadline


# ---------------------------------------------------------------------------
# pure merge
# ---------------------------------------------------------------------------


def _brute_topk(entries, k):
    return sorted(entries, key=lambda e: (-e["score"], e["item"]))[:k]


class TestMergeTopK:
    def test_exact_vs_brute_force(self):
        rng = np.random.default_rng(3)
        entries = [
            {"item": f"i{n}", "score": round(float(s), 6)}
            for n, s in enumerate(rng.normal(size=40))
        ]
        for shards in (3, 5):
            split = [entries[s::shards] for s in range(shards)]
            for k in (1, 5, 17, 40, 100):
                assert merge_item_scores(split, k) == _brute_topk(
                    entries, k
                )

    def test_ties_break_by_item_id(self):
        shards = [
            [{"item": "zz", "score": 1.0}],
            [{"item": "aa", "score": 1.0}, {"item": "mm", "score": 1.0}],
        ]
        merged = merge_item_scores(shards, 3)
        assert [e["item"] for e in merged] == ["aa", "mm", "zz"]

    def test_k_none_returns_all_and_empty_shards_ok(self):
        shards = [[], [{"item": "a", "score": 2.0}], []]
        assert merge_item_scores(shards, None) == [
            {"item": "a", "score": 2.0}
        ]
        assert merge_item_scores([], 5) == []

    def test_unsorted_shard_input_still_exact(self):
        # a misbehaving shard returning unsorted scores must degrade to
        # a sort, never to a wrong answer
        shards = [
            [{"item": "a", "score": 0.1}, {"item": "b", "score": 9.0}],
            [{"item": "c", "score": 5.0}],
        ]
        assert [e["item"] for e in merge_item_scores(shards, 2)] == [
            "b", "c",
        ]

    def test_merge_predictions_item_scores(self):
        bodies = [
            {"itemScores": [{"item": "a", "score": 3.0}]},
            {"itemScores": [{"item": "b", "score": 4.0}]},
        ]
        merged = merge_predictions(bodies, 1)
        assert merged == {"itemScores": [{"item": "b", "score": 4.0}]}

    def test_merge_predictions_passthrough_and_disagreement(self):
        same = {"label": "x"}
        assert merge_predictions([same, dict(same)]) == same
        with pytest.raises(ValueError, match="disagree"):
            merge_predictions([{"label": "x"}, {"label": "y"}])


# ---------------------------------------------------------------------------
# shard partition (model level, no training)
# ---------------------------------------------------------------------------


def _toy_model(n_items=10, n_users=6, rank=4, seed=0):
    from predictionio_tpu.models.recommendation import ALSModel
    from predictionio_tpu.storage import BiMap

    rng = np.random.default_rng(seed)
    return ALSModel(
        rank=rank,
        user_factors=rng.normal(size=(n_users, rank)).astype(np.float32),
        item_factors=rng.normal(size=(n_items, rank)).astype(np.float32),
        user_map=BiMap({f"u{i}": i for i in range(n_users)}),
        item_map=BiMap({f"i{i}": i for i in range(n_items)}),
    )


class TestShardModel:
    def test_partition_is_disjoint_and_covering(self):
        from predictionio_tpu.models.recommendation import ALSAlgorithm

        model = _toy_model()
        algo = ALSAlgorithm()
        shards = [algo.shard_model(model, s, 3) for s in range(3)]
        seen: dict = {}
        for s, shard in enumerate(shards):
            assert shard.user_factors is model.user_factors  # whole users
            for item_id in shard.item_map:
                assert item_id not in seen, "item on two shards"
                seen[item_id] = s
                # round-robin layout: item i lives on shard i % count
                assert int(item_id[1:]) % 3 == s
                # the factor row travelled intact
                np.testing.assert_array_equal(
                    shard.item_factors[shard.item_map[item_id]],
                    model.item_factors[model.item_map[item_id]],
                )
        assert set(seen) == set(model.item_map)

    def test_local_topk_union_contains_global(self):
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm,
            Query,
        )

        model = _toy_model()
        algo = ALSAlgorithm()
        k = 4
        full = algo.predict(model, Query(user="u1", num=k))
        union = set()
        for s in range(3):
            shard = algo.shard_model(model, s, 3)
            local = algo.predict(shard, Query(user="u1", num=k))
            union.update(i.item for i in local.item_scores)
        assert {i.item for i in full.item_scores} <= union

    def test_shard_spec_validated_at_deploy(self):
        from predictionio_tpu.workflow.serving import (
            ServerConfig,
            _shard_models,
        )

        class NoShard:
            pass

        cfg = ServerConfig(shard_index=0, shard_count=2)
        with pytest.raises(ValueError, match="shard_model"):
            _shard_models([NoShard()], [object()], cfg)
        bad = ServerConfig(shard_index=5, shard_count=2)
        with pytest.raises(ValueError, match="out of range"):
            _shard_models([], [], bad)


# ---------------------------------------------------------------------------
# router units (no live backends needed)
# ---------------------------------------------------------------------------


def _router(backends=("h1:1", "h2:1", "h3:1"), **kw) -> RouterServer:
    clock = kw.pop("clock", FakeClock())
    cfg = RouterConfig(ip="127.0.0.1", port=0, backends=backends, **kw)
    return RouterServer(cfg, clock=clock)


class TestRouterUnits:
    def test_needs_backends(self):
        with pytest.raises(ValueError, match="backend"):
            RouterServer(RouterConfig(port=0, backends=()))

    def test_quota_admit_release(self):
        router = _router(quotas={"gold": 2}, default_quota=1)
        try:
            assert router.admit("gold") and router.admit("gold")
            assert not router.admit("gold")  # at its cap
            assert router.admit("other")     # default quota
            assert not router.admit("other")
            router.release("gold")
            assert router.admit("gold")
            # unbounded app: default_quota 0 elsewhere
            unbounded = _router(default_quota=0)
            try:
                assert all(unbounded.admit("x") for _ in range(64))
            finally:
                unbounded.server_close()
        finally:
            router.server_close()

    def test_replica_affinity_is_pure_and_rotates(self):
        router = _router()
        try:
            payload = {"user": "u7"}
            order = router._ordered_replicas(payload)
            assert order == router._ordered_replicas(payload)  # pure
            start = bucket_for_key(
                router.config.routing_salt, "user=u7"
            ) % 3
            ring = list(router.backends[start:] + router.backends[:start])
            assert order == ring  # affinity-first, then ring order
            # an OPEN breaker leaves the rotation...
            router.breakers[order[0]]._trip()
            assert router._ordered_replicas(payload) == order[1:]
            # ...and with every breaker open, the full ring still tries
            for b in router.backends:
                router.breakers[b]._trip()
            assert router._ordered_replicas(payload) == ring
        finally:
            router.server_close()

    def test_leg_timeout_splits_deadline_across_attempts(self):
        clock = FakeClock()
        router = _router(clock=clock, timeout_s=10.0)
        try:
            deadline = Deadline.after_ms(900, clock=clock)
            # three sequential attempts share the 0.9 s budget evenly
            assert router._leg_timeout(deadline, 3) == pytest.approx(0.3)
            assert router._leg_timeout(deadline, 1) == pytest.approx(0.9)
            # config timeout caps the share, never the other way round
            assert router._leg_timeout(None, 3) == 10.0
            tight = Deadline.after_ms(50_000, clock=clock)
            assert router._leg_timeout(tight, 2) == 10.0
        finally:
            router.server_close()

    def test_all_replicas_shedding_relays_503(self):
        """Fleet-wide backpressure must surface as a shed (503 +
        Retry-After semantics via FleetOverloaded), never a generic 502
        that makes well-behaved clients retry straight into the
        overload. A mixed failure (one connect error) stays a 502."""
        from predictionio_tpu.fleet.router import FleetOverloaded

        router = _router()
        try:
            router._leg = lambda *a, **k: (503, {"message": "shed"}, {})
            with pytest.raises(FleetOverloaded) as exc_info:
                router.route_query(b'{"user": "u1"}', None)
            assert exc_info.value.retry_after_s >= 1

            calls = {"n": 0}

            def mixed(backend, *a, **k):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise OSError("connect refused")
                return (503, {"message": "shed"}, {})

            router._leg = mixed
            with pytest.raises(RuntimeError) as exc_info:
                router.route_query(b'{"user": "u1"}', None)
            assert not isinstance(exc_info.value, FleetOverloaded)
        finally:
            router.server_close()

    def test_variant_preview_none_without_registry(self):
        router = _router()
        try:
            assert router.variant_preview({"user": "u1"}) is None
            status = router.status_json()
            assert status["backendsUp"] == 3
            assert [b["backend"] for b in status["backends"]] == [
                "h1:1", "h2:1", "h3:1",
            ]
        finally:
            router.server_close()

    def test_router_cli_grammar(self):
        from predictionio_tpu.tools.console import build_parser

        args = build_parser().parse_args(
            [
                "router", "--backends", "a:1,b:2", "--sharded",
                "--quota", "gold=4", "--default-quota", "8",
                "--replicas-per-shard", "2", "--no-cache",
                "--cache-ttl", "5", "--cache-max-entries", "64",
            ]
        )
        assert args.command == "router"
        assert args.backends == "a:1,b:2"
        assert args.sharded and args.quota == ["gold=4"]
        assert args.default_quota == 8
        assert args.replicas_per_shard == 2
        assert args.no_cache is True
        assert args.cache_ttl == 5.0 and args.cache_max_entries == 64


# ---------------------------------------------------------------------------
# response cache + single-flight (docs/fleet.md#cache)
# ---------------------------------------------------------------------------


class TestResponseCacheUnit:
    def test_canonical_query_is_order_insensitive(self):
        from predictionio_tpu.fleet.cache import canonical_query

        a = canonical_query({"user": "u1", "num": 5})
        b = canonical_query({"num": 5, "user": "u1"})
        assert a == b
        assert canonical_query({"user": "u2"}) != a

    def test_hit_miss_ttl_and_epoch(self):
        from predictionio_tpu.fleet.cache import ResponseCache

        clock = FakeClock()
        dropped = []
        cache = ResponseCache(
            max_entries=8, ttl_s=10.0, clock=clock,
            on_invalidate=lambda reason, n: dropped.append((reason, n)),
        )
        key = ("-", '{"user":"u1"}')
        assert cache.get(key, "e1") is None  # miss
        cache.put(key, {"itemScores": []}, "-", "e1")
        entry = cache.get(key, "e1")
        assert entry is not None and entry.body == {"itemScores": []}
        # TTL expiry on the injected clock
        clock.advance(10.5)
        assert cache.get(key, "e1") is None
        assert ("ttl", 1) in dropped
        # epoch mismatch drops the entry — a cached answer can never
        # outlive the plan/model that produced it
        cache.put(key, {"itemScores": []}, "-", "e1")
        assert cache.get(key, "e2") is None
        assert ("epoch", 1) in dropped
        snap = cache.snapshot()
        assert snap["hits"] == 1 and snap["misses"] == 3
        assert snap["invalidations"] == {"ttl": 1, "epoch": 1}

    def test_lru_bound_and_flush(self):
        from predictionio_tpu.fleet.cache import ResponseCache

        cache = ResponseCache(max_entries=3, ttl_s=60.0, clock=FakeClock())
        for i in range(5):
            cache.put(("-", f"q{i}"), i, "-", "e")
        assert len(cache) == 3
        assert cache.snapshot()["invalidations"]["capacity"] == 2
        # oldest evicted, newest resident
        assert cache.get(("-", "q0"), "e") is None
        assert cache.get(("-", "q4"), "e").body == 4
        # variant-scoped flush drops only that keyspace
        cache.put(("candidate", "qc"), 9, "candidate", "e")
        assert cache.flush(variant="candidate", reason="explicit") == 1
        assert cache.get(("-", "q4"), "e") is not None
        assert cache.flush() == 2  # q3 and q4 remained -> all dropped
        with pytest.raises(ValueError, match="BOUNDED"):
            ResponseCache(max_entries=0)

    def test_single_flight_coalesces(self):
        import threading

        from predictionio_tpu.fleet.cache import SingleFlight

        sf = SingleFlight()
        gate = threading.Event()
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            gate.wait(5)
            return "answer"

        results = []

        def go():
            results.append(sf.do("k", fn))

        threads = [threading.Thread(target=go) for _ in range(6)]
        for t in threads:
            t.start()
        # let the followers pile onto the leader before releasing it
        deadline = time.monotonic() + 5
        while calls["n"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join()
        assert calls["n"] == 1  # ONE execution for six callers
        assert all(value == "answer" for value, _shared in results)
        assert sum(1 for _v, shared in results if shared) == 5

    def test_single_flight_error_sharing_and_deadline_fallback(self):
        import threading

        from predictionio_tpu.fleet.cache import SingleFlight

        sf = SingleFlight()
        gate = threading.Event()
        started = threading.Event()
        calls = {"n": 0}

        def failing():
            calls["n"] += 1
            started.set()
            gate.wait(5)
            raise OSError("backend down")

        errors = []

        def follower():
            try:
                sf.do("k", failing)
            except OSError as exc:
                errors.append(str(exc))

        leader = threading.Thread(target=follower)
        leader.start()
        started.wait(5)
        chaser = threading.Thread(target=follower)
        chaser.start()
        time.sleep(0.05)
        gate.set()
        leader.join()
        chaser.join()
        # a generic failure IS shared (one backend storm, one error)...
        assert calls["n"] == 1 and len(errors) == 2
        # ...but a caller-specific error (share_error False) makes the
        # follower run its own leg instead of inheriting it
        sf2 = SingleFlight()
        gate2 = threading.Event()
        started2 = threading.Event()
        outcome = {}

        def leader_fn():
            started2.set()
            gate2.wait(5)
            raise TimeoutError("my deadline, not yours")

        def follower_fn():
            started2.wait(5)
            try:
                value, shared = sf2.do(
                    "k", lambda: "fresh",
                    share_error=lambda e: not isinstance(e, TimeoutError),
                )
                outcome["value"] = value
            except TimeoutError:
                outcome["inherited"] = True

        def leader_run():
            try:
                sf2.do("k", leader_fn)
            except TimeoutError:
                outcome["leader_raised"] = True

        t1 = threading.Thread(target=leader_run)
        t1.start()
        started2.wait(5)
        t2 = threading.Thread(target=follower_fn)
        t2.start()
        time.sleep(0.05)
        gate2.set()
        t1.join()
        t2.join()
        assert outcome == {"value": "fresh", "leader_raised": True}


class _FakePlan:
    """Just enough RolloutPlan surface for the router's preview/epoch."""

    def __init__(self, stage="CANARY", percent=50.0, plan_id="RP-1",
                 updated="t0"):
        self.id = plan_id
        self.stage = stage
        self.percent = percent
        self.salt = "salt-1"
        self.baseline_instance_id = "EI-base"
        self.candidate_instance_id = "EI-cand"
        self.updated_time = updated


class _FakeInstance:
    def __init__(self, iid):
        self.id = iid


class _FakeRegistry:
    def __init__(self):
        self.plan = None
        self.latest = _FakeInstance("EI-1")

    def get_metadata(self):
        return self

    def rollout_plan_get_active(self, *_key):
        return self.plan

    def engine_instance_get_latest_completed(self, *_key):
        return self.latest


def _cached_router(**kw):
    registry = kw.pop("registry", None)
    clock = kw.pop("clock", FakeClock())
    kw.setdefault("cache_enabled", True)
    kw.setdefault("cache_ttl_s", 30.0)
    kw.setdefault("plan_refresh_s", 0.0)
    kw.setdefault("engine_id", "eng")
    cfg = RouterConfig(
        ip="127.0.0.1", port=0, backends=kw.pop("backends", ("h1:1",)),
        **kw,
    )
    return RouterServer(cfg, registry=registry, clock=clock), clock


class TestRouterCacheUnits:
    def _scrape(self, router, name):
        from predictionio_tpu.obs.expo import parse_text, render

        return parse_text(render(router.metrics)).get(name, [])

    def test_hit_skips_backend_and_stamps_verdict(self):
        router, _clock = _cached_router()
        calls = {"n": 0}

        def leg(*_a, **_k):
            calls["n"] += 1
            return 200, {"itemScores": [{"item": "a", "score": 1.0}]}, {
                "x-pio-variant": "-",
            }

        router._leg = leg
        try:
            info: dict = {}
            status, body, variant = router.route_query(
                b'{"user": "u1", "num": 2}', None, info=info
            )
            assert (status, info["cache"], calls["n"]) == (200, "miss", 1)
            info = {}
            status, body2, variant2 = router.route_query(
                b'{"num": 2, "user": "u1"}', None, info=info  # reordered
            )
            assert (status, info["cache"], calls["n"]) == (200, "hit", 1)
            assert body2 == body and variant2 == variant
            assert [v for _l, v in self._scrape(
                router, "pio_router_cache_hits_total"
            )] == [1.0]
        finally:
            router.server_close()

    def test_ttl_expiry_on_fake_clock(self):
        router, clock = _cached_router(cache_ttl_s=5.0)
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        try:
            router.route_query(b'{"user": "u1"}', None)
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            clock.advance(5.5)
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
            invalidations = {
                labels["reason"]: v
                for labels, v in self._scrape(
                    router, "pio_router_cache_invalidations_total"
                )
            }
            assert invalidations.get("ttl") == 1.0
        finally:
            router.server_close()

    def test_rollout_stage_change_flushes(self):
        """The invalidation contract: an observed plan-epoch move drops
        the affected keyspace — a stage transition can never serve a
        pre-transition answer (docs/fleet.md#cache)."""
        registry = _FakeRegistry()
        router, _clock = _cached_router(registry=registry)
        router._leg = lambda *a, **k: (
            200, {"n": 1}, {"x-pio-variant": "baseline"}
        )
        try:
            router.route_query(b'{"user": "u1"}', None)
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            # SHADOW -> CANARY: stage + updated_time move the epoch
            registry.plan = _FakePlan(stage="CANARY", updated="t1")
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
            invalidations = {
                labels["reason"]: v
                for labels, v in self._scrape(
                    router, "pio_router_cache_invalidations_total"
                )
            }
            assert invalidations.get("epoch", 0) >= 1.0
            # mid-canary percent bump flushes again
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            registry.plan = _FakePlan(
                stage="CANARY", percent=80.0, updated="t2"
            )
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
        finally:
            router.server_close()

    def test_model_swap_flushes(self):
        """A new COMPLETED instance (the continuous plane promoting a
        fresh model) moves the epoch even with no rollout active — a
        cached answer can never outlive the model that produced it."""
        registry = _FakeRegistry()
        router, _clock = _cached_router(registry=registry)
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        try:
            router.route_query(b'{"user": "u1"}', None)
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            registry.latest = _FakeInstance("EI-2")  # model swap observed
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
        finally:
            router.server_close()

    def test_canary_variants_get_distinct_cache_lines(self):
        """Under an active CANARY the cache key includes the router's
        own variant assignment: a baseline user's hit can never serve a
        candidate user's answer (and vice versa)."""
        from predictionio_tpu.rollout.plan import sticky_key, variant_for_key

        registry = _FakeRegistry()
        registry.plan = _FakePlan(stage="CANARY", percent=50.0)
        router, _clock = _cached_router(registry=registry)
        served = []

        def leg(backend, raw, *_a, **_k):
            payload = json.loads(raw)
            variant = variant_for_key(
                "salt-1", sticky_key(payload), 50.0
            )
            served.append(variant)
            return 200, {"for": payload["user"]}, {"x-pio-variant": variant}

        router._leg = leg
        try:
            # find one key per variant
            by_variant: dict = {}
            for n in range(50):
                v = variant_for_key("salt-1", f"user=u{n}", 50.0)
                by_variant.setdefault(v, f"u{n}")
                if len(by_variant) == 2:
                    break
            for variant, user in by_variant.items():
                raw = json.dumps({"user": user}).encode()
                status, body, got = router.route_query(raw, None)
                assert got == variant
                info: dict = {}
                status, body2, got2 = router.route_query(raw, None, info=info)
                assert info["cache"] == "hit" and got2 == variant
                assert body2 == body
            # zero cross-variant contamination, zero mismatches
            assert sum(
                v for _l, v in self._scrape(
                    router, "pio_router_variant_mismatch_total"
                )
            ) == 0
        finally:
            router.server_close()

    def test_sharded_single_flight_coalesces_concurrent_queries(self):
        import threading

        router, _clock = _cached_router(
            backends=("s0:1", "s1:1"), sharded=True, cache_enabled=False
        )
        gate = threading.Event()
        scatters = {"n": 0}

        def slow_scatter(raw, payload, deadline, trace_id):
            scatters["n"] += 1
            gate.wait(5)
            return 200, {"itemScores": []}, "-"

        router._route_sharded = slow_scatter
        results = []

        def go():
            results.append(router.route_query(b'{"user": "u9"}', None))

        try:
            threads = [threading.Thread(target=go) for _ in range(5)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5
            while scatters["n"] < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.05)
            gate.set()
            for t in threads:
                t.join()
            assert scatters["n"] == 1 and len(results) == 5
            assert [v for _l, v in self._scrape(
                router, "pio_router_coalesced_total"
            )] == [4.0]
        finally:
            router.server_close()

    def test_quota_admission_runs_before_the_cache(self):
        """The shed path composes: an app over its quota sheds 503 even
        for a query the cache could answer — admission is the front
        door, memory is not a side entrance around it."""
        router, _clock = _cached_router(quotas={"capped": 1})
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        router.start_background()
        try:
            payload = {"user": "hot"}
            status, _body, headers = _post(
                router.bound_port, payload, headers={APP_HEADER: "capped"}
            )
            assert status == 200 and headers.get("x-pio-cache") == "miss"
            status, _body, headers = _post(
                router.bound_port, payload, headers={APP_HEADER: "capped"}
            )
            assert status == 200 and headers.get("x-pio-cache") == "hit"
            assert router.admit("capped")  # occupy the only slot
            try:
                status, body, _headers = _post(
                    router.bound_port, payload,
                    headers={APP_HEADER: "capped"},
                )
                assert status == 503 and "quota" in body["message"]
            finally:
                router.release("capped")
            # released: the hot entry answers again
            status, _body, headers = _post(
                router.bound_port, payload, headers={APP_HEADER: "capped"}
            )
            assert status == 200 and headers.get("x-pio-cache") == "hit"
        finally:
            router.kill()

    def test_status_json_cache_block_and_disabled(self):
        router, _clock = _cached_router()
        try:
            block = router.status_json()["cache"]
            assert block["enabled"] is True
            assert block["maxEntries"] == 2048 and block["ttlS"] == 30.0
        finally:
            router.server_close()
        off = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0, backends=("h1:1",),
                cache_enabled=False,
            ),
            clock=FakeClock(),
        )
        try:
            assert off.status_json()["cache"] == {"enabled": False}
        finally:
            off.server_close()


class TestRouterHedging:
    """The hedging budget math (docs/fleet.md#hedging): the hedge leg
    is funded from the deadline budget REMAINING at fire time, never
    fires below the leg minimum or into an open breaker, and the
    abandoned loser is counted on ``pio_router_hedges_total``."""

    def _hedges(self, router):
        from predictionio_tpu.obs.expo import parse_text, render

        return {
            labels["outcome"]: v
            for labels, v in parse_text(render(router.metrics)).get(
                "pio_router_hedges_total", []
            )
        }

    def _warm(self, router, delay_s=0.02):
        for _ in range(router._hedge.min_samples):
            router._hedge.observe(delay_s)

    def test_cold_tracker_never_hedges(self):
        """Hedging is ON by default but a cold router has no tail to
        read: the first position degrades to the plain sequential
        attempt, one leg, no hedge bookkeeping."""
        router, _clock = _cached_router(backends=("h1:1", "h2:2"))
        seen = []

        def leg(backend, *_a, **_k):
            seen.append(backend)
            return 200, {"n": 1}, {}

        router._leg = leg
        try:
            assert router._hedge is not None
            assert router._hedge.delay_s() is None
            consumed, verdicts = router._hedged_first(
                ("h1:1", "h2:2"), b"{}", None, None
            )
            assert (consumed, verdicts[0][0]) == (1, "ok")
            assert seen == ["h1:1"]
            assert self._hedges(router) == {}
        finally:
            router.server_close()

    def test_hedge_fires_on_the_remaining_split_and_counts_the_loser(self):
        """A primary past the p9x delay fires ONE hedge leg; the hedge
        is funded with the ring positions remaining at fire time (the
        primary keeps the full split it was launched with), the first
        answer wins, and the abandoned loser is counted."""
        import threading

        router, _clock = _cached_router(backends=("h1:1", "h2:2"))
        self._warm(router, 0.02)
        block = threading.Event()
        calls = []

        def leg(backend, raw, deadline, attempts_left, trace_id):
            calls.append((backend, attempts_left))
            if backend == "h1:1":
                block.wait(5.0)
                return 200, {"from": "primary"}, {}
            return 200, {"from": "hedge"}, {}

        router._leg = leg
        try:
            consumed, verdicts = router._hedged_first(
                ("h1:1", "h2:2"), b"{}", None, None
            )
            assert (consumed, verdicts[0][0]) == (2, "ok")
            assert verdicts[0][1][1] == {"from": "hedge"}
            # launch split: primary got both positions' budget share,
            # the hedge leg only what REMAINED at fire time
            assert ("h1:1", 2) in calls and ("h2:2", 1) in calls
            hedges = self._hedges(router)
            assert hedges.get("fired") == 1.0
            assert hedges.get("hedge_won") == 1.0
            assert hedges.get("loser_cancelled") == 1.0
        finally:
            block.set()
            time.sleep(0.05)
            router.server_close()

    def test_primary_win_still_counts_the_hedged_loser(self):
        import threading

        router, _clock = _cached_router(backends=("h1:1", "h2:2"))
        self._warm(router, 0.02)
        block = threading.Event()

        def leg(backend, raw, deadline, attempts_left, trace_id):
            if backend == "h1:1":
                time.sleep(0.08)
                return 200, {"from": "primary"}, {}
            block.wait(5.0)
            return 200, {"from": "hedge"}, {}

        router._leg = leg
        try:
            consumed, verdicts = router._hedged_first(
                ("h1:1", "h2:2"), b"{}", None, None
            )
            assert (consumed, verdicts[0][0]) == (2, "ok")
            assert verdicts[0][1][1] == {"from": "primary"}
            hedges = self._hedges(router)
            assert hedges.get("fired") == 1.0
            assert hedges.get("primary_won") == 1.0
            assert hedges.get("loser_cancelled") == 1.0
        finally:
            block.set()
            time.sleep(0.05)
            router.server_close()

    def test_hedge_never_fires_below_the_leg_minimum(self):
        """Below ``hedge_leg_min_s`` of remaining deadline the hedge is
        denied and counted — a doomed duplicate would only split
        starvation two ways. The primary still answers."""
        router, _clock = _cached_router(
            backends=("h1:1", "h2:2"), hedge_leg_min_s=10.0
        )
        self._warm(router, 0.02)
        calls = []

        def leg(backend, raw, deadline, attempts_left, trace_id):
            calls.append(backend)
            if backend == "h1:1":
                time.sleep(0.06)
            return 200, {"n": 1}, {}

        router._leg = leg
        try:
            consumed, verdicts = router._hedged_first(
                ("h1:1", "h2:2"), b"{}", Deadline.after_ms(5000.0), None
            )
            assert (consumed, verdicts[0][0]) == (1, "ok")
            assert calls == ["h1:1"]  # the second leg never launched
            hedges = self._hedges(router)
            assert hedges.get("budget_denied") == 1.0
            assert "fired" not in hedges
        finally:
            router.server_close()

    def test_open_breaker_denies_the_hedge(self):
        """A hedge into an open breaker is a guaranteed-loser duplicate:
        denied, counted, and the primary is simply awaited."""
        router, _clock = _cached_router(backends=("h1:1", "h2:2"))
        self._warm(router, 0.02)
        breaker = router.breakers["h2:2"]
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        calls = []

        def leg(backend, raw, deadline, attempts_left, trace_id):
            calls.append(backend)
            if backend == "h1:1":
                time.sleep(0.06)
            return 200, {"n": 1}, {}

        router._leg = leg
        try:
            consumed, verdicts = router._hedged_first(
                ("h1:1", "h2:2"), b"{}", None, None
            )
            assert (consumed, verdicts[0][0]) == (1, "ok")
            assert calls == ["h1:1"]
            hedges = self._hedges(router)
            assert hedges.get("breaker_denied") == 1.0
            assert "fired" not in hedges
        finally:
            router.server_close()


class _FakeSubscriber:
    """Just enough ChangefeedSubscriber surface for the watchdog pin."""

    def __init__(self, alive=True):
        self.live = alive
        self.stopped = False

    def alive(self):
        return self.live

    def status(self):
        return {"alive": self.live, "fetches": 1, "lastError": None}

    def stop(self):
        self.stopped = True


class TestPushPlaneFallback:
    """The push-plane headroom fix: a LIVE subscriber stretches the
    poll to the watchdog cadence, a dead or wedged one silently
    restores ``plan_refresh_s`` — the epoch can never freeze behind a
    stuck push plane — and the state is visible on /router.json."""

    def _events(self, router):
        from predictionio_tpu.obs.expo import parse_text, render

        return {
            labels["source"]: v
            for labels, v in parse_text(render(router.metrics)).get(
                "pio_router_epoch_events_total", []
            )
        }

    def test_wedged_subscriber_never_freezes_the_epoch(self):
        registry = _FakeRegistry()
        router, _clock = _cached_router(
            registry=registry, push_watchdog_s=30.0
        )
        router._subscriber = _FakeSubscriber(alive=True)
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        try:
            router.route_query(b'{"user": "u1"}', None)
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            # the epoch moves but no push event arrives: a subscriber
            # that CLAIMS to be healthy holds the poll to the watchdog
            # cadence, so the stale hit survives (push owns freshness)
            registry.latest = _FakeInstance("EI-2")
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            assert router.status_json()["epochSource"] == "push"
            # the subscriber wedges: the VERY NEXT read re-decides the
            # cadence, polls, and sees the move — no push event, no
            # watchdog wait, no frozen epoch
            router._subscriber.live = False
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
            out = router.status_json()
            assert out["epochSource"] == "poll"
            assert out["subscriber"]["alive"] is False
            assert self._events(router).get("poll") == 1.0
        finally:
            router.server_close()

    def test_watchdog_poll_still_runs_behind_a_live_push_plane(self):
        registry = _FakeRegistry()
        router, clock = _cached_router(
            registry=registry, push_watchdog_s=30.0
        )
        router._subscriber = _FakeSubscriber(alive=True)
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        try:
            router.route_query(b'{"user": "u1"}', None)
            registry.latest = _FakeInstance("EI-2")
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"  # inside the watchdog window
            clock.advance(30.5)
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"  # the watchdog poll caught it
            assert self._events(router).get("poll") == 1.0
        finally:
            router.server_close()

    def test_pushed_op_flushes_without_waiting_for_any_poll(self):
        registry = _FakeRegistry()
        router, _clock = _cached_router(
            registry=registry, push_watchdog_s=30.0
        )
        router._subscriber = _FakeSubscriber(alive=True)
        router._leg = lambda *a, **k: (200, {"n": 1}, {"x-pio-variant": "-"})
        try:
            router.route_query(b'{"user": "u1"}', None)
            registry.latest = _FakeInstance("EI-2")
            info: dict = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
            # the changefeed delivers the instance insert: the flush is
            # immediate and counted against the push source
            router._on_meta_ops(
                [{"kind": "meta", "method": "engine_instance_insert"}],
                gap=False,
            )
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "miss"
            assert self._events(router) == {"push": 1.0}
            # a non-epoch op (an event append) flushes nothing
            router._on_meta_ops([{"kind": "event", "id": "x"}], gap=False)
            info = {}
            router.route_query(b'{"user": "u1"}', None, info=info)
            assert info["cache"] == "hit"
        finally:
            router.server_close()

    def test_subscriber_stops_with_the_server(self):
        router, _clock = _cached_router()
        sub = _FakeSubscriber(alive=True)
        router._subscriber = sub
        router.server_close()
        assert sub.stopped is True


class TestShardReplicaUnits:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="replicas-per-shard"):
            RouterServer(RouterConfig(
                port=0, backends=("a:1", "b:1"), replicas_per_shard=2,
            ))
        with pytest.raises(ValueError, match="divide"):
            RouterServer(RouterConfig(
                port=0, backends=("a:1", "b:1", "c:1"), sharded=True,
                replicas_per_shard=2,
            ))
        with pytest.raises(ValueError, match=">= 1"):
            RouterServer(RouterConfig(
                port=0, backends=("a:1",), sharded=True,
                replicas_per_shard=0,
            ))

    def test_shard_replica_ring_math(self):
        router, _clock = _cached_router(
            backends=("s0a:1", "s0b:1", "s1a:1", "s1b:1"),
            sharded=True, replicas_per_shard=2, cache_enabled=False,
        )
        try:
            assert router.shard_count == 2
            assert router._shard_replicas(0) == ("s0a:1", "s0b:1")
            assert router._shard_replicas(1) == ("s1a:1", "s1b:1")
            order = router._ordered_shard_replicas(0, "user=u7")
            assert sorted(order) == ["s0a:1", "s0b:1"]
            # pure: same key, same order
            assert order == router._ordered_shard_replicas(0, "user=u7")
            # an OPEN breaker leaves the rotation...
            router.breakers[order[0]]._trip()
            assert router._ordered_shard_replicas(0, "user=u7") == order[1:]
            # ...but an all-open group still tries the ring
            router.breakers[order[1]]._trip()
            assert sorted(
                router._ordered_shard_replicas(0, "user=u7")
            ) == ["s0a:1", "s0b:1"]
        finally:
            router.server_close()

    def test_replica_failover_inside_shard(self):
        router, _clock = _cached_router(
            backends=("s0a:1", "s0b:1", "s1a:1", "s1b:1"),
            sharded=True, replicas_per_shard=2, cache_enabled=False,
        )
        home = router._ordered_shard_replicas(0, "user=u1")[0]

        def leg(backend, *_a, **_k):
            if backend == home:
                raise OSError("connect refused")
            shard = 0 if backend.startswith("s0") else 1
            return 200, {
                "itemScores": [{"item": f"i{shard}", "score": 1.0 - shard}]
            }, {"x-pio-variant": "-"}

        router._leg = leg
        try:
            status, body, _variant = router.route_query(
                b'{"user": "u1", "num": 5}', None
            )
            assert status == 200
            assert [e["item"] for e in body["itemScores"]] == ["i0", "i1"]
        finally:
            router.server_close()

    def test_sharded_504_passes_through_without_tripping_breakers(self):
        """A backend 504 is the CLIENT's expired budget, not backend
        sickness — the replicated mode's discipline, now mirrored
        inside the shard replica groups: no breaker trip, no failover
        burn, the 504 relays to the client."""
        router, _clock = _cached_router(
            backends=("s0a:1", "s0b:1", "s1a:1", "s1b:1"),
            sharded=True, replicas_per_shard=2, cache_enabled=False,
        )
        calls = []

        def leg(backend, *_a, **_k):
            calls.append(backend)
            if backend.startswith("s0"):
                return 504, {"message": "deadline exceeded"}, {}
            return 200, {"itemScores": []}, {"x-pio-variant": "-"}

        router._leg = leg
        try:
            status, body, _v = router.route_query(b'{"user": "u1"}', None)
            assert status == 504 and "deadline" in body["message"]
            # exactly ONE s0 replica tried (no failover burned)...
            assert len([b for b in calls if b.startswith("s0")]) == 1
            # ...and its breaker holds no failure
            from predictionio_tpu.utils.resilience import CircuitBreaker

            assert all(
                router.breakers[b].state == CircuitBreaker.CLOSED
                for b in router.backends
            )
        finally:
            router.server_close()

    def test_all_replicas_shedding_relays_fleet_overloaded(self):
        """Every replica of a shard answering 503 is backpressure, not
        shard death: the read relays as FleetOverloaded (503 +
        Retry-After) so clients back off, exactly like the replicated
        ring; a mixed failure stays the loud ShardUnavailable 502."""
        from predictionio_tpu.fleet.router import (
            FleetOverloaded,
            ShardUnavailable,
        )

        router, _clock = _cached_router(
            backends=("s0a:1", "s0b:1", "s1a:1", "s1b:1"),
            sharded=True, replicas_per_shard=2, cache_enabled=False,
        )
        router._leg = lambda backend, *a, **k: (
            (503, {"message": "shed"}, {})
            if backend.startswith("s1")
            else (200, {"itemScores": []}, {"x-pio-variant": "-"})
        )
        try:
            with pytest.raises(FleetOverloaded):
                router.route_query(b'{"user": "u1"}', None)

            def mixed(backend, *_a, **_k):
                if backend == "s1a:1":
                    raise OSError("connect refused")
                if backend == "s1b:1":
                    return 503, {"message": "shed"}, {}
                return 200, {"itemScores": []}, {"x-pio-variant": "-"}

            router._leg = mixed
            with pytest.raises(ShardUnavailable):
                router.route_query(b'{"user": "u1"}', None)
        finally:
            router.server_close()

    def test_dead_shard_names_its_index_and_counts_distinctly(self):
        from predictionio_tpu.fleet.router import ShardUnavailable
        from predictionio_tpu.obs.expo import parse_text, render

        router, _clock = _cached_router(
            backends=("s0a:1", "s0b:1", "s1a:1", "s1b:1"),
            sharded=True, replicas_per_shard=2, cache_enabled=False,
        )

        def leg(backend, *_a, **_k):
            if backend.startswith("s1"):
                raise OSError("dead")
            return 200, {"itemScores": []}, {"x-pio-variant": "-"}

        router._leg = leg
        try:
            with pytest.raises(ShardUnavailable) as exc_info:
                router.route_query(b'{"user": "u1"}', None)
            assert "shard 1" in str(exc_info.value)
            assert exc_info.value.shards == (1,)
            scraped = parse_text(render(router.metrics))
            dead = [
                (labels, v)
                for labels, v in scraped.get(
                    "pio_router_backend_events_total", []
                )
                if labels.get("kind") == "dead_shard"
            ]
            assert dead == [({"backend": "shard-1", "kind": "dead_shard"}, 1.0)]
        finally:
            router.server_close()


# ---------------------------------------------------------------------------
# live in-process fleets
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    """One trained tiny recommendation model in a private registry,
    shared by every live-fleet test in this module."""
    import predictionio_tpu.storage.registry as regmod
    from predictionio_tpu.controller import WorkflowParams
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams,
        RecDataSourceParams,
        engine_factory,
    )
    from predictionio_tpu.storage import DataMap, Event, StorageRegistry
    from predictionio_tpu.workflow.core_workflow import run_train

    tmp = tmp_path_factory.mktemp("fleet")
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp)})
    app_id = 1
    store = registry.get_events()
    store.init(app_id)
    rng = np.random.default_rng(5)
    store.write(
        [
            Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
            )
            for u in range(12)
            for i in range(9)
            if rng.random() < 0.85
        ],
        app_id,
    )
    engine = engine_factory()
    ep = EngineParams(
        data_source_params=("", RecDataSourceParams(app_id=app_id)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=4, num_iterations=2)),
        ],
    )
    prev = regmod._default_registry
    regmod._default_registry = registry
    try:
        instance_id = run_train(
            engine, ep, registry,
            workflow_params=WorkflowParams(batch="fleet-test"),
        )
    finally:
        regmod._default_registry = prev
    return registry, engine, instance_id


def _backend(fleet_registry, shard_index=0, shard_count=1):
    from predictionio_tpu.workflow.serving import QueryServer, ServerConfig

    registry, engine, instance_id = fleet_registry
    server = QueryServer(
        ServerConfig(
            ip="127.0.0.1", port=0, batching=False,
            engine_instance_id=instance_id,
            shard_index=shard_index, shard_count=shard_count,
        ),
        engine, registry,
    )
    server.start_background()
    return server


def _post(port, payload, headers=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request(
            "POST", "/queries.json", body=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, (
            json.loads(body.decode()) if body else {}
        ), {k.lower(): v for k, v in resp.getheaders()}
    finally:
        conn.close()


def _get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


class TestReplicatedFleet:
    @pytest.fixture(scope="class")
    def fleet(self, fleet_registry):
        backends = [_backend(fleet_registry) for _ in range(3)]
        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=tuple(
                    f"127.0.0.1:{s.bound_port}" for s in backends
                ),
                quotas={"capped": 1},
                # routing behavior is what this class measures — a cache
                # hit never exercises affinity/failover (TestCachedFleet
                # owns the cache's own live assertions)
                cache_enabled=False,
            ),
            registry=fleet_registry[0],
        )
        router.start_background()
        yield backends, router
        for srv in [router, *backends]:
            try:
                srv.kill()
            except Exception:
                pass

    def test_routes_and_sticky_affinity(self, fleet):
        backends, router = fleet
        payload = {"user": "u3", "num": 3}
        home = bucket_for_key(router.config.routing_salt, "user=u3") % 3
        before = [s.stats.request_count for s in backends]
        for _ in range(5):
            status, body, _headers = _post(router.bound_port, payload)
            assert status == 200
            assert body["itemScores"]
        after = [s.stats.request_count for s in backends]
        served = [b - a for a, b in zip(before, after)]
        assert served[home] == 5  # every repeat landed on the home replica
        assert sum(served) == 5

    def test_dead_backend_read_retries_on_survivor(self, fleet):
        backends, router = fleet
        # find a key whose home replica we then kill
        key = next(
            f"u{n}" for n in range(100)
            if bucket_for_key(router.config.routing_salt, f"user=u{n}") % 3
            == 2
        )
        backends[2].kill()
        status, body, _headers = _post(
            router.bound_port, {"user": key, "num": 3}
        )
        assert status == 200 and body["itemScores"]
        from predictionio_tpu.obs.expo import parse_text, render

        scraped = parse_text(render(router.metrics))
        retried = sum(
            v for _l, v in scraped.get("pio_router_retries_total", [])
        )
        assert retried >= 1

    def test_quota_sheds_with_503(self, fleet):
        _backends, router = fleet
        assert router.admit("capped")  # occupy the single slot
        try:
            status, body, _headers = _post(
                router.bound_port, {"user": "u1"},
                headers={APP_HEADER: "capped"},
            )
            assert status == 503
            assert "quota" in body["message"]
        finally:
            router.release("capped")
        status, _body, _headers = _post(
            router.bound_port, {"user": "u1"}, headers={APP_HEADER: "capped"}
        )
        assert status == 200

    def test_expired_deadline_is_504_and_bad_json_400(self, fleet):
        _backends, router = fleet
        status, body, _headers = _post(
            router.bound_port, {"user": "u1"},
            headers={"X-PIO-Deadline-Ms": "0"},
        )
        assert status == 504 and "deadline" in body["message"]
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", router.bound_port, timeout=30
        )
        try:
            conn.request(
                "POST", "/queries.json", body=b"{not json",
                headers={"Content-Type": "application/json"},
            )
            assert conn.getresponse().status == 400
        finally:
            conn.close()

    def test_router_rows_in_fleet_table(self, fleet):
        """The router node through the LIVE exposition: pio top's
        scraper must digest pio_router_* into the fleet columns."""
        _backends, router = fleet
        from predictionio_tpu.obs.top import node_row, render_table

        row = node_row(f"127.0.0.1:{router.bound_port}")
        assert row["up"] is True
        assert row["backends_up"] is not None and row["backends_up"] >= 2
        assert row["requests"] and row["requests"] > 0
        table = render_table([row])
        assert "BACKENDS" in table and "RTRETRY" in table

    def test_status_json_shape(self, fleet):
        _backends, router = fleet
        status, body = _get(router.bound_port, "/router.json")
        assert status == 200
        doc = json.loads(body)
        assert doc["role"] == "router" and len(doc["backends"]) == 3
        assert doc["quotas"] == {"capped": 1}


class TestShardedFleet:
    @pytest.fixture(scope="class")
    def fleet(self, fleet_registry):
        shards = [
            _backend(fleet_registry, shard_index=i, shard_count=2)
            for i in range(2)
        ]
        reference = _backend(fleet_registry)  # unsharded twin
        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=tuple(
                    f"127.0.0.1:{s.bound_port}" for s in shards
                ),
                sharded=True,
                cache_enabled=False,  # scatter/gather is the thing under test
            ),
        )
        router.start_background()
        yield shards, reference, router
        for srv in [router, reference, *shards]:
            try:
                srv.kill()
            except Exception:
                pass

    def test_shard_metadata_route(self, fleet):
        shards, _reference, _router = fleet
        status, body = _get(shards[1].bound_port, "/shard.json")
        assert status == 200
        doc = json.loads(body)
        assert doc["sharded"] is True
        assert doc["shardIndex"] == 1 and doc["shardCount"] == 2
        assert doc["models"][0]["items"] > 0
        # the two shards partition the catalog
        other = json.loads(_get(shards[0].bound_port, "/shard.json")[1])
        total = doc["models"][0]["items"] + other["models"][0]["items"]
        ref_doc = json.loads(
            _get(_reference.bound_port, "/shard.json")[1]
        )
        assert ref_doc["sharded"] is False
        assert total == ref_doc["models"][0]["items"]

    def test_merged_topk_equals_unsharded(self, fleet):
        """The exactness contract: identical item RANKING (the top-k
        itself and its order), scores to f32 reassociation tolerance —
        XLA's matmul accumulation order varies with matrix shape, so a
        shard's score can differ from the full catalog's in the last
        ulps (verified live with the rank-10 template; rank-4 happens to
        be bitwise-equal, which is luck, not contract)."""
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        _shards, reference, router = fleet
        for user in ("u0", "u3", "u7", "u11"):
            payload = {"user": user, "num": 4}
            expect, _status = reference.handle_query(dict(payload))
            status, merged, _h = _post(router.bound_port, payload)
            assert status == 200
            assert merged_matches_reference(merged, expect), (
                merged, expect,
            )
            # item ranking specifically is EXACT, not just close
            assert [e["item"] for e in merged["itemScores"]] == [
                e["item"] for e in expect["itemScores"]
            ]

    def test_merged_matches_reference_tolerances(self):
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        a = {"itemScores": [{"item": "x", "score": 1.0}]}
        ulp = {"itemScores": [{"item": "x", "score": 1.0 + 1e-7}]}
        far = {"itemScores": [{"item": "x", "score": 1.01}]}
        other = {"itemScores": [{"item": "y", "score": 1.0}]}
        assert merged_matches_reference(a, ulp)
        assert not merged_matches_reference(a, far)    # real drift fails
        assert not merged_matches_reference(a, other)  # different item
        assert merged_matches_reference({"n": 1}, {"n": 1})  # passthrough
        # near-TIED items may swap rank (the same f32 noise applied to a
        # tie) — accepted when the sets agree and scores align...
        tied = {"itemScores": [{"item": "p", "score": 2.0},
                               {"item": "q", "score": 2.0 + 1e-7}]}
        swapped = {"itemScores": [{"item": "q", "score": 2.0 + 1e-7},
                                  {"item": "p", "score": 2.0}]}
        assert merged_matches_reference(tied, swapped)
        # ...but a swap across a REAL score gap still fails (positionwise
        # scores no longer align)
        gap = {"itemScores": [{"item": "p", "score": 2.0},
                              {"item": "q", "score": 1.0}]}
        gap_swapped = {"itemScores": [{"item": "q", "score": 1.0},
                                      {"item": "p", "score": 2.0}]}
        assert not merged_matches_reference(gap, gap_swapped)

    def test_query_without_num_matches_unsharded(self, fleet):
        """Each shard fills the engine's Query.num default (10)
        independently; without router-side truncation the merged answer
        would be up to shard_count x the unsharded length. The router's
        default_num closes that (review finding)."""
        from predictionio_tpu.tools.loadgen import merged_matches_reference

        _shards, reference, router = fleet
        payload = {"user": "u2"}  # no "num"
        expect, _status = reference.handle_query(dict(payload))
        status, merged, _h = _post(router.bound_port, payload)
        assert status == 200
        assert len(merged["itemScores"]) == len(expect["itemScores"])
        assert merged_matches_reference(merged, expect)

    def test_unknown_user_merges_empty(self, fleet):
        _shards, reference, router = fleet
        status, merged, _h = _post(
            router.bound_port, {"user": "nobody", "num": 4}
        )
        assert status == 200 and merged == {"itemScores": []}

    def test_missing_shard_fails_loudly(self, fleet):
        shards, _reference, router = fleet
        shards[0].kill()
        status, body, _h = _post(router.bound_port, {"user": "u0", "num": 4})
        assert status == 502
        assert "shard" in body["message"]


# ---------------------------------------------------------------------------
# the tier-1 acceptance drill (ISSUE 9)
# ---------------------------------------------------------------------------


class TestFleetChaosDrill:
    def test_kill_backend_zero_failures_identical_variants(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        report = run_fleet_chaos(replicas=3, kill_backend_at=1, queries=72)
        assert report["clientFailures"] == 0
        assert report["variantsIdentical"] is True
        assert report["inconsistentVariants"] == 0
        assert report["variantMismatches"] == 0
        assert report["backendStages"] == ["CANARY"] * 3
        # both variants actually served (the split is real, not 100/0)
        assert set(report["variantCounts"]) == {"baseline", "candidate"}
        assert report["servedQPS"] > 0 and report["servedP99Ms"] > 0
        assert report["ok"] is True

    def test_sharded_merge_matches_unsharded(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        report = run_fleet_chaos(replicas=2, sharded=True, queries=24)
        assert report["mergedEqualsUnsharded"] is True
        assert report["clientFailures"] == 0
        assert report["ok"] is True

    def test_sharded_with_replicas_survives_backend_kill(self):
        """ISSUE 14 acceptance: `--sharded --replicas-per-shard 2
        --kill-backend-at I` — a sharded fleet survives a backend kill
        exactly like the replicated fleet does (zero client failures,
        merged answers still equal the unsharded reference)."""
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        report = run_fleet_chaos(
            replicas=2, sharded=True, replicas_per_shard=2,
            kill_backend_at=1, queries=24,
        )
        assert report["clientFailures"] == 0
        assert report["killedBackend"] == 1
        assert report["mergedEqualsUnsharded"] is True
        assert report["routerRetries"] > 0  # the failover actually ran
        assert report["ok"] is True

    def test_cli_flag_validation(self):
        from predictionio_tpu.tools.loadgen import run_fleet_chaos

        with pytest.raises(ValueError, match="at least 2"):
            run_fleet_chaos(replicas=1)
        with pytest.raises(ValueError, match="kill-backend-at"):
            run_fleet_chaos(replicas=2, kill_backend_at=5)
        with pytest.raises(ValueError, match="replicas-per-shard"):
            run_fleet_chaos(replicas=2, sharded=True, kill_backend_at=0)
        with pytest.raises(ValueError, match="needs --sharded"):
            run_fleet_chaos(replicas=2, replicas_per_shard=2)


# ---------------------------------------------------------------------------
# perf-ledger wiring (the servedQPS/P99 satellite)
# ---------------------------------------------------------------------------


class TestFleetLedger:
    BENCH = {
        "metric": "ml20m_als_rank50_train_s",
        "value": 12.0,
        "unit": "s",
        "device": "TFRT_CPU_0",
        "scale": 0.01,
        "servingFleet": {
            "replicas": 2,
            "sharded": False,
            "servedQPS": 450.0,
            "servedP50Ms": 20.0,
            "servedP99Ms": 80.0,
            "ok": True,
        },
    }

    def test_fleet_records_shape(self):
        from predictionio_tpu.obs.perfledger import fleet_records

        records = fleet_records(self.BENCH)
        by_metric = {r["metric"]: r for r in records}
        p50 = by_metric["fleet_served_p50_s"]
        assert p50["unit"] == "s" and p50["value"] == pytest.approx(0.02)
        # both latency records declare their own noise bands: wall-clock
        # from an in-process drive on a possibly-contended box — the
        # stable median gets 0.25, the hiccup-prone small-sample p99
        # gets 0.5 (only a serving collapse should gate, not weather)
        assert p50["noise_band"] == pytest.approx(0.25)
        p99 = by_metric["fleet_served_p99_s"]
        assert p99["unit"] == "s" and p99["value"] == pytest.approx(0.08)
        assert p99["scale"] == 2  # replica count separates comparisons
        assert p99["noise_band"] == pytest.approx(0.5)
        qps = by_metric["fleet_served_qps"]
        assert qps["unit"] == "qps"  # trend-only: the gate compares "s"

    def test_sharded_drives_never_gate_replicated(self):
        from predictionio_tpu.obs.perfledger import (
            comparable_key,
            fleet_records,
        )

        sharded = dict(
            self.BENCH,
            servingFleet=dict(self.BENCH["servingFleet"], sharded=True),
        )
        names = {r["metric"] for r in fleet_records(sharded)}
        assert names == {
            "fleet_sharded_served_p50_s",
            "fleet_sharded_served_p99_s",
            "fleet_sharded_served_qps",
        }
        # distinct comparable keys: scatter/gather latency must never
        # flag a replicated drive as a regression (or vice versa)
        repl_keys = {comparable_key(r) for r in fleet_records(self.BENCH)}
        shard_keys = {comparable_key(r) for r in fleet_records(sharded)}
        assert repl_keys.isdisjoint(shard_keys)

    def test_failed_fleet_records_nothing(self):
        from predictionio_tpu.obs.perfledger import fleet_records

        bad = dict(self.BENCH, servingFleet={"ok": False, "servedP99Ms": 9})
        assert fleet_records(bad) == []
        assert fleet_records({"metric": "x", "value": 1.0}) == []

    def _history(self, rows):
        from predictionio_tpu.obs.perfledger import fleet_records

        out = []
        for p50, p99 in rows:
            bench = dict(
                self.BENCH,
                servingFleet=dict(self.BENCH["servingFleet"],
                                  servedP50Ms=p50, servedP99Ms=p99),
            )
            out.extend(fleet_records(bench))
        return out

    def test_serving_regressions_gate(self):
        from predictionio_tpu.obs.perfledger import detect_regressions

        flat = [(20.0, 80.0), (21.0, 82.0), (20.5, 81.0)]
        assert detect_regressions(self._history(flat)) == []
        # a CI-weather p99 spike (+37%) stays inside p99's declared
        # wide band — the gate the review asked not to make flaky...
        weather = self._history(flat + [(20.6, 110.0)])
        assert detect_regressions(weather) == []
        # ...but a serving collapse (p99 2.2x) fires it
        collapse = self._history(flat + [(20.6, 180.0)])
        flagged = detect_regressions(collapse)
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_served_p99_s"
        ]
        assert flagged[0]["noise_band"] == pytest.approx(0.5)
        # the median gates at its tighter (0.25) band: a real 1.5×
        # slowdown flags even while p99 sits inside its wide band...
        slower = self._history(flat + [(30.0, 82.0)])
        flagged = detect_regressions(slower)
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_served_p50_s"
        ]
        # ...and a +15% p50 wobble (box weather) stays quiet
        wobble = self._history(flat + [(23.6, 82.0)])
        assert detect_regressions(wobble) == []

    def test_bench_record_carries_fleet_block(self):
        from predictionio_tpu.obs.perfledger import bench_to_record

        record = bench_to_record(self.BENCH)
        assert record["extra"]["servingFleet"]["servedQPS"] == 450.0


# ---------------------------------------------------------------------------
# live cached fleet + the cached-hot-set acceptance drill (ISSUE 14)
# ---------------------------------------------------------------------------


class TestCachedFleet:
    """A live cache-on router over the module's trained backend: the
    byte-identity contract over real HTTP, which no stubbed-leg unit
    can prove."""

    @pytest.fixture(scope="class")
    def cached_fleet(self, fleet_registry):
        backend = _backend(fleet_registry)
        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=(f"127.0.0.1:{backend.bound_port}",),
                cache_enabled=True, cache_ttl_s=60.0,
            ),
            registry=fleet_registry[0],
        )
        router.start_background()
        yield backend, router
        for srv in (router, backend):
            try:
                srv.kill()
            except Exception:
                pass

    def _post_raw(self, port, payload: bytes):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request(
                "POST", "/queries.json", body=payload,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            return resp.status, {
                k.lower(): v for k, v in resp.getheaders()
            }, resp.read()
        finally:
            conn.close()

    def test_hit_body_is_byte_identical_and_headers_stamped(self, cached_fleet):
        _backend_srv, router = cached_fleet
        payload = json.dumps({"user": "u5", "num": 4}).encode()
        s1, h1, b1 = self._post_raw(router.bound_port, payload)
        s2, h2, b2 = self._post_raw(router.bound_port, payload)
        assert s1 == s2 == 200
        assert h1["x-pio-cache"] == "miss" and h2["x-pio-cache"] == "hit"
        # the BODY is byte-identical; only trace id / cache verdict differ
        assert b1 == b2
        assert h1["x-pio-variant"] == h2["x-pio-variant"]
        assert h1["x-pio-trace"] != h2["x-pio-trace"]
        doc = json.loads(b2.decode())
        assert doc["itemScores"]

    def test_canonicalized_payload_shares_the_line(self, cached_fleet):
        _backend_srv, router = cached_fleet
        a = json.dumps({"user": "u7", "num": 3}).encode()
        b = b'{"num": 3,   "user": "u7"}'  # reordered + respaced
        s1, h1, b1 = self._post_raw(router.bound_port, a)
        s2, h2, b2 = self._post_raw(router.bound_port, b)
        assert s1 == s2 == 200
        assert h2["x-pio-cache"] == "hit"
        assert b1 == b2

    def test_router_json_and_top_cache_column(self, cached_fleet):
        from predictionio_tpu.obs.top import node_row, render_table

        _backend_srv, router = cached_fleet
        status, body = _get(router.bound_port, "/router.json")
        assert status == 200
        cache = json.loads(body)["cache"]
        assert cache["enabled"] is True and cache["hits"] >= 1
        row = node_row(f"127.0.0.1:{router.bound_port}")
        assert row["up"] is True
        assert row["cache_hit_rate"] is not None
        assert 0.0 < row["cache_hit_rate"] < 1.0
        table = render_table([row])
        assert "CACHE" in table


class TestCachedHotSetDrill:
    def test_step_win_byte_identity_and_zero_stale(self):
        from predictionio_tpu.tools.loadgen import run_cached_hot_set

        report = run_cached_hot_set(queries=120)
        assert report["clientFailures"] == 0
        assert report["byteIdentical"] is True
        # the rollout-driven invalidation proof: a stage transition
        # mid-drive yields ZERO stale responses, and the flush actually
        # happened (epoch invalidations moved)
        assert report["staleAfterRollout"] == 0
        assert report["epochInvalidations"] > 0
        assert report["hitRate"] > 0.3
        # the step function: serving from memory beats re-fanning out
        assert report["cachedQPS"] > report["uncachedQPS"]
        assert report["ok"] is True


class TestCacheLedger:
    BENCH = {
        "metric": "ml20m_als_rank50_train_s",
        "value": 12.0,
        "unit": "s",
        "device": "TFRT_CPU_0",
        "scale": 0.01,
        "cachedFleet": {
            "replicas": 1,
            "cachedQPS": 400.0,
            "uncachedQPS": 120.0,
            "speedup": 3.33,
            "hitRate": 0.85,
            "cachedP50Ms": 4.0,
            "cachedP99Ms": 40.0,
            "byteIdentical": True,
            "staleAfterRollout": 0,
            "ok": True,
        },
    }

    def test_cache_records_shape(self):
        from predictionio_tpu.obs.perfledger import cache_records

        records = cache_records(self.BENCH)
        by_metric = {r["metric"]: r for r in records}
        p99 = by_metric["fleet_cached_p99_s"]
        assert p99["unit"] == "s" and p99["value"] == pytest.approx(0.04)
        assert p99["noise_band"] == pytest.approx(0.5)
        qps = by_metric["fleet_cached_qps"]
        assert qps["unit"] == "qps"  # trend-only: the gate compares "s"
        assert qps["extra"]["uncachedQPS"] == 120.0
        assert qps["extra"]["speedup"] == pytest.approx(3.33)
        hit = by_metric["fleet_cache_hit_rate"]
        assert hit["unit"] == "ratio" and hit["value"] == pytest.approx(0.85)

    def test_failed_drive_records_nothing(self):
        from predictionio_tpu.obs.perfledger import cache_records

        bad = dict(self.BENCH, cachedFleet={"ok": False, "cachedQPS": 9e9})
        assert cache_records(bad) == []
        assert cache_records({"metric": "x", "value": 1.0}) == []

    def test_cached_records_never_gate_uncached_fleet_records(self):
        from predictionio_tpu.obs.perfledger import (
            cache_records,
            comparable_key,
            fleet_records,
        )

        cached_keys = {comparable_key(r) for r in cache_records(self.BENCH)}
        fleet_keys = {
            comparable_key(r)
            for r in fleet_records(TestFleetLedger.BENCH)
        }
        assert cached_keys.isdisjoint(fleet_keys)

    def test_gate_fires_on_cached_p99_collapse_only(self):
        from predictionio_tpu.obs.perfledger import (
            cache_records,
            detect_regressions,
        )

        def history(p99s):
            out = []
            for p99 in p99s:
                bench = dict(
                    self.BENCH,
                    cachedFleet=dict(
                        self.BENCH["cachedFleet"], cachedP99Ms=p99
                    ),
                )
                out.extend(cache_records(bench))
            return out

        flat = [40.0, 42.0, 41.0]
        assert detect_regressions(history(flat)) == []
        # +40% is inside the declared 0.5 band (CI weather)...
        assert detect_regressions(history(flat + [57.0])) == []
        # ...a 2.2x collapse fires
        flagged = detect_regressions(history(flat + [90.0]))
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_cached_p99_s"
        ]

    def test_bench_record_carries_cached_block(self):
        from predictionio_tpu.obs.perfledger import bench_to_record

        record = bench_to_record(self.BENCH)
        assert record["extra"]["cachedFleet"]["hitRate"] == 0.85


class TestSharedCacheLedger:
    BENCH = {
        "metric": "ml20m_als_rank50_train_s",
        "value": 12.0,
        "unit": "s",
        "device": "TFRT_CPU_0",
        "scale": 0.01,
        "sharedCache": {
            "healthyQPS": 900.0,
            "hedgedP99Ms": 18.0,
            "sharedHitRate": 0.8,
            "degradesRecorded": 12,
            "byteIdenticalAfterKill": True,
            "staleAfterRollout": 0,
            "clientFailures": 0,
            "warmedEntries": 20,
            "ok": True,
        },
    }

    def test_shared_cache_records_shape(self):
        from predictionio_tpu.obs.perfledger import shared_cache_records

        by_metric = {
            r["metric"]: r for r in shared_cache_records(self.BENCH)
        }
        p99 = by_metric["fleet_hedged_p99_s"]
        assert p99["unit"] == "s" and p99["value"] == pytest.approx(0.018)
        assert p99["noise_band"] == pytest.approx(0.5)
        assert p99["extra"]["sharedHitRate"] == pytest.approx(0.8)
        hit = by_metric["fleet_shared_hit_rate"]
        assert hit["unit"] == "ratio"  # trend-only: the gate compares "s"
        assert hit["value"] == pytest.approx(0.8)

    def test_failed_drill_records_nothing(self):
        from predictionio_tpu.obs.perfledger import shared_cache_records

        bad = dict(self.BENCH, sharedCache={"ok": False, "hedgedP99Ms": 1.0})
        assert shared_cache_records(bad) == []
        assert shared_cache_records({"metric": "x", "value": 1.0}) == []

    def test_shared_records_never_gate_the_other_fleet_records(self):
        """Comparable-key separation: the hedged p99 gates only against
        its own history, never the cached or uncached serving tails."""
        from predictionio_tpu.obs.perfledger import (
            cache_records,
            comparable_key,
            fleet_records,
            shared_cache_records,
        )

        shared_keys = {
            comparable_key(r) for r in shared_cache_records(self.BENCH)
        }
        other_keys = {
            comparable_key(r)
            for r in cache_records(TestCacheLedger.BENCH)
        } | {
            comparable_key(r)
            for r in fleet_records(TestFleetLedger.BENCH)
        }
        assert shared_keys and shared_keys.isdisjoint(other_keys)

    def test_gate_fires_on_hedged_p99_collapse_only(self):
        from predictionio_tpu.obs.perfledger import (
            detect_regressions,
            shared_cache_records,
        )

        def history(p99s):
            out = []
            for p99 in p99s:
                bench = dict(
                    self.BENCH,
                    sharedCache=dict(
                        self.BENCH["sharedCache"], hedgedP99Ms=p99
                    ),
                )
                out.extend(shared_cache_records(bench))
            return out

        flat = [18.0, 20.0, 19.0]
        assert detect_regressions(history(flat)) == []
        # +40% is inside the declared 0.5 band (CI weather)...
        assert detect_regressions(history(flat + [26.0])) == []
        # ...a 2.2x collapse fires
        flagged = detect_regressions(history(flat + [42.0]))
        assert [f["key"]["metric"] for f in flagged] == [
            "fleet_hedged_p99_s"
        ]

    def test_bench_record_carries_shared_block(self):
        from predictionio_tpu.obs.perfledger import bench_to_record

        record = bench_to_record(self.BENCH)
        assert record["extra"]["sharedCache"]["sharedHitRate"] == 0.8
