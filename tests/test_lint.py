"""``pio lint`` — the TPU-hygiene static analyzer (predictionio_tpu/lint).

Three layers:

1. **Round-5 fixtures** (``tests/fixtures/lint/``): each of the three
   Mosaic bug classes the round-5 deviceless AOT sweep found (commit
   093d7d2) is reproduced as a bad fixture that must be flagged by
   exactly the intended rule at the marked line — and a clean twin that
   must produce no finding at all (false-positive guard).
2. **Rule semantics**: inline-source tests for the jit-boundary family
   and the suppression machinery.
3. **The self-lint gate**: linting ``predictionio_tpu/`` must yield zero
   unsuppressed findings, and every suppression must carry a reason —
   this is the tier-1 gate that keeps future Pallas PRs from
   reintroducing the round-5 bug classes.

The linter is stdlib-only by design (it must run where jax cannot
import), so these tests never need a device.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from predictionio_tpu.lint import (
    all_rules,
    lint_file,
    lint_paths,
    render_json,
    render_text,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "predictionio_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def _unsuppressed(path: str):
    return [f for f in lint_file(path) if not f.suppressed]


def _marker_line(path: str, marker: str) -> int:
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            if marker in line:
                return lineno
    raise AssertionError(f"marker {marker!r} not in {path}")


def _package_findings(result, path_suffix: str, rule_prefix: str):
    """Unsuppressed findings for one in-tree file, filtered out of the
    shared module-scoped package sweep — the exemplar pins read the one
    LintResult instead of each re-running the engine."""
    suffix = path_suffix.replace("/", os.sep)
    return [
        f for f in result.findings
        if f.path.endswith(suffix) and f.rule_id.startswith(rule_prefix)
    ]


# ---------------------------------------------------------------------------
# 1. Round-5 Mosaic bug-class fixtures
# ---------------------------------------------------------------------------


class TestRound5Fixtures:
    """Each bad fixture fires exactly its intended rule, at the marked
    line; each clean twin is silent."""

    @pytest.mark.parametrize(
        "fixture,rule_id",
        [
            ("unaligned_lane_slice_bad.py", "mosaic-unaligned-lane-slice"),
            ("rank3_compare_bad.py", "mosaic-rank3-compare"),
            ("per_row_dma_bad.py", "mosaic-per-row-dma"),
        ],
    )
    def test_bad_fixture_fires_exactly_intended_rule(self, fixture, rule_id):
        path = os.path.join(FIXTURES, fixture)
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [rule_id], (
            f"{fixture}: expected exactly one {rule_id} finding, got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )
        assert findings[0].line == _marker_line(path, "BAD")

    @pytest.mark.parametrize(
        "fixture",
        [
            "unaligned_lane_slice_clean.py",
            "rank3_compare_clean.py",
            "per_row_dma_clean.py",
        ],
    )
    def test_clean_twin_has_no_findings(self, fixture):
        path = os.path.join(FIXTURES, fixture)
        findings = lint_file(path)
        assert findings == [], (
            f"false positive(s) on clean twin {fixture}: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )


class TestBf16AccumFixtures:
    """``mosaic-bf16-accum`` (the round-12 bf16-gather default's safety
    rule): every contraction shape in the bad twin fires — direct cast,
    the conditional-dtype ``gdt`` idiom, and one-hop taint through a pad
    — the clean twin (kwarg pinned / explicit upcast / no bf16) is
    silent, and the REAL gather-build site in ops/als.py is the clean
    exemplar the rule's message cites."""

    def test_bad_fixture_fires_on_every_contraction(self):
        path = os.path.join(FIXTURES, "bf16_accum_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == ["mosaic-bf16-accum"] * 5, (
            f"expected five mosaic-bf16-accum findings (einsum, "
            f"dot_general, matmul, the @ operator form, and the "
            f"tuple-unpacked operands), got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_clean_twin_has_no_findings(self):
        path = os.path.join(FIXTURES, "bf16_accum_clean.py")
        findings = lint_file(path)
        assert findings == [], (
            f"false positive(s) on clean twin: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_als_gather_site_is_clean_exemplar(self, package_result):
        """ops/als.py mentions bfloat16 (the rule engages — the
        source-text bail does NOT skip it) yet carries zero findings:
        every normal-equation contraction pins f32 accumulation.
        Judged from the shared package sweep: one engine run serves
        every in-tree exemplar pin."""
        als_path = os.path.join(
            REPO, "predictionio_tpu", "ops", "als.py"
        )
        with open(als_path, encoding="utf-8") as fh:
            assert "bfloat16" in fh.read()
        findings = _package_findings(
            package_result, "ops/als.py", "mosaic-bf16-accum"
        )
        assert findings == [], (
            f"als.py gather build regressed the bf16 accumulation "
            f"contract: {[(f.rule_id, f.line) for f in findings]}"
        )


class TestRobustFixtures:
    """Family C (robustness) bad/clean twins, same contract as the
    round-5 fixtures: the bad file fires exactly its intended rule at
    the marked line, the clean twin is silent."""

    @pytest.mark.parametrize(
        "fixture,rule_id",
        [
            ("no_timeout_bad.py", "robust-no-timeout"),
            ("bare_sleep_retry_bad.py", "robust-bare-sleep-retry"),
            ("rename_no_fsync_bad.py", "robust-rename-no-fsync"),
        ],
    )
    def test_bad_fixture_fires_exactly_intended_rule(self, fixture, rule_id):
        path = os.path.join(FIXTURES, fixture)
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [rule_id], (
            f"{fixture}: expected exactly one {rule_id} finding, got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )
        assert findings[0].line == _marker_line(path, "BAD")

    @pytest.mark.parametrize(
        "fixture",
        ["no_timeout_clean.py", "bare_sleep_retry_clean.py",
         "rename_no_fsync_clean.py", "unbounded_retry_clean.py",
         "unbounded_cache_clean.py", "cutover_no_watermark_clean.py",
         "fallback_swallows_clean.py", "nonatomic_checkpoint_clean.py"],
    )
    def test_clean_twin_has_no_findings(self, fixture):
        path = os.path.join(FIXTURES, fixture)
        findings = lint_file(path)
        assert findings == [], (
            f"false positive(s) on clean twin {fixture}: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_unbounded_retry_bad_fires_on_both_loops(self):
        """The bad twin carries TWO unbounded retry shapes (swallow-and-
        continue, swallow-and-log); each fires exactly the intended
        rule at its while line."""
        path = os.path.join(FIXTURES, "unbounded_retry_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [
            "robust-unbounded-retry", "robust-unbounded-retry"
        ], [(f.rule_id, f.line) for f in findings]
        with open(path) as fh:
            while_lines = [
                lineno for lineno, line in enumerate(fh, start=1)
                if line.strip().startswith("while True")
            ]
        assert [f.line for f in findings] == while_lines

    def test_unbounded_cache_bad_fires_on_both_containers(self):
        """The bad twin carries TWO unbounded cache shapes (locked
        module-global dict, OrderedDict attribute over a class); each
        fires exactly robust-unbounded-cache at its marked store line."""
        path = os.path.join(FIXTURES, "unbounded_cache_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [
            "robust-unbounded-cache", "robust-unbounded-cache"
        ], [(f.rule_id, f.line) for f in findings]
        with open(path) as fh:
            marked = [
                lineno for lineno, line in enumerate(fh, start=1)
                if "# BAD:" in line
            ]
        assert sorted(f.line for f in findings) == marked

    def test_cutover_no_watermark_bad_fires_on_both_shapes(self):
        """The bad twin carries TWO flip shapes (if/else branch pair,
        bare conditional expression) inside cutover-named functions;
        each fires exactly robust-cutover-no-watermark at its marked
        flip line."""
        path = os.path.join(FIXTURES, "cutover_no_watermark_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [
            "robust-cutover-no-watermark", "robust-cutover-no-watermark"
        ], [(f.rule_id, f.line) for f in findings]
        with open(path) as fh:
            marked = [
                lineno for lineno, line in enumerate(fh, start=1)
                if "# BAD:" in line
            ]
        assert sorted(f.line for f in findings) == marked

    def test_fallback_swallows_bad_fires_on_both_shapes(self):
        """The bad twin carries TWO swallow shapes (function named for
        the fallback, handler that flips a ``degraded`` flag); each
        fires exactly robust-fallback-swallows at its marked except
        line."""
        path = os.path.join(FIXTURES, "fallback_swallows_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [
            "robust-fallback-swallows", "robust-fallback-swallows"
        ], [(f.rule_id, f.line) for f in findings]
        with open(path) as fh:
            marked = [
                lineno for lineno, line in enumerate(fh, start=1)
                if "# BAD:" in line
            ]
        assert sorted(f.line for f in findings) == marked

    def test_sharedcache_degrade_is_the_clean_exemplar(self, package_result):
        """fleet/sharedcache.py's client IS wall-to-wall degrade paths
        (every handler calls _record_degrade, so the name gate engages
        on each one) yet carries zero findings: the outcome counter,
        the lastError capture and the debug log are exactly the
        recording evidence the rule demands."""
        findings = _package_findings(
            package_result, "fleet/sharedcache.py",
            "robust-fallback-swallows",
        )
        assert findings == [], (
            f"fleet/sharedcache.py regressed its exemplar status: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_sharedcache_mutated_swallow_is_caught(self):
        """Strip ONE degrade site of its recording (swap the
        _record_degrade call for a bare advisory-named helper, drop the
        bound exception) and the rule bites — proof the exemplar above
        is load-bearing, not accidentally exempt."""
        path = os.path.join(
            PACKAGE, "fleet", "sharedcache.py"
        )
        with open(path) as fh:
            source = fh.read()
        anchor = (
            "except CircuitOpen as exc:\n"
            '            return self._record_degrade("open", exc)'
        )
        mutated = source.replace(
            anchor,
            "except CircuitOpen:\n"
            "            return self._advisory_miss()",
            1,
        )
        assert mutated != source, "mutation anchor drifted out of source"
        findings = [
            f for f in lint_file(path, source=mutated)
            if f.rule_id == "robust-fallback-swallows" and not f.suppressed
        ]
        assert len(findings) == 1, [(f.rule_id, f.line) for f in findings]

    def test_nonatomic_checkpoint_bad_fires_on_all_marked_writes(self):
        """The bad twin carries FOUR raw-write shapes across two
        checkpoint-marked scopes (np.save to the final path, open-w +
        json.dump, open-wb in a persist method); each fires exactly
        robust-nonatomic-checkpoint at its marked line."""
        path = os.path.join(FIXTURES, "nonatomic_checkpoint_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [
            "robust-nonatomic-checkpoint"
        ] * 4, [(f.rule_id, f.line) for f in findings]
        with open(path) as fh:
            marked = [
                lineno for lineno, line in enumerate(fh, start=1)
                if "# BAD:" in line
            ]
        assert sorted(f.line for f in findings) == marked

    def test_ckpt_store_is_the_clean_exemplar(self, package_result):
        """ckpt/store.py's save path IS the rule's target shape (the
        name gate engages on save/_save_files, both write checkpoint
        files) yet carries zero findings: every byte goes through
        atomic_write_bytes, which is exactly the commit evidence the
        rule demands."""
        findings = _package_findings(
            package_result, "ckpt/store.py",
            "robust-nonatomic-checkpoint",
        )
        assert findings == [], (
            f"ckpt/store.py regressed its exemplar status: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_ckpt_store_mutated_raw_write_is_caught(self):
        """Swap the store's one atomic per-file write for a raw
        open().write() and the rule bites — proof the exemplar above is
        load-bearing, not accidentally exempt."""
        path = os.path.join(PACKAGE, "ckpt", "store.py")
        with open(path) as fh:
            source = fh.read()
        anchor = "atomic_write_bytes(os.path.join(d, fname), data)"
        mutated = source.replace(
            anchor,
            'open(os.path.join(d, fname), "wb").write(data)',
            1,
        )
        assert mutated != source, "mutation anchor drifted out of source"
        findings = [
            f for f in lint_file(path, source=mutated)
            if f.rule_id == "robust-nonatomic-checkpoint"
            and not f.suppressed
        ]
        assert len(findings) == 1, [(f.rule_id, f.line) for f in findings]

    def test_migration_cutover_is_the_clean_exemplar(self, package_result):
        """storage/migration.py's cutover() IS a layout flip (the name
        gate engages, self._active is assigned one store per branch)
        yet carries zero findings: the freeze, the final drain_queue
        and the per-keyspace watermark loop ahead of the flip are the
        barrier evidence the rule demands."""
        findings = _package_findings(
            package_result, "storage/migration.py",
            "robust-cutover-no-watermark",
        )
        assert findings == [], (
            f"storage/migration.py regressed its exemplar status: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_response_cache_is_the_clean_exemplar(self, package_result):
        """fleet/cache.py IS a cache (the name gate engages, it stores
        under request-derived keys) yet carries zero findings: the LRU
        popitem under the len() bound and the TTL/epoch drops are the
        eviction evidence the rule demands."""
        findings = _package_findings(
            package_result, "fleet/cache.py", "robust-unbounded-cache"
        )
        assert findings == [], (
            f"fleet/cache.py regressed its own bound: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )


#: family E/F fixture slug → the one rule its bad twin must trip
_CONC_FIXTURES = [
    ("unguarded_attr", "conc-unguarded-attr"),
    ("acquire_no_with", "conc-acquire-no-with"),
    ("blocking_under_lock", "conc-blocking-under-lock"),
    ("lock_order", "conc-lock-order"),
    ("module_mutable", "conc-module-mutable"),
    ("contextvar_thread_hop", "conc-contextvar-thread-hop"),
]

_SPMD_FIXTURES = [
    ("collective_host_branch", "spmd-collective-host-branch"),
    ("axis_name_mismatch", "spmd-axis-name-mismatch"),
    ("spec_rank_mismatch", "spmd-spec-rank-mismatch"),
    ("shard_map_arity", "spmd-shard-map-arity"),
    ("unordered_operand", "spmd-unordered-collective-operand"),
    ("host_dependent_rng", "spmd-host-dependent-rng"),
    ("collective_missing_axis", "spmd-collective-missing-axis"),
    # the *args-forwarding direction: judged through the call graph
    # (family G's deep component shares the per-file rule's id)
    ("collective_vararg_axis", "spmd-collective-missing-axis"),
    ("unguarded_downcast", "spmd-unguarded-downcast"),
]

#: family G (cross-file flow) fixture slug → its rule — single-file
#: twins work through lint_file's one-module package context
_FLOW_FIXTURES = [
    ("flow_blocking_under_lock", "flow-blocking-under-lock"),
    ("flow_deadline_dropped", "flow-deadline-dropped"),
    ("flow_thread_leak", "flow-thread-leak"),
]


class TestShardedTrainerExemplar:
    """ops/als_sharded.py is the spmd family's clean exemplar BY TEST:
    its shard_map-mapped body carries a psum + all_gather the rules
    genuinely inspect (proven by mutating the source: stripping the
    psum's axis makes the new rule fire), and the real file is clean."""

    _PSUM_CALL = "jax.lax.psum(local_yty, SHARD_AXIS)"

    def _path(self):
        return os.path.join(
            REPO, "predictionio_tpu", "ops", "als_sharded.py"
        )

    def test_sharded_trainer_is_clean(self, package_result):
        findings = _package_findings(
            package_result, "ops/als_sharded.py", "spmd-"
        )
        assert findings == [], (
            f"als_sharded.py regressed the spmd contract: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_rule_genuinely_engages_on_the_trainer(self):
        """Strip the Gramian psum's axis argument and the new rule must
        fire — the exemplar is inside the rule's scope, not skipped."""
        with open(self._path(), encoding="utf-8") as fh:
            src = fh.read()
        assert self._PSUM_CALL in src  # the collective the pin rides on
        mutated = src.replace(self._PSUM_CALL, "jax.lax.psum(local_yty)")
        findings = [
            f
            for f in lint_file(self._path(), source=mutated)
            if f.rule_id == "spmd-collective-missing-axis"
        ]
        assert len(findings) == 1, (
            f"expected the axis-stripped psum to fire exactly once, got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )


class TestQuantTableExemplar:
    """quant/table.py is spmd-unguarded-downcast's clean exemplar BY
    TEST: ``quantize_serving_table`` is serve-marked AND narrows to int8
    in-scope, yet carries zero findings because ``topk_match_gate`` sits
    in the same scope — the cut-precision-AND-measure adjacency the rule
    demands. The mutation proves the rule genuinely inspects it."""

    _GATE_CALL = "match_rate = topk_match_gate("

    def _path(self):
        return os.path.join(
            REPO, "predictionio_tpu", "quant", "table.py"
        )

    def test_quant_table_is_clean(self, package_result):
        findings = _package_findings(
            package_result, "quant/table.py", "spmd-"
        )
        assert findings == [], (
            f"quant/table.py regressed its exemplar status: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_rule_genuinely_engages_on_the_table(self):
        """Swap the gate call for a non-gate-shaped name and the rule
        must fire on the inlined int8 encode — the exemplar is inside
        the rule's scope, not skipped."""
        with open(self._path(), encoding="utf-8") as fh:
            src = fh.read()
        assert self._GATE_CALL in src  # the gate the pin rides on
        mutated = src.replace(self._GATE_CALL, "match_rate = probe_overlap(")
        findings = [
            f
            for f in lint_file(self._path(), source=mutated)
            if f.rule_id == "spmd-unguarded-downcast"
        ]
        assert len(findings) == 1, (
            f"expected the ungated int8 encode to fire exactly once, got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )


class TestConcSpmdFixtures:
    """Family E (concurrency) and family F (SPMD) bad/clean twins, same
    contract as the other families: the bad twin fires exactly its
    intended rule at the marked line, the clean twin is silent under the
    FULL rule set (no cross-family false positives)."""

    @pytest.mark.parametrize(
        "slug,rule_id", _CONC_FIXTURES + _SPMD_FIXTURES + _FLOW_FIXTURES
    )
    def test_bad_fixture_fires_exactly_intended_rule(self, slug, rule_id):
        path = os.path.join(FIXTURES, f"{slug}_bad.py")
        findings = _unsuppressed(path)
        assert [f.rule_id for f in findings] == [rule_id], (
            f"{slug}: expected exactly one {rule_id} finding, got "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )
        assert findings[0].line == _marker_line(path, "BAD")

    @pytest.mark.parametrize(
        "slug",
        [s for s, _ in _CONC_FIXTURES + _SPMD_FIXTURES + _FLOW_FIXTURES],
    )
    def test_clean_twin_has_no_findings(self, slug):
        path = os.path.join(FIXTURES, f"{slug}_clean.py")
        findings = lint_file(path)
        assert findings == [], (
            f"false positive(s) on clean twin {slug}: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    @pytest.mark.parametrize(
        "slug,rule_id",
        [_CONC_FIXTURES[0], _SPMD_FIXTURES[0]],
        ids=["conc", "spmd"],
    )
    def test_suppression_without_reason_is_a_finding(self, slug, rule_id):
        """Per-family: a bare suppression on a family E/F finding is
        itself a finding — the reason stays mandatory for the new
        families."""
        path = os.path.join(FIXTURES, f"{slug}_bad.py")
        with open(path) as fh:
            lines = fh.read().splitlines()
        marker = _marker_line(path, "BAD") - 1
        code = lines[marker].split("#")[0].rstrip()
        lines[marker] = f"{code}  # pio: lint-ok[{rule_id}]"
        findings = lint_file(path, source="\n".join(lines) + "\n")
        unsuppressed = {f.rule_id for f in findings if not f.suppressed}
        assert "lint-suppression-missing-reason" in unsuppressed
        suppressed = [f for f in findings if f.suppressed]
        assert [f.rule_id for f in suppressed] == [rule_id]

    @pytest.mark.parametrize(
        "slug,rule_id",
        [_CONC_FIXTURES[0], _SPMD_FIXTURES[0]],
        ids=["conc", "spmd"],
    )
    def test_suppression_with_reason_suppresses(self, slug, rule_id):
        path = os.path.join(FIXTURES, f"{slug}_bad.py")
        with open(path) as fh:
            lines = fh.read().splitlines()
        marker = _marker_line(path, "BAD") - 1
        code = lines[marker].split("#")[0].rstrip()
        lines[marker] = f"{code}  # pio: lint-ok[{rule_id}] reviewed"
        findings = lint_file(path, source="\n".join(lines) + "\n")
        assert [f.rule_id for f in findings if not f.suppressed] == []
        assert [f.rule_id for f in findings if f.suppressed] == [rule_id]


# ---------------------------------------------------------------------------
# 2. Rule semantics (inline sources)
# ---------------------------------------------------------------------------


def _lint_source(source: str, path: str = "predictionio_tpu/x.py"):
    return lint_file(path, source=source)


class TestJitRules:
    def test_python_branch_on_traced_arg_fires(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["jit-python-branch"]
        assert findings[0].line == 4

    def test_branch_on_static_arg_is_clean(self):
        src = (
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnames=('flag',))\n"
            "def f(x, flag):\n"
            "    if flag:\n"
            "        return x\n"
            "    return -x\n"
        )
        assert _lint_source(src) == []

    def test_branch_on_shape_facet_is_clean(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x.shape[0] > 8:\n"
            "        return x[:8]\n"
            "    return x\n"
        )
        assert _lint_source(src) == []

    def test_jit_in_loop_fires(self):
        src = (
            "import jax\n"
            "def warm(fns):\n"
            "    out = []\n"
            "    for fn in fns:\n"
            "        out.append(jax.jit(fn))\n"
            "    return out\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["jit-in-loop"]

    def test_host_sync_scoped_to_hot_path_modules(self):
        src = (
            "def respond(result):\n"
            "    return result.block_until_ready()\n"
        )
        hot = _lint_source(src, path="predictionio_tpu/workflow/serving.py")
        assert [f.rule_id for f in hot] == ["jit-host-sync-serving"]
        # same source outside the hot path: no finding
        assert _lint_source(src, path="predictionio_tpu/ops/als.py") == []

    def test_module_level_device_array_fires(self):
        src = (
            "import jax.numpy as jnp\n"
            "SCALE = jnp.ones((8, 128))\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["jit-module-device-array"]

    def test_nonhashable_static_default_fires(self):
        src = (
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnames=('opts',))\n"
            "def f(x, opts=[]):\n"
            "    return x\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["jit-nonhashable-static"]


class TestRobustRules:
    def test_requests_without_timeout_fires(self):
        src = (
            "import requests\n"
            "def post(url, data):\n"
            "    return requests.post(url, json=data)\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["robust-no-timeout"]

    def test_requests_with_timeout_is_clean(self):
        src = (
            "import requests\n"
            "def post(url, data):\n"
            "    return requests.post(url, json=data, timeout=10)\n"
        )
        assert _lint_source(src) == []

    def test_kwargs_splat_gets_benefit_of_the_doubt(self):
        src = (
            "import requests\n"
            "def post(url, **kw):\n"
            "    return requests.post(url, **kw)\n"
        )
        assert _lint_source(src) == []

    def test_urlopen_positional_timeout_is_clean(self):
        src = (
            "import urllib.request\n"
            "def get(url):\n"
            "    return urllib.request.urlopen(url, None, 5).read()\n"
        )
        assert _lint_source(src) == []

    def test_urlopen_without_timeout_fires(self):
        src = (
            "import urllib.request\n"
            "def get(url):\n"
            "    return urllib.request.urlopen(url).read()\n"
        )
        assert [f.rule_id for f in _lint_source(src)] == ["robust-no-timeout"]

    def test_http_connection_without_timeout_fires(self):
        src = (
            "import http.client\n"
            "def conn(host):\n"
            "    return http.client.HTTPConnection(host, 80)\n"
        )
        assert [f.rule_id for f in _lint_source(src)] == ["robust-no-timeout"]

    def test_constant_sleep_in_retry_loop_fires(self):
        src = (
            "import time\n"
            "def poll(fn):\n"
            "    while True:\n"
            "        try:\n"
            "            return fn()\n"
            "        except OSError:\n"
            "            time.sleep(5)\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["robust-bare-sleep-retry"]
        assert findings[0].line == 7

    def test_variable_delay_sleep_is_clean(self):
        # a computed (e.g. jittered) delay is exactly the fix — no finding
        src = (
            "import random, time\n"
            "def poll(fn, base):\n"
            "    while True:\n"
            "        try:\n"
            "            return fn()\n"
            "        except OSError:\n"
            "            time.sleep(random.uniform(0, base))\n"
        )
        assert _lint_source(src) == []

    def test_pacing_sleep_outside_except_is_clean(self):
        src = (
            "import time\n"
            "def drain(pending):\n"
            "    while pending():\n"
            "        time.sleep(0.005)\n"
        )
        assert _lint_source(src) == []

    def test_sleep_in_except_outside_any_loop_is_clean(self):
        src = (
            "import time\n"
            "def once(fn):\n"
            "    try:\n"
            "        return fn()\n"
            "    except OSError:\n"
            "        time.sleep(1)\n"
        )
        assert _lint_source(src) == []

    def test_one_shot_fallback_defined_inside_a_loop_is_clean(self):
        # a def nested in a loop body is a NEW scope: its one-shot
        # except/sleep is not part of the loop's retry schedule
        src = (
            "import time\n"
            "def wire(fns):\n"
            "    out = []\n"
            "    for fn in fns:\n"
            "        def once(fn=fn):\n"
            "            try:\n"
            "                return fn()\n"
            "            except OSError:\n"
            "                time.sleep(1)\n"
            "        out.append(once)\n"
            "    return out\n"
        )
        assert _lint_source(src) == []


class TestMosaicRuleScoping:
    def test_blockspec_tiling_fires_on_unaligned_literal(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        _k,\n"
            "        in_specs=[pl.BlockSpec((8, 56), lambda i: (i, 0))],\n"
            "    )(x)\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["mosaic-blockspec-tiling"]

    def test_smem_blockspec_exempt(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        _k,\n"
            "        in_specs=[pl.BlockSpec((4, 60), lambda i: (i, 0),\n"
            "                               memory_space=pltpu.SMEM)],\n"
            "    )(x)\n"
        )
        assert _lint_source(src) == []

    def test_non_kernel_function_not_scanned_for_lane_slices(self):
        # pl.ds-looking code outside any pallas_call kernel: Family A
        # does not apply (host-side helpers may slice freely)
        src = (
            "import jax.numpy as jnp\n"
            "def host_helper(x_ref):\n"
            "    return x_ref[:, 3:19]\n"
        )
        assert _lint_source(src) == []


class TestSuppressions:
    BAD_KERNEL = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def _k(x_ref, o_ref):\n"
        "    o_ref[:] = x_ref[:, pl.ds(16, 16)]{comment}\n"
        "def call(x, out_shape):\n"
        "    return pl.pallas_call(_k, out_shape=out_shape)(x)\n"
    )

    def test_suppression_with_reason_suppresses(self):
        src = self.BAD_KERNEL.format(
            comment="  # pio: lint-ok[mosaic-unaligned-lane-slice] fixture"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["mosaic-unaligned-lane-slice"]
        assert findings[0].suppressed
        assert findings[0].suppress_reason == "fixture"

    def test_suppression_on_line_above_applies(self):
        src = self.BAD_KERNEL.replace(
            "    o_ref[:] = x_ref[:, pl.ds(16, 16)]{comment}\n",
            "    # pio: lint-ok[mosaic-unaligned-lane-slice] one above\n"
            "    o_ref[:] = x_ref[:, pl.ds(16, 16)]\n",
        )
        findings = _lint_source(src)
        assert [f.suppressed for f in findings] == [True]

    def test_bare_suppression_is_itself_a_finding(self):
        src = self.BAD_KERNEL.format(
            comment="  # pio: lint-ok[mosaic-unaligned-lane-slice]"
        )
        findings = _lint_source(src)
        ids = {f.rule_id for f in findings if not f.suppressed}
        assert "lint-suppression-missing-reason" in ids

    def test_wrong_rule_id_does_not_suppress(self):
        src = self.BAD_KERNEL.format(
            comment="  # pio: lint-ok[mosaic-rank3-compare] wrong id"
        )
        findings = [f for f in _lint_source(src) if not f.suppressed]
        assert "mosaic-unaligned-lane-slice" in [f.rule_id for f in findings]

    def test_unused_suppression_is_reported_stale(self):
        src = (
            "import jax.numpy as jnp\n"
            "# pio: lint-ok[jit-in-loop] exception long since fixed\n"
            "def f(x):\n"
            "    return x\n"
        )
        findings = _lint_source(src)
        assert [f.rule_id for f in findings] == ["lint-unused-suppression"]

    def test_select_cannot_manufacture_staleness(self):
        # the suppression's rule did not run, so its use is unknowable —
        # no stale report
        src = (
            "# pio: lint-ok[jit-in-loop] exception long since fixed\n"
            "def f(x):\n"
            "    return x\n"
        )
        from predictionio_tpu.lint import all_rules as _all

        rules = [r for r in _all() if r.id == "jit-python-branch"]
        findings = lint_file("predictionio_tpu/x.py", rules=rules, source=src)
        assert findings == []

    def test_trailing_suppression_does_not_cover_next_line(self):
        # a suppression trailing code on line N covers line N only; the
        # same-rule violation on line N+1 must still be reported
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "def _k(x_ref, o_ref):\n"
            "    a = x_ref[:, pl.ds(16, 16)]  "
            "# pio: lint-ok[mosaic-unaligned-lane-slice] reviewed\n"
            "    b = x_ref[:, pl.ds(32, 16)]\n"
            "    o_ref[:] = a + b\n"
            "def call(x, out_shape):\n"
            "    return pl.pallas_call(_k, out_shape=out_shape)(x)\n"
        )
        findings = _lint_source(src)
        unsuppressed = [f for f in findings if not f.suppressed]
        assert [(f.rule_id, f.line) for f in unsuppressed] == [
            ("mosaic-unaligned-lane-slice", 6)
        ]

    def test_pattern_in_string_literal_is_not_a_suppression(self):
        # the pattern inside a string on the line directly above the
        # finding — only a real comment may suppress
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "def _k(x_ref, o_ref):\n"
            '    doc = "# pio: lint-ok[mosaic-unaligned-lane-slice] ok"\n'
            "    o_ref[:] = x_ref[:, pl.ds(16, 16)]\n"
            "def call(x, out_shape):\n"
            "    return pl.pallas_call(_k, out_shape=out_shape)(x)\n"
        )
        unsuppressed = [f for f in _lint_source(src) if not f.suppressed]
        assert [f.rule_id for f in unsuppressed] == [
            "mosaic-unaligned-lane-slice"
        ]


# ---------------------------------------------------------------------------
# 3. CLI contract + the self-lint gate
# ---------------------------------------------------------------------------


def _run_cli(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.lint", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


class TestCLI:
    def test_exit_nonzero_on_unsuppressed_findings(self):
        proc = _run_cli(os.path.join(FIXTURES, "rank3_compare_bad.py"))
        assert proc.returncode == 1
        assert "mosaic-rank3-compare" in proc.stdout

    def test_exit_zero_on_clean_file(self):
        proc = _run_cli(os.path.join(FIXTURES, "rank3_compare_clean.py"))
        assert proc.returncode == 0

    def test_closed_pipe_dies_quietly(self, tmp_path):
        # `pio lint ... | head` closes stdout early: no traceback may
        # reach stderr (the old behavior raised BrokenPipeError out of
        # print at interpreter exit)
        for i in range(250):
            (tmp_path / f"f{i}.py").write_text(
                open(
                    os.path.join(FIXTURES, "unaligned_lane_slice_bad.py")
                ).read()
            )
        proc = subprocess.run(
            f"{sys.executable} -m predictionio_tpu.tools.lint "
            f"{tmp_path} | head -c 100 > /dev/null",
            shell=True, capture_output=True, text=True, cwd=REPO,
            timeout=120,
        )
        assert "Traceback" not in proc.stderr, proc.stderr[-2000:]

    def test_nonexistent_path_is_an_engine_error(self):
        # a typo'd target must never read as lint-clean — and it is an
        # ENGINE error (exit 2), not a finding (exit 1): the run proved
        # nothing
        proc = _run_cli("no/such/dir_xyz")
        assert proc.returncode == 2
        assert "no such file or directory" in proc.stdout

    def test_json_format_is_machine_readable(self):
        proc = _run_cli(
            os.path.join(FIXTURES, "per_row_dma_bad.py"), "--format", "json"
        )
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["ok"] is False
        assert [f["rule"] for f in doc["findings"]] == ["mosaic-per-row-dma"]
        assert doc["findings"][0]["path"].endswith("per_row_dma_bad.py")

    def test_select_restricts_rules(self):
        proc = _run_cli(
            os.path.join(FIXTURES, "per_row_dma_bad.py"),
            "--select", "mosaic-rank3-compare",
        )
        assert proc.returncode == 0  # the only finding is a per-row-dma

    def test_list_rules_covers_all_families(self):
        proc = _run_cli("--list-rules")
        assert proc.returncode == 0
        assert "mosaic-unaligned-lane-slice" in proc.stdout
        assert "jit-python-branch" in proc.stdout
        assert "conc-unguarded-attr" in proc.stdout
        assert "spmd-collective-host-branch" in proc.stdout

    def test_unreadable_file_is_a_parse_error_not_a_crash(self, tmp_path):
        # null bytes raise ValueError from ast.parse; the run must record
        # a parse error and exit 2 (engine error), not hand the watcher
        # a traceback
        bad = tmp_path / "nul.py"
        bad.write_bytes(b"x = 1\x00\n")
        proc = _run_cli(str(tmp_path))
        assert proc.returncode == 2
        assert "parse-error" in proc.stdout
        assert "Traceback" not in proc.stderr

    def test_hidden_and_vendored_dirs_are_pruned(self, tmp_path):
        venv = tmp_path / ".venv"
        venv.mkdir()
        (venv / "vendored.py").write_text(
            "import jax.numpy as jnp\nX = jnp.ones((8, 128))\n"
        )
        (tmp_path / "ok.py").write_text("x = 1\n")
        proc = _run_cli(str(tmp_path))
        assert proc.returncode == 0
        assert "1 files" in proc.stdout

    def test_hot_path_scoping_survives_relative_invocation(
        self, tmp_path, monkeypatch
    ):
        # the `cd workflow && pio lint serving.py` shape: path-scoped
        # rules must see the module identity through a bare filename
        wf = tmp_path / "workflow"
        wf.mkdir()
        (wf / "serving.py").write_text(
            "def respond(r):\n    return r.block_until_ready()\n"
        )
        monkeypatch.chdir(wf)
        findings = lint_file("serving.py")
        assert [f.rule_id for f in findings] == ["jit-host-sync-serving"]

    def test_console_subcommand_dispatches(self):
        # `pio lint` rides bin/pio -> tools.console -> tools.lint; the
        # console path must work without a storage plane or jax import
        proc = subprocess.run(
            [sys.executable, "-m", "predictionio_tpu.tools.console",
             "lint", os.path.join(FIXTURES, "rank3_compare_bad.py")],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert proc.returncode == 1
        assert "mosaic-rank3-compare" in proc.stdout


class TestChangedAndBaseline:
    """``pio lint --changed`` (git-diff-scoped) and ``--baseline``
    (adopt/ratchet), plus the pinned exit-code contract: 0 clean,
    1 findings, 2 engine error.

    These call ``tools.lint.main`` in-process (exit code = return
    value, output via capsys): the subprocess transport is already
    covered by TestCLI, and a fresh interpreter per case would cost
    the tier-1 budget ~20 s for no extra coverage."""

    BAD = os.path.join(FIXTURES, "rank3_compare_bad.py")
    CLEAN = os.path.join(FIXTURES, "rank3_compare_clean.py")

    def _run(self, capsys, *argv):
        from predictionio_tpu.tools import lint as lint_cli

        rc = lint_cli.main(list(argv))
        return rc, capsys.readouterr().out

    def test_exit_codes_pinned(self, tmp_path, capsys):
        assert self._run(capsys, self.CLEAN)[0] == 0
        assert self._run(capsys, self.BAD)[0] == 1
        nul = tmp_path / "nul.py"
        nul.write_bytes(b"x\x00\n")
        assert self._run(capsys, str(nul))[0] == 2

    def _git(self, cwd, *args):
        return subprocess.run(
            ["git", *args], cwd=cwd, capture_output=True, text=True,
            timeout=30,
        )

    def _make_repo(self, tmp_path):
        repo = tmp_path / "repo"
        repo.mkdir()
        assert self._git(repo, "init", "-q").returncode == 0
        self._git(repo, "config", "user.email", "t@example.com")
        self._git(repo, "config", "user.name", "t")
        return repo

    def test_changed_lints_only_git_modified_files(
        self, tmp_path, capsys, monkeypatch
    ):
        repo = self._make_repo(tmp_path)
        monkeypatch.chdir(repo)
        # a committed file WITH a violation: out of scope for --changed
        (repo / "legacy.py").write_text(open(self.BAD).read())
        self._git(repo, "add", "legacy.py")
        assert self._git(repo, "commit", "-qm", "seed").returncode == 0
        rc, out = self._run(capsys, "--changed", str(repo))
        assert rc == 0, out
        assert "no changed files" in out
        # an uncommitted (untracked) violation IS in scope
        (repo / "fresh.py").write_text(open(self.BAD).read())
        rc, out = self._run(capsys, "--changed", str(repo))
        assert rc == 1, out
        assert "fresh.py" in out
        assert "legacy.py" not in out
        assert "1 files" in out
        # a modified tracked file joins the scope too
        (repo / "legacy.py").write_text(
            open(self.BAD).read() + "\nX = 1\n"
        )
        _rc, out = self._run(capsys, "--changed", str(repo))
        assert "2 files" in out

    def test_changed_outside_a_git_repo_is_an_engine_error(
        self, tmp_path, capsys, monkeypatch
    ):
        # a silent empty set would read as "clean" — it must be exit 2
        lone = tmp_path / "lone"
        lone.mkdir()
        monkeypatch.chdir(lone)
        monkeypatch.setenv("GIT_CEILING_DIRECTORIES", str(tmp_path))
        rc, out = self._run(capsys, "--changed", str(lone))
        assert rc == 2, out
        assert "--changed" in out

    def test_baseline_adopts_then_ratchets(self, tmp_path, capsys):
        # adopt: record today's findings; the same run is then clean
        rc, recorded = self._run(capsys, self.BAD, "--format", "json")
        assert rc == 1
        baseline = tmp_path / "baseline.json"
        baseline.write_text(recorded)
        rc, out = self._run(capsys, self.BAD, "--baseline", str(baseline))
        assert rc == 0, out
        assert "1 baselined" in out
        doc = json.loads(recorded)
        assert [f["rule"] for f in doc["findings"]] == [
            "mosaic-rank3-compare"
        ]
        # different path: the baseline keys on (path, rule), so the
        # same content elsewhere is NEW debt, not adopted
        grown = tmp_path / "grown.py"
        grown.write_text(open(self.BAD).read())
        rc, _out = self._run(
            capsys, str(grown), "--baseline", str(baseline)
        )
        assert rc == 1

    def test_baseline_same_path_absorbs_only_the_recorded_count(
        self, tmp_path, capsys
    ):
        bad_src = open(self.BAD).read()
        target = tmp_path / "mod.py"
        target.write_text(bad_src)
        _rc, recorded = self._run(capsys, str(target), "--format", "json")
        baseline = tmp_path / "baseline.json"
        baseline.write_text(recorded)
        # same content: adopted clean
        assert self._run(
            capsys, str(target), "--baseline", str(baseline)
        )[0] == 0
        # duplicate the kernel under new names -> more findings of the
        # same rule in the same file than the baseline recorded: fails
        clone = bad_src.replace("_mask_kernel", "_mask_kernel2").replace(
            "def run(", "def run2("
        )
        target.write_text(bad_src + "\n\n" + clone)
        rc, out = self._run(
            capsys, str(target), "--baseline", str(baseline)
        )
        assert rc == 1, out

    def test_baseline_unreadable_is_an_engine_error(self, tmp_path, capsys):
        missing = tmp_path / "nope.json"
        assert self._run(
            capsys, self.CLEAN, "--baseline", str(missing)
        )[0] == 2
        bad_json = tmp_path / "bad.json"
        bad_json.write_text("{\"not\": \"findings\"}")
        assert self._run(
            capsys, self.CLEAN, "--baseline", str(bad_json)
        )[0] == 2

    def test_baselined_findings_are_reported_in_json(
        self, tmp_path, capsys
    ):
        _rc, recorded = self._run(capsys, self.BAD, "--format", "json")
        baseline = tmp_path / "baseline.json"
        baseline.write_text(recorded)
        rc, out = self._run(
            capsys, self.BAD, "--baseline", str(baseline),
            "--format", "json",
        )
        assert rc == 0
        doc = json.loads(out)
        assert doc["findings"] == []
        assert [f["rule"] for f in doc["baselined"]] == [
            "mosaic-rank3-compare"
        ]


@pytest.fixture(scope="module")
def package_result():
    """ONE package sweep shared by every gate assertion: the sweep is
    the expensive part (~15 s over 100+ files), the assertions are
    free — three tests each doing their own sweep cost the tier-1
    budget ~30 s for identical coverage."""
    return lint_paths([PACKAGE])


class TestSelfLintGate:
    """The tier-1 gate: the package itself must stay lint-clean. A new
    Pallas PR that reintroduces a round-5 bug class fails here before it
    ever reaches a compile."""

    def test_package_has_zero_unsuppressed_findings(self, package_result):
        result = package_result
        assert result.errors == [], result.errors
        assert result.findings == [], (
            "unsuppressed lint findings in the package:\n"
            + render_text(result)
        )

    def test_every_suppression_carries_a_reason(self, package_result):
        result = package_result
        missing = [f for f in result.suppressed if not f.suppress_reason]
        assert missing == [], [f.as_dict() for f in missing]

    def test_families_e_f_and_g_are_in_the_gate(self):
        """The self-lint gate runs ``all_rules()``; every conc-*/spmd-*/
        flow-* rule must be registered there (a family that quietly
        drops out of the default set stops gating anything)."""
        ids = {r.id for r in all_rules()}
        for _slug, rule_id in (
            _CONC_FIXTURES + _SPMD_FIXTURES + _FLOW_FIXTURES
        ):
            assert rule_id in ids, f"{rule_id} missing from all_rules()"
        assert sum(1 for i in ids if i.startswith("conc-")) >= 6
        assert sum(1 for i in ids if i.startswith("spmd-")) >= 7
        assert sum(1 for i in ids if i.startswith("flow-")) >= 3

    def test_rule_catalog_is_documented(self):
        """docs/lint.md is the catalog the suppression workflow points
        people at — every shipped rule id must appear there."""
        with open(os.path.join(REPO, "docs", "lint.md")) as fh:
            doc = fh.read()
        for rule in all_rules():
            assert rule.id in doc, f"rule {rule.id} missing from docs/lint.md"

    def test_json_reporter_roundtrips_package_result(self, package_result):
        result = package_result
        doc = json.loads(render_json(result))
        assert doc["ok"] is True
        assert doc["files"] == result.files
        assert all(f["suppressed"] for f in doc["suppressed"])


# ---------------------------------------------------------------------------
# 6. Family G — cross-file resolution, in-tree exemplars, cache contract
# ---------------------------------------------------------------------------


def _tmp_pkg(tmp_path, files):
    """A throwaway package directory for genuine multi-file flow tests
    (the single-file fixture twins cannot exercise import resolution)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    for name, src in files.items():
        (pkg / name).write_text(src)
    return str(pkg)


class TestFlowCrossFile:
    """Family G judged over a real multi-file package via lint_paths:
    the helper and its caller live in different modules."""

    def test_blocking_helper_in_another_module(self, tmp_path):
        pkg = _tmp_pkg(tmp_path, {
            "io_helpers.py":
                "import time\n\n\ndef flush():\n    time.sleep(0.2)\n",
            "server.py": (
                "import threading\n\n"
                "from pkg.io_helpers import flush\n\n\n"
                "class Store:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n\n"
                "    def put(self, v):\n"
                "        with self._lock:\n"
                "            flush()\n"
            ),
        })
        res = lint_paths([pkg])
        assert [
            (f.rule_id, os.path.basename(f.path)) for f in res.findings
        ] == [("flow-blocking-under-lock", "server.py")]
        # the verdict names both source locations: the held lock at the
        # call site and the blocking call inside the helper's file
        assert "io_helpers" in res.findings[0].message
        assert "time.sleep" in res.findings[0].message

    def test_one_level_limit_is_the_contract(self, tmp_path):
        # helper -> inner -> sleep is TWO hops from the lock: out of
        # contract by design (docs/lint.md#family-g) — must not fire
        pkg = _tmp_pkg(tmp_path, {
            "deep.py": (
                "import time\n\n\n"
                "def inner():\n    time.sleep(0.2)\n\n\n"
                "def helper():\n    return inner()\n"
            ),
            "server.py": (
                "import threading\n\n"
                "from pkg.deep import helper\n\n\n"
                "class Store:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n\n"
                "    def put(self, v):\n"
                "        with self._lock:\n"
                "            helper()\n"
            ),
        })
        assert lint_paths([pkg]).findings == []

    def test_deadline_dropped_across_modules(self, tmp_path):
        pkg = _tmp_pkg(tmp_path, {
            "store.py": (
                "def read_rows(shard, deadline=None):\n"
                "    return shard.read(deadline=deadline)\n"
            ),
            "router.py": (
                "from pkg.store import read_rows\n\n\n"
                "def fan_out(shards, deadline):\n"
                "    return [read_rows(s) for s in shards]\n"
            ),
        })
        res = lint_paths([pkg])
        assert [
            (f.rule_id, os.path.basename(f.path)) for f in res.findings
        ] == [("flow-deadline-dropped", "router.py")]

    def test_mapped_body_in_another_module(self, tmp_path):
        pkg = _tmp_pkg(tmp_path, {
            "bodies.py":
                "import jax\n\n\ndef gram(x):\n    return jax.lax.psum(x)\n",
            "train.py": (
                "from jax.experimental.shard_map import shard_map\n\n"
                "from pkg import bodies\n\n\n"
                "def fit(mesh, x):\n"
                "    f = shard_map(bodies.gram, mesh=mesh,\n"
                "                  in_specs=None, out_specs=None)\n"
                "    return f(x)\n"
            ),
        })
        res = lint_paths([pkg])
        assert [
            (f.rule_id, os.path.basename(f.path)) for f in res.findings
        ] == [("spmd-collective-missing-axis", "train.py")]

    def test_thread_leak_stop_resolved_through_base_class(self, tmp_path):
        sub_src = (
            "import threading\n\n"
            "from pkg.base import StoppableBase\n\n\n"
            "class Ticker(StoppableBase):\n"
            "    def __init__(self):\n"
            "        self._worker = threading.Thread(target=self._run)\n"
            "        self._worker.start()\n\n"
            "    def _run(self):\n"
            "        pass\n"
        )
        pkg = _tmp_pkg(tmp_path, {
            "base.py": (
                "class StoppableBase:\n"
                "    def close(self):\n"
                "        self._worker.join(timeout=5)\n"
            ),
            "sub.py": sub_src,
        })
        # the join lives in the in-package base class: clean
        assert lint_paths([pkg]).findings == []
        # sever the base and the same class leaks
        (tmp_path / "pkg" / "sub.py").write_text(
            sub_src.replace("(StoppableBase)", "")
        )
        res = lint_paths([pkg])
        assert [f.rule_id for f in res.findings] == ["flow-thread-leak"]


class TestFlowExemplars:
    """In-tree clean exemplars for each flow-* rule, pinned from the
    shared package sweep: the classes that got the discipline right by
    review stay the executable documentation of it."""

    @pytest.mark.parametrize(
        "path_suffix,rule",
        [
            ("fleet/router.py", "flow-blocking-under-lock"),
            ("fleet/router.py", "flow-thread-leak"),
            ("workflow/batching.py", "flow-thread-leak"),
            ("obs/slo.py", "flow-thread-leak"),
            ("storage/remote.py", "flow-deadline-dropped"),
        ],
    )
    def test_in_tree_exemplar_is_clean(
        self, package_result, path_suffix, rule
    ):
        findings = _package_findings(package_result, path_suffix, rule)
        assert findings == [], (
            f"{path_suffix} regressed its {rule} exemplar status: "
            f"{[(f.rule_id, f.line) for f in findings]}"
        )

    def test_thread_leak_genuinely_engages_on_the_replica_tailer(self):
        """Strip the tailer's stop-Event set and the rule must fire:
        the real class is inside the rule's scope, not skipped."""
        path = os.path.join(
            REPO, "predictionio_tpu", "storage", "replica.py"
        )
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        assert "self._stop_polling.set()" in src  # the evidence the pin rides on
        mutated = src.replace("self._stop_polling.set()", "pass")
        findings = [
            f for f in lint_file(path, source=mutated)
            if f.rule_id == "flow-thread-leak" and not f.suppressed
        ]
        assert len(findings) == 1, (
            f"expected the de-evidenced tailer to fire exactly once, "
            f"got {[(f.rule_id, f.line) for f in findings]}"
        )


class TestLintCache:
    """The incremental-cache contract (docs/lint.md failure-mode table):
    warm is byte-identical to cold, invalidation is exactly the
    reverse-import closure for flow-* and the file itself for per-file
    families, a rules change invalidates the world, and a corrupt cache
    is simply a cold sweep — a stale cache can never suppress a
    finding."""

    A = "import time\n\n\ndef pause():\n    time.sleep(0.01)\n"
    B = (
        "import threading\n\n"
        "from pkg.a import pause\n\n\n"
        "class Gate:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def wait(self):\n"
        "        with self._lock:\n"
        "            pause()\n"
    )
    C = "def free():\n    return 1\n"

    def _pkg(self, tmp_path):
        return _tmp_pkg(
            tmp_path, {"a.py": self.A, "b.py": self.B, "c.py": self.C}
        )

    def _sweep(self, pkg, cache):
        return lint_paths([pkg], cache_path=str(cache))

    def test_warm_run_is_byte_identical_and_fully_cached(self, tmp_path):
        pkg = self._pkg(tmp_path)
        cache = tmp_path / "cache.json"
        cold = self._sweep(pkg, cache)
        warm = self._sweep(pkg, cache)
        # the cross-file finding exists AND survives cache round-trip
        assert [f.rule_id for f in cold.findings] == [
            "flow-blocking-under-lock"
        ]
        assert render_json(cold) == render_json(warm)
        assert cold.stats["cache_hits"] == 0
        assert len(cold.stats["parsed"]) == 3
        assert warm.stats["cache_hits"] == 3
        assert warm.stats["parsed"] == []
        assert warm.stats["flow_ran"] == []
        assert warm.stats["flow_cached"] == 3

    def test_edit_relints_exactly_the_reverse_import_closure(
        self, tmp_path
    ):
        pkg = self._pkg(tmp_path)
        cache = tmp_path / "cache.json"
        self._sweep(pkg, cache)
        (tmp_path / "pkg" / "a.py").write_text(
            self.A.replace("0.01", "0.02")
        )
        res = self._sweep(pkg, cache)
        parsed = [os.path.basename(p) for p in res.stats["parsed"]]
        flow_ran = [os.path.basename(p) for p in res.stats["flow_ran"]]
        # per-file families: only the edited file re-parses
        assert parsed == ["a.py"]
        # flow-*: the edited file plus its reverse importers; c.py's
        # flow verdict comes from cache untouched
        assert flow_ran == ["a.py", "b.py"]
        assert res.stats["flow_cached"] == 1
        assert [f.rule_id for f in res.findings] == [
            "flow-blocking-under-lock"
        ]

    def test_from_package_import_submodule_is_a_tracked_dep(
        self, tmp_path
    ):
        """``from pkg import a`` binds a submodule the resolver follows,
        so the cache's dependency set must cover it too: editing the
        helper into a blocker must surface the importer's new finding
        on the very next warm run — the resolver and the deps
        disagreeing here IS the stale-cache-suppresses-a-finding mode."""
        pkg = _tmp_pkg(tmp_path, {
            "__init__.py": "",
            "a.py": "def pause():\n    return 0\n",
            "b.py": (
                "import threading\n\n"
                "from pkg import a\n\n\n"
                "class Gate:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n\n"
                "    def wait(self):\n"
                "        with self._lock:\n"
                "            a.pause()\n"
            ),
        })
        cache = tmp_path / "cache.json"
        cold = self._sweep(pkg, cache)
        assert cold.findings == []
        (tmp_path / "pkg" / "a.py").write_text(
            "import time\n\n\ndef pause():\n    time.sleep(0.2)\n"
        )
        warm = self._sweep(pkg, cache)
        flow_ran = [os.path.basename(p) for p in warm.stats["flow_ran"]]
        assert "b.py" in flow_ran
        assert [f.rule_id for f in warm.findings] == [
            "flow-blocking-under-lock"
        ]
        assert warm.findings[0].path.endswith("b.py")

    def test_rules_version_bump_invalidates_everything(
        self, tmp_path, monkeypatch
    ):
        from predictionio_tpu.lint import engine

        pkg = self._pkg(tmp_path)
        cache = tmp_path / "cache.json"
        self._sweep(pkg, cache)
        monkeypatch.setattr(engine, "RULES_VERSION", "bumped-for-test")
        res = self._sweep(pkg, cache)
        assert res.stats["cache_hits"] == 0
        assert len(res.stats["parsed"]) == 3

    def test_corrupt_cache_falls_back_to_cold_sweep(self, tmp_path):
        pkg = self._pkg(tmp_path)
        cache = tmp_path / "cache.json"
        cold = self._sweep(pkg, cache)
        cache.write_text('{"version": 1, "files": [torn mid-write')
        res = self._sweep(pkg, cache)
        assert res.stats["cache_hits"] == 0
        assert render_json(res) == render_json(cold)  # verdict unchanged
        # and the torn file was atomically replaced with a good one
        assert self._sweep(pkg, cache).stats["cache_hits"] == 3

    def test_partial_rule_sets_never_touch_the_cache(self, tmp_path):
        # a --select run writing results a full run would later trust
        # IS the stale-cache-suppresses-a-finding failure mode
        pkg = self._pkg(tmp_path)
        cache = tmp_path / "cache.json"
        lint_paths(
            [pkg], select={"flow-blocking-under-lock"},
            cache_path=str(cache),
        )
        assert not cache.exists()


class TestExplainAndChangedClosure:
    """``pio lint --explain`` and the ``--changed`` reverse-import
    closure, in-process like TestChangedAndBaseline."""

    def _run(self, capsys, *argv):
        from predictionio_tpu.tools import lint as lint_cli

        rc = lint_cli.main(list(argv))
        return rc, capsys.readouterr().out

    def test_explain_prints_docstring_and_doc_anchor(self, capsys):
        rc, out = self._run(capsys, "--explain", "flow-thread-leak")
        assert rc == 0
        assert "docs/lint.md#flow-thread-leak" in out
        # a docstring phrase, not just the --list-rules short line
        assert "story reachable from" in out

    def test_explain_unknown_rule_is_an_engine_error(self, capsys):
        rc, out = self._run(capsys, "--explain", "no-such-rule")
        assert rc == 2
        assert "no-such-rule" in out

    def _git(self, cwd, *args):
        return subprocess.run(
            ["git", *args], cwd=cwd, capture_output=True, text=True,
            timeout=30,
        )

    def test_changed_pulls_in_reverse_import_closure(
        self, tmp_path, capsys, monkeypatch
    ):
        """Editing only the helper must re-judge its importer: the
        flow-* finding lands in a file git does NOT report changed."""
        repo = tmp_path / "repo"
        repo.mkdir()
        assert self._git(repo, "init", "-q").returncode == 0
        self._git(repo, "config", "user.email", "t@example.com")
        self._git(repo, "config", "user.name", "t")
        (repo / "a.py").write_text(
            "def pause():\n    return 0\n"
        )
        (repo / "b.py").write_text(
            "import threading\n\n"
            "from a import pause\n\n\n"
            "class Gate:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def wait(self):\n"
            "        with self._lock:\n"
            "            pause()\n"
        )
        self._git(repo, "add", "-A")
        assert self._git(repo, "commit", "-qm", "seed").returncode == 0
        monkeypatch.chdir(repo)
        # edit ONLY the helper: it now blocks
        (repo / "a.py").write_text(
            "import time\n\n\ndef pause():\n    time.sleep(0.2)\n"
        )
        rc, out = self._run(capsys, "--changed", str(repo))
        assert rc == 1, out
        assert "2 files" in out  # a.py (changed) + b.py (closure)
        assert "flow-blocking-under-lock" in out
        assert "b.py" in out
