"""The prewarm tool's aval mirror must match the real ``stage()``.

``prewarm_cache._stage_avals`` reproduces ``ops.als.stage()``'s chunked
device layout (block rounding, padding, uint16 index narrowing) as
ShapeDtypeStructs so programs can be AOT-compiled without a device. If
the two ever drift, the prewarmed programs are not the programs the
bench runs — the cache warms the wrong keys and the offline validation
validates the wrong shapes. This test pins them together.
"""

from __future__ import annotations

import jax
import numpy as np

from predictionio_tpu.ops import als
from predictionio_tpu.tools.prewarm_cache import _stage_avals


def test_stage_avals_match_real_stage():
    rng = np.random.default_rng(3)
    nnz, n_u, n_i = 50_000, 3_000, 700
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int64)
    i = rng.integers(0, n_i, nnz).astype(np.int64)
    v = rng.integers(1, 6, nnz).astype(np.float32)

    side = als.bucketize(u, i, v, n_u, n_i, pad_to_blocks=True)
    staged = als.stage(side)
    avals = _stage_avals(side, None)

    real = als._bucket_tensors(staged)
    assert len(avals) == len(real)
    for got, want in zip(avals, real):
        for g, wt in zip(got, want):
            assert g.shape == wt.shape, (g.shape, wt.shape)
            assert g.dtype == wt.dtype, (g.dtype, wt.dtype)

    # mesh layout: the data-axis row_multiple round-up must match too
    # (used by the multichip AOT compiles)
    staged2 = als.stage(side, row_multiple=4)
    avals2 = _stage_avals(side, None, row_multiple=4)
    for got, want in zip(avals2, als._bucket_tensors(staged2)):
        for g, wt in zip(got, want):
            assert g.shape == wt.shape, (g.shape, wt.shape)


def test_stage_avals_uint16_narrowing():
    # few columns -> stage() narrows idx to uint16; the mirror must too
    rng = np.random.default_rng(4)
    u = rng.integers(0, 500, 5_000).astype(np.int64)
    i = rng.integers(0, 100, 5_000).astype(np.int64)
    v = np.ones(5_000, np.float32)
    side = als.bucketize(u, i, v, 500, 100, pad_to_blocks=True)
    staged = als.stage(side)
    avals = _stage_avals(side, None)
    for got, want in zip(avals, als._bucket_tensors(staged)):
        assert got[1].dtype == np.asarray(want[1]).dtype == np.uint16
