"""Server-mode (remote) storage backend + pluggable-registry tests.

The remote family is the rebuild's analogue of the reference's networked
backends (HBase/Elasticsearch clients); registry pluggability mirrors the
reflective DAO lookup of ``Storage.scala:176-217``. The event-store surface
itself is covered by the shared ``event_store`` fixture (conftest) running
every storage test against the remote backend; this file covers the
metadata RPC, model blobs, registry resolution from env config, and
third-party registration without editing ``registry.py``.
"""

import datetime as dt
import textwrap

import pytest

from predictionio_tpu.storage import MetadataStore, SqliteEventStore
from predictionio_tpu.storage.backends import (
    BackendLookupError,
    registered_backends,
    resolve_backend,
)
from predictionio_tpu.storage.metadata import (
    AccessKey,
    App,
    EngineInstance,
    EngineManifest,
    STATUS_COMPLETED,
    STATUS_INIT,
)
from predictionio_tpu.storage.model_store import Model, SqliteModelStore
from predictionio_tpu.storage.registry import StorageRegistry
from predictionio_tpu.storage.remote import (
    RemoteEventStore,
    RemoteMetadataStore,
    RemoteModelStore,
    RemoteStorageError,
)
from predictionio_tpu.storage.storage_server import StorageServer
from predictionio_tpu.storage.wire import decode, encode

UTC = dt.timezone.utc


@pytest.fixture()
def server():
    srv = StorageServer(
        "127.0.0.1",
        0,
        SqliteEventStore(":memory:"),
        MetadataStore(":memory:"),
        SqliteModelStore(":memory:"),
    )
    srv.start_background()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def base_url(server):
    return f"http://127.0.0.1:{server.bound_port}"


# -- wire codec -----------------------------------------------------------


def test_wire_roundtrip_records():
    inst = EngineInstance(
        id="i1",
        status=STATUS_INIT,
        start_time=dt.datetime(2026, 7, 1, 12, 0, tzinfo=UTC),
        end_time=dt.datetime(2026, 7, 1, 12, 5, tzinfo=UTC),
        engine_id="e",
        engine_version="1",
        engine_variant="default",
        engine_factory="f",
        env={"A": "B"},
    )
    out = decode(encode(inst))
    assert out == inst
    # nested containers
    assert decode(encode([inst, {"k": inst}])) == [inst, {"k": inst}]
    # plain values pass through
    assert decode(encode({"x": [1, "a", None, 2.5]})) == {"x": [1, "a", None, 2.5]}


# -- metadata over RPC ----------------------------------------------------


def test_remote_metadata_app_and_accesskey(base_url):
    md = RemoteMetadataStore(base_url)
    app_id = md.app_insert(App(id=0, name="remoteapp"))
    assert isinstance(app_id, int)
    assert md.app_get(app_id).name == "remoteapp"
    assert md.app_get_by_name("remoteapp").id == app_id
    assert [a.name for a in md.app_get_all()] == ["remoteapp"]

    key = md.access_key_insert(AccessKey(key="", appid=app_id, events=["rate"]))
    got = md.access_key_get(key)
    assert got.appid == app_id and list(got.events) == ["rate"]
    assert md.access_key_delete(key)


def test_remote_metadata_engine_instances(base_url):
    md = RemoteMetadataStore(base_url)
    t0 = dt.datetime(2026, 7, 2, tzinfo=UTC)
    inst = EngineInstance(
        id="", status=STATUS_INIT, start_time=t0, end_time=t0,
        engine_id="e", engine_version="v", engine_variant="default",
        engine_factory="pkg.Factory",
    )
    iid = md.engine_instance_insert(inst)
    got = md.engine_instance_get(iid)
    assert got.start_time == t0 and got.status == STATUS_INIT
    import dataclasses

    md.engine_instance_update(
        dataclasses.replace(got, status=STATUS_COMPLETED)
    )
    latest = md.engine_instance_get_latest_completed("e", "v", "default")
    assert latest is not None and latest.id == iid

    assert md.manifest_update(
        EngineManifest(id="m", version="1", name="n", engine_factory="f")
    )
    assert md.manifest_get("m", "1").name == "n"
    assert md.gen_next("seq") == 1 and md.gen_next("seq") == 2


def test_remote_metadata_rejects_unknown_method(base_url):
    from predictionio_tpu.storage.remote import _RemoteRPC

    with pytest.raises(RemoteStorageError, match="HTTP 400"):
        _RemoteRPC(base_url, "os_system", 5.0)("rm -rf /")


# -- model blobs ----------------------------------------------------------


def test_remote_models_roundtrip(base_url):
    ms = RemoteModelStore(base_url)
    blob = bytes(range(256)) * 10
    ms.insert(Model(id="m1", models=blob))
    assert ms.get("m1").models == blob
    ms.delete("m1")
    assert ms.get("m1") is None


# -- registry resolution --------------------------------------------------


def test_registry_resolves_remote_type_from_env(base_url, server):
    env = {
        "PIO_STORAGE_SOURCES_RS_TYPE": "remote",
        "PIO_STORAGE_SOURCES_RS_HOST": "127.0.0.1",
        "PIO_STORAGE_SOURCES_RS_PORT": str(server.bound_port),
    }
    reg = StorageRegistry(env)
    ev = reg.get_events()
    assert isinstance(ev, RemoteEventStore)
    from predictionio_tpu.storage.event import Event, utcnow

    ev.init(7)
    eid = ev.insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=utcnow()),
        7,
    )
    assert ev.get(eid, 7).entity_id == "u1"
    assert isinstance(reg.get_metadata(), RemoteMetadataStore)
    assert isinstance(reg.get_models(), RemoteModelStore)
    # and the registry verification path works end-to-end over the wire
    assert reg.verify_all_data_objects() == {
        "metadata": True, "modeldata": True, "eventdata": True,
    }


def test_unknown_backend_type_reports_candidates():
    reg = StorageRegistry({"PIO_STORAGE_SOURCES_X_TYPE": "nosuchbackend"})
    from predictionio_tpu.storage.registry import StorageError

    with pytest.raises(StorageError, match="nosuchbackend"):
        reg.get_events()


# -- third-party pluggability (the Storage.scala:176-217 contract) --------


def test_third_party_backend_registers_without_editing_registry(
    tmp_path, monkeypatch
):
    """A backend shipped outside predictionio_tpu plugs in via the source's
    ``module`` conf key — nothing in registry.py names it."""
    pkg = tmp_path / "thirdparty_kv.py"
    pkg.write_text(
        textwrap.dedent(
            """
            from predictionio_tpu.storage.backends import (
                BackendFamily, register_backend,
            )
            from predictionio_tpu.storage.sqlite_events import SqliteEventStore

            def _events(conf):
                store = SqliteEventStore(":memory:")
                store.thirdparty_marker = conf.get("flavor", "")
                return store

            register_backend(BackendFamily(name="kvtest", events=_events))
            """
        )
    )
    monkeypatch.syspath_prepend(str(tmp_path))
    assert "kvtest" not in registered_backends()
    reg = StorageRegistry(
        {
            "PIO_STORAGE_SOURCES_KV_TYPE": "kvtest",
            "PIO_STORAGE_SOURCES_KV_MODULE": "thirdparty_kv",
            "PIO_STORAGE_SOURCES_KV_FLAVOR": "tangy",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "KV",
        }
    )
    ev = reg.get_events()
    assert ev.thirdparty_marker == "tangy"
    assert "kvtest" in registered_backends()


def test_resolve_backend_error_lists_tried_modules():
    with pytest.raises(BackendLookupError, match="predictionio_tpu.storage.zzz"):
        resolve_backend("zzz", {})


def test_builtin_families_present():
    fams = registered_backends()
    for name in ("sqlite", "localfs", "memory", "native"):
        assert name in fams


# -- connection pooling ----------------------------------------------------


class TestConnectionPooling:
    def _store(self, base_url):
        from predictionio_tpu.storage.remote import RemoteEventStore

        return RemoteEventStore(base_url)

    def _event(self):
        from predictionio_tpu.storage import DataMap, Event

        return Event(
            event="rate", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1",
            properties=DataMap({"rating": 4.0}),
        )

    def test_write_path_reuses_live_connection(self, base_url):
        """Writes keep keep-alive (no per-event TCP handshake): a pooled
        connection that passes the liveness probe is reused; reads share
        the same pool."""
        from predictionio_tpu.storage import remote

        st = self._store(base_url)
        st.init(7)
        st.write_new([self._event()], 7)
        conn1 = remote._pool.conns.get(base_url)
        assert conn1 is not None, "connection not pooled after write"
        st.write_new([self._event()], 7)
        assert remote._pool.conns.get(base_url) is conn1, (
            "live pooled connection not reused by the write path"
        )
        from predictionio_tpu.storage.events import EventFilter

        assert len(list(st.find(7, EventFilter()))) == 2
        assert remote._pool.conns.get(base_url) is conn1, "read not pooled"

    @staticmethod
    def _lying_keepalive_server():
        """A server that claims keep-alive (HTTP/1.1, no Connection: close)
        but closes the TCP connection after every response — the exact
        idle-stale-connection scenario the retry exists for. Returns
        (port, hits list, closer)."""
        import socket
        import threading

        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)
        port = lsock.getsockname()[1]
        hits = []

        def serve():
            while True:
                try:
                    c, _ = lsock.accept()
                except OSError:
                    return
                with c:
                    data = b""
                    while b"\r\n\r\n" not in data:
                        chunk = c.recv(4096)
                        if not chunk:
                            break
                        data += chunk
                    if not data:
                        continue
                    hits.append(data.split(b"\r\n", 1)[0].decode())
                    body = b'{"ok": true}'
                    c.sendall(
                        b"HTTP/1.1 200 OK\r\nContent-Type: application/json"
                        b"\r\nContent-Length: %d\r\n\r\n%s"
                        % (len(body), body)
                    )
                    # close WITHOUT having announced Connection: close

        threading.Thread(target=serve, daemon=True).start()
        return port, hits, lsock.close

    def test_stale_pooled_connection_retries_idempotent_request(self):
        from predictionio_tpu.storage import remote

        port, hits, closer = self._lying_keepalive_server()
        try:
            url = f"http://127.0.0.1:{port}/x"
            with remote._request(url) as r:
                assert b"ok" in r.read()
            # response looked reusable -> pooled, but the server closed it
            assert remote._pool.conns.get(f"http://127.0.0.1:{port}")
            with remote._request(url) as r:  # GET: retries transparently
                assert b"ok" in r.read()
            assert len(hits) == 2
        finally:
            closer()

    def test_non_idempotent_write_survives_stale_pooled_conn(self):
        """Against a server that drops keep-alive connections while idle,
        a write must neither fail (the pre-pooling behavior regression the
        round-2 advisor flagged) nor silently replay: the liveness probe
        sees EOF on the stale socket and the write goes out exactly once
        on a fresh connection."""
        from predictionio_tpu.storage import remote

        port, hits, closer = self._lying_keepalive_server()
        try:
            url = f"http://127.0.0.1:{port}/x"
            netloc = f"http://127.0.0.1:{port}"
            with remote._request(url, "POST", b"{}") as r:
                r.read()
            assert remote._pool.conns.get(netloc)  # stale conn pooled
            import time

            time.sleep(0.1)  # let the server's FIN land so the probe sees EOF
            # POST probes the pooled conn, finds it dead, sends once fresh
            with remote._request(url, "POST", b"{}") as r:
                r.read()
            assert len(hits) == 2
        finally:
            closer()

    def test_idempotent_read_retries_stale_pooled_conn(self):
        """GETs keep the pool + one-shot stale retry: the pooled connection
        the server closed while idle is transparently replaced."""
        from predictionio_tpu.storage import remote

        port, hits, closer = self._lying_keepalive_server()
        try:
            url = f"http://127.0.0.1:{port}/x"
            with remote._request(url, "GET") as r:
                r.read()
            assert len(hits) == 1
            # pooled conn is stale (server closed it); GET retries fresh
            with remote._request(url, "GET") as r:
                r.read()
            assert len(hits) == 2
        finally:
            closer()

    def test_abandoned_stream_discards_connection(self, base_url):
        from predictionio_tpu.storage import remote
        from predictionio_tpu.storage.events import EventFilter

        st = self._store(base_url)
        st.init(9)
        # enough events that the abandoned remainder exceeds the bounded
        # drain in _PooledResponse.close (64 KB) — a small remainder is
        # deliberately drained and the connection reused
        for _ in range(5):
            st.write_new([self._event() for _ in range(200)], 9)
        it = st.find(9, EventFilter(event_names=["rate"]))
        next(it)
        before = remote._pool.conns.get(base_url)
        it.close()  # abandon mid-stream
        # the streaming connection must NOT have been pooled for reuse
        after = remote._pool.conns.get(base_url)
        assert after is before
        # and subsequent ops still work
        assert len(list(st.find(9, EventFilter(event_names=["rate"])))) == 1000
