"""Workflow runtime tests: run_train / run_evaluation lifecycle against the
storage registry (``CoreWorkflow.scala`` behavior)."""

import json

import pytest

from predictionio_tpu.controller import (
    EngineParamsGenerator,
    Evaluation,
    Metric,
    MetricEvaluator,
    WorkflowParams,
)
from predictionio_tpu.storage import STATUS_COMPLETED, STATUS_EVALCOMPLETED, StorageRegistry
from predictionio_tpu.workflow.core_workflow import load_models, run_evaluation, run_train
from predictionio_tpu.workflow.context import WorkflowContext, pio_env_vars

from sample_engine import (
    IdParams,
    SampleModel,
    reset_all_counts,
)
from test_engine import IdSumMetric, make_engine, make_params


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()


@pytest.fixture()
def registry(tmp_path):
    return StorageRegistry(env={"PIO_FS_BASEDIR": str(tmp_path)})


class TestRunTrain:
    def test_full_lifecycle(self, registry):
        engine = make_engine()
        params = make_params(ds_id=2, prep_id=4, algo_ids=(8,))
        iid = run_train(
            engine,
            params,
            registry,
            engine_id="sample",
            engine_factory="tests.sample_engine",
            workflow_params=WorkflowParams(batch="b1"),
        )
        md = registry.get_metadata()
        inst = md.engine_instance_get(iid)
        assert inst.status == STATUS_COMPLETED
        assert inst.engine_id == "sample"
        assert inst.batch == "b1"
        assert inst.end_time >= inst.start_time
        # params columns are JSON
        assert json.loads(inst.algorithms_params)[0]["params"]["id"] == 8
        # model blob loads back
        models = load_models(registry, iid)
        assert models == [SampleModel(algo_id=8, pd_id=4)]
        # deploy path finds the latest completed instance
        latest = md.engine_instance_get_latest_completed(
            "sample", "1", "engine.json"
        )
        assert latest.id == iid

    def test_instance_params_roundtrip_to_engine_params(self, registry):
        engine = make_engine()
        params = make_params(algo_ids=(3,))
        iid = run_train(engine, params, registry)
        inst = registry.get_metadata().engine_instance_get(iid)
        assert engine.engine_instance_to_engine_params(inst) == params

    def test_train_failure_leaves_init_row(self, registry):
        engine = make_engine()
        bad = make_params().copy(
            data_source_params=("missing-name", IdParams())
        )
        with pytest.raises(KeyError):
            run_train(engine, bad, registry)
        # crash leaves non-COMPLETED row (reference leaves INIT)
        instances = registry.get_metadata().engine_instance_get_all()
        assert len(instances) == 1
        assert instances[0].status == "INIT"


class TestRunEvaluation:
    def test_full_lifecycle(self, registry):
        ev = Evaluation()
        ev.engine_metric = (make_engine(), IdSumMetric())
        gen = EngineParamsGenerator(
            [make_params(algo_ids=(i,)) for i in (1, 9, 4)]
        )
        iid = run_evaluation(ev, gen, registry)
        inst = registry.get_metadata().evaluation_instance_get(iid)
        assert inst.status == STATUS_EVALCOMPLETED
        assert "IdSumMetric" in inst.evaluator_results
        parsed = json.loads(inst.evaluator_results_json)
        assert parsed["bestIdx"] == 1
        assert parsed["bestEngineParams"]["algorithms"][0]["params"]["id"] == 9
        assert "<html>" in inst.evaluator_results_html
        assert [i.id for i in
                registry.get_metadata().evaluation_instance_get_completed()] == [iid]


class TestContext:
    def test_app_name_and_env(self):
        ctx = WorkflowContext(mode="Serving", batch="bb",
                              executor_env={"PIO_X": "1"})
        assert ctx.app_name == "PredictionIO Serving: bb"
        assert ctx.env == {"PIO_X": "1"}

    def test_pio_env_vars_filter(self):
        out = pio_env_vars({"PIO_A": "1", "OTHER": "2", "PIO_B": "3"})
        assert out == {"PIO_A": "1", "PIO_B": "3"}

    def test_mesh_lazy_build(self):
        ctx = WorkflowContext()
        mesh = ctx.mesh
        assert mesh.shape["data"] == 8  # virtual CPU devices from conftest
        ctx.stop()


class TestRuntimeConf:
    """engine.json runtimeConf — the embedded-sparkConf analogue
    (WorkflowUtils.scala:321-339)."""

    def test_apply_env_and_flags(self, monkeypatch):
        import os

        from predictionio_tpu.workflow.loader import apply_runtime_conf

        # own the env var so teardown restores it even though the code
        # under test (not monkeypatch) performs the write
        monkeypatch.setenv("PIO_RTCONF_PROBE", "sentinel")
        monkeypatch.setenv("XLA_FLAGS", "--existing_flag")
        applied = apply_runtime_conf(
            {
                "runtimeConf": {
                    "env": {"PIO_RTCONF_PROBE": "42"},
                    "xla_flags": "--xla_fake_probe_flag=1",
                }
            }
        )
        assert os.environ["PIO_RTCONF_PROBE"] == "42"
        assert "--existing_flag" in os.environ["XLA_FLAGS"]
        assert "--xla_fake_probe_flag=1" in os.environ["XLA_FLAGS"]
        assert applied["env"] == {"PIO_RTCONF_PROBE": "42"}
        # idempotent: reapplying does not duplicate the flag
        apply_runtime_conf(
            {"runtimeConf": {"xla_flags": "--xla_fake_probe_flag=1"}}
        )
        assert os.environ["XLA_FLAGS"].count("--xla_fake_probe_flag") == 1
        # flag-NAME-aware: a new value REPLACES the old, no duplicates
        apply_runtime_conf(
            {"runtimeConf": {"xla_flags": "--xla_fake_probe_flag=2"}}
        )
        assert os.environ["XLA_FLAGS"].count("--xla_fake_probe_flag") == 1
        assert "--xla_fake_probe_flag=2" in os.environ["XLA_FLAGS"]

    def test_jax_config_keys(self):
        import jax

        from predictionio_tpu.workflow.loader import apply_runtime_conf

        before = jax.config.jax_default_matmul_precision
        try:
            applied = apply_runtime_conf(
                {"runtimeConf": {"jax": {"jax_default_matmul_precision": "float32"}}}
            )
            assert applied["jax"] == {"jax_default_matmul_precision": "float32"}
            assert jax.config.jax_default_matmul_precision == "float32"
        finally:
            jax.config.update("jax_default_matmul_precision", before)

    def test_absent_conf_is_noop(self):
        from predictionio_tpu.workflow.loader import apply_runtime_conf

        assert apply_runtime_conf({}) == {}
        assert apply_runtime_conf(None) == {}
